"""Native decoder loader: builds and binds decoder.cpp via ctypes.

No pybind11 in this environment, so the boundary is a plain C ABI + ctypes
with NumPy-owned buffers (zero-copy in both directions).  The shared object
is compiled on first use with g++ and cached next to the source, keyed by a
source hash so edits rebuild automatically; any build/load failure degrades
silently to the pure-Python encoder (``encoder/events.py``), which is the
semantics oracle for this code path anyway.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "decoder.cpp")

_lib = None
_lib_err: Optional[str] = None


_FLAGS = ["-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
          "-pthread"]


def _cpu_fingerprint() -> bytes:
    """ISA identity for the build cache: -march=native binaries must never
    be picked up by a host with a different feature set (SIGILL, not a
    loadable-module error, so the silent-fallback path would miss it)."""
    try:
        with open("/proc/cpuinfo", "r") as fh:
            for line in fh:
                # x86 spells the ISA-extension line "flags"; ARM spells it
                # "Features" — either one identifies what -march=native
                # actually compiled for
                if line.startswith(("flags", "Features")):
                    return line.encode()
    except OSError:
        pass
    return os.uname().machine.encode()


def _build_so() -> str:
    h = hashlib.sha256()
    with open(_SRC, "rb") as fh:
        h.update(fh.read())
    h.update(" ".join(_FLAGS).encode())
    h.update(_cpu_fingerprint())
    tag = h.hexdigest()[:16]
    so_path = os.path.join(_DIR, f"_decoder_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    # compile to a temp name then rename so concurrent builders can't race
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", *_FLAGS, _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=300)
        os.replace(tmp, so_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return so_path


def load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the decoder; None if unavailable."""
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        lib = ctypes.CDLL(_build_so())
    except (OSError, subprocess.SubprocessError) as exc:
        _lib_err = str(exc)
        return None
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.s2c_decode.restype = ctypes.c_long
    lib.s2c_decode.argtypes = [
        u8p, ctypes.c_long,                    # text (uint8 view: resuming
                                               #   mid-buffer is zero-copy)
        ctypes.c_char_p, i64p, ctypes.c_long,  # names, name_off, n_contigs
        i64p, i64p,                            # ctg_offset, ctg_len
        ctypes.c_long, ctypes.c_long,          # maxdel, strict
        ctypes.c_long,                         # width
        i32p, u8p, ctypes.c_long,              # starts, codes, rows_cap
        i32p, i32p, i32p, ctypes.c_long,       # ins contig/local/mlen, cap
        u8p, ctypes.c_long,                    # ins_chars, cap
        i64p, ctypes.c_long,                   # overflow_off, cap
        i64p,                                  # out stats
        u8p, i32p, ctypes.c_int64,             # fused pileup u8 shadow,
                                               #   +256 overflow bank, len
        ctypes.c_long,                         # direct int32 mode flag
    ]
    lib.s2c_decode_bam.restype = ctypes.c_long
    lib.s2c_decode_bam.argtypes = [
        u8p, ctypes.c_long,                    # inflated record bytes
        i32p, i64p, i64p, ctypes.c_long,       # ref ci/offset/len, n_refs
        ctypes.c_long, ctypes.c_long,          # maxdel, strict
        ctypes.c_long,                         # width
        i32p, u8p, ctypes.c_long,              # starts, codes, rows_cap
        i32p, i32p, i32p, ctypes.c_long,       # ins contig/local/mlen, cap
        u8p, ctypes.c_long,                    # ins_chars, cap
        i64p, ctypes.c_long,                   # overflow_off, cap
        i64p,                                  # out stats
        u8p, i32p, ctypes.c_int64,             # fused pileup (as s2c_decode)
        ctypes.c_long,                         # direct int32 mode flag
    ]
    lib.s2c_accumulate_rows.restype = None
    lib.s2c_accumulate_rows.argtypes = [
        i32p, u8p,                             # starts, codes
        ctypes.c_long, ctypes.c_long,          # n_rows, width
        i32p, ctypes.c_long,                   # counts [L*6], total_len
    ]
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    lib.s2c_ins_table.restype = None
    lib.s2c_ins_table.argtypes = [
        i32p, i32p, i32p, ctypes.c_long,       # ev key/col/code, n_events
        i32p, ctypes.c_long,                   # table [K*C*6], C
    ]
    lib.s2c_ins_vote.restype = None
    lib.s2c_ins_vote.argtypes = [
        i32p, ctypes.c_long, ctypes.c_long,    # table, K, C
        i32p, i32p,                            # site_cov, n_cols
        f64p, ctypes.c_long,                   # thresholds, T
        u8p, u8p,                              # lut64, out [T*K*C]
    ]
    lib.s2c_merge_u8.restype = None
    lib.s2c_merge_u8.argtypes = [
        i32p, u8p, ctypes.c_int64,             # acc [n], u8 shadow [n], n
    ]
    lib.s2c_snap_shards.restype = None
    lib.s2c_snap_shards.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64,   # text, start, end
        ctypes.c_long, i64p,                   # n_shards, bounds [n+1]
    ]
    lib.s2c_cov_sums.restype = None
    lib.s2c_cov_sums.argtypes = [
        i32p, i64p,                            # cov [L], offsets [C+1]
        ctypes.c_long, i64p,                   # n_contigs, out sums [C]
    ]
    lib.s2c_finalize.restype = ctypes.c_int64  # returns '-' count
    lib.s2c_finalize.argtypes = [
        u8p, ctypes.c_int64,                   # syms [n] (0 = fill), n
        ctypes.c_long, u8p,                    # fill char, out ascii [n]
    ]
    lib.s2c_vote.restype = None
    lib.s2c_vote.argtypes = [
        i32p, ctypes.c_int64,                  # counts [L*6], L
        f64p, ctypes.c_long, ctypes.c_long,    # thresholds, T, min_depth
        u8p,                                   # 64-entry mask->byte LUT
        u8p, i32p,                             # out syms [T*L], out cov [L]
        ctypes.c_long,                         # worker threads
    ]
    _lib = lib
    return _lib


def load_error() -> Optional[str]:
    return _lib_err
