// Native SAM -> segment-row decoder.
//
// The one justified native component of the framework (SURVEY.md §2b): at
// TPU throughput the per-read Python/NumPy decode loop is the end-to-end
// bottleneck, so the hot text path — SAM field split, CIGAR walk, base
// translation, segment-row emission — is C++ behind a ctypes boundary.
// Semantics replicate the Python encoder exactly
// (sam2consensus_tpu/encoder/events.py, itself pinned to
// /root/reference/sam2consensus.py:46-82,191-221); on any flagged line the
// Python wrapper replays that line through the Python path so error
// behavior (exception type and message) stays byte-for-byte identical.
//
// Contract notes mirrored from the Python encoder:
//  * field use: RNAME (whitespace-truncated), POS-1, CIGAR, SEQ — no
//    FLAG/MAPQ filtering (sam2consensus.py:195-206);
//  * CIGAR parsed with regex-equivalent semantics: a digit run must be
//    immediately followed by a valid op, otherwise scanning resumes one
//    character later (re.findall on r"(\d+)([MIDNSHPX=])");
//  * M/=/X copy read bases (SEQ truncation leaves PAD cells), D/N/P emit
//    GAP and advance the reference cursor (P included — quirk 2), I records
//    a motif keyed by the next reference index (quirk 3), S skips read
//    bases, H is a no-op;
//  * the maxdel gate counts GAP cells (deletion runs AND literal '-' SEQ
//    bases) and, when tripped, turns them into PAD (skipped but advancing);
//  * POS-1 may be negative down to -reflen: rows wrap Python-style and
//    split in two;
//  * errors: malformed lines (too few fields / bad int / empty RNAME) stop
//    decoding in every mode (Python raises from the record iterator);
//    contract violations (unknown RNAME, out-of-bounds span,
//    out-of-alphabet base) stop in strict mode and skip the read in
//    permissive mode.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#ifdef __AVX512BW__
#include <immintrin.h>
#endif

#if defined(__AVX512BW__) && defined(__AVX512VBMI__)
#define S2C_SIMD 1
#endif

namespace {

constexpr unsigned char kPad = 255;   // == encoder PAD_CODE
constexpr unsigned char kGap = 0;

struct BaseLut {
  unsigned char m[256];
  BaseLut() {
    memset(m, 255, sizeof(m));
    m[static_cast<unsigned char>('-')] = 0;
    m[static_cast<unsigned char>('A')] = 1;
    m[static_cast<unsigned char>('C')] = 2;
    m[static_cast<unsigned char>('G')] = 3;
    m[static_cast<unsigned char>('N')] = 4;
    m[static_cast<unsigned char>('T')] = 5;
  }
};
const BaseLut kLut;

// Saturating uint8 count cell: the pileup accumulates into a uint8 shadow
// tensor (6 B/position instead of 24 — 4x fewer cache lines on the hot
// random-access increments) with wraps banked as +256 in a lazily-touched
// int32 tensor of the same shape.  Exact: cell + ovf == true count; the
// Python wrapper merges both into the int32 pileup at stream end
// (encoder/native_encoder.py merge_shadow).
inline void u8_inc(unsigned char* cell, int32_t* ovf_cell,
                   int64_t& banked) {
  const unsigned char v = *cell;
  if (__builtin_expect(v == 255, 0)) {
    *cell = 0;
    *ovf_cell += 256;
    ++banked;
  } else {
    *cell = v + 1;
  }
}

#ifdef S2C_SIMD
// AVX-512VBMI tables for the vectorized base translation and the one-hot
// count expansion.  Translation: ascii & 63 is collision-free over the
// 6-symbol alphabet {-,A,C,G,N,T}, so one vpermb maps 64 chars to codes
// and a second vpermb reconstructs the expected ascii for validation —
// any byte whose reconstruction mismatches is out-of-alphabet (code 255),
// replicating the scalar LUT's 255 marker.  Counting: for 10 consecutive
// positions (60 cells of the [L, 6] uint8 tensor), expand codes with
// vpermb (j -> code[j/6]), compare against the j%6 lane pattern, and
// masked-add the resulting 0/1 bytes onto the cells — the host-SIMD twin
// of the device MXU's one-hot matmul pileup (ops/mxu_pileup.py).
struct SimdTables {
  __m512i code, chr, expand, rem;
  SimdTables() {
    alignas(64) unsigned char c[64], a[64], e[64], r[64];
    memset(c, 255, 64);
    // unused reconstruction slots hold j+1, never == any byte whose low
    // 6 bits select slot j (j+1 != j mod 64) — a zero fill would let
    // '\0' reconstruct itself through slot 0 and pass validation
    for (int j = 0; j < 64; ++j)
      a[j] = static_cast<unsigned char>(j + 1);
    const char* bases = "-ACGNT";
    for (int i = 0; i < 6; ++i) {
      const unsigned char ch = static_cast<unsigned char>(bases[i]);
      c[ch & 63] = static_cast<unsigned char>(i);
      a[ch & 63] = ch;
    }
    for (int j = 0; j < 64; ++j) {
      e[j] = static_cast<unsigned char>(j / 6);
      r[j] = static_cast<unsigned char>(j % 6);
    }
    code = _mm512_load_si512(c);
    chr = _mm512_load_si512(a);
    expand = _mm512_load_si512(e);
    rem = _mm512_load_si512(r);
  }
};
const SimdTables kSimd;

// Translate n ascii bases into codes; accumulates the bad-base flag and
// the gap ('-') count exactly like the scalar loop.
inline void simd_translate(const char* src, unsigned char* dst, long n,
                           bool& bad, long& gaps) {
  long k = 0;
  while (k < n) {
    const long rem_n = n - k;
    const __mmask64 lm =
        (rem_n >= 64) ? ~0ULL : ((1ULL << rem_n) - 1);
    const __m512i s = _mm512_maskz_loadu_epi8(lm, src + k);
    const __m512i idx = _mm512_and_si512(s, _mm512_set1_epi8(63));
    __m512i code = _mm512_permutexvar_epi8(idx, kSimd.code);
    const __m512i expect = _mm512_permutexvar_epi8(idx, kSimd.chr);
    const __mmask64 valid = _mm512_cmpeq_epi8_mask(expect, s);
    code = _mm512_mask_blend_epi8(valid, _mm512_set1_epi8((char)255),
                                  code);
    _mm512_mask_storeu_epi8(dst + k, lm, code);
    bad |= ((valid & lm) != lm);
    gaps += __builtin_popcountll(
        _mm512_mask_cmpeq_epi8_mask(lm, code, _mm512_setzero_si512()));
    k += 64;
  }
}

// Validation-only walk for insertion motifs (no code store needed).
inline bool simd_validate(const char* src, long n) {
  bool bad = false;
  long k = 0;
  while (k < n) {
    const long rem_n = n - k;
    const __mmask64 lm =
        (rem_n >= 64) ? ~0ULL : ((1ULL << rem_n) - 1);
    const __m512i s = _mm512_maskz_loadu_epi8(lm, src + k);
    const __m512i idx = _mm512_and_si512(s, _mm512_set1_epi8(63));
    const __m512i expect = _mm512_permutexvar_epi8(idx, kSimd.chr);
    bad |= ((_mm512_cmpeq_epi8_mask(expect, s) & lm) != lm);
    k += 64;
  }
  return bad;
}
#endif  // S2C_SIMD

// Accumulate one translated row (codes[0..span), PAD cells skipped) into
// the uint8 shadow pileup at genome position gstart.  Bounds are the
// caller's contract (fast path: 0 <= gstart, gstart + span <= total).
inline void count_row_u8(const unsigned char* codes, long span,
                         int64_t gstart, unsigned char* u8, int32_t* ovf,
                         int64_t& banked) {
  unsigned char* ap = u8 + gstart * 6;
#ifdef S2C_SIMD
  for (long k0 = 0; k0 < span; k0 += 10) {
    long npos = span - k0;
    if (npos > 10) npos = 10;
    const __mmask64 mc = (1ULL << (npos * 6)) - 1;
    const __m512i cvec = _mm512_maskz_loadu_epi8(
        (__mmask64)((1ULL << npos) - 1), codes + k0);
    const __m512i ce = _mm512_permutexvar_epi8(kSimd.expand, cvec);
    __mmask64 inc = _mm512_mask_cmpeq_epi8_mask(mc, ce, kSimd.rem);
    unsigned char* cp = ap + k0 * 6;
    __m512i cells = _mm512_maskz_loadu_epi8(mc, cp);
    const __mmask64 sat = _mm512_mask_cmpeq_epi8_mask(
        inc, cells, _mm512_set1_epi8((char)255));
    if (__builtin_expect(sat != 0, 0)) {
      unsigned long long s = sat;
      banked += __builtin_popcountll(s);
      while (s) {
        const int j = __builtin_ctzll(s);
        cp[j] = 0;
        ovf[(gstart + k0) * 6 + j] += 256;
        s &= s - 1;
      }
      inc &= ~sat;
      cells = _mm512_maskz_loadu_epi8(mc, cp);
    }
    cells = _mm512_mask_add_epi8(cells, inc, cells, _mm512_set1_epi8(1));
    _mm512_mask_storeu_epi8(cp, mc, cells);
  }
#else
  for (long k = 0; k < span; ++k) {
    const unsigned char c = codes[k];
    if (c < 6) u8_inc(ap + k * 6 + c, ovf + (gstart + k) * 6 + c, banked);
  }
#endif
}

inline bool is_ws(char c) {
  // ASCII subset of Python str.split() whitespace (input is ascii-decoded)
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\v' ||
         c == '\f';
}

inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

inline bool is_op(char c) {
  switch (c) {
    case 'M': case 'I': case 'D': case 'N': case 'S': case 'H': case 'P':
    case 'X': case '=':
      return true;
    default:
      return false;
  }
}

// Advance `c` to the next CIGAR (num, op) token, replicating python's
// re.findall(r"(\d+)([MIDNSHPX=])") semantics: non-digits are skipped, a
// digit run not immediately followed by a valid op resumes one char later,
// lengths clamp at 2^40 (they can only fail the bounds check, whose message
// comes from the python replay).  Returns false at end of string.  The
// pre-scan and both translation walks share this so their (num, op)
// sequences can never diverge — the fast path's capacity pre-check and
// direct slab writes rely on that agreement.
inline bool next_cigar_op(const char* text, long ce, long& c, int64_t& num,
                          char& op) {
  while (c < ce) {
    if (!is_digit(text[c])) {
      ++c;
      continue;
    }
    long j = c;
    int64_t n = 0;
    while (j < ce && is_digit(text[j])) {
      n = n * 10 + (text[j] - '0');
      if (n > (int64_t(1) << 40)) n = int64_t(1) << 40;
      ++j;
    }
    if (j >= ce || !is_op(text[j])) {
      ++c;  // regex-style: resume scanning one char later
      continue;
    }
    num = n;
    op = text[j];
    c = j + 1;
    return true;
  }
  return false;
}

uint64_t hash_bytes(const char* s, long n) {
  uint64_t h = 1469598103934665603ULL;
  for (long i = 0; i < n; ++i) {
    h ^= static_cast<unsigned char>(s[i]);
    h *= 1099511628211ULL;
  }
  return h;
}

// Open-addressing contig-name table (names are pre-deduplicated by the
// GenomeLayout, so insertion order conflicts cannot happen).
struct NameTable {
  std::vector<int32_t> slot;  // contig index + 1; 0 = empty
  uint64_t mask = 0;
  const char* names = nullptr;
  const int64_t* off = nullptr;

  void build(const char* names_, const int64_t* off_, long n) {
    names = names_;
    off = off_;
    long cap = 16;
    while (cap < 2 * n) cap <<= 1;
    slot.assign(cap, 0);
    mask = cap - 1;
    for (long i = 0; i < n; ++i) {
      uint64_t h = hash_bytes(names + off[i], off[i + 1] - off[i]) & mask;
      while (slot[h]) h = (h + 1) & mask;
      slot[h] = static_cast<int32_t>(i) + 1;
    }
  }

  long find(const char* s, long len) const {
    uint64_t h = hash_bytes(s, len) & mask;
    while (slot[h]) {
      long i = slot[h] - 1;
      if (off[i + 1] - off[i] == len && memcmp(names + off[i], s, len) == 0)
        return i;
      h = (h + 1) & mask;
    }
    return -1;
  }
};

enum Status : long {
  kOk = 0,
  kCapacity = 1,   // out buffers full; out[3] = consumed bytes so far
  kErrorLine = 2,  // line flagged; out[7] = its byte offset (python replays)
};

enum OutIdx : int {
  oRows = 0,
  oReads = 1,
  oSkipped = 2,
  oConsumed = 3,
  oIns = 4,
  oInsChars = 5,
  oStatus = 6,
  oErrorOff = 7,
  oEvents = 8,
  oLines = 9,
  oOverflow = 10,
  oMaxSpan = 11,
  oBanked = 12,  // u8-shadow saturation wraps banked into acc_ovf: when 0
                 // the bank is untouched and merge_shadow skips its fold
  oSegmented = 13,  // BAM path: reads emitted as multiple width-bounded
                    // segment rows (the long-read segmented layout,
                    // handled in C instead of the python replay lane)
  oErrReason = 14,  // BadReason code for the kErrorLine record (0 when
                    // status != kErrorLine).  A HINT for the tolerant-
                    // decode observability counters (ingest/flagged/*):
                    // classification authority stays with the python
                    // replay, whose exception types/messages are the
                    // oracle-parity contract shared with the pure-
                    // python rung.
};

// why a line/record was flagged (out[oErrReason]; mirrored by
// ingest/badrecords.py C_REASONS — keep the two tables in lockstep)
enum BadReason : long {
  rNone = 0,
  rFieldCount = 1,        // too few tab fields / empty RNAME token
  rBadPos = 2,            // POS is not an integer
  rBadCigar = 3,          // invalid binary CIGAR op (BAM)
  rSeqCigarMismatch = 4,  // SEQ shorter than the CIGAR claims
  rUnknownRef = 5,        // RNAME/refID outside the reference table
  rOutOfBounds = 6,       // span leaves the reference
  rBadAlphabet = 7,       // out-of-contract base / seq nibble
  rBadBamRecord = 8,      // record-bounded BAM structural damage
};

}  // namespace

extern "C" long s2c_decode(
    const char* text, long text_len,
    const char* names, const int64_t* name_off, long n_contigs,
    const int64_t* ctg_offset, const int64_t* ctg_len,
    long maxdel,  // -1 = gate disabled
    long strict,
    long width,
    int32_t* starts, unsigned char* codes, long rows_cap,
    int32_t* ins_contig, int32_t* ins_local, int32_t* ins_mlen, long ins_cap,
    unsigned char* ins_chars, long ins_chars_cap,
    int64_t* overflow_off, long overflow_cap,
    int64_t* out,
    // fused host pileup (ops/pileup.py HostPileupAccumulator): when
    // acc_total_len > 0, every committed row is accumulated — AFTER its
    // bad-base / maxdel fate is settled, so no rollback paths exist.
    // Two counting modes (the wrapper picks by genome size):
    //  * acc_direct == 0: SIMD one-hot increments into the uint8 shadow
    //    tensor acc_u8 [acc_total_len * 6], saturation wraps banked in
    //    acc_ovf (+256 per wrap; see u8_inc / count_row_u8) — 4x fewer
    //    cache lines on the hot increments, right when coverage is deep
    //    (counts revisited many times); the wrapper merges shadow+bank
    //    into the int32 pileup at stream end.
    //  * acc_direct != 0: plain int32 increments straight into acc_ovf,
    //    which IS the pileup tensor then (acc_u8 unused) — no shadow
    //    init and no L-proportional merge, right for huge sparse
    //    genomes where each count line is touched ~once and a 240 MB
    //    shadow merge would dominate (measured: 40 Mbp config).
    // Rows are still written to the slab (the wrapper treats it as
    // scratch and resets its fill).
    unsigned char* acc_u8, int32_t* acc_ovf, int64_t acc_total_len,
    long acc_direct) {
  NameTable table;
  table.build(names, name_off, n_contigs);

  long n_rows = 0, n_reads = 0, n_skipped = 0, n_ins = 0, n_ins_chars = 0;
  long n_events = 0, n_lines = 0, n_overflow = 0, max_span = 0;
  long status = kOk;
  long err_off = -1;
  long err_reason = rNone;
  int64_t n_banked = 0;

  std::vector<unsigned char> row;           // reused per line (slow path)
  std::vector<int64_t> ins_pos_tmp;         // insertion local positions
  std::vector<long> ins_seq_tmp;            // (seq offset, length) pairs

  long i = 0;
  while (i < text_len) {
    const char* nl = static_cast<const char*>(
        memchr(text + i, '\n', text_len - i));
    long line_end = nl ? (nl - text) : text_len;
    long next = line_end + 1;
    long ls = i;  // line start

    ++n_lines;
    if (line_end == ls || text[ls] == '@') {
      if (line_end == ls) {  // empty line: python IndexErrors on fields[5]
        status = kErrorLine;
        err_off = ls;
        err_reason = rFieldCount;
        break;
      }
      i = next;
      continue;
    }

    // --- split into tab fields (need 0..9; record starts/ends) ---
    long fs[11], fe[11];
    int nf = 0;
    long p = ls;
    fs[0] = p;
#ifdef __AVX512BW__
    // the first 9 tabs of a SAM line sit within the first few dozen
    // bytes (QNAME..MAPQ are short); one masked 64-byte compare finds
    // them all where per-field memchr paid call overhead on ~5-byte
    // spans.  Semantically identical to the memchr loop.
    while (nf < 10 && p < line_end) {
      long span = line_end - p;
      if (span > 64) span = 64;
      const __mmask64 lm =
          (span == 64) ? ~0ULL : ((1ULL << span) - 1);
      __mmask64 m = _mm512_mask_cmpeq_epi8_mask(
          lm, _mm512_maskz_loadu_epi8(lm, text + p),
          _mm512_set1_epi8('\t'));
      while (m && nf < 10) {
        const int off = __builtin_ctzll(m);
        fe[nf++] = p + off;
        fs[nf] = p + off + 1;
        m &= m - 1;
      }
      p += span;
    }
#else
    while (nf < 10) {
      const char* tab = static_cast<const char*>(
          memchr(text + p, '\t', line_end - p));
      if (!tab) break;
      fe[nf++] = tab - text;
      p = (tab - text) + 1;
      fs[nf] = p;
    }
#endif
    if (nf < 10) fe[nf++] = line_end;

    if (nf < 6) {  // python: line.split("\t")[5] -> IndexError
      status = kErrorLine;
      err_off = ls;
      err_reason = rFieldCount;
      break;
    }
    // CIGAR "*" -> unmapped, skipped before any further field access
    if (fe[5] - fs[5] == 1 && text[fs[5]] == '*') {
      i = next;
      continue;
    }
    if (nf < 10) {  // python: fields[9] -> IndexError
      status = kErrorLine;
      err_off = ls;
      err_reason = rFieldCount;
      break;
    }

    // --- RNAME: leading-whitespace skip + whitespace-truncated token ---
    long rs = fs[2], re_ = fe[2];
    while (rs < re_ && is_ws(text[rs])) ++rs;
    long rtok = rs;
    while (rtok < re_ && !is_ws(text[rtok])) ++rtok;
    if (rtok == rs) {  // empty token: python fields[2].split()[0] IndexErrors
      status = kErrorLine;
      err_off = ls;
      err_reason = rFieldCount;
      break;
    }

    // --- POS: python int() semantics (ascii): ws* [+-] digits+ ws* ---
    long ps = fs[3], pe = fe[3];
    while (ps < pe && is_ws(text[ps])) ++ps;
    while (pe > ps && is_ws(text[pe - 1])) --pe;
    bool negpos = false;
    if (ps < pe && (text[ps] == '+' || text[ps] == '-')) {
      negpos = text[ps] == '-';
      ++ps;
    }
    if (ps == pe) {
      status = kErrorLine;
      err_off = ls;
      err_reason = rBadPos;
      break;
    }
    int64_t posv = 0;
    bool badint = false;
    for (long k = ps; k < pe; ++k) {
      if (!is_digit(text[k])) {
        badint = true;
        break;
      }
      posv = posv * 10 + (text[k] - '0');
      if (posv > (int64_t(1) << 60)) posv = int64_t(1) << 60;  // clamp, errors below
    }
    if (badint) {
      status = kErrorLine;
      err_off = ls;
      err_reason = rBadPos;
      break;
    }
    if (negpos) posv = -posv;
    int64_t pos = posv - 1;  // 0-based

    // --- contig lookup (contract violation, not a parse error) ---
    long ci = table.find(text + rs, rtok - rs);
    int64_t reflen = (ci < 0) ? 0 : ctg_len[ci];

    long ss = fs[9], se = fe[9];
    long seq_len = se - ss;
    long cs = fs[5], ce = fe[5];

    // --- CIGAR pre-scan: span / insertion sizes / huge-span guard, no
    //     base translation (one cheap pass over the short CIGAR string);
    //     lets the common case translate straight into the slab row and
    //     the capacity pre-check run before any commit ---
    long span = 0;         // ref-consuming cells (== row length)
    long pre_rc = 0;       // read-cursor simulation (M/I/S advance it)
    long pre_ins = 0, pre_chars = 0;
    bool huge_span = false;
    char first_rc_op = 0;  // first read-consuming op (M/=/X/I/S, num>0)
    // op cache: the translate walk below replays these instead of
    // re-parsing the CIGAR string (digit loop + bounds per op, ~tens of
    // ms per 1M reads); CIGARs longer than the cache re-parse exactly
    // as before
    constexpr int kCigCache = 32;
    int64_t cig_num[kCigCache];
    char cig_op[kCigCache];
    int n_ops = 0;
    bool ops_cached = true;
    {
      long c = cs;
      int64_t num;
      char op;
      while (next_cigar_op(text, ce, c, num, op)) {
        if (n_ops < kCigCache) {
          cig_num[n_ops] = num;
          cig_op[n_ops] = op;
          ++n_ops;
        } else {
          ops_cached = false;
        }
        if (num > 0 && first_rc_op == 0 &&
            (op == 'M' || op == '=' || op == 'X' || op == 'I' || op == 'S'))
          first_rc_op = (op == '=' || op == 'X') ? 'M' : op;
        switch (op) {
          case 'M': case '=': case 'X':
            // guard absurd lengths: such a span can only fail the bounds
            // check, which the python replay will report.  pre_rc keeps
            // accumulating so the short-SEQ test below stays decisive.
            if (huge_span || span + num > 2 * reflen + 64)
              huge_span = true;
            else
              span += num;
            pre_rc += num;
            break;
          case 'D': case 'N': case 'P':
            if (huge_span || span + num > 2 * reflen + 64)
              huge_span = true;
            else
              span += num;
            break;
          case 'I': {
            long take = seq_len - pre_rc;
            if (take < 0) take = 0;
            if (take > num) take = num;
            ++pre_ins;
            pre_chars += take;
            pre_rc += num;
            break;
          }
          case 'S':
            pre_rc += num;
            break;
          default:  // 'H'
            break;
        }
      }
    }
    if (span > max_span) max_span = span;

    // SEQ shorter than its CIGAR claims: the reference's concatenation
    // semantics shift every later BASE/GAP op left of its claimed
    // position (python encoder reproduces them exactly,
    // encoder/events.py) — too rare to mirror here, replay the line.
    // Carve-out: SEQ "*" with a real CIGAR (common for secondary
    // alignments) whose FIRST read-consuming op is M/=/X — that op reads
    // the '*' immediately, so the line is doomed to the bad-base path
    // and the fast path can skip it in C instead of replaying it.  A
    // leading S or I would consume the '*' first and reach the
    // reference's concatenation-shift semantics after all (later gap
    // cells land left of their claimed offsets, an I records an
    // empty-or-'*' motif): those lines still replay exactly.
    if (pre_rc > seq_len &&
        !(seq_len == 1 && text[ss] == '*' && first_rc_op == 'M')) {
      status = kErrorLine;
      err_off = ls;
      err_reason = rSeqCigarMismatch;
      break;
    }

    // --- structural validation (bad bases are found during translation;
    //     the python replay reproduces the exact message either way) ---
    if (ci < 0 || huge_span ||
        (span > 0 && (pos < -reflen || pos + span > reflen))) {
      if (strict) {
        status = kErrorLine;
        err_off = ls;
        err_reason = (ci < 0) ? rUnknownRef : rOutOfBounds;
        break;
      }
      ++n_skipped;
      i = next;
      continue;
    }

    bool overflow = span > width;
    if (pos >= 0 && !overflow) {
      // ---- FAST PATH: capacity first, then translate directly into the
      //      next slab row (uncommitted until n_rows advances) ----
      long rows_needed = span > 0 ? 1 : 0;
      if (n_rows + rows_needed > rows_cap || n_ins + pre_ins > ins_cap ||
          n_ins_chars + pre_chars > ins_chars_cap) {
        status = kCapacity;
        break;  // consumed stops at this line's start
      }
      unsigned char* dst = codes + static_cast<int64_t>(n_rows) * width;
      long o = 0, rc = 0, gaps = 0, pads = 0;
      bool bad_base = false;
      long ins_base = n_ins, chars_base = n_ins_chars;
      long c = cs;
      int64_t num;
      char op;
      int oi = 0;
      while (ops_cached
                 ? (oi < n_ops
                    && (num = cig_num[oi], op = cig_op[oi], ++oi, true))
                 : next_cigar_op(text, ce, c, num, op)) {
        switch (op) {
          case 'M': case '=': case 'X': {
            long take = seq_len - rc;
            if (take < 0) take = 0;
            if (take > num) take = num;
            const char* sp = text + ss + rc;
#ifdef S2C_SIMD
            simd_translate(sp, dst + o, take, bad_base, gaps);
#else
            for (long k = 0; k < take; ++k) {
              unsigned char code =
                  kLut.m[static_cast<unsigned char>(sp[k])];
              bad_base |= (code == 255);
              gaps += (code == kGap);
              dst[o + k] = code;
            }
#endif
            if (num > take) {
              // reachable only for SEQ "*" reads (short-SEQ carve-out
              // above): memory safety until bad_base aborts the commit
              memset(dst + o + take, kPad, num - take);
              pads += num - take;
            }
            o += num;
            rc += num;
            break;
          }
          case 'D': case 'N': case 'P':
            memset(dst + o, kGap, num);
            gaps += num;
            o += num;
            break;
          case 'I': {
            long take = seq_len - rc;
            if (take < 0) take = 0;
            if (take > num) take = num;
            const char* sp = text + ss + rc;
#ifdef S2C_SIMD
            bad_base |= simd_validate(sp, take);
#else
            for (long k = 0; k < take; ++k)
              bad_base |= (kLut.m[static_cast<unsigned char>(sp[k])] == 255);
#endif
            // commit now (capacity pre-checked); rolled back on bad_base
            ins_contig[n_ins] = static_cast<int32_t>(ci);
            ins_local[n_ins] = static_cast<int32_t>(pos + o);
            ins_mlen[n_ins] = static_cast<int32_t>(take);
            memcpy(ins_chars + n_ins_chars, sp, take);
            n_ins_chars += take;
            ++n_ins;
            rc += num;
            break;
          }
          case 'S':
            rc += num;
            break;
          default:  // 'H'
            break;
        }
      }
      if (bad_base) {
        // nothing was counted yet (the pileup accumulates below, after
        // the row's fate is settled): only the insertions roll back
        n_ins = ins_base;
        n_ins_chars = chars_base;
        if (strict) {
          status = kErrorLine;
          err_off = ls;
          err_reason = rBadAlphabet;
          break;
        }
        ++n_skipped;
        i = next;
        continue;
      }
      if (maxdel >= 0 && gaps > maxdel) {
        for (long k = 0; k < span; ++k)
          if (dst[k] == kGap) dst[k] = kPad;
        pads += gaps;
      }
      if (span > 0) {
        if (acc_total_len == 0) {
          // fused mode skips the pad-tail memset: the slab is scratch
          // there (the wrapper resets its fill; counting below reads
          // only [0, span)) — ~width-span bytes/row of saved writes
          memset(dst + span, kPad, width - span);
        }
        starts[n_rows] = static_cast<int32_t>(ctg_offset[ci] + pos);
        ++n_rows;
        n_events += span - pads;
        // fused pileup: the row's final codes are still cache-hot —
        // bounds guaranteed (pos >= 0, structural validation pinned
        // pos + span <= reflen)
        if (acc_total_len > 0) {
          if (acc_direct) {
            int32_t* ap = acc_ovf + (ctg_offset[ci] + pos) * 6;
            for (long k = 0; k < span; ++k) {
              const unsigned char cd = dst[k];
              if (cd < 6) ++ap[k * 6 + cd];
            }
          } else {
            count_row_u8(dst, span, ctg_offset[ci] + pos, acc_u8,
                         acc_ovf, n_banked);
          }
        }
      }
      ++n_reads;
      i = next;
      continue;
    }

    // ---- SLOW PATH (negative POS wrap, or span > width): translate into
    //      the temp row, then the original capacity / overflow / commit
    //      protocol ----
    long rc = 0;
    int64_t ref_cursor = pos;
    bool bad_base = false;
    row.clear();
    ins_pos_tmp.clear();
    ins_seq_tmp.clear();
    {
      long c = cs;
      int64_t num;
      char op;
      while (next_cigar_op(text, ce, c, num, op)) {
        switch (op) {
          case 'M': case '=': case 'X': {
            long take = seq_len - rc;
            if (take < 0) take = 0;
            if (take > num) take = num;
            size_t base = row.size();
            row.resize(base + num, kPad);
            for (long k = 0; k < take; ++k) {
              unsigned char code =
                  kLut.m[static_cast<unsigned char>(text[ss + rc + k])];
              if (code == 255) bad_base = true;
              row[base + k] = code;
            }
            rc += num;
            ref_cursor += num;
            break;
          }
          case 'D': case 'N': case 'P':
            row.resize(row.size() + num, kGap);
            ref_cursor += num;
            break;
          case 'I': {
            long take = seq_len - rc;
            if (take < 0) take = 0;
            if (take > num) take = num;
            for (long k = 0; k < take; ++k) {
              unsigned char code =
                  kLut.m[static_cast<unsigned char>(text[ss + rc + k])];
              if (code == 255) bad_base = true;
            }
            ins_pos_tmp.push_back(ref_cursor);
            ins_seq_tmp.push_back(ss + rc);
            ins_seq_tmp.push_back(take);
            rc += num;
            break;
          }
          case 'S':
            rc += num;
            break;
          default:  // 'H'
            break;
        }
      }
    }

    if (bad_base) {
      if (strict) {
        status = kErrorLine;
        err_off = ls;
        err_reason = rBadAlphabet;
        break;
      }
      ++n_skipped;
      i = next;
      continue;
    }

    // --- maxdel gate ---
    long gaps = 0;
    for (unsigned char ch : row)
      if (ch == kGap) ++gaps;
    if (maxdel >= 0 && gaps > maxdel)
      for (auto& ch : row)
        if (ch == kGap) ch = kPad;

    // --- capacity pre-check (whole line commits or none) ---
    long rows_needed = 0;
    if (span > 0 && !overflow)
      rows_needed = (pos < 0 && pos + span > 0) ? 2 : 1;
    long chars_needed = 0;
    for (size_t k = 1; k < ins_seq_tmp.size(); k += 2)
      chars_needed += ins_seq_tmp[k];
    if (n_rows + rows_needed > rows_cap ||
        (overflow && n_overflow + 1 > overflow_cap) ||
        (!overflow &&
         (n_ins + static_cast<long>(ins_pos_tmp.size()) > ins_cap ||
          n_ins_chars + chars_needed > ins_chars_cap))) {
      status = kCapacity;
      break;  // consumed stops at this line's start
    }

    if (overflow) {
      // whole read (rows AND insertions) delegated to the python fallback
      overflow_off[n_overflow++] = ls;
      i = next;
      continue;
    }

    // --- commit: insertions (raw ASCII motifs; python translates) ---
    for (size_t k = 0; k < ins_pos_tmp.size(); ++k) {
      ins_contig[n_ins] = static_cast<int32_t>(ci);
      ins_local[n_ins] = static_cast<int32_t>(ins_pos_tmp[k]);
      long moff = ins_seq_tmp[2 * k], mlen = ins_seq_tmp[2 * k + 1];
      ins_mlen[n_ins] = static_cast<int32_t>(mlen);
      memcpy(ins_chars + n_ins_chars, text + moff, mlen);
      n_ins_chars += mlen;
      ++n_ins;
    }

    // --- commit: segment rows (wrapping negative POS python-style) ---
    if (span > 0) {
      int64_t base_off = ctg_offset[ci];
      long neg = 0;
      if (pos < 0) neg = (span < -pos) ? span : -pos;
      const unsigned char* rp = row.data();
      if (neg > 0) {
        starts[n_rows] = static_cast<int32_t>(base_off + reflen + pos);
        unsigned char* dst = codes + static_cast<int64_t>(n_rows) * width;
        memcpy(dst, rp, neg);
        memset(dst + neg, kPad, width - neg);
        ++n_rows;
      }
      if (span > neg) {
        starts[n_rows] =
            static_cast<int32_t>(base_off + (pos < 0 ? 0 : pos));
        unsigned char* dst = codes + static_cast<int64_t>(n_rows) * width;
        memcpy(dst, rp + neg, span - neg);
        memset(dst + (span - neg), kPad, width - (span - neg));
        ++n_rows;
      }
      for (long k = 0; k < span; ++k)
        if (row[k] != kPad) ++n_events;
      if (acc_total_len > 0) {
        for (long k = 0; k < span; ++k) {
          const unsigned char code = rp[k];
          if (code >= 6) continue;
          const int64_t gp = (k < neg)
              ? base_off + reflen + pos + k
              : base_off + (pos < 0 ? 0 : pos) + (k - neg);
          if (gp >= 0 && gp < acc_total_len) {
            if (acc_direct)
              ++acc_ovf[gp * 6 + code];
            else
              u8_inc(acc_u8 + gp * 6 + code, acc_ovf + gp * 6 + code,
                     n_banked);
          }
        }
      }
    }
    ++n_reads;
    i = next;
  }

  // n_lines counts fully-consumed lines only: every break above happens
  // after ++n_lines but before the line is consumed (the wrapper re-feeds
  // or replays it), so roll that one back.
  if (status != kOk) --n_lines;

  out[oRows] = n_rows;
  out[oReads] = n_reads;
  out[oSkipped] = n_skipped;
  out[oConsumed] = (status == kOk) ? text_len : i;
  out[oIns] = n_ins;
  out[oInsChars] = n_ins_chars;
  out[oStatus] = status;
  out[oErrorOff] = err_off;
  out[oEvents] = n_events;
  out[oLines] = n_lines;
  out[oOverflow] = n_overflow;
  out[oMaxSpan] = max_span;
  out[oBanked] = n_banked;
  out[oErrReason] = err_reason;
  return status;
}

// ---------------------------------------------------------------------------
// Binary BAM record decoder — the text path's twin over the BGZF-inflated
// record stream (formats/bam.py feeds whole-record buffers; BGZF blocks
// inflate block-parallel upstream).  No field split, no int parse, no
// CIGAR regex: ops are (u32 >> 4, u32 & 0xF) and SEQ is 4-bit nibbles,
// which is exactly why BAM ingest skips the text-tokenization bill.
//
// Protocol mirrors s2c_decode: same out[] indices, same status codes.
//  * kErrorLine: err_off = the RECORD's byte offset (at its block_size
//    field); the python wrapper replays that one record through the
//    golden encoder so exception type/message match the oracle exactly;
//  * kCapacity: slab/insertion buffers full, consumed stops before the
//    record;
//  * overflow_off records reads the wrapper must replay in python:
//    span > width (the segmented-layout fallback) and negative-POS
//    wraps (rare; python owns the wrap split).
// refID indexing replaces the name hash: the wrapper passes per-refid
// (layout contig index, flat offset, length) arrays resolved through the
// GenomeLayout, so duplicate-name semantics match the text path.

namespace {

// BAM nibble -> consensus code ("=ACMGRSVTWYHKDBN"; only ACGTN valid)
constexpr unsigned char kNibCode[16] = {255, 1, 2, 255, 3, 255, 255, 255,
                                        5, 255, 255, 255, 255, 255, 255, 4};
constexpr char kNibChr[17] = "=ACMGRSVTWYHKDBN";
// BAM op code -> text op (index > 8 is corrupt; wrapper replay reports)
constexpr char kOpChr[9] = {'M', 'I', 'D', 'N', 'S', 'H', 'P', '=', 'X'};

inline int32_t le32(const unsigned char* p) {
  int32_t v;
  memcpy(&v, p, 4);
  return v;  // build targets are little-endian (x86/arm64)
}

inline uint32_t leu32(const unsigned char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

inline unsigned char nib_at(const unsigned char* seq, long j) {
  const unsigned char b = seq[j >> 1];
  return (j & 1) ? (b & 0xF) : (b >> 4);
}

}  // namespace

extern "C" long s2c_decode_bam(
    const unsigned char* data, long data_len,
    const int32_t* ref_ci, const int64_t* ref_offset,
    const int64_t* ref_len, long n_refs,
    long maxdel, long strict, long width,
    int32_t* starts, unsigned char* codes, long rows_cap,
    int32_t* ins_contig, int32_t* ins_local, int32_t* ins_mlen, long ins_cap,
    unsigned char* ins_chars, long ins_chars_cap,
    int64_t* overflow_off, long overflow_cap,
    int64_t* out,
    unsigned char* acc_u8, int32_t* acc_ovf, int64_t acc_total_len,
    long acc_direct) {
  long n_rows = 0, n_reads = 0, n_skipped = 0, n_ins = 0, n_ins_chars = 0;
  long n_events = 0, n_lines = 0, n_overflow = 0, max_span = 0;
  long n_segmented = 0;
  long status = kOk;
  long err_off = -1;
  long err_reason = rNone;
  int64_t n_banked = 0;
  std::vector<unsigned char> scratch;  // wide-read translate buffer

  long i = 0;
  while (i + 4 <= data_len) {
    const int64_t block_size = le32(data + i);
    if (block_size < 32 || block_size > (int64_t(1) << 31)) {
      status = kErrorLine;  // corrupt framing: python replay reports it
      err_off = i;
      err_reason = rBadBamRecord;
      ++n_lines;            // rolled back below like the text path
      break;
    }
    if (i + 4 + block_size > data_len) break;  // partial record: stop here
    const long next = i + 4 + static_cast<long>(block_size);
    const unsigned char* r = data + i + 4;
    ++n_lines;

    const int64_t refid = le32(r + 0);
    const int64_t pos = le32(r + 4);
    const long l_rn = r[8];
    const long n_cig = r[12] | (static_cast<long>(r[13]) << 8);
    const int64_t l_seq = le32(r + 16);
    const unsigned char* cig = r + 32 + l_rn;
    const unsigned char* seq = cig + 4 * n_cig;
    if (l_seq < 0 ||
        32 + l_rn + 4 * n_cig + (l_seq + 1) / 2 + l_seq > block_size) {
      status = kErrorLine;  // fields overrun the record: replay reports
      err_off = i;
      err_reason = rBadBamRecord;
      break;
    }
    if (n_cig == 0) {  // the binary form of CIGAR "*": skip, still counts
      i = next;
      continue;
    }

    // --- refid resolution (contract violation, not a parse error) ---
    const bool known_ref = refid >= 0 && refid < n_refs;
    if (refid < -1 || refid >= n_refs) {
      status = kErrorLine;  // corrupt table index: replay reports
      err_off = i;
      err_reason = rUnknownRef;
      break;
    }
    const int64_t reflen = known_ref ? ref_len[refid] : 0;

    // --- op pre-scan: span / read-cursor / insertion sizing ---
    long span = 0, pre_rc = 0, pre_ins = 0, pre_chars = 0;
    bool huge_span = false, bad_op = false;
    for (long k = 0; k < n_cig; ++k) {
      const uint32_t v = leu32(cig + 4 * k);
      const int64_t num = v >> 4;
      const unsigned op = v & 0xF;
      if (op > 8) {
        bad_op = true;  // outside MIDNSHP=X: python replay IndexErrors
        break;
      }
      const char oc = kOpChr[op];
      switch (oc) {
        case 'M': case '=': case 'X':
          if (huge_span || span + num > 2 * reflen + 64) huge_span = true;
          else span += num;
          pre_rc += num;
          break;
        case 'D': case 'N': case 'P':
          if (huge_span || span + num > 2 * reflen + 64) huge_span = true;
          else span += num;
          break;
        case 'I': {
          long take = l_seq - pre_rc;
          if (take < 0) take = 0;
          if (take > num) take = num;
          ++pre_ins;
          pre_chars += take;
          pre_rc += num;
          break;
        }
        case 'S':
          pre_rc += num;
          break;
        default:  // 'H'
          break;
      }
    }
    if (bad_op || pre_rc > l_seq) {
      // corrupt op nibble, or SEQ shorter than the CIGAR claims (the
      // reference's concatenation-shift semantics): replay in python
      status = kErrorLine;
      err_off = i;
      err_reason = bad_op ? rBadCigar : rSeqCigarMismatch;
      break;
    }
    if (span > max_span) max_span = span;

    if (!known_ref || huge_span ||
        (span > 0 && (pos < -reflen || pos + span > reflen))) {
      if (strict) {
        status = kErrorLine;  // replay raises the oracle's exact error
        err_off = i;
        err_reason = !known_ref ? rUnknownRef : rOutOfBounds;
        break;
      }
      ++n_skipped;
      i = next;
      continue;
    }

    if (pos < 0) {
      // python fallback: negative-POS wrap split (python owns the wrap)
      if (n_overflow + 1 > overflow_cap) {
        status = kCapacity;
        break;
      }
      overflow_off[n_overflow++] = i;
      i = next;
      continue;
    }

    // ---- fast path: capacity, then translate nibbles into the slab.
    //      Wide reads (span > width — the long-read case) translate
    //      into a scratch row and commit as ceil(span/width) segment
    //      rows at exact width boundaries: the segmented slab layout,
    //      done here so a 10-100 kb CIGAR never pays the per-read
    //      python replay lane ----
    const bool wide = span > width;
    const long rows_needed =
        span > 0 ? (wide ? (span + width - 1) / width : 1) : 0;
    if (n_rows + rows_needed > rows_cap || n_ins + pre_ins > ins_cap ||
        n_ins_chars + pre_chars > ins_chars_cap) {
      status = kCapacity;
      break;
    }
    const int64_t ci = known_ref ? ref_ci[refid] : -1;
    const int64_t goff = known_ref ? ref_offset[refid] : 0;
    unsigned char* dst;
    if (wide) {
      if (static_cast<long>(scratch.size()) < span) scratch.resize(span);
      dst = scratch.data();
    } else {
      dst = codes + static_cast<int64_t>(n_rows) * width;
    }
    long o = 0, rc = 0, gaps = 0, pads = 0;
    bool bad_base = false;
    const long ins_base = n_ins, chars_base = n_ins_chars;
    for (long k = 0; k < n_cig; ++k) {
      const uint32_t v = leu32(cig + 4 * k);
      const int64_t num = v >> 4;
      const char oc = kOpChr[v & 0xF];
      switch (oc) {
        case 'M': case '=': case 'X': {
          // pre_rc <= l_seq guaranteed above: the full claim is present
          for (long k2 = 0; k2 < num; ++k2) {
            const unsigned char code = kNibCode[nib_at(seq, rc + k2)];
            bad_base |= (code == 255);
            dst[o + k2] = code;
          }
          // '-' has no BAM nibble: M runs contribute no gap cells
          o += num;
          rc += num;
          break;
        }
        case 'D': case 'N': case 'P':
          memset(dst + o, kGap, num);
          gaps += num;
          o += num;
          break;
        case 'I': {
          long take = l_seq - rc;
          if (take < 0) take = 0;
          if (take > num) take = num;
          for (long k2 = 0; k2 < take; ++k2) {
            const unsigned char nb = nib_at(seq, rc + k2);
            bad_base |= (kNibCode[nb] == 255);
            ins_chars[n_ins_chars + k2] =
                static_cast<unsigned char>(kNibChr[nb]);
          }
          ins_contig[n_ins] = static_cast<int32_t>(ci);
          ins_local[n_ins] = static_cast<int32_t>(pos + o);
          ins_mlen[n_ins] = static_cast<int32_t>(take);
          n_ins_chars += take;
          ++n_ins;
          rc += num;
          break;
        }
        case 'S':
          rc += num;
          break;
        default:  // 'H'
          break;
      }
    }
    if (bad_base) {
      n_ins = ins_base;  // roll back; nothing was counted yet
      n_ins_chars = chars_base;
      if (strict) {
        status = kErrorLine;  // replay raises the oracle's KeyError
        err_off = i;
        err_reason = rBadAlphabet;
        break;
      }
      ++n_skipped;
      i = next;
      continue;
    }
    if (maxdel >= 0 && gaps > maxdel) {
      for (long k = 0; k < span; ++k)
        if (dst[k] == kGap) dst[k] = kPad;
      pads += gaps;
    }
    if (span > 0) {
      // fused counting runs over the CONTIGUOUS row once, segmented or
      // not (the counts don't care where the slab rows split)
      if (acc_total_len > 0) {
        if (acc_direct) {
          int32_t* ap = acc_ovf + (goff + pos) * 6;
          for (long k = 0; k < span; ++k) {
            const unsigned char cd = dst[k];
            if (cd < 6) ++ap[k * 6 + cd];
          }
        } else {
          count_row_u8(dst, span, goff + pos, acc_u8, acc_ovf, n_banked);
        }
      }
      if (!wide) {
        if (acc_total_len == 0) memset(dst + span, kPad, width - span);
        starts[n_rows] = static_cast<int32_t>(goff + pos);
        ++n_rows;
      } else {
        ++n_segmented;
        for (long lo = 0; lo < span; lo += width) {
          long len = span - lo;
          if (len > width) len = width;
          unsigned char* seg =
              codes + static_cast<int64_t>(n_rows) * width;
          memcpy(seg, dst + lo, len);
          if (len < width) memset(seg + len, kPad, width - len);
          starts[n_rows] = static_cast<int32_t>(goff + pos + lo);
          ++n_rows;
        }
      }
      n_events += span - pads;
    }
    ++n_reads;
    i = next;
  }

  if (status != kOk) --n_lines;  // the flagged record is not consumed

  out[oRows] = n_rows;
  out[oReads] = n_reads;
  out[oSkipped] = n_skipped;
  // always the last whole-record boundary: on kOk a trailing partial
  // record stays unconsumed and the wrapper carries it into the next
  // chunk (binary records straddle inflate chunks, unlike text lines)
  out[oConsumed] = i;
  out[oIns] = n_ins;
  out[oInsChars] = n_ins_chars;
  out[oStatus] = status;
  out[oErrorOff] = err_off;
  out[oEvents] = n_events;
  out[oLines] = n_lines;
  out[oOverflow] = n_overflow;
  out[oMaxSpan] = max_span;
  out[oBanked] = n_banked;
  out[oSegmented] = n_segmented;
  out[oErrReason] = err_reason;
  return status;
}

// ---------------------------------------------------------------------------
// Line-snapped shard boundaries for the byte-range ingest planner
// (sam2consensus_tpu/ingest plan_byte_shards): bounds[0..n] with
// bounds[0] = start, bounds[n] = end, and interior cut k snapped forward
// to one past the newline at or after (raw cut - 1) — a cut whose
// preceding byte is already '\n' sits on a line start and stays put, so
// every line of [start, end) begins in exactly one shard.  This is the
// contract the shard-owned decode workers rely on: s2c_decode is
// range-bounded (pointer + length) and each worker's range starts at a
// line start, so N workers decode N disjoint ranges with no feed thread
// and no straddled records.  One memchr per cut (~a line of text
// scanned), so planning a multi-GB input costs microseconds.
extern "C" void s2c_snap_shards(const char* text, int64_t start,
                                int64_t end, long n, int64_t* bounds) {
  bounds[0] = start;
  bounds[n] = end;
  const int64_t size = end - start;
  for (long k = 1; k < n; ++k) {
    int64_t cut = start + (size * k) / n;
    if (cut <= start) {
      bounds[k] = start;
    } else if (cut >= end) {
      bounds[k] = end;
    } else {
      const char* nl = static_cast<const char*>(
          memchr(text + cut - 1, '\n', end - (cut - 1)));
      bounds[k] = nl ? (nl - text) + 1 : end;
    }
  }
}

// ---------------------------------------------------------------------------
// Fold the uint8 shadow into the int32 pileup and clear it, in one pass.
//
// numpy's mixed-dtype `np.add(acc, u8, out=acc)` routes through a buffered
// int32 upcast (measured ~96 ms at 27.6 M cells) and the separate bank
// reset dirties the whole tensor again; this kernel widen-adds in SIMD
// registers and skips 64-byte blocks that are entirely zero — untouched
// genome regions cost one load + test and stay clean (no acc write, no
// store), so sparse-coverage merges run at read speed.
extern "C" void s2c_merge_u8(int32_t* acc, unsigned char* u8, int64_t n) {
  int64_t k = 0;
#ifdef S2C_SIMD
  const __m512i zero = _mm512_setzero_si512();
  for (; k + 64 <= n; k += 64) {
    const __m512i b = _mm512_loadu_si512(u8 + k);
    if (_mm512_test_epi8_mask(b, b) == 0) continue;
    for (int q = 0; q < 4; ++q) {
      const __m128i lane = _mm_loadu_si128(
          reinterpret_cast<const __m128i*>(u8 + k + q * 16));
      const __m512i w = _mm512_cvtepu8_epi32(lane);
      __m512i a = _mm512_loadu_si512(acc + k + q * 16);
      _mm512_storeu_si512(acc + k + q * 16, _mm512_add_epi32(a, w));
    }
    _mm512_storeu_si512(u8 + k, zero);
  }
#endif
  for (; k < n; ++k) {
    if (u8[k]) {
      acc[k] += u8[k];
      u8[k] = 0;
    }
  }
}

// ---------------------------------------------------------------------------
// Host-side pileup accumulation over decoded segment-row slabs.
//
// Companion to the host-counts pileup strategy (ops/pileup.py
// HostPileupAccumulator): when aligned bases far exceed L*6 count cells
// (deep coverage / small genomes), shipping the count tensor once beats
// shipping ~1 byte per aligned base over the ~40 MB/s tunneled link, and
// this pass — a plain slab walk at memory speed — rides with decode.
// Cells outside [0, total_len) or with non-symbol codes (PAD) are skipped,
// mirroring the device scatter's sacrificial-row redirect.
extern "C" void s2c_accumulate_rows(
    const int32_t* starts, const unsigned char* codes,
    long n_rows, long width, int32_t* counts /* [total_len * 6] */,
    long total_len) {
  for (long r = 0; r < n_rows; ++r) {
    const int64_t start = starts[r];
    const unsigned char* row = codes + static_cast<int64_t>(r) * width;
    for (long c = 0; c < width; ++c) {
      const unsigned char code = row[c];
      const int64_t pos = start + c;
      if (code < 6 && pos >= 0 && pos < total_len)
        ++counts[pos * 6 + code];
    }
  }
}

// ---------------------------------------------------------------------------
// Render finalize: substitute the vote's 0x00 fill sentinel and count
// '-' in ONE pass.  The python chain (find + bytes.translate + decode +
// str.count) walks the 40 MB sequence ~4 times (~0.1 s at wide-genome
// scale); this does translate+count in one read+write.  The dash count
// is taken AFTER substitution, matching the oracle's str.count on the
// final sequence (fill may itself be '-').
extern "C" int64_t s2c_finalize(const unsigned char* syms, int64_t n,
                                long fill, unsigned char* out) {
  int64_t dashes = 0;
  int64_t k = 0;
#ifdef S2C_SIMD
  const __m512i zero = _mm512_setzero_si512();
  const __m512i fl = _mm512_set1_epi8(static_cast<char>(fill));
  const __m512i dash = _mm512_set1_epi8('-');
  for (; k + 64 <= n; k += 64) {
    const __m512i v = _mm512_loadu_si512(syms + k);
    const __mmask64 z = _mm512_cmpeq_epi8_mask(v, zero);
    const __m512i w = _mm512_mask_blend_epi8(z, v, fl);
    _mm512_storeu_si512(out + k, w);
    dashes += __builtin_popcountll(_mm512_cmpeq_epi8_mask(w, dash));
  }
#endif
  for (; k < n; ++k) {
    const unsigned char c =
        syms[k] ? syms[k] : static_cast<unsigned char>(fill);
    out[k] = c;
    dashes += (c == '-');
  }
  return dashes;
}

// ---------------------------------------------------------------------------
// Per-contig coverage sums: segmented int64 reduction over the [L] int32
// coverage vector.  numpy's np.add.reduceat(cov, starts, dtype=int64)
// measured ~0.21 s at 40 M positions (no SIMD through the dtype cast);
// this widen-accumulate runs at memory speed (~0.02 s).  Empty contigs
// (lo == hi) sum to zero structurally — no special-casing like the
// reduceat path needed.
extern "C" void s2c_cov_sums(const int32_t* cov, const int64_t* offsets,
                             long n_contigs, int64_t* out) {
  for (long c = 0; c < n_contigs; ++c) {
    const int64_t lo = offsets[c], hi = offsets[c + 1];
    int64_t acc = 0;
    int64_t k = lo;
#ifdef S2C_SIMD
    __m512i a = _mm512_setzero_si512();
    for (; k + 8 <= hi; k += 8) {
      const __m256i v = _mm256_loadu_si256(
          reinterpret_cast<const __m256i*>(cov + k));
      a = _mm512_add_epi64(a, _mm512_cvtepi32_epi64(v));
    }
    acc = _mm512_reduce_add_epi64(a);
#endif
    for (; k < hi; ++k) acc += cov[k];
    out[c] = acc;
  }
}

// ---------------------------------------------------------------------------
// Threshold consensus vote over a host-resident count tensor.
//
// The closed-form greedy vote (ops/vote.py: lane i is included iff
// c_i != 0 and S_i < ceil(float64(t) * cov), S_i = sum of lanes with a
// strictly greater count), in C++ for tails routed to the host: the XLA
// CPU backend votes at ~5 M positions/s/threshold on this one-core host,
// while this loop — S[6] hoisted per position, ~12 ops per threshold —
// runs at memory speed.  The float64 product + ceil matches the oracle's
// semantics directly (the device needed int32 limb arithmetic only
// because the chip lacks float64, ops/cutoff.py).  lut64 is the 64-entry
// called-set-mask -> output-byte table (constants.IUPAC_MASK_LUT), so
// symbol mapping shares one definition with the device path.  Positions
// failing the emit gate (cov == 0 or cov < min_depth) get sentinel 0.
namespace {

// scalar position vote over [lo, hi) (the semantics reference for the
// SIMD path below, and the portable fallback / remainder handler)
void vote_range_scalar(const int32_t* counts, int64_t L, int64_t lo,
                       int64_t hi, const double* thresholds, long T,
                       long min_depth, const unsigned char* lut64,
                       unsigned char* out_syms, int32_t* out_cov) {
  for (int64_t p = lo; p < hi; ++p) {
    const int32_t* c = counts + p * 6;
    const int32_t cov =
        c[0] + c[1] + c[2] + c[3] + c[4] + c[5];
    out_cov[p] = cov;
    if (cov <= 0 || cov < min_depth) {
      for (long t = 0; t < T; ++t) out_syms[t * L + p] = 0;
      continue;
    }
    int32_t S[6];
    for (int i = 0; i < 6; ++i) {
      int32_t s = 0;
      for (int j = 0; j < 6; ++j)
        if (c[j] > c[i]) s += c[j];
      S[i] = s;
    }
    const double dcov = static_cast<double>(cov);
    for (long t = 0; t < T; ++t) {
      // S < t*cov for integer S  <=>  S < ceil(t*cov) (oracle float
      // comparison, sam2consensus semantics; ops/vote.threshold_luts)
      const double cut = __builtin_ceil(thresholds[t] * dcov);
      const int64_t cutoff =
          cut > 2147483647.0 ? 2147483647 : static_cast<int64_t>(cut);
      unsigned mask = 0;
      for (int i = 0; i < 6; ++i)
        if (c[i] != 0 && S[i] < cutoff) mask |= (1u << i);
      out_syms[t * L + p] = lut64[mask];
    }
  }
}

#ifdef S2C_SIMD
// AVX-512 position vote: 16 positions per iteration.
//
// Layout: 16 positions x 6 lanes = 96 interleaved int32 = six zmm loads,
// transposed to per-lane vectors C[0..5] with three maskz_permutex2var
// picks (disjoint masks, OR-merged) per lane.  The strictly-greater sums
// and the threshold comparison run in the DOUBLE domain — every count
// converts exactly (|c| < 2^31 < 2^53) and sums of five lanes stay
// exact, so the comparison `S < ceil(t*cov)` reproduces the device's
// exact-integer semantics (ops/cutoff.py).  Shared precondition with
// the scalar path and the device: per-position coverage < 2^31 (the
// scalar's int32 sums are signed-overflow UB past that; here only the
// results would diverge).
// The 64-entry mask->byte LUT is one vpermb over a zmm-resident table.
// Byte output per threshold goes through the same emit gate as the
// scalar path (cov > 0 and cov >= min_depth, else sentinel 0).
void vote_range_simd(const int32_t* counts, int64_t L, int64_t lo,
                     int64_t hi, const double* thresholds, long T,
                     long min_depth, const unsigned char* lut64,
                     unsigned char* out_syms, int32_t* out_cov) {
  // transpose pick tables: lane i's 16 values sit at flat index i + 6j
  // (j = 0..15) across the six source registers
  __m512i idx[6][3];
  __mmask16 pm[6][3];
  for (int i = 0; i < 6; ++i) {
    alignas(64) int32_t ix[3][16];
    uint16_t m[3] = {0, 0, 0};
    for (int j = 0; j < 16; ++j) {
      const int f = i + 6 * j;
      const int grp = f >> 5;            // which (z2g, z2g+1) pair
      ix[0][j] = ix[1][j] = ix[2][j] = 0;
      ix[grp][j] = f & 31;
      m[grp] = static_cast<uint16_t>(m[grp] | (1u << j));
    }
    for (int g = 0; g < 3; ++g) {
      idx[i][g] = _mm512_load_si512(ix[g]);
      pm[i][g] = m[g];
    }
  }
  const __m512i lut_z = _mm512_loadu_si512(lut64);
  const int64_t md = min_depth < 1 ? 1 : min_depth;
  const __m512i md_v = _mm512_set1_epi32(static_cast<int32_t>(
      md > 2147483647 ? 2147483647 : md));

  int64_t p = lo;
  for (; p + 16 <= hi; p += 16) {
    const int32_t* base = counts + p * 6;
    __m512i z[6];
    for (int g = 0; g < 6; ++g)
      z[g] = _mm512_loadu_si512(base + 16 * g);
    // sparse fast path: a fully-zero block (all 96 cells) is 16
    // positions with cov 0 -> sentinel syms, exactly what the scalar
    // path emits.  Long-context genomes are mostly this (~78% of
    // blocks at 0.25x coverage), and 5 ORs + 1 test replace the whole
    // transpose/double pipeline there.
    __m512i any = z[0];
    for (int g = 1; g < 6; ++g) any = _mm512_or_si512(any, z[g]);
    if (_mm512_test_epi32_mask(any, any) == 0) {
      _mm512_storeu_si512(out_cov + p, _mm512_setzero_si512());
      for (long t = 0; t < T; ++t)
        memset(out_syms + t * L + p, 0, 16);
      continue;
    }
    __m512i C[6];
    for (int i = 0; i < 6; ++i) {
      __m512i r = _mm512_maskz_permutex2var_epi32(
          pm[i][0], z[0], idx[i][0], z[1]);
      r = _mm512_or_si512(r, _mm512_maskz_permutex2var_epi32(
          pm[i][1], z[2], idx[i][1], z[3]));
      C[i] = _mm512_or_si512(r, _mm512_maskz_permutex2var_epi32(
          pm[i][2], z[4], idx[i][2], z[5]));
    }
    __m512i cov = C[0];
    for (int i = 1; i < 6; ++i) cov = _mm512_add_epi32(cov, C[i]);
    _mm512_storeu_si512(out_cov + p, cov);
    const __mmask16 emit =
        _mm512_cmpge_epi32_mask(cov, md_v);      // cov >= max(1, md)

    // exact doubles: counts, cov, and the strictly-greater sums
    __m512d Cd[6][2], Sd[6][2];
    for (int i = 0; i < 6; ++i) {
      Cd[i][0] = _mm512_cvtepi32_pd(_mm512_castsi512_si256(C[i]));
      Cd[i][1] = _mm512_cvtepi32_pd(_mm512_extracti32x8_epi32(C[i], 1));
    }
    for (int i = 0; i < 6; ++i)
      for (int h = 0; h < 2; ++h) {
        __m512d s = _mm512_setzero_pd();
        for (int j = 0; j < 6; ++j) {
          if (j == i) continue;
          const __mmask8 gt =
              _mm512_cmp_pd_mask(Cd[j][h], Cd[i][h], _CMP_GT_OQ);
          s = _mm512_mask_add_pd(s, gt, s, Cd[j][h]);
        }
        Sd[i][h] = s;
      }
    const __m512d covd0 = _mm512_cvtepi32_pd(_mm512_castsi512_si256(cov));
    const __m512d covd1 =
        _mm512_cvtepi32_pd(_mm512_extracti32x8_epi32(cov, 1));
    __mmask16 nonzero[6];
    for (int i = 0; i < 6; ++i)
      nonzero[i] = _mm512_cmpneq_epi32_mask(C[i], _mm512_setzero_si512());

    for (long t = 0; t < T; ++t) {
      const __m512d tv = _mm512_set1_pd(thresholds[t]);
      // ceil via roundscale toward +inf (suppress exceptions): the
      // float64 product rounds RNE exactly like the scalar/oracle path
      const __m512d cut0 = _mm512_roundscale_pd(
          _mm512_mul_pd(tv, covd0), 0x0A);
      const __m512d cut1 = _mm512_roundscale_pd(
          _mm512_mul_pd(tv, covd1), 0x0A);
      __m512i mv = _mm512_setzero_si512();
      for (int i = 0; i < 6; ++i) {
        const __mmask8 lt0 =
            _mm512_cmp_pd_mask(Sd[i][0], cut0, _CMP_LT_OQ);
        const __mmask8 lt1 =
            _mm512_cmp_pd_mask(Sd[i][1], cut1, _CMP_LT_OQ);
        const __mmask16 inc = nonzero[i]
            & static_cast<__mmask16>(lt0 | (static_cast<unsigned>(lt1)
                                            << 8));
        mv = _mm512_mask_or_epi32(mv, inc, mv,
                                  _mm512_set1_epi32(1 << i));
      }
      // 6-bit mask -> output byte: one vpermb over the 64-entry table
      const __m128i mb = _mm512_cvtepi32_epi8(mv);
      const __m512i sym_z = _mm512_permutexvar_epi8(
          _mm512_castsi128_si512(mb), lut_z);
      const __m128i sym = _mm_maskz_mov_epi8(
          emit, _mm512_castsi512_si128(sym_z));
      _mm_storeu_si128(
          reinterpret_cast<__m128i*>(out_syms + t * L + p), sym);
    }
  }
  if (p < hi)
    vote_range_scalar(counts, L, p, hi, thresholds, T, min_depth, lut64,
                      out_syms, out_cov);
}
#endif  // S2C_SIMD

inline void vote_range(const int32_t* counts, int64_t L, int64_t lo,
                       int64_t hi, const double* thresholds, long T,
                       long min_depth, const unsigned char* lut64,
                       unsigned char* out_syms, int32_t* out_cov) {
#ifdef S2C_SIMD
  vote_range_simd(counts, L, lo, hi, thresholds, T, min_depth, lut64,
                  out_syms, out_cov);
#else
  vote_range_scalar(counts, L, lo, hi, thresholds, T, min_depth, lut64,
                    out_syms, out_cov);
#endif
}

}  // namespace

// ---------------------------------------------------------------------------
// Insertion-table build + vote for link-free tails (the C++ twin of
// ops/insertions.py build_insertion_table / vote_insertions — same
// greedy semantics, measured ~10x the numpy twin and ~25x the XLA CPU
// dispatches at north-star scale).  The caller passes the PADDED table
// (K rows including the sacrificial pad row) but votes only the first
// k_valid rows.
extern "C" void s2c_ins_table(
    const int32_t* ev_key, const int32_t* ev_col, const int32_t* ev_code,
    long n_events, int32_t* table /* [K * C * 6], zeroed */, long C) {
  for (long e = 0; e < n_events; ++e)
    ++table[(static_cast<int64_t>(ev_key[e]) * C + ev_col[e]) * 6 +
            ev_code[e]];
}

extern "C" void s2c_ins_vote(
    const int32_t* table /* [K * C * 6] */, long K, long C,
    const int32_t* site_cov, const int32_t* n_cols,
    const double* thresholds, long T, const unsigned char* lut64,
    unsigned char* out /* [T * K * C], sentinel 0 where skipped */) {
  for (long k = 0; k < K; ++k) {
    const int32_t cov = site_cov[k];
    const double dcov = static_cast<double>(cov);
    const long nc = n_cols[k];
    for (long c = 0; c < C; ++c) {
      const int32_t* cell = table + (k * C + c) * 6;
      // gap-lane completion: cov - sum(all lanes); may go negative
      // (quirk 4, sam2consensus.py:294)
      int64_t v[6];
      int64_t colsum = 0;
      for (int i = 0; i < 6; ++i) colsum += cell[i];
      v[0] = cov - colsum;
      for (int i = 1; i < 6; ++i) v[i] = cell[i];
      int64_t S[6];
      for (int i = 0; i < 6; ++i) {
        int64_t s = 0;
        for (int j = 0; j < 6; ++j)
          if (v[j] > v[i]) s += v[j];
        S[i] = s;
      }
      const bool col_valid = c < nc;
      for (long t = 0; t < T; ++t) {
        const double cut = __builtin_ceil(thresholds[t] * dcov);
        unsigned mask = 0;
        for (int i = 0; i < 6; ++i)
          if (v[i] != 0 && static_cast<double>(S[i]) < cut)
            mask |= (1u << i);
        const unsigned char sym = lut64[mask];
        out[(t * K + k) * C + c] =
            (!col_valid || sym == '-') ? 0 : sym;
      }
    }
  }
}

extern "C" void s2c_vote(
    const int32_t* counts /* [L * 6] */, int64_t L,
    const double* thresholds, long T, long min_depth,
    const unsigned char* lut64,
    unsigned char* out_syms /* [T * L] */, int32_t* out_cov /* [L] */,
    long n_threads) {
  if (n_threads < 2 || L < (1 << 20)) {
    vote_range(counts, L, 0, L, thresholds, T, min_depth, lut64,
               out_syms, out_cov);
    return;
  }
  // position ranges are independent: one thread per contiguous slice
  // (multi-core hosts scale the tail the way --decode-threads scales
  // decode; below 1M positions the spawn overhead isn't worth it)
  std::vector<std::thread> workers;
  const int64_t step = (L + n_threads - 1) / n_threads;
  for (long w = 0; w < n_threads; ++w) {
    const int64_t lo = w * step;
    const int64_t hi = (lo + step < L) ? lo + step : L;
    if (lo >= hi) break;
    workers.emplace_back(vote_range, counts, L, lo, hi, thresholds, T,
                         min_depth, lut64, out_syms, out_cov);
  }
  for (auto& th : workers) th.join();
}
