"""Tolerant decode: per-record malformation handling with quarantine.

Every decode rung is strict-first-error by design — correct for byte
identity against the reference oracle, but wrong for a serving fleet:
one malformed record in a multi-GB upload kills the whole job, and a
retrying tenant burns capacity re-failing on the same byte.  This
module makes malformed input a *per-record* event, uniformly across
the four ingest rungs (serial C text, sharded zero-copy, streaming
gzip, native BAM):

* ``--on-bad-record fail`` (default) keeps today's byte-identity and
  strict first-error parity: nothing in this module engages.
* ``--on-bad-record skip`` drops the record and counts it
  (``ingest/bad_records``; per-reason sub-counters).
* ``--on-bad-record quarantine`` additionally captures the raw record
  plus a structured reason (the malformation taxonomy below) into a
  bounded sidecar file next to the run's outputs.
* ``--max-bad-records N|x%`` is the error budget that converts a
  rotten file back into a clean job-level failure — a typed
  :class:`BadRecordBudgetExceeded` carrying a precise summary, never a
  retry storm.

The tolerance point is the PYTHON replay layer shared by every rung:
the C decoders keep running in line/record-flagging mode (their clean
fast path is untouched, so tolerant-mode overhead on clean input is
~zero), the flagged record replays through the golden
:class:`~..encoder.events.ReadEncoder`, and the replay's exception —
whose type/message is the strict-mode contract — is classified and
absorbed here instead of raised.

Rung invariance: the sink is partition-keyed.  Serial rungs record
into partition ``(0,)``; the sharded rung's workers record into
``(shard_idx,)`` (cleared whole on a shard retry, dropped whole on an
ingest demotion — exactly the count-bank discipline); the streaming
rung tags each worker's records with the block index it is decoding.
``entries()`` merges partitions in sorted key order, which is stream
order on every rung, so a completed tolerant run yields the same
quarantine sequence no matter which rung decoded it.

Classification taxonomy (``reason`` in counters and sidecar entries):

========================  ==============================================
``bad_field_count``       line has too few tab fields / empty RNAME
``bad_pos``               POS field is not an integer
``bad_cigar``             CIGAR op/length invalid (BAM binary op codes;
                          text CIGARs are regex-scanned like the
                          reference, so garbage text ops drop silently)
``seq_cigar_mismatch``    SEQ/CIGAR length disagreement the replay
                          could not absorb
``unknown_reference``     RNAME/refID not in the header's table
``out_of_bounds_pos``     read span leaves the reference
``bad_alphabet``          out-of-contract base (SAM text char or BAM
                          seq nibble)
``non_ascii``             undecodable byte in a text record
``bad_bam_record``        BAM structural damage bounded to one record
                          (fields overrun the record's block_size)
``malformed``             anything else the strict path would raise
========================  ==============================================

Failures that cannot be bounded to one record — a corrupt BAM
block_size that loses framing, BGZF container damage, a malformed
header — stay job-level in every mode.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: sidecar entry cap (stored records; everything past it is counted
#: but not stored, and the summary says so) — env-overridable
DEFAULT_SIDECAR_MAX = 10_000

MODES = ("fail", "skip", "quarantine")

#: native decoder reason-code hints (decoder.cpp ``enum BadReason``,
#: surfaced in out[oErrReason]); observability-only — classification
#: authority stays with the python replay so the pure-python rung can
#: never disagree with the native ones
C_REASONS = {
    1: "bad_field_count",
    2: "bad_pos",
    3: "bad_cigar",
    4: "seq_cigar_mismatch",
    5: "unknown_reference",
    6: "out_of_bounds_pos",
    7: "bad_alphabet",
    8: "bad_bam_record",
}


#: exception types that bound to ONE record on the strict decode paths
#: (the replay layer's tolerant catch): parse-level IndexError/ValueError
#: from the positional field access, KeyError from the base alphabet,
#: UnicodeDecodeError from a non-ascii byte in a text line.  EncodeError
#: subclasses ValueError, so encode-level contract violations are
#: covered too.  Anything OUTSIDE this tuple — container damage, header
#: corruption, MemoryError — stays job-level in every mode.
RECORD_ERRORS = (ValueError, KeyError, IndexError, UnicodeDecodeError)


class BadRecordBudgetExceeded(RuntimeError):
    """The run's ``--max-bad-records`` budget is spent: the input is
    rotten, not merely blemished, and the job fails as a unit with a
    precise summary.

    ``data_error`` marks the DATA resilience class
    (``resilience/policy.py``): the failure is a property of the INPUT
    BYTES — retrying cannot fix it, demoting the ladder rung cannot fix
    it, and a serve tenant submitting it must not be pinned off the
    device path for it."""

    data_error = True
    budget_exhausted = True

    def __init__(self, msg: str, summary: Optional[dict] = None):
        super().__init__(msg)
        self.summary = summary or {}


def is_data_error(exc: BaseException) -> bool:
    """The DATA-class marker check (mirrors the ``transient`` marker
    protocol: an attribute, not an import, so low layers never cycle)."""
    return bool(getattr(exc, "data_error", False))


def classify_reason(exc: BaseException) -> str:
    """Map a strict-mode decode exception to its taxonomy reason.

    Works from the exception's type and the contract MESSAGES the
    encoders raise (which are themselves pinned by the oracle-parity
    tests), so the pure-python and native rungs classify identically.
    """
    if isinstance(exc, UnicodeDecodeError):
        return "non_ascii"
    msg = str(exc)
    if "unknown reference" in msg or "outside the reference table" in msg:
        return "unknown_reference"
    if "outside reference" in msg:
        return "out_of_bounds_pos"
    if "out-of-alphabet" in msg:
        return "bad_alphabet"
    if "BAM record" in msg or "CIGAR op code" in msg \
            or "CIGAR runs past" in msg:
        # record-bounded BAM damage (formats/bam.py BamParseError and
        # the binary-CIGAR decode errors)
        return "bad_cigar" if "CIGAR" in msg else "bad_bam_record"
    if isinstance(exc, ValueError) and ("invalid literal" in msg
                                        or "int()" in msg):
        return "bad_pos"
    if isinstance(exc, IndexError):
        # iter_records' positional field access: fields[5]/fields[9]/
        # RNAME .split()[0] on a short line
        return "bad_field_count"
    if isinstance(exc, KeyError):
        return "bad_alphabet"
    return "malformed"


@dataclass
class BadRecordPolicy:
    """The resolved ``--on-bad-record`` / ``--max-bad-records`` policy."""

    mode: str = "fail"
    max_bad: Optional[int] = None        # absolute budget (count >= N fails)
    max_pct: Optional[float] = None      # percent budget, checked at finish
    sidecar_path: Optional[str] = None
    sidecar_max: int = DEFAULT_SIDECAR_MAX

    @property
    def tolerant(self) -> bool:
        return self.mode in ("skip", "quarantine")


def parse_budget(spec: str) -> Tuple[Optional[int], Optional[float]]:
    """``--max-bad-records`` grammar: "" (no budget), ``N`` (absolute:
    the Nth bad record fails the job) or ``x%`` (fraction of all
    records processed, checked at stream end).  Raises ValueError on
    anything else."""
    spec = (spec or "").strip()
    if not spec:
        return None, None
    if spec.endswith("%"):
        try:
            pct = float(spec[:-1])
        except ValueError:
            raise ValueError(
                f"--max-bad-records: not a percentage: {spec!r}") from None
        if not 0 <= pct <= 100:
            raise ValueError(
                f"--max-bad-records percentage out of range: {spec!r}")
        return None, pct / 100.0
    try:
        n = int(spec)
    except ValueError:
        raise ValueError(
            f"--max-bad-records: not a count or percentage: "
            f"{spec!r}") from None
    if n < 0:
        raise ValueError(f"--max-bad-records must be >= 0: {spec!r}")
    return n, None


def policy_from_config(cfg) -> BadRecordPolicy:
    """Resolve the run's bad-record policy from a RunConfig (validated
    at CLI parse time; API callers get the same ValueError)."""
    mode = getattr(cfg, "on_bad_record", "fail") or "fail"
    if mode not in MODES:
        raise ValueError(
            f"on_bad_record={mode!r}: use one of {MODES}")
    max_bad, max_pct = parse_budget(getattr(cfg, "max_bad_records", ""))
    if (max_bad is not None or max_pct is not None) and mode == "fail":
        raise ValueError(
            "--max-bad-records needs a tolerant mode "
            "(--on-bad-record skip|quarantine)")
    sidecar = getattr(cfg, "quarantine_out", None)
    if sidecar and mode != "quarantine":
        raise ValueError(
            "--quarantine-out needs --on-bad-record quarantine "
            f"(got --on-bad-record {mode}): refusing to silently "
            "ignore the requested evidence sidecar")
    if mode == "quarantine" and not sidecar:
        out = getattr(cfg, "outfolder", "./") or "./"
        prefix = getattr(cfg, "prefix", "") or "quarantine"
        sidecar = os.path.join(out, f"{prefix}_quarantine.jsonl")
    try:
        sidecar_max = int(os.environ.get("S2C_QUARANTINE_MAX",
                                         str(DEFAULT_SIDECAR_MAX)))
    except ValueError:
        sidecar_max = DEFAULT_SIDECAR_MAX
    return BadRecordPolicy(mode=mode, max_bad=max_bad, max_pct=max_pct,
                           sidecar_path=sidecar if mode == "quarantine"
                           else None,
                           sidecar_max=max(0, sidecar_max))


def _entry_nbytes(entry: dict) -> int:
    """Approximate resident bytes of one stored quarantine entry (the
    record text dominates; 160 covers the dict/key overhead) — the
    memory plane's sizing for the ``quarantine`` family."""
    return len(entry.get("record") or "") \
        + len(entry.get("error") or "") + 160


def _release_quarantine(cell: dict) -> None:
    """weakref finalizer: release whatever the sink still tracked when
    it was collected (module-level so the finalizer holds no sink ref)."""
    from ..observability import memplane

    memplane.adjust("quarantine", -cell["bytes"])


class _Partition:
    """One partition's bad-record state: counts always, stored entries
    only in quarantine mode (the skip mode still needs exact per-
    partition counts so a shard retry can roll its attempt back)."""

    __slots__ = ("count", "reasons", "entries")

    def __init__(self):
        self.count = 0
        self.reasons: Dict[str, int] = {}
        self.entries: List[dict] = []


class QuarantineSink:
    """Thread-safe, partition-keyed collector of bad records.

    One sink per run, shared by every encoder the run builds (the
    shard scheduler's workers, their python replay twins, the BAM
    encoder).  ``record`` absorbs one bad record; the ABSOLUTE error
    budget is enforced here — the recording thread raises
    :class:`BadRecordBudgetExceeded` the moment the global count
    reaches the budget, on whichever rung it is, so a rotten file
    fails as early as the rung's ordering allows.  The PERCENT budget
    is enforced by :meth:`finish` once the total record count is
    known.
    """

    def __init__(self, policy: BadRecordPolicy):
        self.policy = policy
        self._lock = threading.Lock()
        self._parts: Dict[Tuple, _Partition] = {}
        self._sidecar_written: Optional[str] = None
        self._total = 0               # bad records across all partitions
        self._stored = 0              # entries held across all partitions
        self._hi: Optional[Tuple] = None   # cached max stored key
        self._hi_valid = True
        # residency accounting (observability/memplane.py): the cell
        # holds this sink's live quarantine bytes so the finalizer can
        # release exactly what is still tracked when the sink goes away
        self._mem_cell = {"bytes": 0}
        import weakref

        weakref.finalize(self, _release_quarantine, self._mem_cell)

    def _mem_adjust(self, delta: int) -> None:
        from ..observability import memplane

        self._mem_cell["bytes"] = max(0, self._mem_cell["bytes"] + delta)
        memplane.adjust("quarantine", delta)

    # -- recording ---------------------------------------------------------
    def record(self, raw, exc: BaseException,
               partition: Tuple = (0,), offset: Optional[int] = None,
               reason: Optional[str] = None) -> None:
        """Absorb one bad record.  ``raw`` is the record's raw bytes/str
        (text line or rendered BAM record); ``offset`` the input offset
        when the rung knows it.  Raises the budget error when the
        absolute budget is spent."""
        why = reason or classify_reason(exc)
        budget_hit = None
        with self._lock:
            part = self._parts.setdefault(tuple(partition), _Partition())
            part.count += 1
            self._total += 1
            part.reasons[why] = part.reasons.get(why, 0) + 1
            if self.policy.mode == "quarantine":
                if isinstance(raw, (bytes, bytearray, memoryview)):
                    raw = bytes(raw).decode("ascii",
                                            errors="backslashreplace")
                self._store(tuple(partition), part, {
                    "record": str(raw).rstrip("\r\n"),
                    "reason": why,
                    "error": f"{type(exc).__name__}: {exc}",
                    "offset": int(offset) if offset is not None else None,
                })
            if self.policy.max_bad is not None \
                    and self._total >= self.policy.max_bad:
                budget_hit = self._total
        if budget_hit is not None:
            err = BadRecordBudgetExceeded(
                f"bad-record budget exhausted: {budget_hit} bad "
                f"record(s) >= --max-bad-records {self.policy.max_bad} "
                f"(last: {why})", self.summary())
            err.sink = self      # abort bookkeeping finds the evidence
            raise err

    def _store(self, key: Tuple, part: _Partition, entry: dict) -> None:
        """Bounded, merge-order-correct storage (caller holds the lock).

        The sidecar wants the FIRST ``sidecar_max`` entries in merged
        partition order plus the knowledge that more existed, so the
        sink retains at most ``sidecar_max + 1`` entries across all
        partitions.  An entry whose partition key sorts after every
        stored entry while the window is already full can never make
        the sidecar — it is counted but not stored (that is what keeps
        a million-bad-record file from holding a million dicts).  An
        entry belonging BEFORE the window's tail is stored and the
        merge-order-last stored entry is evicted to keep the bound."""
        cap = self.policy.sidecar_max + 1
        if not self._hi_valid:
            self._hi = max((k for k, p in self._parts.items()
                            if p.entries), default=None)
            self._hi_valid = True
        if self._stored >= cap and self._hi is not None and key > self._hi:
            return                      # count-only: past the window
        part.entries.append(entry)
        self._stored += 1
        # residency accounting: the bounded sidecar window is the
        # quarantine mode's one real in-process allocation
        self._mem_adjust(_entry_nbytes(entry))
        if self._hi is None or key > self._hi:
            self._hi = key
        while self._stored > cap:
            hi_part = self._parts[self._hi]
            evicted = hi_part.entries.pop()  # merge-order-last stored
            self._mem_adjust(-_entry_nbytes(evicted))
            self._stored -= 1
            if not hi_part.entries:
                self._hi = max((k for k, p in self._parts.items()
                                if p.entries), default=None)

    def clear_partition(self, partition: Tuple) -> None:
        """Roll back one partition whole — a shard attempt that failed
        on an infrastructure fault retries against a clean slate, so
        nothing can double-count."""
        with self._lock:
            part = self._parts.pop(tuple(partition), None)
            if part is not None:
                self._total -= part.count
                self._stored -= len(part.entries)
                self._mem_adjust(-sum(_entry_nbytes(e)
                                      for e in part.entries))
                self._hi_valid = False

    def reset(self) -> None:
        """Roll back everything — the sharded ingest demoted to the
        serial rung against zeroed counts; the fresh pass re-records."""
        with self._lock:
            self._parts.clear()
            self._total = 0
            self._stored = 0
            self._mem_adjust(-self._mem_cell["bytes"])
            self._hi = None
            self._hi_valid = True

    # -- read side ---------------------------------------------------------
    @property
    def count(self) -> int:
        with self._lock:
            return self._total

    def reason_counts(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for p in self._parts.values():
                for why, n in p.reasons.items():
                    out[why] = out.get(why, 0) + n
            return dict(sorted(out.items()))

    def entries(self) -> List[dict]:
        """Quarantined entries merged deterministically: partitions in
        sorted key order (stream order on every rung), entries in
        decode order within each partition."""
        with self._lock:
            out: List[dict] = []
            for key in sorted(self._parts):
                out.extend(self._parts[key].entries)
            return out

    def summary(self) -> dict:
        entries = self.entries()
        n = self.count
        return {
            "mode": self.policy.mode,
            "bad_records": n,
            "quarantined": min(len(entries), self.policy.sidecar_max)
            if self.policy.mode == "quarantine" else 0,
            "truncated": len(entries) > self.policy.sidecar_max,
            "reasons": self.reason_counts(),
            "sidecar": self._sidecar_written,
        }

    # -- finish ------------------------------------------------------------
    def finish(self, total_records: int) -> dict:
        """End-of-stream bookkeeping: enforce the percent budget, write
        the sidecar (quarantine mode, when anything was caught), and
        return the summary.  Raises :class:`BadRecordBudgetExceeded`
        when the percent budget is blown — AFTER the sidecar write, so
        the failed job still leaves its evidence on disk."""
        n = self.count
        if self.policy.mode == "quarantine" and n \
                and self.policy.sidecar_path:
            self.write_sidecar(self.policy.sidecar_path)
        if self.policy.max_pct is not None and total_records > 0:
            frac = n / float(total_records)
            if frac > self.policy.max_pct:
                err = BadRecordBudgetExceeded(
                    f"bad-record budget exhausted: {n}/{total_records} "
                    f"records ({100.0 * frac:.2f}%) exceed "
                    f"--max-bad-records "
                    f"{100.0 * self.policy.max_pct:g}%", self.summary())
                err.sink = self
                raise err
        return self.summary()

    def write_sidecar(self, path: str) -> str:
        """Write the bounded sidecar (atomic tmp+replace, like every
        other artifact a prober may poll): a schema header line, one
        JSON object per stored record, and a trailing summary line."""
        entries = self.entries()
        stored = entries[: self.policy.sidecar_max]
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            # evidence tries hard to land: a sidecar path in a not-yet-
            # existing directory must not fail the job after a decode
            # that succeeded (nor vanish silently on a budget abort)
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(json.dumps({"schema": "s2c-quarantine/1"}) + "\n")
            for k, e in enumerate(stored):
                fh.write(json.dumps({"seq": k, **e},
                                    ensure_ascii=False) + "\n")
            self._sidecar_written = os.path.abspath(path)
            fh.write(json.dumps({"summary": self.summary()},
                                ensure_ascii=False) + "\n")
        os.replace(tmp, path)
        return self._sidecar_written

    def publish(self, reg) -> None:
        """Counters into the run's registry: ``ingest/bad_records`` (+
        per-reason), ``quarantine/records``/``quarantine/truncated``,
        and the ``quarantine/summary`` gauge the manifest picks up."""
        n = self.count
        if n:
            reg.add("ingest/bad_records", n)
            for why, k in self.reason_counts().items():
                reg.add(f"ingest/bad_records/{why}", k)
        if self.policy.mode == "quarantine":
            s = self.summary()
            reg.add("quarantine/records", s["quarantined"])
            if s["truncated"]:
                reg.add("quarantine/truncated", 1)
        if n or self.policy.tolerant:
            reg.gauge("quarantine/summary").set_info(self.summary())


def abort_bookkeeping(exc: BaseException, reg) -> None:
    """Budget-abort evidence: called by the backends' run wrappers when
    a :class:`BadRecordBudgetExceeded` escapes the pipeline — whichever
    rung/thread raised it.  Writes the sidecar if quarantine mode never
    got to (the absolute budget aborts mid-decode, before ``finish``),
    publishes the counters into the run's registry so the manifest and
    ``--metrics-out`` carry the story, and refreshes the exception's
    summary with the final sidecar path."""
    sink = getattr(exc, "sink", None)
    if sink is None:
        return
    pol = sink.policy
    if pol.mode == "quarantine" and pol.sidecar_path \
            and sink._sidecar_written is None:
        try:
            sink.write_sidecar(pol.sidecar_path)
        except OSError:      # failed evidence write never masks the error
            pass
    if reg is not None:
        sink.publish(reg)
    exc.summary = sink.summary()


def sink_from_config(cfg) -> Optional[QuarantineSink]:
    """The run's sink, or None when ``--on-bad-record fail`` (the
    default): a None sink is the signal to every encoder that strict
    semantics apply unchanged."""
    policy = policy_from_config(cfg)
    if not policy.tolerant:
        return None
    return QuarantineSink(policy)


def mark_offset(exc: BaseException, offset: Optional[int]) -> BaseException:
    """Attach the input offset to a strict-mode decode error (attribute,
    not message — the message is oracle-parity contract).  First marker
    wins: the deepest frame knows the true offset."""
    if offset is not None and getattr(exc, "s2c_offset", None) is None:
        try:
            exc.s2c_offset = int(offset)
        except (AttributeError, TypeError):  # pragma: no cover - exotic exc
            pass
    return exc
