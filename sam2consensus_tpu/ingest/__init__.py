"""Sharded ingest: byte-range planning and the shared decode pool.

The multi-core ingest path (``encoder/parallel_decode.py``) is built on
two primitives that live here so every input family — plain SAM text,
BGZF containers, BAM — shares ONE definition of each:

* :func:`plan_byte_shards` — split a record-oriented byte buffer into
  line-snapped ranges.  Each range starts exactly at a line start and
  ends exactly after a line terminator (or at EOF), so N decode workers
  can own N disjoint ranges with zero coordination: no feed thread, no
  queue hops, no line straddling two workers.  The snapping rule is the
  classic "a line belongs to the shard containing its first byte":
  an interior cut point is advanced to one past the next newline at or
  after ``cut - 1`` (a cut already sitting on a line start stays put).

* :func:`shared_pool` — the process-wide inflate executor.  BGZF
  readers (``formats/bgzf.py``) used to spin a private pool per open
  container; a serve queue with many containers accumulated idle
  inflate threads, and a BGZF-SAM run stacked an inflate pool on top of
  the decode workers.  Now every BGZF stripe from every reader runs on
  one pool sized by the run's ``--decode-threads`` policy
  (``config.resolve_decode_threads``) — the ONE thread budget shared by
  the shard scheduler, the BGZF stripes and the native vote tail.

Observability vocabulary (counters/gauges the scheduler records, all
surfaced into ``stats.extra`` / bench rows by
``observability.publish_stats_extra``):

========================  ==============================================
``ingest/shards``         byte-range shards decoded this run
``ingest/worker_sec``     summed wall seconds across shard workers (the
                          parallelism story: worker_sec / decode_sec)
``ingest/fallback``       input could not be byte-sharded (gzip stream,
                          BGZF text, in-memory handle) — the streaming
                          rung served instead
``ingest/shard_retries``  shard decode attempts retried after an
                          infrastructure fault (``ingest_decode_shard``
                          site)
``ingest/demoted``        the whole ingest fell back to the serial rung
                          after a shard failed its retry
``ingest/mode``           gauge: rung + input class + shard count
========================  ==============================================
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

#: default floor on shard size: below this, per-shard fixed costs
#: (encoder construction, thread spawn, final-slab padding) dominate
#: and the serial path is faster anyway
DEFAULT_MIN_SHARD_BYTES = 1 << 20


def snap_line_start(data, pos: int, start: int, end: int) -> int:
    """Advance ``pos`` to the nearest line start at or after it.

    ``data`` is any buffer with ``find`` (mmap, bytes).  A position is a
    line start when the preceding byte is a newline (or it is ``start``
    itself), so the probe looks at ``pos - 1``: if that byte is ``\\n``
    the cut already sits on a line start and stays; otherwise the cut
    moves one past the newline that terminates the line containing
    ``pos``.  Returns ``end`` when no newline remains (the tail is one
    unterminated line belonging to the previous shard).
    """
    if pos <= start:
        return start
    if pos >= end:
        return end
    nl = data.find(b"\n", pos - 1, end)
    return end if nl < 0 else nl + 1


def plan_byte_shards(data, start: int, end: int, n_shards: int,
                     min_bytes: int = DEFAULT_MIN_SHARD_BYTES
                     ) -> List[Tuple[int, int]]:
    """Line-snapped byte ranges ``[(lo, hi), ...]`` tiling
    ``data[start:end]`` exactly.

    At most ``n_shards`` ranges, each (before snapping) at least
    ``min_bytes`` long — tiny inputs collapse to fewer shards rather
    than paying per-shard overhead for nothing.  Ranges are disjoint,
    ordered, non-empty, and every line of the input starts in exactly
    one range (CRLF and a truncated final line included: the ``\\r``
    travels with its line, and an unterminated tail belongs to the last
    range).  Snapping can empty a range (a shard narrower than one
    line); empty ranges are dropped, so fewer ranges than requested can
    come back — including zero for an empty body.
    """
    size = end - start
    if size <= 0:
        return []
    n = max(1, min(int(n_shards), size // max(1, int(min_bytes)) or 1))
    bounds = _snap_bounds(data, start, end, n)
    ranges: List[Tuple[int, int]] = []
    prev = start
    for b in bounds[1:]:
        if b > prev:
            ranges.append((prev, b))
            prev = b
    return ranges


def _snap_bounds(data, start: int, end: int, n: int) -> List[int]:
    """All n+1 snapped boundaries, via the native one-pass snapper
    (``s2c_snap_shards``) when the decoder library is loaded — the
    python loop below is its semantics twin and the fallback."""
    from .. import native

    lib = native.load()
    if lib is not None and hasattr(lib, "s2c_snap_shards"):
        import numpy as np

        buf = np.frombuffer(data, dtype=np.uint8) \
            if not isinstance(data, np.ndarray) else data
        out = np.empty(n + 1, dtype=np.int64)
        lib.s2c_snap_shards(buf, start, end, n, out)
        return [int(b) for b in out]
    bounds = [start]
    for k in range(1, n):
        bounds.append(snap_line_start(data, start + (end - start) * k // n,
                                      start, end))
    bounds.append(end)
    return bounds


@dataclass
class ShardPlan:
    """A planned byte-sharded input: the backing buffer plus the
    line-snapped ranges decode workers will own.  ``data`` is typically
    an ``mmap`` of the input file — workers slice ``memoryview`` windows
    off it, so the whole plan is zero-copy down to the C decoder."""

    data: object
    ranges: List[Tuple[int, int]] = field(default_factory=list)
    start: int = 0
    end: int = 0
    source: str = "mmap"

    @property
    def nbytes(self) -> int:
        return max(0, self.end - self.start)


# -- shared inflate pool ----------------------------------------------------
_pool = None
_pool_workers = 0
_pool_lock = threading.Lock()


def shared_pool(threads: int):
    """The process-wide ingest executor, grown to at least ``threads``
    workers (never shrunk — the high-water budget is what the operator
    asked for at some point this process).  Returns None for
    ``threads <= 1``: serial callers should stay poolless.

    Only SHORT tasks (BGZF stripe inflates) belong here.  Shard decode
    workers are dedicated threads — a long-running decode task parked on
    this pool would starve the inflate stripes it is itself waiting on.
    """
    global _pool, _pool_workers
    if threads <= 1:
        return None
    with _pool_lock:
        if _pool is None or _pool_workers < threads:
            from concurrent.futures import ThreadPoolExecutor

            old = _pool
            _pool = ThreadPoolExecutor(max_workers=int(threads),
                                       thread_name_prefix="s2c-ingest")
            _pool_workers = int(threads)
            if old is not None:
                # in-flight stripes finish on the old pool's threads;
                # new submissions land on the grown pool
                old.shutdown(wait=False)
        return _pool


def pool_submit(threads: int, fn, *args):
    """Submit a short task to the shared pool, safe against concurrent
    growth.  ``shared_pool`` retires the old executor when a larger
    budget arrives; a caller that fetched the pool just before that
    loses the race and its submit raises RuntimeError — retry against
    the current pool (already-submitted work is unaffected: retirement
    uses ``shutdown(wait=False)``, which drains the queue).  Callers
    must NOT cache the executor across submits; always come through
    here."""
    while True:
        pool = shared_pool(threads)
        if pool is None:
            raise ValueError("pool_submit needs threads > 1")
        try:
            return pool.submit(fn, *args)
        except RuntimeError:
            # only a RETIRED executor justifies a retry; if the refusing
            # pool is still the current one the error is real (e.g.
            # interpreter shutdown) and must propagate, not busy-spin
            with _pool_lock:
                if _pool is pool:
                    raise


def pool_info() -> dict:
    """Introspection for gauges/tests: current shared-pool size."""
    with _pool_lock:
        return {"workers": _pool_workers, "active": _pool is not None}


def _reset_pool_for_tests() -> None:
    global _pool, _pool_workers
    with _pool_lock:
        if _pool is not None:
            _pool.shutdown(wait=False)
        _pool = None
        _pool_workers = 0
