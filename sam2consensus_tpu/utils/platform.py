"""Platform selection guard.

Some environments pre-register a remote accelerator backend from a
``sitecustomize`` hook and override ``jax_platforms`` through ``jax.config``
— which silently trumps the ``JAX_PLATFORMS`` environment variable the user
(or a test/driver harness) set.  ``pin_platform_from_env`` restores the
env var's authority; it is a no-op when the env var is unset or explicitly
includes the remote platform.
"""

from __future__ import annotations

import os


def pin_platform_from_env() -> None:
    env_p = os.environ.get("JAX_PLATFORMS", "")
    if env_p and "axon" not in env_p:
        import jax

        jax.config.update("jax_platforms", env_p)
