"""Checkpoint/resume for the streaming consensus job.

SURVEY.md §5: the count tensor IS the entire job state and is
sum-decomposable, so a checkpoint is just ``[total_len, 6]`` counts plus
the insertion event log and the number of input lines already consumed —
a killed run resumes by loading the arrays and skipping that many body
lines (the reference has nothing comparable: two full passes, all state in
RAM, ``/root/reference/sam2consensus.py:149,180``).

Checkpoints are written at batch boundaries, where the pipeline guarantees
every decoded line's contribution is either in the count tensor or the
insertion log (nothing in flight).  Files are plain ``.npz`` written via a
temp file + atomic rename, so a crash mid-write leaves the previous
checkpoint intact.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..encoder.events import InsertionEvents

_FILE = "sam2consensus_ckpt.npz"


@dataclass
class CheckpointState:
    counts: np.ndarray           # [total_len, 6] int32
    lines_consumed: int
    reads_mapped: int
    reads_skipped: int
    aligned_bases: int
    insertions: InsertionEvents
    #: identity of the in-flight input the line offset refers to; an
    #: --incremental run whose input differs treats the checkpoint as an
    #: accumulated base and starts the new file from line 0
    source: str = ""
    #: identities of inputs FULLY absorbed into counts; an --incremental
    #: run whose input is listed here is a duplicate and adds nothing
    sources: list = None
    #: absolute byte offset in the (uncompressed) input matching
    #: lines_consumed; resume seeks here in O(1) instead of re-reading
    #: the consumed lines.  -1 = unknown (non-seekable stream): resume
    #: falls back to the line-skipping loop.
    byte_offset: int = -1
    #: widest segment-row bucket the encoder emitted so far (0 =
    #: unknown/old checkpoint); a resumed sharded run sizes its sp/dpsp
    #: halo from this instead of re-observing (round-4 verdict #5)
    max_row_width: int = 0


def path_for(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, _FILE)


def save(checkpoint_dir: str, state: CheckpointState) -> None:
    os.makedirs(checkpoint_dir, exist_ok=True)
    ic, il, im, ich = state.insertions.to_arrays()
    fd, tmp = tempfile.mkstemp(suffix=".npz.tmp", dir=checkpoint_dir)
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez_compressed(
                fh,
                counts=state.counts.astype(np.int32),
                meta=np.array([state.lines_consumed, state.reads_mapped,
                               state.reads_skipped, state.aligned_bases,
                               state.byte_offset, state.max_row_width],
                              dtype=np.int64),
                ins_contig=ic.astype(np.int32),
                ins_local=il.astype(np.int32),
                ins_mlen=im.astype(np.int32),
                ins_chars=ich.astype(np.uint8),
                source=np.frombuffer(state.source.encode("utf-8"),
                                     dtype=np.uint8),
                sources=np.frombuffer(
                    "\n".join(state.sources or []).encode("utf-8"),
                    dtype=np.uint8))
        os.replace(tmp, path_for(checkpoint_dir))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load(checkpoint_dir: str, total_len: int) -> Optional[CheckpointState]:
    """Load the checkpoint if present and shape-compatible, else None."""
    p = path_for(checkpoint_dir)
    if not os.path.exists(p):
        return None
    with np.load(p, allow_pickle=False) as z:
        counts = z["counts"]
        if counts.shape != (total_len, 6):
            raise ValueError(
                f"checkpoint at {p} is for a genome of length "
                f"{counts.shape[0]}, not {total_len} — wrong input file?")
        meta = z["meta"]
        ins = InsertionEvents()
        if len(z["ins_contig"]):
            ins.array_chunks.append(
                (z["ins_contig"], z["ins_local"], z["ins_mlen"],
                 z["ins_chars"]))
        source = bytes(z["source"]).decode("utf-8") \
            if "source" in z.files else ""
        blob = bytes(z["sources"]).decode("utf-8") \
            if "sources" in z.files else ""
        sources = [s for s in blob.split("\n") if s]
        return CheckpointState(
            counts=counts, lines_consumed=int(meta[0]),
            reads_mapped=int(meta[1]), reads_skipped=int(meta[2]),
            aligned_bases=int(meta[3]), insertions=ins, source=source,
            sources=sources,
            byte_offset=int(meta[4]) if len(meta) > 4 else -1,
            max_row_width=int(meta[5]) if len(meta) > 5 else 0)
