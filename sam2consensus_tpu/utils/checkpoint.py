"""Checkpoint/resume for the streaming consensus job.

SURVEY.md §5: the count tensor IS the entire job state and is
sum-decomposable, so a checkpoint is just ``[total_len, 6]`` counts plus
the insertion event log and the number of input lines already consumed —
a killed run resumes by loading the arrays and skipping that many body
lines (the reference has nothing comparable: two full passes, all state in
RAM, ``/root/reference/sam2consensus.py:149,180``).

Checkpoints are written at batch boundaries, where the pipeline guarantees
every decoded line's contribution is either in the count tensor or the
insertion log (nothing in flight).  Files are plain ``.npz`` written via a
temp file + atomic rename, so a crash mid-write leaves the previous
checkpoint intact.

Integrity: the payload arrays carry a ``zlib.crc32`` digest (``digest``
entry) computed over their raw bytes at save time.  ``load`` verifies
it — and treats ANY unreadable checkpoint (truncated/corrupt npz,
digest mismatch) as absent-with-warning (``checkpoint/corrupt``
counter) instead of raising: a corrupt checkpoint mid-resume must cost
a from-scratch re-run, never wedge the job that was trying to recover.
A checkpoint whose shape doesn't match the input still raises — that is
a *wrong input* contract error, not corruption.
"""

from __future__ import annotations

import logging
import os
import tempfile
import zlib
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..encoder.events import InsertionEvents

logger = logging.getLogger("sam2consensus_tpu.utils.checkpoint")

_FILE = "sam2consensus_ckpt.npz"


@dataclass
class CheckpointState:
    counts: np.ndarray           # [total_len, 6] int32
    lines_consumed: int
    reads_mapped: int
    reads_skipped: int
    aligned_bases: int
    insertions: InsertionEvents
    #: identity of the in-flight input the line offset refers to; an
    #: --incremental run whose input differs treats the checkpoint as an
    #: accumulated base and starts the new file from line 0
    source: str = ""
    #: identities of inputs FULLY absorbed into counts; an --incremental
    #: run whose input is listed here is a duplicate and adds nothing
    sources: list = None
    #: absolute byte offset in the (uncompressed) input matching
    #: lines_consumed; resume seeks here in O(1) instead of re-reading
    #: the consumed lines.  -1 = unknown (non-seekable stream): resume
    #: falls back to the line-skipping loop.
    byte_offset: int = -1
    #: widest segment-row bucket the encoder emitted so far (0 =
    #: unknown/old checkpoint); a resumed sharded run sizes its sp/dpsp
    #: halo from this instead of re-observing (round-4 verdict #5)
    max_row_width: int = 0


def path_for(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, _FILE)


def _payload_digest(arrays) -> int:
    """crc32 over the payload arrays' raw bytes, in a fixed order —
    cheap (~100 MB/s-class) next to the npz compression that follows,
    and enough to catch the failure this guards: a torn/bit-rotted file
    served as a resume base."""
    crc = 0
    for a in arrays:
        crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
    return crc & 0xFFFFFFFF


def save(checkpoint_dir: str, state: CheckpointState) -> None:
    os.makedirs(checkpoint_dir, exist_ok=True)
    ic, il, im, ich = state.insertions.to_arrays()
    counts = state.counts.astype(np.int32)
    meta = np.array([state.lines_consumed, state.reads_mapped,
                     state.reads_skipped, state.aligned_bases,
                     state.byte_offset, state.max_row_width],
                    dtype=np.int64)
    ins_contig = ic.astype(np.int32)
    ins_local = il.astype(np.int32)
    ins_mlen = im.astype(np.int32)
    ins_chars = ich.astype(np.uint8)
    source = np.frombuffer(state.source.encode("utf-8"), dtype=np.uint8)
    sources = np.frombuffer(
        "\n".join(state.sources or []).encode("utf-8"), dtype=np.uint8)
    digest = _payload_digest((counts, meta, ins_contig, ins_local,
                              ins_mlen, ins_chars, source, sources))
    fd, tmp = tempfile.mkstemp(suffix=".npz.tmp", dir=checkpoint_dir)
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez_compressed(
                fh,
                counts=counts,
                meta=meta,
                ins_contig=ins_contig,
                ins_local=ins_local,
                ins_mlen=ins_mlen,
                ins_chars=ins_chars,
                source=source,
                sources=sources,
                digest=np.array([digest], dtype=np.uint32))
        os.replace(tmp, path_for(checkpoint_dir))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _corrupt(path: str, why: str) -> None:
    """Record + warn: the checkpoint is unusable and will be ignored."""
    from .. import observability as obs

    obs.metrics().add("checkpoint/corrupt", 1)
    obs.tracer().event("checkpoint/corrupt", path=path, reason=why)
    logger.warning(
        "checkpoint at %s is unusable (%s): resuming from scratch — the "
        "corrupt file is left in place for forensics and will be "
        "overwritten by the next checkpoint write", path, why)


def load(checkpoint_dir: str, total_len: int) -> Optional[CheckpointState]:
    """Load the checkpoint if present, intact, and shape-compatible.

    Returns None when absent — or when the file is corrupt/truncated or
    its crc32 digest mismatches (counted ``checkpoint/corrupt``, warned;
    the run resumes from scratch).  A shape mismatch still raises: that
    is a wrong-input error the user must see, not damage to absorb."""
    p = path_for(checkpoint_dir)
    if not os.path.exists(p):
        return None
    try:
        z = np.load(p, allow_pickle=False)
    except Exception as exc:            # zipfile/npz corruption shapes vary
        _corrupt(p, f"unreadable npz: {type(exc).__name__}: {exc}")
        return None
    with z:
        try:
            counts = z["counts"]
            meta = z["meta"]
            payload = (counts.astype(np.int32), meta,
                       z["ins_contig"].astype(np.int32),
                       z["ins_local"].astype(np.int32),
                       z["ins_mlen"].astype(np.int32),
                       z["ins_chars"].astype(np.uint8),
                       z["source"] if "source" in z.files
                       else np.zeros(0, np.uint8),
                       z["sources"] if "sources" in z.files
                       else np.zeros(0, np.uint8))
        except Exception as exc:        # truncated member / missing key
            _corrupt(p, f"truncated payload: {type(exc).__name__}: {exc}")
            return None
        if "digest" in z.files:
            want = int(z["digest"][0])
            got = _payload_digest(payload)
            if got != want:
                _corrupt(p, f"digest mismatch (crc32 {got:#010x} != "
                            f"recorded {want:#010x})")
                return None
        # pre-digest checkpoints (older writers) load undigested
        if counts.shape != (total_len, 6):
            raise ValueError(
                f"checkpoint at {p} is for a genome of length "
                f"{counts.shape[0]}, not {total_len} — wrong input file?")
        ins = InsertionEvents()
        if len(z["ins_contig"]):
            ins.array_chunks.append(
                (z["ins_contig"], z["ins_local"], z["ins_mlen"],
                 z["ins_chars"]))
        source = bytes(z["source"]).decode("utf-8") \
            if "source" in z.files else ""
        blob = bytes(z["sources"]).decode("utf-8") \
            if "sources" in z.files else ""
        sources = [s for s in blob.split("\n") if s]
        return CheckpointState(
            counts=counts, lines_consumed=int(meta[0]),
            reads_mapped=int(meta[1]), reads_skipped=int(meta[2]),
            aligned_bases=int(meta[3]), insertions=ins, source=source,
            sources=sources,
            byte_offset=int(meta[4]) if len(meta) > 4 else -1,
            max_row_width=int(meta[5]) if len(meta) > 5 else 0)
