"""Synthetic SAM fixtures: deterministic, code-defined, no binary blobs.

SURVEY.md §4 calls for a fixture generator covering the BASELINE.md config
shapes (single-contig phiX-like, many-contig target capture, deep
insertion-heavy amplicon).  Two levels:

* :func:`sam_text` — hand-specified records for unit tests;
* :func:`simulate` — a tiny read simulator over a random genome, emitting
  reads with substitutions, insertions, deletions and soft clips, for
  differential and benchmark corpora.
"""

from __future__ import annotations

import gzip
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

_BASES = "ACGT"


def sam_text(contigs: Sequence[Tuple[str, int]],
             reads: Sequence[Tuple[str, int, str, str]],
             extra_header: Sequence[str] = ()) -> str:
    """Build SAM text from (name, length) contigs and (ref, pos1, cigar, seq)
    reads.  ``pos1`` is 1-based as in a real SAM file."""
    lines = ["@HD\tVN:1.6\tSO:unsorted"]
    for name, length in contigs:
        lines.append(f"@SQ\tSN:{name}\tLN:{length}")
    lines.extend(extra_header)
    for i, (ref, pos1, cigar, seq) in enumerate(reads):
        qual = "I" * len(seq) if seq != "*" else "*"
        lines.append(f"read{i}\t0\t{ref}\t{pos1}\t60\t{cigar}\t*\t0\t0\t{seq}\t{qual}")
    return "\n".join(lines) + "\n"


def write_sam(text: str, path: str) -> str:
    if path.endswith(".gz"):
        with gzip.open(path, "wb") as fh:
            fh.write(text.encode("ascii"))
    else:
        with open(path, "w") as fh:
            fh.write(text)
    return path


@dataclass
class SimSpec:
    """Knobs for the read simulator (rates are per-read probabilities)."""
    n_contigs: int = 1
    contig_len: int = 5000
    n_reads: int = 5000
    read_len: int = 100
    sub_rate: float = 0.01        # per-base substitution probability
    n_rate: float = 0.001         # per-base N probability
    ins_read_rate: float = 0.05   # reads carrying one insertion
    del_read_rate: float = 0.05   # reads carrying one deletion
    softclip_rate: float = 0.05   # reads with a soft-clipped prefix
    max_indel: int = 5
    contig_len_jitter: float = 0.3
    seed: int = 0
    contig_prefix: str = "contig"
    #: long-read mode (ONT/PacBio-like): every read carries this many
    #: indel events spread across its length (alternating I/D), instead
    #: of the at-most-one event the short-read rates draw.  0 keeps the
    #: legacy single-event path (and its exact rng stream — existing
    #: seeds stay byte-stable).
    n_indels: int = 0


def simulate(spec: SimSpec) -> str:
    """Generate a deterministic SAM corpus; returns the SAM text."""
    rng = np.random.RandomState(spec.seed)
    # worst-case reference span a read can consume past its start
    # (n_indels > 0 may stack several D events; == max_indel for the
    # legacy path so existing seeds keep their exact streams)
    margin = spec.max_indel * (spec.n_indels if spec.n_indels > 0 else 1)
    contigs: List[Tuple[str, int]] = []
    genomes: List[np.ndarray] = []
    for i in range(spec.n_contigs):
        jitter = 1.0 + spec.contig_len_jitter * (rng.rand() - 0.5) * 2
        length = max(spec.read_len + margin + 2,
                     int(spec.contig_len * jitter))
        contigs.append((f"{spec.contig_prefix}{i:04d}", length))
        genomes.append(rng.randint(0, 4, size=length))

    reads: List[Tuple[str, int, str, str]] = []
    for _ in range(spec.n_reads):
        ci = int(rng.randint(0, spec.n_contigs))
        name, length = contigs[ci]
        genome = genomes[ci]
        rl = spec.read_len
        start = int(rng.randint(0, max(1, length - rl - margin)))

        cigar_parts: List[str] = []
        seq_parts: List[str] = []
        gpos = start

        def take_match(n):
            nonlocal gpos
            codes = genome[gpos:gpos + n].copy()
            sub = rng.rand(n) < spec.sub_rate
            codes[sub] = rng.randint(0, 4, size=int(sub.sum()))
            chars = np.array(list(_BASES))[codes]
            nmask = rng.rand(n) < spec.n_rate
            chars[nmask] = "N"
            seq_parts.append("".join(chars))
            cigar_parts.append(f"{n}M")
            gpos += n

        if rng.rand() < spec.softclip_rate:
            clip = int(rng.randint(1, 8))
            seq_parts.append("".join(_BASES[c] for c in rng.randint(0, 4, clip)))
            cigar_parts.append(f"{clip}S")

        if spec.n_indels > 0:
            # dense-indel long read: split the read into n_indels+1 match
            # chunks with an alternating I/D event between consecutive
            # chunks — the CIGAR shape that stresses the insertion table
            # and the segmented slab layout
            cuts = np.sort(rng.choice(np.arange(1, rl),
                                      size=min(spec.n_indels, rl - 1),
                                      replace=False))
            prev = 0
            for j, cut in enumerate(cuts):
                take_match(int(cut) - prev)
                k = int(rng.randint(1, spec.max_indel + 1))
                if j % 2 == 0:
                    seq_parts.append("".join(
                        _BASES[c] for c in rng.randint(0, 4, k)))
                    cigar_parts.append(f"{k}I")
                else:
                    cigar_parts.append(f"{k}D")
                    gpos += k
                prev = int(cut)
            take_match(rl - prev)
            reads.append((name, start + 1, "".join(cigar_parts),
                          "".join(seq_parts)))
            continue

        event = rng.rand()
        if event < spec.ins_read_rate:
            k = int(rng.randint(1, spec.max_indel + 1))
            split = int(rng.randint(1, rl))
            take_match(split)
            seq_parts.append("".join(_BASES[c] for c in rng.randint(0, 4, k)))
            cigar_parts.append(f"{k}I")
            take_match(rl - split)
        elif event < spec.ins_read_rate + spec.del_read_rate:
            k = int(rng.randint(1, spec.max_indel + 1))
            split = int(rng.randint(1, rl))
            take_match(split)
            cigar_parts.append(f"{k}D")
            gpos += k
            take_match(rl - split)
        else:
            take_match(rl)

        reads.append((name, start + 1, "".join(cigar_parts), "".join(seq_parts)))

    # sprinkle a few unmapped records (CIGAR "*"), skipped by the tool
    for _ in range(max(1, spec.n_reads // 500)):
        reads.append((contigs[0][0], 1, "*", "*"))

    return sam_text(contigs, reads)


# Shapes mirroring BASELINE.md's five benchmark configs, scaled for tests.
BASELINE_SPECS = {
    "phix_like": SimSpec(n_contigs=1, contig_len=5386, n_reads=5000,
                         read_len=100, seed=101, contig_prefix="phiX"),
    "target_capture": SimSpec(n_contigs=350, contig_len=1200, n_reads=40000,
                              read_len=100, seed=202, contig_prefix="gene"),
    "amplicon_deep": SimSpec(n_contigs=1, contig_len=400, n_reads=30000,
                             read_len=80, ins_read_rate=0.3, del_read_rate=0.2,
                             seed=303, contig_prefix="amplicon"),
}
