"""Cheap startup probe of the host<->accelerator link.

The tail-placement model (backends/jax_backend.py ``_tail_cpu_wins``) and
the output-encoding gate price every decision in round trips and bytes on
the link.  Those constants differ by ~3 orders of magnitude between the
bench rig's tunneled chip (~65 ms RT, ~40 MB/s) and a real TPU-VM's PCIe
link (sub-ms RT, ~GB/s) — baked defaults mis-route on whichever rig they
were not measured on (round-3 verdict).  This probe measures both numbers
once per process in ~3 round trips:

* dispatch round trip: a jitted identity on 8 int32s, best of 3 after a
  compile warm-up — the same null-dispatch cost ``tools/tunnel_probe.py``
  reports;
* link bandwidth: warmed best-of-2 1 MB transfers in EACH direction,
  RT-corrected; the slower direction is reported, because the placement
  model bills both the counts upload and the output fetch with this one
  rate.

Results are cached for the process.  The caller (``_link_constants``)
only probes real accelerators — the XLA CPU backend is link-free — and
env overrides (S2C_TAIL_RT_MS / S2C_TAIL_LINK_MBPS) skip the probe
entirely; S2C_LINK_PROBE=0 disables it.

Failure semantics (resilience subsystem): the measurement runs under a
watchdog deadline (S2C_LINK_PROBE_TIMEOUT_S), and a failed or hung
probe falls back to STALE constants — the last successful measurement
this process (or, via S2C_LINK_CACHE, a previous process) took on this
link — before resorting to the baked rig defaults.  Stale service is
flagged in the run's metrics (``link/stale``).  The probe body carries
the ``link_probe`` fault-injection site (resilience/faultinject.py).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger("sam2consensus_tpu.utils.linkprobe")

_cached: Optional[Tuple[float, float]] = None
_failed = False
#: last SUCCESSFUL measurement, surviving later failures — the stale
#: fallback a flaky tunnel gets instead of the rig defaults (a probe
#: that worked ten minutes ago describes this link far better than
#: constants measured on a different machine)
_last_good: Optional[Tuple[float, float]] = None
#: when the in-process measurement was taken (unix seconds)
_last_good_at: Optional[float] = None
#: provenance of the constants last served to a consumer, for the
#: run manifest (observability/manifest.py): source is one of
#: "probed" | "stale-memory" | "stale-cache" | None (never measured)
_served: dict = {"source": None, "measured_at": None}

#: probe transfer size: big enough that bandwidth dominates the RT term
#: after correction, small enough to cost <1 s even on a ~10 MB/s link
PROBE_BYTES = 1 << 20

#: default S2C_LINK_CACHE_MAX_AGE: constants older than this (seconds)
#: are still served on probe failure — there is nothing better — but
#: loudly: ``link/stale_age`` gauge + warning, instead of silently
#: pricing every placement decision from drifted numbers (the round-5
#: failure mode: 40 MB/s baked vs 10-15 MB/s measured).  7 days.
CACHE_MAX_AGE_SEC = 7 * 86400.0


def cache_max_age() -> float:
    # one staleness knob for both aged-constant planes: the rate card
    # (observability/ratecard.py) reads the SAME env var for its
    # confidence gate, so "how old may a learned constant be" is
    # answered once per rig
    from ..observability import ratecard as _rc

    return _rc.max_age_sec()


def _cache_file() -> Optional[str]:
    """Optional cross-process stale cache (S2C_LINK_CACHE: a json path).
    Lets a re-launched run on a dropped tunnel reuse the previous
    process's measured constants instead of the baked defaults."""
    return os.environ.get("S2C_LINK_CACHE") or None


def _read_cache() -> Optional[Tuple[float, float, Optional[float]]]:
    """(rt_sec, bps, measured_at) from the cache file; measured_at is
    None for pre-timestamp cache entries (treated as unknown age =
    stale).  A corrupt/truncated cache — a torn write from a pre-atomic
    writer, or plain disk damage — is NOT an error: it reads as absent
    (the caller falls back to probing / the baked defaults) with a
    ``link/cache_corrupt`` gauge + warning so the artifact shows the
    cache was there but unusable."""
    path = _cache_file()
    if not path or not os.path.exists(path):
        return None
    try:
        import json

        with open(path) as fh:
            blob = json.load(fh)
        at = blob.get("measured_at")
        return (float(blob["rt_sec"]), float(blob["bps"]),
                float(at) if at is not None else None)
    except Exception as exc:
        from .. import observability as obs

        obs.metrics().gauge("link/cache_corrupt").set(1.0)
        obs.tracer().event("link/cache_corrupt", path=path,
                           error=f"{type(exc).__name__}: {exc}")
        logger.warning(
            "link cache %s is corrupt/truncated (%s: %s): ignoring it "
            "and probing the link instead", path,
            type(exc).__name__, exc)
        return None


def _write_cache(probed: Tuple[float, float]) -> None:
    """Persist via tmp + ``os.replace`` (same discipline as
    utils/checkpoint.py): a crash mid-write must leave the previous
    cache intact, never a truncated JSON a later process chokes on."""
    path = _cache_file()
    if not path:
        return
    try:
        import json
        import tempfile

        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(suffix=".tmp", dir=d)
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump({"rt_sec": probed[0], "bps": probed[1],
                           "measured_at": time.time()}, fh)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    except OSError:
        pass


def _stale_constants() -> Optional[Tuple[float, float, Optional[float],
                                         str]]:
    """(rt_sec, bps, measured_at, source) of the last known-good
    constants (in-process first, then the optional cache file), or None
    when the link was never measured."""
    if _last_good is not None:
        return (*_last_good, _last_good_at, "stale-memory")
    cached = _read_cache()
    if cached is not None:
        return (*cached, "stale-cache")
    return None


def link_info() -> dict:
    """Provenance of the constants this process last served: source
    (probed/stale-memory/stale-cache/None), measured-at and age — the
    manifest's link section (observability/manifest.py)."""
    info = dict(_served)
    at = info.get("measured_at")
    if at is not None:
        info["age_sec"] = round(max(0.0, time.time() - at), 1)
    return info


def probe_link(force: bool = False) -> Optional[Tuple[float, float]]:
    """Measure (round_trip_sec, h2d_bytes_per_sec) on the default device.

    Returns None (and remembers the failure) if the device cannot be
    reached.  The measurement runs on a watchdog thread with a deadline
    (S2C_LINK_PROBE_TIMEOUT_S, default 20 s): a tunneled accelerator
    whose transport died AFTER backend init blocks forever inside
    ``block_until_ready`` — without the deadline the probe (and the
    placement gate consulting it) would hang instead of falling back to
    the default constants, which route host-side and complete link-free
    on every workload the gates would have kept local anyway.
    """
    global _cached, _failed, _last_good, _last_good_at
    if _cached is not None and not force:
        _record_link(_cached)          # fresh per-run registry, cached probe
        _served.update(source="probed", measured_at=_last_good_at)
        return _cached
    if _failed and not force:
        return _stale_fallback()
    from .. import observability as obs

    timeout = float(os.environ.get("S2C_LINK_PROBE_TIMEOUT_S", "20"))
    box: list = []
    with obs.tracer().span("link_probe") as sp:
        t = threading.Thread(target=_probe_into, args=(box,),
                             daemon=True)
        t.start()
        t.join(timeout)
        if t.is_alive() or not box or box[0] is None:
            # hung (thread left blocked; it is a daemon) or raised
            _failed = True
            sp.set_args(failed=True)
            obs.metrics().gauge("link/probe_failed").set(1.0)
            return _stale_fallback()
        _cached = box[0]
        _last_good = _cached
        _last_good_at = time.time()
        _write_cache(_cached)
        sp.set_args(rt_sec=_cached[0], bps=_cached[1])
    _record_link(_cached)
    _served.update(source="probed", measured_at=_last_good_at)
    return _cached


def _stale_fallback() -> Optional[Tuple[float, float]]:
    """On probe failure: serve the last known-good constants when any
    exist (marked stale in the run's registry so the artifact shows the
    placement model ran on memory, not measurement); None otherwise —
    the consumers then fall to the baked rig defaults.  Constants older
    than S2C_LINK_CACHE_MAX_AGE (or of unknown age — a pre-timestamp
    cache entry) additionally emit a ``link/stale_age`` gauge and a
    warning: they still describe this link better than another rig's
    baked defaults, but nobody should trust a week-old tunnel number
    silently."""
    stale = _stale_constants()
    if stale is None:
        return None
    rt, bps, measured_at, source = stale
    from .. import observability as obs

    reg = obs.metrics()
    reg.gauge("link/stale").set(1.0)
    age = time.time() - measured_at if measured_at is not None else None
    if age is None or age > cache_max_age():
        # -1.0 = unknown age (legacy cache entry without measured_at)
        reg.gauge("link/stale_age").set(round(age, 1)
                                        if age is not None else -1.0)
        logger.warning(
            "link constants from %s are %s old (max age %.0f s): the "
            "placement model is pricing from a link that may no longer "
            "exist — re-probe (unset S2C_LINK_PROBE=0) or override "
            "S2C_TAIL_RT_MS / S2C_TAIL_LINK_MBPS",
            source,
            f"{age:.0f} s" if age is not None else "an unknown age",
            cache_max_age())
    obs.tracer().event("link/stale_constants", rt_sec=rt, bps=bps,
                       age_sec=age)
    _record_link((rt, bps))
    _served.update(source=source, measured_at=measured_at)
    return (rt, bps)


def _record_link(probed: Tuple[float, float]) -> None:
    """Publish the measured link constants into the CURRENT run's
    registry — called on fresh probes AND cache hits, because every run
    after the first gets a fresh registry while the probe result is
    process-cached."""
    from .. import observability as obs

    reg = obs.metrics()
    reg.gauge("link/rt_sec").set(probed[0])
    reg.gauge("link/bps").set(probed[1])
    # measured link constants are ALSO rate-card entries: the card's
    # EWMA + staleness age is the unified learned-constant plane the
    # wire/placement decisions consult (best-effort — a serve runner
    # installs a card; one-shot runs have none)
    from ..observability import ratecard as _rc

    card = _rc.installed()
    if card is not None:
        try:
            card.observe("link_rt_sec", probed[0])
            card.observe("link_bps", probed[1])
        except Exception:
            pass


def _probe_into(box: list) -> None:
    try:
        from ..resilience.faultinject import fault_check

        fault_check("link_probe")
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: x + 1)
        x = jnp.zeros(8, jnp.int32)
        f(x).block_until_ready()          # pays the compile
        rt = min(_timed(lambda: f(x).block_until_ready())
                 for _ in range(3))

        # both directions, first transfer discarded (pinned-buffer /
        # registration overhead); the model bills upload AND fetch with
        # one rate, so take the slower direction
        buf = np.zeros(PROBE_BYTES, np.uint8)
        dev = jax.device_put(buf)
        dev.block_until_ready()           # warm h2d
        put = min(_timed(lambda: jax.device_put(buf).block_until_ready())
                  for _ in range(2))
        # d2h must read DISTINCT device arrays: jax caches the host copy
        # per array, so re-reading one array times a memcpy, not the link
        g = jax.jit(lambda a, s: a + s)
        outs = [g(dev, jnp.uint8(i + 1)) for i in range(3)]
        for o in outs:
            o.block_until_ready()
        np.asarray(outs[0])               # warm d2h
        get = min(_timed(lambda o=o: np.asarray(o)) for o in outs[1:])
        bw = PROBE_BYTES / max(max(put, get) - rt / 2, 1e-9)
    except Exception:
        box.append(None)
        return
    # clamp to sane bounds: a sub-us "RT" (fully async dispatch) or a
    # TB/s "bandwidth" (buffer donation / page sharing) would make the
    # model treat the link as free and ship everything
    rt = float(min(max(rt, 1e-6), 10.0))
    bw = float(min(max(bw, 1e5), 1e12))
    box.append((rt, bw))


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _reset_for_tests(drop_last_good: bool = True) -> None:
    global _cached, _failed, _last_good, _last_good_at
    _cached = None
    _failed = False
    _served.update(source=None, measured_at=None)
    if drop_last_good:
        _last_good = None
        _last_good_at = None
