"""Shared machinery for the sharded accumulators (dp and sp layouts).

Both pipelines keep the count tensor position-sharded across the
flattened ("dp", "sp") mesh axes and share the same state surface
(``counts`` / ``counts_host`` / ``restore`` for checkpointing, and the
position-sharded ``vote``); only *accumulation* differs — dp scatters
full-length local tensors and reduce-scatters, sp routes rows to the
owning position block and halo-exchanges.  Keeping the common parts here
means a vote or checkpoint fix cannot silently diverge the two modes.
"""

from __future__ import annotations

import inspect
import time
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

try:
    _SHARD_MAP_PARAMS = frozenset(
        inspect.signature(shard_map).parameters)
except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
    _SHARD_MAP_PARAMS = frozenset(("check_vma",))
if "check_vma" not in _SHARD_MAP_PARAMS:
    # version shim: the replication check's kwarg was renamed
    # check_rep -> check_vma across jax releases; route whichever
    # spelling the installed jax accepts so the sp/dpsp kernels (which
    # pass check_vma=False) import everywhere
    _shard_map_native = shard_map

    def shard_map(*args, check_vma=None, **kwargs):  # noqa: F811
        if check_vma is not None and "check_rep" in _SHARD_MAP_PARAMS:
            kwargs["check_rep"] = check_vma
        return _shard_map_native(*args, **kwargs)

from ..constants import NUM_SYMBOLS, PAD_CODE
from .partition import (gather_from_mesh, make_shard_and_gather_fns,
                        match_partition_rules, partition_rules,
                        publish_mesh_gauges)

#: both mesh axes flattened: every collective treats the mesh as one ring
ALL = ("dp", "sp")


def fetch_host(x: jax.Array) -> np.ndarray:
    """Host copy of a possibly process-spanning sharded array.

    Single-controller meshes (every shard addressable) and fully
    replicated outputs take the plain fetch.  On a multi-host mesh
    (``jax.distributed`` — DCN topology; validated by
    ``tools/multihost_dryrun.py``) a position-sharded array spans
    processes, so each process assembles the global value with one
    ``process_allgather`` (tiled: shards land in their global slots).

    Every fetch bills the run's d2h choke point (``wire.account_d2h``)
    — the gather-based sharded tails (vote symbols, tail stats, count
    snapshots) previously bypassed ``wire/d2h_bytes`` entirely.  The
    one implementation lives in ``parallel.partition`` next to the
    shard path so the two directions cannot diverge.
    """
    return gather_from_mesh(x)


def record_slab(key: str, t0: float, n_rows: int, width: int) -> None:
    """Per-slab observability for the sp/dpsp routers: a ``slab`` span
    (child of the backend's pileup_dispatch span) plus a per-strategy
    seconds histogram.  The dp path rides the identical instrumentation
    in ``ops.pileup.run_tuned_slab``."""
    from .. import observability as obs

    obs.tracer().complete("slab", t0, strategy=key, n_rows=n_rows,
                          width=width)
    reg = obs.metrics()
    reg.observe(f"pileup/slab_sec/{key}", time.perf_counter() - t0)
    # same run-level slab counter the dp/single-device path keeps
    # (ops.pileup.run_tuned_slab): the shard-mode decision's measured
    # per-slab join divides phase/pileup_dispatch_sec by this
    reg.add("pileup/slabs", 1)


def block_for(total_len: int, n_devices: int) -> int:
    """Rows of the position axis each device owns (+1 covers the
    scatter path's sacrificial row inside the pad)."""
    return -(-(total_len + 1) // n_devices)


def split_wide_rows(starts: np.ndarray, codes: np.ndarray, w: int,
                    halo: int, padded_len: int):
    """Split rows wider than the halo into halo-width pieces.

    Exact because segment rows are position-contiguous.  Trailing all-PAD
    pieces may nominally start past the genome; their starts clamp to the
    pad region (their cells are PAD and never count).  Shared by the sp
    and dpsp accumulators so the clamp/pad semantics cannot diverge.
    Returns (starts, codes, halo) — the new bucket width is the halo.
    """
    k = -(-w // halo)
    wp = k * halo
    if wp != w:
        codes = np.concatenate(
            [codes, np.full((len(codes), wp - w), PAD_CODE,
                            dtype=np.uint8)], axis=1)
    starts = (starts[:, None]
              + (np.arange(k) * halo)[None, :]).reshape(-1)
    starts = np.minimum(starts, padded_len - 1).astype(np.int32)
    return starts, codes.reshape(-1, halo), halo


def real_row_mask(starts: np.ndarray, codes: np.ndarray) -> np.ndarray:
    """True for real rows; False for encoder pad rows.

    Pad rows are all-PAD code rows parked at start 0 (encoder slab
    pow2 padding).  They count nothing anywhere — PAD cells
    self-suppress — but routed into kernel planners they inflate
    device 0 / tile 0, and fed to the shard-mode model they read as
    phantom clustering.  The ONE definition of the invariant, shared
    by the sp/dpsp routers and parallel.auto.slab_stats (a real row
    may still START with PAD cells — maxdel-skipped leading gaps — so
    consumers must never rely on this mask for correctness, only for
    planning).
    """
    real = np.ones(len(starts), dtype=bool)
    zero = np.nonzero(starts == 0)[0]
    if len(zero):
        real[zero[(codes[zero] == PAD_CODE).all(axis=1)]] = False
    return real


def plan_mxu_grids(s_local: np.ndarray, reals: np.ndarray, w: int,
                   local_len: int, max_blowup: float = 16.0):
    """Per-unit MXU slot plans over a shared local space, uniform E.

    ``s_local`` is ``[D, R]`` local starts (a routed slot grid); real
    rows occupy each unit's row prefix (``reals[d]`` of them —
    route_to_slots packs them contiguously); pad slots all map to tile
    0's rank ``E`` slot, which ``rows_per_tile = E+1`` reserves (their
    PAD codes one-hot to zero, and slot collisions among identical pad
    rows are harmless).  Shared by the sp and dpsp routed-kernel paths
    (verdict r4 #4).  Returns ``(slots [D, R], e1, n_tiles)`` or None
    on padding blowup.
    """
    from ..ops import mxu_pileup
    from ..ops.pileup import round_rows_grid

    tile = mxu_pileup.TILE_POSITIONS
    nt = -(-local_len // tile)
    d_units = s_local.shape[0]
    hists = []
    emax = 1
    for d in range(d_units):
        tile_of = s_local[d, : reals[d]] // tile
        per_tile = np.bincount(tile_of, minlength=nt)
        hists.append((tile_of, per_tile))
        emax = max(emax, int(per_tile.max(initial=1)))
    e = round_rows_grid(emax)
    total_real = max(1, int(reals.sum()))
    if d_units * nt * (e + 1) / total_real > max_blowup:
        return None
    slots = np.full(s_local.shape, e, dtype=np.int32)
    for d, (tile_of, per_tile) in enumerate(hists):
        slots[d, : reals[d]] = mxu_pileup.assign_slots(
            tile_of, per_tile, e + 1)
    return slots, e + 1, nt


def route_to_slots(targets: np.ndarray, n_targets: int, r: int,
                   starts: np.ndarray, codes: np.ndarray,
                   pin_starts: np.ndarray):
    """Counting-sort rows into an ``[n_targets, r]`` slot grid.

    Shared by the sp (targets = owning devices) and dpsp (targets = macro
    position blocks) routers so the slot math and pad-slot pinning cannot
    diverge.  Unfilled slots carry ``pin_starts[target]`` (a start inside
    the target's block, so shifted scatter indices stay in range) and
    all-PAD codes (which never count).  Returns
    ``(s_grid [n_targets, r] int32, c_grid [n_targets, r, w] uint8)``.
    """
    w = codes.shape[1]
    order = np.argsort(targets, kind="stable")
    t_sorted = targets[order]
    per = np.bincount(t_sorted, minlength=n_targets)
    s_grid = np.broadcast_to(
        pin_starts.astype(np.int32)[:, None], (n_targets, r)).copy()
    c_grid = np.full((n_targets, r, w), PAD_CODE, dtype=np.uint8)
    hi = np.cumsum(per)
    flat = (t_sorted * r
            + (np.arange(len(targets)) - (hi - per)[t_sorted]))
    s_grid.reshape(-1)[flat] = starts[order]
    c_grid.reshape(-1, w)[flat] = codes[order]
    return s_grid, c_grid


class ShardedCountsBase:
    """Position-sharded count-tensor state + vote, layout-agnostic.

    ``pos_axes`` is the mesh-axis ordering of the position-axis sharding:
    the flattened ``("dp", "sp")`` ring for the pure dp and sp pipelines,
    ``("sp", "dp")`` for the dp x sp product mode (parallel/dpsp.py),
    whose reduce-scatter over ``dp`` leaves device (d, s) holding
    sub-block d of macro-block s — i.e. global block ``s * n_dp + d``.
    Every state/vote/stats spec derives from it, so the layouts cannot
    drift between accumulation and the tail.
    """

    def __init__(self, mesh: Mesh, total_len: int,
                 pos_axes: Tuple[str, str] = ALL, wire: str = "packed5"):
        self.mesh = mesh
        self.n = mesh.size
        self.pos_axes = pos_axes
        self.total_len = total_len
        self.block = block_for(total_len, self.n)
        self.padded_len = self.block * self.n
        #: resolved row wire codec (sam2consensus_tpu/wire); the routers
        #: ship the SAME slab payloads as the single-device path, so the
        #: same codec applies to every routed/windowed/dp slice
        self.wire = wire
        self._wire_decode = None               # lazily built sharded jit

        # counts allocate lazily: memory-bound tests compile the sharded
        # accumulate at chromosome scale (250 Mbp) via ShapeDtypeStruct
        # without ever materializing the tensor
        self._counts = None
        # every placement this accumulator makes comes from the ONE
        # partition-rule table (parallel/partition.py): named arrays →
        # PartitionSpecs, matched once here, turned into shard/gather
        # fns that are multi-host aware (per-process window shipping,
        # d2h-billed gathers).  _row_spec/_mat_spec remain as derived
        # views because the jitted decode needs raw shardings for
        # out_shardings.
        self.partition_specs = match_partition_rules(
            partition_rules(pos_axes), {
                "counts": jax.ShapeDtypeStruct(
                    (self.padded_len, NUM_SYMBOLS), jnp.int32),
                "row_starts": jax.ShapeDtypeStruct((0,), jnp.int32),
                "row_codes": jax.ShapeDtypeStruct((0, 0), jnp.uint8),
                "kernel_rank": jax.ShapeDtypeStruct((0,), jnp.int32),
                "kernel_aux": jax.ShapeDtypeStruct((0, 0), jnp.int32),
                "wire_lane": jax.ShapeDtypeStruct((0,), jnp.uint8),
                "vote_syms": jax.ShapeDtypeStruct((0, 0), jnp.uint8),
                "insertion_bank": jax.ShapeDtypeStruct((0, 0), jnp.int32),
                "thresholds": jax.ShapeDtypeStruct((0,), jnp.uint8),
                "contig_offsets": jax.ShapeDtypeStruct((0,), jnp.int32),
                "site_keys": jax.ShapeDtypeStruct((0,), jnp.int32),
                "contig_sums": jax.ShapeDtypeStruct((0,), jnp.int32),
                "site_cov": jax.ShapeDtypeStruct((0,), jnp.int32),
            })
        self._shard_fns, self._gather_fns = make_shard_and_gather_fns(
            mesh, self.partition_specs)
        publish_mesh_gauges(mesh)
        self._row_spec = NamedSharding(
            mesh, self.partition_specs["row_starts"])
        self._mat_spec = NamedSharding(
            mesh, self.partition_specs["row_codes"])
        self.bytes_h2d = 0                     # wire accounting for bench

    def put_rows(self, starts: np.ndarray, codes: np.ndarray):
        """Ship one slice's row operands, wire-encoded when it pays.

        Returns ``(starts_dev [S] row-sharded, packed_dev [S, ⌈W/2⌉])``
        — exactly what every shard_map accumulate kernel consumes — so
        the dp scatter, the sp window/routed paths and the dpsp product
        router all compress through ONE shipping point.  The slice is
        encoded in ``n`` chunks matching the row sharding (each device's
        contiguous rows form one delta chain), and the decode runs as a
        sharded jit with the legacy operand shardings, so the unpack is
        local to the owning device.  Slices whose row count does not
        chunk evenly, or whose encoding would not shrink, ship the
        legacy packed5 lanes (recorded per slab).
        """
        from ..ops.pileup import (account_wire, encode_wire_slab,
                                  pack_nibbles)
        from ..wire import account_h2d
        from ..wire import codec as wire_codec

        raw = wire_codec.packed5_slab_bytes(len(starts), codes.shape[1])
        slab = encode_wire_slab(self.wire, starts, codes, chunks=self.n)
        if slab is None:
            packed = pack_nibbles(codes)
            self.bytes_h2d += starts.nbytes + packed.nbytes
            account_h2d(starts.nbytes + packed.nbytes)
            account_wire("packed5", starts.nbytes + packed.nbytes, raw)
            return (self._shard_fns["row_starts"](starts),
                    self._shard_fns["row_codes"](packed))
        if self._wire_decode is None:
            from ..wire import device as wire_device

            self._wire_decode = wire_device.decode_fn(
                out_shardings=(self._row_spec, self._mat_spec))
        # every lane is chunk-major: sharding dim 0 over the flattened
        # mesh puts each chunk's lanes on the device that owns its rows
        # (rule ``wire_lane``; on a process-spanning mesh each host
        # ships only the chunks its own devices decode)
        ops = tuple(self._shard_fns["wire_lane"](a)
                    for a in slab.arrays())
        self.bytes_h2d += slab.wire_bytes
        account_h2d(slab.wire_bytes)
        account_wire("delta8", slab.wire_bytes, raw)
        return self._wire_decode(*ops, width=slab.width,
                                 sentinel=slab.sentinel)

    def ship_kernel_operand(self, a: np.ndarray) -> jax.Array:
        """Ship one routed-kernel side operand (MXU slot grids, Pallas
        rank/block lanes) under the partition table: 1-d lanes ride the
        ``kernel_rank`` rule, matrices ``kernel_aux`` — the same row
        ring as the slab operands they accompany."""
        name = "kernel_rank" if a.ndim == 1 else "kernel_aux"
        return self._shard_fns[name](a)

    def sync(self) -> None:
        """Profiling barrier (S2C_SYNC_ACCUMULATE): block until every
        dispatched accumulation has landed in the sharded count tensor —
        see ops.pileup.PileupAccumulator.sync.  One-element fetch (the
        tunneled runtime returns early from block_until_ready); no-op
        before the first add() materializes the counts."""
        if self._counts is not None:
            np.asarray(self._counts[(0,) * self._counts.ndim])

    def _flat_pos_index(self):
        """Device's block index along the position axis (traceable; call
        inside shard_map)."""
        a0, a1 = self.pos_axes
        return (jax.lax.axis_index(a0) * self.mesh.shape[a1]
                + jax.lax.axis_index(a1))

    # -- state ------------------------------------------------------------
    @property
    def counts(self) -> jax.Array:
        """Position-sharded counts including pad rows ([padded_len, 6])."""
        if self._counts is None:
            # must be jax-owned (jnp, not np): device_put of raw numpy can
            # zero-copy alias host memory on cpu, and the fused tail /
            # scatter kernels DONATE this buffer — aliased donation
            # corrupts warm serve jobs (fleet byte-identity catches it)
            self._counts = self._shard_fns["counts"](
                jnp.zeros((self.padded_len, NUM_SYMBOLS), dtype=jnp.int32))
            self._track_counts()
        return self._counts

    def counts_host(self) -> np.ndarray:
        """Valid counts on host, ``[total_len, 6]``."""
        return self._gather_fns["counts"](self.counts)[: self.total_len]

    def restore(self, counts: np.ndarray) -> None:
        """Load checkpointed counts (``[total_len, 6]``), re-sharded."""
        padded = np.zeros((self.padded_len, NUM_SYMBOLS), dtype=np.int32)
        padded[: self.total_len] = counts
        # jnp.asarray first: same donation/aliasing constraint as counts()
        self._counts = self._shard_fns["counts"](jnp.asarray(padded))
        self._track_counts()

    def _track_counts(self) -> None:
        """Residency accounting for the sharded count tensor — once per
        accumulator (lazy alloc and checkpoint restore both land here),
        released with the accumulator (observability/memplane.py).  On
        a process-spanning mesh THIS process is resident for only its
        addressable fraction of the tensor — billing the global bytes
        would make every host's tracked peak read as if it held the
        whole genome, exactly the per-host headroom the mesh_shards
        capacity planner needs to see."""
        if not getattr(self, "_mem_tracked", False):
            self._mem_tracked = True
            from ..observability import memplane

            n_local = sum(
                d.process_index == jax.process_index()
                for d in np.asarray(self.mesh.devices).reshape(-1))
            frac = n_local / max(1, self.n)
            memplane.track_obj(
                "counts", self,
                int(self.padded_len * NUM_SYMBOLS * 4 * frac))

    # -- vote -------------------------------------------------------------
    def vote(self, thr_enc: np.ndarray, min_depth: int) -> np.ndarray:
        """Position-sharded vote; returns host syms ``[T, total_len]``.

        Sequence parallelism with zero extra communication: the vote is
        elementwise per position (cutoffs computed on device,
        ``ops.cutoff``), so it runs on the resident blocks.
        """
        from ..ops.vote import vote_block

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(self.pos_axes, None), P(None, None)),
                 out_specs=P(None, self.pos_axes))
        def voted(counts_blk, enc):
            syms, _cov = vote_block(counts_blk, enc, min_depth)
            return syms

        syms = jax.jit(voted)(self.counts, jnp.asarray(thr_enc))
        return self._gather_fns["vote_syms"](syms)[:, : self.total_len]

    def tail_stats(self, offsets: np.ndarray, site_keys: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """Device-side replacement for the full-coverage host fetch.

        Returns host ``(contig_sums [C], site_cov [K])`` — the only
        coverage facts the host rendering needs (ops/fused.py) — without
        moving the [L] coverage vector off device.  Per-contig sums come
        from local prefix sums differenced at the contig offsets and one
        psum; per-site coverage from an owned-block gather and one psum.
        """
        from jax import lax

        block = self.padded_len // self.n

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(self.pos_axes, None), P(None), P(None)),
                 out_specs=(P(None), P(None)))
        def stats(counts_blk, offs, keys):
            cov_blk = counts_blk.sum(axis=-1)                  # [Lb]
            i = self._flat_pos_index()
            lo = i * block
            prefix = jnp.concatenate(
                [jnp.zeros(1, dtype=cov_blk.dtype), jnp.cumsum(cov_blk)])
            part = prefix[jnp.clip(offs - lo, 0, block)]       # [C+1]
            gsum = lax.psum(part, ALL)     # global prefix at each offset
            contig_sums = gsum[1:] - gsum[:-1]
            owned = (keys >= lo) & (keys < lo + block)
            local = jnp.where(
                owned, cov_blk[jnp.clip(keys - lo, 0, block - 1)], 0)
            site_cov = lax.psum(local, ALL)
            return contig_sums.astype(jnp.int32), site_cov.astype(jnp.int32)

        if len(site_keys) == 0:
            site_keys = np.full(1, -1, dtype=np.int32)
        contig_sums, site_cov = jax.jit(stats)(
            self.counts, jnp.asarray(offsets.astype(np.int32)),
            jnp.asarray(site_keys.astype(np.int32)))
        return (self._gather_fns["contig_sums"](contig_sums)
                .astype(np.int64),
                self._gather_fns["site_cov"](site_cov).astype(np.int64))
