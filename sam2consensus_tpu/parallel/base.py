"""Shared machinery for the sharded accumulators (dp and sp layouts).

Both pipelines keep the count tensor position-sharded across the
flattened ("dp", "sp") mesh axes and share the same state surface
(``counts`` / ``counts_host`` / ``restore`` for checkpointing, and the
position-sharded ``vote``); only *accumulation* differs — dp scatters
full-length local tensors and reduce-scatters, sp routes rows to the
owning position block and halo-exchanges.  Keeping the common parts here
means a vote or checkpoint fix cannot silently diverge the two modes.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..constants import NUM_SYMBOLS

#: both mesh axes flattened: every collective treats the mesh as one ring
ALL = ("dp", "sp")


def block_for(total_len: int, n_devices: int) -> int:
    """Rows of the position axis each device owns (+1 covers the
    scatter path's sacrificial row inside the pad)."""
    return -(-(total_len + 1) // n_devices)


class ShardedCountsBase:
    """Position-sharded count-tensor state + vote, layout-agnostic."""

    def __init__(self, mesh: Mesh, total_len: int):
        self.mesh = mesh
        self.n = mesh.size
        self.total_len = total_len
        self.block = block_for(total_len, self.n)
        self.padded_len = self.block * self.n

        self._counts = jax.device_put(
            jnp.zeros((self.padded_len, NUM_SYMBOLS), dtype=jnp.int32),
            NamedSharding(mesh, P(ALL, None)))
        self._row_spec = NamedSharding(mesh, P(ALL))
        self._mat_spec = NamedSharding(mesh, P(ALL, None))

    # -- state ------------------------------------------------------------
    @property
    def counts(self) -> jax.Array:
        """Position-sharded counts including pad rows ([padded_len, 6])."""
        return self._counts

    def counts_host(self) -> np.ndarray:
        """Valid counts on host, ``[total_len, 6]``."""
        return np.asarray(self._counts)[: self.total_len]

    def restore(self, counts: np.ndarray) -> None:
        """Load checkpointed counts (``[total_len, 6]``), re-sharded."""
        padded = np.zeros((self.padded_len, NUM_SYMBOLS), dtype=np.int32)
        padded[: self.total_len] = counts
        self._counts = jax.device_put(
            jnp.asarray(padded), NamedSharding(self.mesh, P(ALL, None)))

    # -- vote -------------------------------------------------------------
    def vote(self, t_luts: np.ndarray, min_depth: int
             ) -> Tuple[np.ndarray, np.ndarray]:
        """Position-sharded vote; returns host (syms [T, total_len], cov).

        Sequence parallelism with zero extra communication: the vote is
        elementwise per position, so it runs on the resident blocks.
        """
        from ..ops.vote import vote_block

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(ALL, None), P(None, None)),
                 out_specs=(P(None, ALL), P(ALL)))
        def voted(counts_blk, luts):
            return vote_block(counts_blk, luts, min_depth)

        syms, cov = jax.jit(voted)(self._counts, jnp.asarray(t_luts))
        return (np.asarray(syms)[:, : self.total_len],
                np.asarray(cov, dtype=np.int64)[: self.total_len])
