"""Model-driven shard-mode selection: dp vs sp vs dpsp from observed data.

Replaces the round-4 single test ``total_len >= 2^25`` (round-4 verdict
#3).  All three layouts ship the same row payload; what differs is the
per-slab OVERHEAD each adds on top, priced here in seconds from the
observed first slab and the calibrated machine constants:

* **dp** adds one reduce-scatter of the full ``[Lp, 6]`` int32 tensor
  per slab (each device sends ~``L*24*(n-1)/n`` bytes over ICI) plus an
  O(L) local-tensor transient — zero host routing, so it wins whenever
  the genome is small relative to a slab's row bytes;
* **sp** adds only a ``[H, 6]`` halo shift (~free) but pays host-side
  routing (one counting sort + slot-grid materialization per slab) and
  ships the dense grid — ``n * max_rows_per_device`` row slots, which
  inflates by the observed per-device imbalance.  Coordinate-sorted
  slabs take sp's window strategy instead (even split, no routing), so
  imbalance only bills the residual unsorted fraction;
* **dpsp** splits reads evenly across dp (no routing, imbalance-immune)
  and routes among only ``n_sp`` macro blocks, paying a
  ``L/n_sp * 24``-byte reduce-scatter per slab — between the other two
  on both axes, the right pick when a huge genome meets deep coverage
  on a true 2-D mesh.

The constants are deliberately coarse (decisions here flip on order-of-
magnitude ratios, not percents) and env-overridable for other rigs:
``S2C_ICI_GBPS`` (per-device collective bandwidth), ``S2C_ROUTE_MROWS``
(host routing rate).  The row-payload wire term is common to all modes
and cancels, so the link rate does not appear.  The decision table is
pinned by tests/test_shard_auto.py; the measured sweep lives in
``tools/shard_sweep.py`` → ``campaign/shard_sweep_r05.jsonl``.
"""

from __future__ import annotations

import os

import numpy as np

#: int32 count-lane bytes per genome position ([*, 6] int32)
_POS_BYTES = 24

#: sp's window-strategy position cap — the ONE shared definition
#: (constants.SP_WINDOW_CAP, also PositionShardedConsensus.WINDOW_CAP);
#: a drifted copy here would mis-model which slabs the window path
#: absorbs.  Imported from the jax-free constants module so the pure
#: cost model stays jax-free (ADVICE r5 #4).
from ..constants import SP_WINDOW_CAP as _WINDOW_CAP  # noqa: E402


def _ici_bps() -> float:
    """Per-device collective bandwidth for reduce-scatter terms.  The
    default is deliberately conservative for a v5e ICI (~45 GB/s links);
    the 8-virtual-device CPU "mesh" moves memcpy-speed (~5 GB/s), which
    the same default models within the decision's tolerance."""
    return float(os.environ.get("S2C_ICI_GBPS", "10")) * 1e9


def _dcn_bps() -> float:
    """Per-host cross-host collective bandwidth on a process-spanning
    mesh (``jax.distributed``).  DCN is the slow fabric the mesh design
    keeps counts off of — but the per-slab collectives every layout
    pays (reduce-scatter, window psum, halo shift) DO cross it, so on
    a multi-host mesh they bill this rate, not ICI.  Default is
    conservative for data-center ethernet (and the gloo CPU stand-in
    moves loopback-speed, which the same order of magnitude covers)."""
    return float(os.environ.get("S2C_DCN_GBPS", "1")) * 1e9


def _route_rows_per_sec() -> float:
    """Host routing throughput: counting sort + slot-grid scatter,
    measured ~5-20 M rows/s on one core (numpy argsort dominated)."""
    return float(os.environ.get("S2C_ROUTE_MROWS", "8")) * 1e6


def _dp_max_local_bytes() -> float:
    """dp's per-device transient is a FULL-length [Lp, 6] int32 tensor
    per slab; past this budget dp is memory-infeasible — which is the
    original reason position sharding exists (SURVEY.md §5
    long-context), so the gate is part of the model, not a tuning."""
    return float(os.environ.get("S2C_DP_MAX_LOCAL_GB", "2")) * 2**30


#: fixed per-slab plumbing the sp/dpsp paths add over dp (grid
#: materialization, extra host passes, window dispatch) — a tie-break
#: keeping tiny workloads on the simpler dp pipeline
_SP_FIXED_SEC = 2e-4


def slab_stats(buckets, total_len: int, wire: str = "packed5") -> tuple:
    """(rows, row_bytes, max_width, peak_frac, sorted_frac) of one
    decoded slab for :func:`choose_shard_mode`.

    ``wire`` is the run's resolved row wire codec
    (``sam2consensus_tpu/wire``): the routers ship the same slab
    payloads as the single-device path, so the model's link terms must
    bill POST-codec bytes — a delta8 run's grid-inflation penalty is
    roughly halved, which can flip a clustered-tunnel decision from
    dpsp back to sp (pinned by tests/test_wire.py).

    ``peak_frac`` is the heaviest 1/64th-of-genome bin's share of the
    slab's rows — a device owning that region of the position axis
    would receive ``peak_frac * rows``, so a router's slot grid (sized
    by the fullest target) inflates to ``~peak_frac * n_targets``;
    ``sorted_frac`` is the fraction of rows in buckets the sp WINDOW
    strategy would absorb, judged by the window path's real gates
    (parallel.sp: pow2 span within the cap and the density bound).
    """
    from ..wire.codec import row_bytes_estimate

    rows = 0
    row_bytes = 0
    max_w = 0
    window_rows = 0
    bins = np.zeros(64, dtype=np.int64)
    scale = max(1, total_len)
    for w, (starts, codes) in buckets.items():
        from .base import real_row_mask

        s = np.asarray(starts)
        # drop encoder pad rows: they count nothing and would otherwise
        # pile into bin 0, reading as phantom clustering on every
        # shallow slab (pow2 slab padding can double the row count)
        keep = real_row_mask(s, np.asarray(codes))
        if not keep.all():
            s = s[keep]
        if len(s) == 0:
            continue
        rows += len(s)
        row_bytes += int(len(s) * row_bytes_estimate(w, wire))
        max_w = max(max_w, w)
        span = float(s.max()) + w - float(s.min())
        wp = 1 << max(10, int(span - 1).bit_length())
        if (wp * _POS_BYTES <= 16 * len(s) * w
                and wp <= min(_WINDOW_CAP, total_len)):
            window_rows += len(s)
        idx = (s / scale * 63).astype(np.int64)
        bins += np.bincount(np.clip(idx, 0, 63), minlength=64)
    if rows == 0:
        return 0, 0, 0, 1.0, 0.0
    return (rows, row_bytes, max_w, float(bins.max() / rows),
            window_rows / rows)


def choose_shard_mode(total_len: int, n_devices: int, mesh_shape: dict,
                      rows_per_slab: int, row_bytes_per_slab: int,
                      peak_frac: float, sorted_frac: float,
                      halo: int, link_bps: float,
                      n_hosts: int = 1) -> str:
    """Pick dp / sp / dpsp by modeled per-slab overhead (module doc);
    see :func:`shard_mode_costs` for the full priced table (the
    decision ledger records it alongside the pick)."""
    mode, _costs = shard_mode_costs(
        total_len, n_devices, mesh_shape, rows_per_slab,
        row_bytes_per_slab, peak_frac, sorted_frac, halo, link_bps,
        n_hosts=n_hosts)
    return mode


def shard_mode_costs(total_len: int, n_devices: int, mesh_shape: dict,
                     rows_per_slab: int, row_bytes_per_slab: int,
                     peak_frac: float, sorted_frac: float,
                     halo: int, link_bps: float,
                     n_hosts: int = 1) -> tuple:
    """(chosen_mode, {mode: modeled_per_slab_overhead_sec}) — the pick
    plus every feasible candidate's priced cost, so the decision ledger
    (observability/ledger.py) can record prediction AND alternatives.

    The routers' dense slot grids ship ``targets * max_rows_per_target``
    row slots, so a clustered-but-not-window-eligible slab inflates the
    HOST→DEVICE wire by up to the target count — ``n`` for sp, only
    ``n_sp`` for dpsp (its dp axis splits evenly, imbalance-immune).
    That inflation bills the LINK (the scarce resource on a tunneled
    chip), which is exactly where dpsp earns its reduce-scatter tax:
    huge genome + clustered reads + 2-D mesh.  ``link_bps`` is the
    placement model's calibrated rate (backends.jax_backend
    ``_link_constants``).
    """
    n = max(1, n_devices)
    n_sp = max(1, mesh_shape.get("sp", 1))
    padded = -(-(total_len + 1) // n) * n
    # on a process-spanning mesh every flattened-ring collective
    # crosses host boundaries: bill the slow fabric, not ICI — this is
    # what makes dp's full-tensor reduce-scatter lose to sp's
    # O(halo)/O(window) traffic on multi-host meshes even when the
    # genome would fit dp's memory gate
    ici = _ici_bps() if max(1, int(n_hosts)) == 1 \
        else min(_ici_bps(), _dcn_bps())
    route = _route_rows_per_sec()
    rows = max(1, rows_per_slab)
    rb = max(1, row_bytes_per_slab)

    cost_dp = padded * _POS_BYTES / ici
    # routing and grid inflation bill only the unsorted residue; the
    # window strategy absorbs coordinate-sorted slabs at the cost of a
    # window-sized psum instead
    unsorted = max(0.0, 1.0 - sorted_frac)
    # the slot grid sizes by the fullest target: peak_frac * n_targets
    # for sp's n devices, bounded by n_sp macro blocks for dpsp
    infl_sp = max(0.0, min(peak_frac * n, n) - 1.0)
    infl_dpsp = max(0.0, min(peak_frac * n_sp, n_sp) - 1.0)
    window = sorted_frac * min(padded, _WINDOW_CAP) * _POS_BYTES / ici
    cost_sp = (_SP_FIXED_SEC + window
               + rows * unsorted / route
               + rb * unsorted * infl_sp / link_bps
               + halo * _POS_BYTES / ici)
    feasible_sp = padded // n >= halo
    feasible_dpsp = (min(mesh_shape.get("dp", 1), n_sp) > 1
                     and padded // n_sp >= halo)
    cost_dpsp = (_SP_FIXED_SEC + window
                 + rows * unsorted / route
                 + rb * unsorted * infl_dpsp / link_bps
                 + padded // n_sp * _POS_BYTES / ici
                 + halo * _POS_BYTES / ici)

    costs = {}
    # dp's transient memory gate comes first: the full-length local
    # tensor is the thing position sharding exists to avoid
    if padded * _POS_BYTES <= _dp_max_local_bytes():
        costs["dp"] = cost_dp
    if feasible_sp:
        costs["sp"] = cost_sp
    if feasible_dpsp:
        costs["dpsp"] = cost_dpsp
    if not costs:
        return "dp", {}                # nothing feasible: dp, best effort
    return min(costs, key=costs.get), costs
