"""Rule-driven PartitionSpecs for every named array the mesh touches.

Before this module each sharded accumulator placed its operands with
ad-hoc ``NamedSharding`` literals scattered through ``put_rows`` /
``fetch_host`` call sites — fine on one host, but a multi-host mesh
(``jax.distributed`` over DCN) needs every placement decision in ONE
auditable place: which arrays shard over the position ring, which ride
the row ring, which stay replicated, and which may legally cross the
slow fabric on the way home.  The shape is the classic LLM-scale
pattern (SNIPPETS.md [2]/[3]): a regex→PartitionSpec *rule table*
matched against array NAMES, plus factories that turn the matched
specs into shard/gather functions.

* :data:`PARTITION_RULES` / :func:`partition_rules` — the table.  One
  ordered list of ``(regex, PartitionSpec)``; first match wins; a name
  no rule covers raises (placement must never be accidental).
* :func:`match_partition_rules` — names → specs, scalars replicated.
* :func:`make_shard_and_gather_fns` — specs → per-name shard fns
  (host array → mesh-placed ``jax.Array``; on a process-spanning mesh
  each host ships ONLY its addressable window's rows) and gather fns
  (mesh array → host, billed through the ``wire`` d2h choke point).

The shard path is the multi-host rung of ``put_rows``: on a
single-controller mesh it is a plain ``device_put`` (XLA splits
locally, no copy crosses any fabric it shouldn't); when the mesh spans
processes it assembles the global array from per-device slices of the
host value via ``make_array_from_single_device_arrays``, so the bytes
leaving THIS host are exactly its own devices' shards — the DCN never
carries another host's rows.  Both paths bill ``wire.account_h2d``
with the LOCAL bytes only and feed the ``mesh/*`` gauges the
``s2c_mesh_*`` OpenMetrics family renders.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Mapping, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: both mesh axes flattened: every collective treats the mesh as one ring
#: (mirrors parallel.base.ALL; redefined here so the table imports
#: jax-light)
ALL = ("dp", "sp")


def partition_rules(pos_axes: Tuple[str, str] = ALL
                    ) -> Tuple[Tuple[str, P], ...]:
    """The partition-rule table for one accumulator layout.

    ``pos_axes`` is the mesh-axis ordering of the position sharding —
    the flattened ``("dp", "sp")`` ring for the dp/sp pipelines,
    ``("sp", "dp")`` for the dpsp product mode — exactly the knob
    ``ShardedCountsBase`` already threads through every spec.

    Rules are ordered; the FIRST match wins.  Names:

    * ``counts`` — the position-sharded count tensor ``[padded, 6]``;
    * ``row_starts`` / ``kernel_rank`` — per-row int32 lanes, sharded
      over the flattened row ring (each device owns its slice's rows);
    * ``row_codes`` / ``kernel_aux`` — per-row matrices (packed nibble
      lanes, MXU slot grids), row-sharded, trailing dim local;
    * ``wire_lane*`` — chunk-major delta8 codec lanes: sharding dim 0
      over the ring lands each chunk's lanes on the device that owns
      its rows, so the decode is shard-local by construction;
    * ``vote_syms`` — the vote's ``[T, padded]`` symbol planes,
      position-sharded on the SECOND axis (threshold axis replicated);
    * ``insertion_bank*`` — host-built insertion evidence shipped for
      device-side filtering: row-sharded like every per-row operand;
    * ``thresholds`` / ``contig_offsets`` / ``site_keys`` /
      ``contig_sums`` / ``site_cov`` — small control/stat vectors,
      replicated (every device needs them whole; crossing DCN for
      these is the design: base strings and stats move, counts don't).
    """
    pos = tuple(pos_axes)
    return (
        (r"^counts$",               P(pos, None)),
        (r"^row_starts$",           P(ALL)),
        (r"^kernel_rank$",          P(ALL)),
        (r"^row_codes$",            P(ALL, None)),
        (r"^kernel_aux$",           P(ALL, None)),
        (r"^wire_lane(_[a-z0-9]+)?$", P(ALL)),
        (r"^vote_syms$",            P(None, pos)),
        (r"^insertion_bank(_[a-z0-9]+)?$", P(ALL, None)),
        (r"^(thresholds|contig_offsets|site_keys|contig_sums|site_cov)$",
         P()),
    )


#: the default (flattened-ring) table — what dp and sp use
PARTITION_RULES: Tuple[Tuple[str, P], ...] = partition_rules()


def matching_rules(rules: Sequence[Tuple[str, P]], name: str):
    """Every rule whose regex matches ``name`` (test surface: the
    canonical names must each match EXACTLY one rule)."""
    return [(pat, spec) for pat, spec in rules if re.search(pat, name)]


def match_partition_rules(rules: Sequence[Tuple[str, P]],
                          named: Mapping[str, object]) -> Dict[str, P]:
    """Map array names to PartitionSpecs via the rule table.

    ``named`` maps name → array-like (anything with ``ndim``/``shape``,
    including ``jax.ShapeDtypeStruct``) or a scalar.  Scalars and
    0-d arrays replicate (``P()``) without consulting the table —
    there is nothing to shard.  A non-scalar name no rule matches
    raises ``ValueError``: silent replication of a tensor that should
    have been sharded is exactly the OOM this module exists to prevent.
    """
    specs: Dict[str, P] = {}
    for name, arr in named.items():
        ndim = getattr(arr, "ndim", None)
        if ndim is None:
            ndim = np.ndim(arr)
        if ndim == 0:
            specs[name] = P()
            continue
        hits = matching_rules(rules, name)
        if not hits:
            raise ValueError(
                f"partition rules don't cover array {name!r} "
                f"(shape {getattr(arr, 'shape', ())}): add a rule to "
                f"parallel.partition.partition_rules — placement must "
                f"never be accidental")
        spec = hits[0][1]
        if len([a for a in spec if a is not None]) > ndim:
            raise ValueError(
                f"partition rule {hits[0][0]!r} wants "
                f"{len(tuple(spec))} dims but {name!r} has {ndim}")
        specs[name] = spec
    return specs


def _record_mesh_bytes(counter: str, nbytes: int) -> None:
    """Bill one shard/gather transfer to the ``mesh/*`` plane (the
    ``s2c_mesh_*`` exposition family; observability is optional and
    must never break shipping)."""
    if nbytes <= 0:
        return
    try:
        from .. import observability as obs

        obs.metrics().add(counter, int(nbytes))
    except Exception:
        pass


def shard_to_mesh(arr, sharding: NamedSharding,
                  force_assemble: bool = False) -> jax.Array:
    """Place one host array on the mesh under ``sharding``.

    Single-controller meshes take the plain ``device_put``.  When the
    mesh spans processes, the host value (identical on every process —
    multi-controller SPMD feeds the same globals) is sliced to THIS
    process's addressable windows and assembled with
    ``make_array_from_single_device_arrays``: each host ships only the
    rows its own devices own, so count-plane operands never ride DCN.
    ``force_assemble`` takes the per-device assembly path even on a
    single-controller mesh — the test surface for the multi-host rung
    (every virtual-device test can exercise the exact code a real DCN
    mesh runs).

    Caveat: ``device_put`` of a raw numpy array may zero-copy alias the
    host buffer (cpu backend).  Callers whose placed array is later
    DONATED (the counts plane) must pass a jax-owned array
    (``jnp.asarray`` first) or the donation scribbles over aliased
    memory.
    """
    mesh_devs = getattr(sharding, "mesh", None)
    spans = force_assemble or (
        jax.process_count() > 1 and mesh_devs is not None and any(
            d.process_index != jax.process_index()
            for d in np.asarray(sharding.mesh.devices).reshape(-1)))
    if not spans:
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    idx_map = sharding.addressable_devices_indices_map(arr.shape)
    local = [(d, np.ascontiguousarray(arr[idx]))
             for d, idx in idx_map.items()]
    _record_mesh_bytes(f"mesh/shard_bytes/{jax.process_index()}",
                       sum(s.nbytes for _d, s in local))
    shards = [jax.device_put(s, d) for d, s in local]
    return jax.make_array_from_single_device_arrays(
        arr.shape, sharding, shards)


def gather_from_mesh(x: jax.Array) -> np.ndarray:
    """Host copy of a mesh-placed array, billed through the wire's d2h
    choke point; process-spanning shards assemble via one
    ``process_allgather`` (the only collective that legally moves
    count-plane data over DCN — and the tails that ride it are base
    strings and stats, not counts)."""
    from ..wire import fetch_d2h

    if x.is_fully_addressable or x.sharding.is_fully_replicated:
        return fetch_d2h(x)
    from jax.experimental import multihost_utils

    out = fetch_d2h(multihost_utils.process_allgather(x, tiled=True))
    _record_mesh_bytes("mesh/gather_bytes", out.nbytes)
    return out


def make_shard_and_gather_fns(mesh: Mesh, specs: Mapping[str, P]
                              ) -> Tuple[Dict[str, Callable],
                                         Dict[str, Callable]]:
    """Per-name shard/gather functions from matched PartitionSpecs.

    ``shard_fns[name](host_array)`` returns the mesh-placed
    ``jax.Array`` (multi-host aware, h2d-billed by the caller's wire
    path); ``gather_fns[name](mesh_array)`` returns the host value
    (d2h-billed).  The pytree-of-functions shape mirrors the exemplar
    (SNIPPETS.md [3]) so downstream code can thread them like specs.
    """
    shard_fns: Dict[str, Callable] = {}
    gather_fns: Dict[str, Callable] = {}
    for name, spec in specs.items():
        sharding = NamedSharding(mesh, spec)

        def shard_fn(arr, _s=sharding):
            return shard_to_mesh(arr, _s)

        shard_fns[name] = shard_fn
        gather_fns[name] = gather_from_mesh
    return shard_fns, gather_fns


def mesh_process_count(mesh: Mesh) -> int:
    """Distinct OS processes owning this mesh's devices (1 on any
    single-controller mesh; the ``s2c_mesh_hosts`` gauge)."""
    return len({d.process_index
                for d in np.asarray(mesh.devices).reshape(-1)})


def publish_mesh_gauges(mesh: Mesh) -> None:
    """Surface the mesh's shape to the metrics plane: hosts, shard
    count, per-host addressable shard bytes come from the shard path's
    counters; these two gauges pin the topology every row of the
    MULTICHIP bench joins against."""
    try:
        from .. import observability as obs

        reg = obs.metrics()
        reg.gauge("mesh/hosts").set(mesh_process_count(mesh))
        reg.gauge("mesh/shards").set(mesh.size)
    except Exception:
        pass
