"""Long-context mode: position-sharded accumulation with halo exchange.

The DP pipeline (``parallel/dp.py``) scatters every read shard into a
FULL-length local count tensor and reduce-scatters — communication-optimal,
but each device transiently holds O(total_len) memory.  For huge genomes
(the reference would allocate one Python dict per position and die,
``/root/reference/sam2consensus.py:167``; SURVEY.md §5 "long-context")
this module shards the *position axis itself*, the counting-workload
analogue of sequence/context parallelism:

* each device owns one contiguous position block of ``B = padded_len / n``
  rows and materializes only ``[B + H, 6]`` locally (H = halo width);
* the host routes each segment row to the device owning its start
  position (a counting sort, same shape as the MXU pileup's tile plan);
  rows wider than the halo are split into halo-width pieces first
  (segment rows are position-contiguous, so splitting is exact);
* a routed row may overhang its owner's block by up to ``H - 1``
  positions; the overhang accumulates into the local halo tail, and ONE
  ``lax.ppermute`` per chunk shifts every halo to the next device, which
  folds it into its block head.  Addition commutes, so the result is
  exactly the unsharded pileup (pinned by tests/test_parallel_sp.py);
* the vote then runs on the resident position-sharded blocks with zero
  extra communication (``ShardedCountsBase.vote``).

Memory per device: O(total_len / n + H).  Communication per chunk: one
neighbor-shift of ``[H, 6]`` int32 over ICI — independent of genome and
chunk size.  The same code rides DCN on multi-host meshes (the mesh
abstraction covers both fabrics; SURVEY.md §5 "distributed backend").

Two accumulation strategies, picked per slab by the slab's position span:

* **window** — when the slab's rows span a narrow position window (the
  coordinate-sorted common case): rows split EVENLY across devices (no
  routing, transfer ∝ real rows), each device scatters into a small
  ``[Wp, 6]`` window-local tensor, one ``psum`` of the window rides ICI,
  and each device folds the slice overlapping its resident block.
  Transfer is minimal; communication is O(window), independent of genome
  size.
* **routed** — scattered input: rows route to the device owning their
  start position (dense SPMD, ``n * max_rows_per_device`` slots — which
  is ≈ the real row count precisely when the input is NOT sorted), with
  the halo exchange folding block overhangs.

``rows_shipped`` / ``rows_real`` count row slots actually transferred vs
received, pinning the sorted-input fix (tests/test_parallel_sp.py).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import NUM_SYMBOLS, PAD_CODE, SP_WINDOW_CAP
from ..encoder.events import SegmentBatch
from ..wire import account_h2d
from ..ops.pileup import (expand_segment_positions, iter_row_slices,
                          round_rows_grid, unpack_nibbles)
from .base import (ALL, ShardedCountsBase, block_for, plan_mxu_grids,
                   real_row_mask, record_slab, route_to_slots, shard_map,
                   split_wide_rows)
from jax.sharding import PartitionSpec as P

__all__ = ["PositionShardedConsensus", "block_for"]


class PositionShardedConsensus(ShardedCountsBase):
    """Streaming position-sharded accumulate + vote over a device mesh.

    Same surface as ``parallel.dp.ShardedConsensus`` so the backend can
    pick either by genome size.
    """

    #: largest position window the window strategy will materialize per
    #: device ([Wp, 6] int32 local + one psum of the same size over ICI);
    #: the shared definition lives in constants.SP_WINDOW_CAP so the
    #: parallel.auto cost model can mirror it without importing jax
    WINDOW_CAP = SP_WINDOW_CAP

    def __init__(self, mesh, total_len: int, halo: int = 1 << 16,
                 pileup: str = "scatter", wire: str = "packed5"):
        super().__init__(mesh, total_len, wire=wire)
        self.halo = halo
        if self.block < halo:
            raise ValueError(
                f"position block {self.block} smaller than halo {halo}: "
                "use the DP pipeline for genomes this small")
        #: per-device accumulation kernel for ROUTED slabs: the XLA
        #: scatter (default), the Pallas tile-CSR histogram, or the MXU
        #: one-hot matmul — the router's counting sort already delivers
        #: rows in exactly the per-device layout the kernel planners
        #: consume (round-4 verdict #4); window-strategy slabs (narrow
        #:  span) keep the scatter, whose window tensor is small
        self.pileup = pileup if pileup in ("mxu", "pallas") else "scatter"
        self.strategy_used: dict = {}
        self.rows_shipped = 0
        self.rows_real = 0
        self._window_cache: dict = {}
        self._kernel_cache: dict = {}

        block = self.block
        n = self.n

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(ALL, None), P(ALL), P(ALL, None)),
                 out_specs=P(ALL, None))
        def accumulate(counts_blk, starts, packed):
            # device index along the flattened ("dp","sp") axes
            di = jax.lax.axis_index(ALL)
            # one slot PAST the halo is the PAD-cell sacrifice: it must
            # live outside [0, block + halo) or pad garbage would ride
            # the halo shift into the next device's real positions
            local = jnp.zeros((block + halo + 1, NUM_SYMBOLS),
                              dtype=jnp.int32)
            pos, code = expand_segment_positions(
                starts - di * block, unpack_nibbles(packed), block + halo)
            local = local.at[pos, code].add(1)
            # one neighbor shift moves every halo to its owner; the last
            # device's halo covers pad positions only (valid cells never
            # pass padded_len), so the non-wrapping drop is exact
            shifted = jax.lax.ppermute(
                local[block:block + halo], ALL,
                perm=[(i, i + 1) for i in range(n - 1)])
            out = counts_blk + local[:block]
            return out.at[:halo].add(shifted)

        self._accumulate = jax.jit(accumulate, donate_argnums=0)

    def _window_accumulate(self, wp: int):
        """Per-Wp jitted window-strategy accumulate (pow2 Wp keeps the
        cache O(log))."""
        if wp not in self._window_cache:
            block, n = self.block, self.n

            @partial(shard_map, mesh=self.mesh,
                     in_specs=(P(ALL, None), P(ALL), P(ALL, None), P()),
                     out_specs=P(ALL, None))
            def accumulate_window(counts_blk, starts, packed, wlo):
                di = jax.lax.axis_index(ALL)
                local = jnp.zeros((wp + 1, NUM_SYMBOLS), dtype=jnp.int32)
                pos, code = expand_segment_positions(
                    starts - wlo, unpack_nibbles(packed), wp)
                local = local.at[pos, code].add(1)
                # one window-sized all-reduce rides ICI; every device then
                # folds the slice overlapping its resident position block
                win = jax.lax.psum(local[:wp], ALL)
                idx = di * block + jnp.arange(block) - wlo
                valid = (idx >= 0) & (idx < wp)
                safe = jnp.clip(idx, 0, wp - 1)
                return counts_blk + jnp.where(valid[:, None], win[safe], 0)

            self._window_cache[wp] = jax.jit(accumulate_window,
                                             donate_argnums=0)
        return self._window_cache[wp]

    # -- routed-slab device kernels (pallas / mxu; verdict r4 #4) ---------
    def _pallas_fn(self, w: int, plan):
        """Cached shard_map'd Pallas accumulate for one static shape:
        per-device tile-CSR histogram over the local [block+halo+1]
        coordinate space, then the same halo exchange as the scatter
        path (addition commutes, so the result is exact)."""
        from ..ops import pallas_pileup as pp

        key = ("pallas", w, plan.row_block, plan.max_blocks,
               plan.n_rows_padded, plan.n_tiles)
        if key in self._kernel_cache:
            return self._kernel_cache[key]
        block, halo, n = self.block, self.halo, self.n
        local_len = block + halo + 1
        interp = jax.default_backend() != "tpu"

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(ALL, None), P(ALL), P(ALL, None), P(ALL),
                           P(ALL, None), P(ALL, None)),
                 out_specs=P(ALL, None), check_vma=False)
        def accumulate(counts_blk, s_local, packed, rank, blk_lo, blk_n):
            local = pp.local_tile_counts(
                s_local, packed, rank, blk_lo[0], blk_n[0],
                tile=pp.TILE_POSITIONS, n_tiles=plan.n_tiles, width=w,
                row_block=plan.row_block, max_blocks=plan.max_blocks,
                n_rows_padded=plan.n_rows_padded, out_len=local_len,
                interpret=interp)
            shifted = jax.lax.ppermute(
                local[block:block + halo], ALL,
                perm=[(i, i + 1) for i in range(n - 1)])
            out = counts_blk + local[:block]
            return out.at[:halo].add(shifted)

        fn = jax.jit(accumulate, donate_argnums=0)
        self._kernel_cache[key] = fn
        return fn

    def _mxu_fn(self, w: int, e1: int, n_tiles_l: int):
        """Cached shard_map'd MXU accumulate (one-hot matmul tiles over
        the local coordinate space + halo exchange)."""
        from ..ops import mxu_pileup

        key = ("mxu", w, e1, n_tiles_l)
        if key in self._kernel_cache:
            return self._kernel_cache[key]
        block, halo, n = self.block, self.halo, self.n
        local_len = block + halo + 1
        tile = mxu_pileup.TILE_POSITIONS
        tiles_len = n_tiles_l * tile

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(ALL, None), P(ALL), P(ALL, None), P(ALL)),
                 out_specs=P(ALL, None))
        def accumulate(counts_blk, s_local, packed, slot):
            loc, cod = mxu_pileup.build_padded_layout(
                s_local, unpack_nibbles(packed), slot, tile=tile,
                n_tiles=n_tiles_l, rows_per_tile=e1, width=w)
            local = mxu_pileup._accumulate_tiles(
                jnp.zeros((tiles_len, NUM_SYMBOLS), dtype=jnp.int32),
                loc, cod, tile=tile, n_tiles=n_tiles_l,
                rows_per_tile=e1, width=w)[:local_len]
            shifted = jax.lax.ppermute(
                local[block:block + halo], ALL,
                perm=[(i, i + 1) for i in range(n - 1)])
            out = counts_blk + local[:block]
            return out.at[:halo].add(shifted)

        fn = jax.jit(accumulate, donate_argnums=0)
        self._kernel_cache[key] = fn
        return fn

    def _routed_kernel_add(self, s_grid: np.ndarray, c_grid: np.ndarray,
                           per_dev: np.ndarray, w: int) -> bool:
        """Accumulate a routed slot grid via the configured device
        kernel; False falls the slab back to the scatter route (odd
        halo-split widths — the nibble wire widens them — or MXU
        padding blowup)."""
        if self.pileup == "scatter" or w % 2:
            return False
        from ..ops import pallas_pileup as pp

        local_len = self.block + self.halo + 1
        if self.pileup == "pallas" and pp._cw(w) * 2 > pp.TILE_POSITIONS:
            return False
        s_local = (s_grid
                   - (np.arange(self.n) * self.block)[:, None]).astype(
                       np.int32)
        r = s_grid.shape[1]
        # two phases: plan EVERY slice before executing any, so an MXU
        # skew fallback on a later slice cannot leave earlier slices'
        # counts committed and then re-count the whole slab via scatter
        # (double-count; round-5 review finding)
        staged = []
        for lo, hi in iter_row_slices(r, w):
            sl = np.ascontiguousarray(s_local[:, lo:hi])
            reals = np.clip(per_dev - lo, 0, hi - lo)
            if self.pileup == "pallas":
                plan = pp.plan_rows_stacked(sl, w, local_len,
                                            pp.TILE_POSITIONS)
                fn = self._pallas_fn(w, plan)
                extra = (plan.rank.reshape(-1), plan.blk_lo, plan.blk_n)
            else:
                planned = plan_mxu_grids(sl, reals, w, local_len)
                if planned is None:
                    return False       # skew: whole slab rides scatter
                slots, e1, nt = planned
                fn = self._mxu_fn(w, e1, nt)
                extra = (slots.reshape(-1),)
            staged.append((lo, hi, sl, fn, extra))
        for lo, hi, sl, fn, extra in staged:
            extra_dev = tuple(self.ship_kernel_operand(a)
                              for a in extra)
            self.bytes_h2d += sum(a.nbytes for a in extra)
            account_h2d(sum(a.nbytes for a in extra))
            st_dev, pk_dev = self.put_rows(
                sl.reshape(-1),
                np.ascontiguousarray(c_grid[:, lo:hi]).reshape(-1, w))
            self._counts = fn(self.counts, st_dev, pk_dev, *extra_dev)
            self.rows_shipped += self.n * (hi - lo)
        key = f"routed_{self.pileup}_w{w}"
        self.strategy_used[key] = self.strategy_used.get(key, 0) + 1
        return True

    # -- streaming input --------------------------------------------------
    def add(self, batch: SegmentBatch) -> None:
        from ..resilience.faultinject import fault_check

        fault_check("pileup_dispatch")
        for w, (starts, codes) in sorted(batch.buckets.items()):
            t0 = time.perf_counter()
            starts = np.asarray(starts)
            codes = np.asarray(codes)
            if self.wire == "delta8":
                from ..wire.codec import canonicalize_rows

                starts, codes = canonicalize_rows(starts, codes)
            if w > self.halo:
                starts, codes, w = split_wide_rows(
                    starts, codes, w, self.halo, self.padded_len)

            self.rows_real += len(starts)
            # strategy pick: a narrow position span (coordinate-sorted
            # input) takes the window path — even row split, minimal
            # transfer, one O(window) psum — instead of routing, whose
            # dense slot grid would ship ~n x the real rows.
            # Encoder pad rows (parallel.base.real_row_mask): the window
            # math never relies on this mask for correctness — PAD cells
            # self-redirect to the sacrificial slot regardless.
            real = real_row_mask(starts, codes)
            if real.any():
                wlo = int(starts[real].min())
                span = int(starts[real].max()) + w - wlo
                wp = 1 << max(10, (span - 1).bit_length())
            else:
                continue  # nothing but pad rows: nothing to count
            # density gate: the window psum moves wp*6*4 bytes over ICI
            # per slice; demand it stay within a small multiple of the
            # slab's own row bytes so a sparse-but-sorted slab doesn't
            # buy a 50MB all-reduce with 64KB of data (routing serves it
            # fine — sparse rows spread over devices anyway)
            dense_enough = wp * NUM_SYMBOLS * 4 <= 16 * len(starts) * w
            if dense_enough and wp <= min(self.WINDOW_CAP, self.padded_len):
                # pad-row starts may sit outside the window; pin them to
                # wlo so the shifted scatter index stays in range (their
                # cells are PAD and redirect anyway)
                starts = np.where(real, starts, wlo).astype(np.int32)
                n_rows = -(-len(starts) // self.n) * self.n
                if n_rows != len(starts):
                    starts = np.concatenate(
                        [starts,
                         np.full(n_rows - len(starts), wlo, np.int32)])
                    codes = np.concatenate(
                        [codes, np.full((n_rows - len(codes), w), PAD_CODE,
                                        dtype=np.uint8)])
                fn = self._window_accumulate(wp)
                for lo, hi in iter_row_slices(n_rows, w, multiple_of=self.n):
                    st_dev, pk_dev = self.put_rows(starts[lo:hi],
                                                   codes[lo:hi])
                    self._counts = fn(self.counts, st_dev, pk_dev,
                                      np.int32(wlo))
                    self.rows_shipped += hi - lo
                key = f"window_w{w}"
                self.strategy_used[key] = self.strategy_used.get(key, 0) + 1
                record_slab(key, t0, len(starts), w)
                continue

            # route rows to the device owning their start position.
            # Encoder pad rows (all-PAD codes, start 0) are dropped
            # first: they count nothing anywhere, and routed to device
            # 0 they would only pile into its tile-0 kernel plans
            # (inflating the MXU E) — grid rounding keeps the jit cache
            # bounded without them
            starts, codes = starts[real], codes[real]
            dev = starts // self.block
            per_dev = np.bincount(dev, minlength=self.n)
            r = round_rows_grid(int(per_dev.max(initial=1)))
            s_routed, c_routed = route_to_slots(
                dev, self.n, r, starts, codes,
                np.arange(self.n) * self.block)
            if self._routed_kernel_add(s_routed, c_routed, per_dev, w):
                record_slab(f"routed_{self.pileup}_w{w}", t0,
                            len(starts), w)
                continue

            # cap expanded cells per device call (same budget discipline
            # as the unsharded and dp paths, ops.pileup.iter_row_slices)
            for lo, hi_r in iter_row_slices(r, w):
                st_dev, pk_dev = self.put_rows(
                    s_routed[:, lo:hi_r].reshape(-1).copy(),
                    np.ascontiguousarray(
                        c_routed[:, lo:hi_r]).reshape(-1, w))
                self._counts = self._accumulate(self.counts, st_dev,
                                                pk_dev)
                self.rows_shipped += self.n * (hi_r - lo)
            key = f"routed_w{w}"
            self.strategy_used[key] = self.strategy_used.get(key, 0) + 1
            record_slab(key, t0, len(starts), w)
