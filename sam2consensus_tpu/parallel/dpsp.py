"""dp x sp product mode: read shards x position blocks on the TRUE 2-D mesh.

The pure pipelines flatten the ("dp", "sp") mesh into one ring: dp
scatters full-length local tensors (transient O(L) per device), sp routes
every row to the single device owning its position (host routing fans out
to all n devices, and scattered input inflates the dense slot grid ~n x).
For huge-genome + deep-coverage workloads neither fits (round-3 verdict
item 5).  This mode composes both axes the way the mesh was designed to
be used (parallel/mesh.py: dp maps to DCN, sp to ICI on multi-host
layouts):

* reads split EVENLY into ``n_dp`` shards — no routing across dp at all;
* within each dp shard, rows route among only ``n_sp`` macro position
  blocks of ``B_sp = padded_len / n_sp`` (the counting-workload analogue
  of 2-D context parallelism: slot-grid inflation is bounded by n_sp,
  not n);
* device (d, s) scatters its shard's rows for macro-block s into a local
  ``[B_sp + H + 1, 6]`` tensor; one ``lax.ppermute`` over **sp** shifts
  each halo to the next macro-block (within the dp group), then one
  ``lax.psum_scatter`` over **dp** both sums the dp partials and leaves
  device d holding sub-block d of the macro-block — addition commutes,
  so the result is exactly the unsharded pileup
  (tests/test_parallel_dpsp.py pins byte-identity on (2,4) and (4,2)
  meshes).

Resulting state layout: position axis sharded ``P(("sp", "dp"))`` —
macro-blocks over sp, sub-blocks over dp — which the shared base
(``ShardedCountsBase(pos_axes=("sp", "dp"))``) threads through the vote,
tail stats, and checkpoint restore, so the whole tail runs on the 2-D
layout with zero resharding.

Memory per device: O(L / n_sp + H) transient, O(L / n) resident.
Communication per chunk: one [H, 6] neighbor shift over sp (ICI) + one
reduce-scatter of [B_sp, 6] over dp — the dp term is the price of never
routing reads across dp groups, the right trade precisely when decode
throughput (many reads) meets a genome too big for dp's O(L) transient.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..constants import NUM_SYMBOLS
from ..encoder.events import SegmentBatch
from ..ops.pileup import (expand_segment_positions, iter_row_slices,
                          pack_nibbles, round_rows_grid, unpack_nibbles)
from .base import (ALL, ShardedCountsBase, route_to_slots, shard_map,
                   split_wide_rows)

__all__ = ["ProductShardedConsensus"]


class ProductShardedConsensus(ShardedCountsBase):
    """Streaming dp x sp accumulate + vote over the 2-D mesh."""

    def __init__(self, mesh, total_len: int, halo: int = 1 << 16):
        super().__init__(mesh, total_len, pos_axes=("sp", "dp"))
        self.n_dp = mesh.shape["dp"]
        self.n_sp = mesh.shape["sp"]
        if self.n_dp < 2 or self.n_sp < 2:
            raise ValueError(
                f"dp x sp product mode needs a true 2-D mesh, got "
                f"dp={self.n_dp} x sp={self.n_sp}; use --shard-mode dp "
                f"or sp on a 1-D mesh")
        self.halo = halo
        self.block_sp = self.padded_len // self.n_sp    # macro block
        if self.block_sp < halo:
            raise ValueError(
                f"macro position block {self.block_sp} smaller than halo "
                f"{halo}: use the DP pipeline for genomes this small")
        self.strategy_used: dict = {}
        self.rows_shipped = 0
        self.rows_real = 0

        block_sp, n_sp = self.block_sp, self.n_sp

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(self.pos_axes, None), P(ALL), P(ALL, None)),
                 out_specs=P(self.pos_axes, None))
        def accumulate(counts_blk, starts, packed):
            s = jax.lax.axis_index("sp")
            # slot past the halo is the PAD-cell sacrifice (outside
            # [0, block_sp + halo) so pad garbage never rides the shift)
            local = jnp.zeros((block_sp + halo + 1, NUM_SYMBOLS),
                              dtype=jnp.int32)
            pos, code = expand_segment_positions(
                starts - s * block_sp, unpack_nibbles(packed),
                block_sp + halo)
            local = local.at[pos, code].add(1)
            # halo -> next macro-block, within each dp group; the last
            # macro-block's halo covers pad positions only (valid cells
            # never pass padded_len), so the non-wrapping drop is exact
            shifted = jax.lax.ppermute(
                local[block_sp:block_sp + halo], "sp",
                perm=[(i, i + 1) for i in range(n_sp - 1)])
            acc = local[:block_sp].at[:halo].add(shifted)
            # reduce the dp partials AND scatter sub-blocks: device d
            # leaves holding sub-block d of macro-block s, which is
            # exactly the P(("sp","dp")) resident layout
            return counts_blk + jax.lax.psum_scatter(
                acc, "dp", scatter_dimension=0, tiled=True)

        self._accumulate = jax.jit(accumulate, donate_argnums=0)

    # -- streaming input --------------------------------------------------
    def add(self, batch: SegmentBatch) -> None:
        for w, (starts, codes) in sorted(batch.buckets.items()):
            starts = np.asarray(starts)
            codes = np.asarray(codes)
            if w > self.halo:
                starts, codes, w = split_wide_rows(
                    starts, codes, w, self.halo, self.padded_len)

            self.rows_real += len(starts)
            # dp split: contiguous even chunks (order irrelevant — the
            # count tensor is sum-decomposable); within each chunk, route
            # rows to their macro block via one counting sort over n_sp
            # targets (route_to_slots: the same slot math as sp routing)
            n_rows = len(starts)
            per_dp = -(-n_rows // self.n_dp)
            macro = np.minimum(starts // self.block_sp, self.n_sp - 1)
            # slot capacity: max rows any (dp chunk, macro block) pair
            # receives, rounded on the shared eighth-pow2 grid
            # (ops.pileup.round_rows_grid: O(log) jit cache, <=12.5%
            # wire padding)
            counts_dm = np.zeros((self.n_dp, self.n_sp), dtype=np.int64)
            for d in range(self.n_dp):
                lo, hi = d * per_dp, min((d + 1) * per_dp, n_rows)
                if lo < hi:
                    counts_dm[d] = np.bincount(macro[lo:hi],
                                               minlength=self.n_sp)
            r = round_rows_grid(int(counts_dm.max(initial=1)))

            pins = np.arange(self.n_sp, dtype=np.int32) * self.block_sp
            s_routed = np.empty((self.n_dp, self.n_sp, r), dtype=np.int32)
            c_routed = np.empty((self.n_dp, self.n_sp, r, w),
                                dtype=np.uint8)
            for d in range(self.n_dp):
                lo, hi = d * per_dp, min((d + 1) * per_dp, n_rows)
                s_routed[d], c_routed[d] = route_to_slots(
                    macro[lo:hi], self.n_sp, r, starts[lo:hi],
                    codes[lo:hi], pins)

            for lo_r, hi_r in iter_row_slices(r, w):
                s_slab = np.ascontiguousarray(
                    s_routed[:, :, lo_r:hi_r]).reshape(-1)
                p_slab = pack_nibbles(np.ascontiguousarray(
                    c_routed[:, :, lo_r:hi_r]).reshape(-1, w))
                self.bytes_h2d += s_slab.nbytes + p_slab.nbytes
                self._counts = self._accumulate(
                    self.counts,
                    jax.device_put(s_slab, self._row_spec),
                    jax.device_put(p_slab, self._mat_spec))
                self.rows_shipped += self.n * (hi_r - lo_r)
            key = f"dpsp_w{w}"
            self.strategy_used[key] = self.strategy_used.get(key, 0) + 1
