"""dp x sp product mode: read shards x position blocks on the TRUE 2-D mesh.

The pure pipelines flatten the ("dp", "sp") mesh into one ring: dp
scatters full-length local tensors (transient O(L) per device), sp routes
every row to the single device owning its position (host routing fans out
to all n devices, and scattered input inflates the dense slot grid ~n x).
For huge-genome + deep-coverage workloads neither fits (round-3 verdict
item 5).  This mode composes both axes the way the mesh was designed to
be used (parallel/mesh.py: dp maps to DCN, sp to ICI on multi-host
layouts):

* reads split EVENLY into ``n_dp`` shards — no routing across dp at all;
* within each dp shard, rows route among only ``n_sp`` macro position
  blocks of ``B_sp = padded_len / n_sp`` (the counting-workload analogue
  of 2-D context parallelism: slot-grid inflation is bounded by n_sp,
  not n);
* device (d, s) scatters its shard's rows for macro-block s into a local
  ``[B_sp + H + 1, 6]`` tensor; one ``lax.ppermute`` over **sp** shifts
  each halo to the next macro-block (within the dp group), then one
  ``lax.psum_scatter`` over **dp** both sums the dp partials and leaves
  device d holding sub-block d of the macro-block — addition commutes,
  so the result is exactly the unsharded pileup
  (tests/test_parallel_dpsp.py pins byte-identity on (2,4) and (4,2)
  meshes).

Resulting state layout: position axis sharded ``P(("sp", "dp"))`` —
macro-blocks over sp, sub-blocks over dp — which the shared base
(``ShardedCountsBase(pos_axes=("sp", "dp"))``) threads through the vote,
tail stats, and checkpoint restore, so the whole tail runs on the 2-D
layout with zero resharding.

Memory per device: O(L / n_sp + H) transient, O(L / n) resident.
Communication per chunk: one [H, 6] neighbor shift over sp (ICI) + one
reduce-scatter of [B_sp, 6] over dp — the dp term is the price of never
routing reads across dp groups, the right trade precisely when decode
throughput (many reads) meets a genome too big for dp's O(L) transient.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..constants import NUM_SYMBOLS
from ..encoder.events import SegmentBatch
from ..wire import account_h2d
from ..ops.pileup import (expand_segment_positions, iter_row_slices,
                          round_rows_grid, unpack_nibbles)
from .base import (ALL, ShardedCountsBase, plan_mxu_grids, real_row_mask,
                   record_slab, route_to_slots, shard_map,
                   split_wide_rows)

__all__ = ["ProductShardedConsensus"]


class ProductShardedConsensus(ShardedCountsBase):
    """Streaming dp x sp accumulate + vote over the 2-D mesh."""

    def __init__(self, mesh, total_len: int, halo: int = 1 << 16,
                 pileup: str = "scatter", wire: str = "packed5"):
        super().__init__(mesh, total_len, pos_axes=("sp", "dp"),
                         wire=wire)
        self.n_dp = mesh.shape["dp"]
        self.n_sp = mesh.shape["sp"]
        if self.n_dp < 2 or self.n_sp < 2:
            raise ValueError(
                f"dp x sp product mode needs a true 2-D mesh, got "
                f"dp={self.n_dp} x sp={self.n_sp}; use --shard-mode dp "
                f"or sp on a 1-D mesh")
        self.halo = halo
        self.block_sp = self.padded_len // self.n_sp    # macro block
        if self.block_sp < halo:
            raise ValueError(
                f"macro position block {self.block_sp} smaller than halo "
                f"{halo}: use the DP pipeline for genomes this small")
        #: per-device accumulation kernel for the routed slot grids,
        #: same contract as PositionShardedConsensus.pileup (verdict
        #: r4 #4): scatter (default) / pallas / mxu
        self.pileup = pileup if pileup in ("mxu", "pallas") else "scatter"
        self.strategy_used: dict = {}
        self.rows_shipped = 0
        self.rows_real = 0
        self._kernel_cache: dict = {}

        block_sp, n_sp = self.block_sp, self.n_sp

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(self.pos_axes, None), P(ALL), P(ALL, None)),
                 out_specs=P(self.pos_axes, None))
        def accumulate(counts_blk, starts, packed):
            s = jax.lax.axis_index("sp")
            # slot past the halo is the PAD-cell sacrifice (outside
            # [0, block_sp + halo) so pad garbage never rides the shift)
            local = jnp.zeros((block_sp + halo + 1, NUM_SYMBOLS),
                              dtype=jnp.int32)
            pos, code = expand_segment_positions(
                starts - s * block_sp, unpack_nibbles(packed),
                block_sp + halo)
            local = local.at[pos, code].add(1)
            # halo -> next macro-block, within each dp group; the last
            # macro-block's halo covers pad positions only (valid cells
            # never pass padded_len), so the non-wrapping drop is exact
            shifted = jax.lax.ppermute(
                local[block_sp:block_sp + halo], "sp",
                perm=[(i, i + 1) for i in range(n_sp - 1)])
            acc = local[:block_sp].at[:halo].add(shifted)
            # reduce the dp partials AND scatter sub-blocks: device d
            # leaves holding sub-block d of macro-block s, which is
            # exactly the P(("sp","dp")) resident layout
            return counts_blk + jax.lax.psum_scatter(
                acc, "dp", scatter_dimension=0, tiled=True)

        self._accumulate = jax.jit(accumulate, donate_argnums=0)

    # -- routed-slab device kernels (pallas / mxu; verdict r4 #4) ---------
    def _kernel_body(self):
        """The dpsp collectives applied to a per-device local-counts
        tensor: halo ppermute over sp, then psum_scatter over dp
        (identical to the scatter accumulate's tail, so the result is
        exact)."""
        block_sp, halo, n_sp = self.block_sp, self.halo, self.n_sp

        def tail(counts_blk, local):
            shifted = jax.lax.ppermute(
                local[block_sp:block_sp + halo], "sp",
                perm=[(i, i + 1) for i in range(n_sp - 1)])
            acc = local[:block_sp].at[:halo].add(shifted)
            return counts_blk + jax.lax.psum_scatter(
                acc, "dp", scatter_dimension=0, tiled=True)

        return tail

    def _pallas_fn(self, w: int, plan):
        from ..ops import pallas_pileup as pp

        key = ("pallas", w, plan.row_block, plan.max_blocks,
               plan.n_rows_padded, plan.n_tiles)
        if key in self._kernel_cache:
            return self._kernel_cache[key]
        local_len = self.block_sp + self.halo + 1
        interp = jax.default_backend() != "tpu"
        tail = self._kernel_body()

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(self.pos_axes, None), P(ALL), P(ALL, None),
                           P(ALL), P(ALL, None), P(ALL, None)),
                 out_specs=P(self.pos_axes, None), check_vma=False)
        def accumulate(counts_blk, s_local, packed, rank, blk_lo, blk_n):
            local = pp.local_tile_counts(
                s_local, packed, rank, blk_lo[0], blk_n[0],
                tile=pp.TILE_POSITIONS, n_tiles=plan.n_tiles, width=w,
                row_block=plan.row_block, max_blocks=plan.max_blocks,
                n_rows_padded=plan.n_rows_padded, out_len=local_len,
                interpret=interp)
            return tail(counts_blk, local)

        fn = jax.jit(accumulate, donate_argnums=0)
        self._kernel_cache[key] = fn
        return fn

    def _mxu_fn(self, w: int, e1: int, n_tiles_l: int):
        from ..ops import mxu_pileup

        key = ("mxu", w, e1, n_tiles_l)
        if key in self._kernel_cache:
            return self._kernel_cache[key]
        local_len = self.block_sp + self.halo + 1
        tile = mxu_pileup.TILE_POSITIONS
        tiles_len = n_tiles_l * tile
        tail = self._kernel_body()

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(self.pos_axes, None), P(ALL), P(ALL, None),
                           P(ALL)),
                 out_specs=P(self.pos_axes, None))
        def accumulate(counts_blk, s_local, packed, slot):
            loc, cod = mxu_pileup.build_padded_layout(
                s_local, unpack_nibbles(packed), slot, tile=tile,
                n_tiles=n_tiles_l, rows_per_tile=e1, width=w)
            local = mxu_pileup._accumulate_tiles(
                jnp.zeros((tiles_len, NUM_SYMBOLS), dtype=jnp.int32),
                loc, cod, tile=tile, n_tiles=n_tiles_l,
                rows_per_tile=e1, width=w)[:local_len]
            return tail(counts_blk, local)

        fn = jax.jit(accumulate, donate_argnums=0)
        self._kernel_cache[key] = fn
        return fn

    def _routed_kernel_add(self, s_grid: np.ndarray, c_grid: np.ndarray,
                           counts_dm: np.ndarray, w: int) -> bool:
        """Accumulate routed ``[n_dp, n_sp, R]`` grids via the
        configured kernel; False falls the slab back to scatter."""
        if self.pileup == "scatter" or w % 2:
            return False
        from ..ops import pallas_pileup as pp

        local_len = self.block_sp + self.halo + 1
        if self.pileup == "pallas" and pp._cw(w) * 2 > pp.TILE_POSITIONS:
            return False
        pins = (np.arange(self.n_sp, dtype=np.int64)
                * self.block_sp)[None, :, None]
        s_local = (s_grid - pins).astype(np.int32)
        r = s_grid.shape[2]
        d_units = self.n_dp * self.n_sp
        # two phases: plan EVERY slice before executing any, so an MXU
        # skew fallback on a later slice cannot double-count the slab
        # (see PositionShardedConsensus._routed_kernel_add)
        staged = []
        for lo, hi in iter_row_slices(r, w):
            sl = np.ascontiguousarray(
                s_local[:, :, lo:hi]).reshape(d_units, hi - lo)
            reals = np.clip(counts_dm.reshape(-1) - lo, 0, hi - lo)
            if self.pileup == "pallas":
                plan = pp.plan_rows_stacked(sl, w, local_len,
                                            pp.TILE_POSITIONS)
                fn = self._pallas_fn(w, plan)
                extra = (plan.rank.reshape(-1), plan.blk_lo, plan.blk_n)
            else:
                planned = plan_mxu_grids(sl, reals, w, local_len)
                if planned is None:
                    return False
                slots, e1, nt = planned
                fn = self._mxu_fn(w, e1, nt)
                extra = (slots.reshape(-1),)
            staged.append((lo, hi, sl, fn, extra))
        for lo, hi, sl, fn, extra in staged:
            extra_dev = tuple(self.ship_kernel_operand(a)
                              for a in extra)
            self.bytes_h2d += sum(a.nbytes for a in extra)
            account_h2d(sum(a.nbytes for a in extra))
            st_dev, pk_dev = self.put_rows(
                sl.reshape(-1),
                np.ascontiguousarray(c_grid[:, :, lo:hi]).reshape(-1, w))
            self._counts = fn(self.counts, st_dev, pk_dev, *extra_dev)
            self.rows_shipped += self.n * (hi - lo)
        key = f"dpsp_{self.pileup}_w{w}"
        self.strategy_used[key] = self.strategy_used.get(key, 0) + 1
        return True

    # -- streaming input --------------------------------------------------
    def add(self, batch: SegmentBatch) -> None:
        from ..resilience.faultinject import fault_check

        fault_check("pileup_dispatch")
        for w, (starts, codes) in sorted(batch.buckets.items()):
            t0 = time.perf_counter()
            starts = np.asarray(starts)
            codes = np.asarray(codes)
            if self.wire == "delta8":
                from ..wire.codec import canonicalize_rows

                starts, codes = canonicalize_rows(starts, codes)
            if w > self.halo:
                starts, codes, w = split_wide_rows(
                    starts, codes, w, self.halo, self.padded_len)

            self.rows_real += len(starts)
            if self.pileup != "scatter":
                # drop encoder pad rows: they count nothing and would
                # only inflate device (0, 0)'s tile-0 kernel plans
                keep = real_row_mask(starts, codes)
                if not keep.all():
                    starts, codes = starts[keep], codes[keep]
                if len(starts) == 0:
                    continue
            # dp split: contiguous even chunks (order irrelevant — the
            # count tensor is sum-decomposable); within each chunk, route
            # rows to their macro block via one counting sort over n_sp
            # targets (route_to_slots: the same slot math as sp routing)
            n_rows = len(starts)
            per_dp = -(-n_rows // self.n_dp)
            macro = np.minimum(starts // self.block_sp, self.n_sp - 1)
            # slot capacity: max rows any (dp chunk, macro block) pair
            # receives, rounded on the shared eighth-pow2 grid
            # (ops.pileup.round_rows_grid: O(log) jit cache, <=12.5%
            # wire padding)
            counts_dm = np.zeros((self.n_dp, self.n_sp), dtype=np.int64)
            for d in range(self.n_dp):
                lo, hi = d * per_dp, min((d + 1) * per_dp, n_rows)
                if lo < hi:
                    counts_dm[d] = np.bincount(macro[lo:hi],
                                               minlength=self.n_sp)
            r = round_rows_grid(int(counts_dm.max(initial=1)))

            pins = np.arange(self.n_sp, dtype=np.int32) * self.block_sp
            s_routed = np.empty((self.n_dp, self.n_sp, r), dtype=np.int32)
            c_routed = np.empty((self.n_dp, self.n_sp, r, w),
                                dtype=np.uint8)
            for d in range(self.n_dp):
                lo, hi = d * per_dp, min((d + 1) * per_dp, n_rows)
                s_routed[d], c_routed[d] = route_to_slots(
                    macro[lo:hi], self.n_sp, r, starts[lo:hi],
                    codes[lo:hi], pins)

            if self._routed_kernel_add(s_routed, c_routed, counts_dm, w):
                record_slab(f"dpsp_{self.pileup}_w{w}", t0,
                            len(starts), w)
                continue
            for lo_r, hi_r in iter_row_slices(r, w):
                st_dev, pk_dev = self.put_rows(
                    np.ascontiguousarray(
                        s_routed[:, :, lo_r:hi_r]).reshape(-1),
                    np.ascontiguousarray(
                        c_routed[:, :, lo_r:hi_r]).reshape(-1, w))
                self._counts = self._accumulate(self.counts, st_dev,
                                                pk_dev)
                self.rows_shipped += self.n * (hi_r - lo_r)
            key = f"dpsp_w{w}"
            self.strategy_used[key] = self.strategy_used.get(key, 0) + 1
            record_slab(key, t0, len(starts), w)
