"""Device-mesh construction for the consensus workload.

The workload has exactly two meaningful parallel dimensions (SURVEY.md §2b):

* **dp** — data parallelism over SAM reads: each device scatter-adds its
  read shard into a local count tensor; addition commutes, so a single
  collective reduction makes this exact.
* **sp** — sequence (genome-position) parallelism: the count tensor's flat
  position axis is sharded for the vote and for huge references (the
  counting-workload analogue of context parallelism, SURVEY.md §5).

TP/PP/EP have no analogue in a counting pipeline and are deliberately not
faked.  Reads and positions are both flat axes, so when a phase uses only
one dimension it shards over the ("dp", "sp") axes *flattened* — the mesh
stays 2-D so multi-host layouts can later map dp to DCN and sp to ICI
without code changes (JAX meshes abstract both fabrics).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


class MeshCapacityError(ValueError):
    """Typed up-front rejection of an unplaceable mesh request.

    Raised at CLI parse / serve admission / backend config resolution —
    BEFORE any XLA compilation — when ``--shards`` asks for more
    devices than the runtime has, or combines with a host-only pileup.
    Subclasses ``ValueError`` so every existing reject-with-reason
    path (CLI SystemExit mapping, serve ``_validate``) keeps working.
    """


def available_devices() -> int:
    """Global device count the mesh can draw on (honors
    ``JAX_PLATFORMS`` / ``--xla_force_host_platform_device_count``
    forcing and ``jax.distributed`` process-spanning runtimes)."""
    return len(jax.devices())


def validate_shards(shards: int, n_available: Optional[int] = None,
                    pileup: Optional[str] = None) -> None:
    """Reject impossible ``--shards`` requests up front, typed.

    The late failure this replaces: ``make_mesh`` raising deep inside
    backend construction after the input was already opened and the
    first batch staged — or worse, XLA failing on a device put.  Both
    CLI and serve admission call this before any work is committed.
    """
    if shards is None or shards <= 1:
        return
    if pileup == "host":
        raise MeshCapacityError(
            "--pileup host accumulates on the single host; it does "
            "not compose with --shards")
    if n_available is None:
        n_available = available_devices()
    if shards > n_available:
        raise MeshCapacityError(
            f"--shards {shards} exceeds the {n_available} available "
            f"device(s): shrink --shards, or widen the mesh "
            f"(more hosts via jax.distributed, or "
            f"--xla_force_host_platform_device_count on CPU)")


def factor_mesh(n: int) -> Tuple[int, int]:
    """Split ``n`` devices into (dp, sp), preferring a balanced 2-D mesh."""
    sp = 1
    for cand in range(int(np.sqrt(n)), 0, -1):
        if n % cand == 0:
            sp = cand
            break
    return n // sp, sp


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the ("dp", "sp") mesh over the first ``n_devices`` devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise MeshCapacityError(
                f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    dp, sp = factor_mesh(len(devices))
    return Mesh(np.asarray(devices).reshape(dp, sp), ("dp", "sp"))
