"""Sharded consensus pipeline: DP segment scatter → reduce-scatter → SP vote.

The distributed design (SURVEY.md §5 "Distributed communication backend"):
the count tensor is a sum-decomposable sufficient statistic, so data
parallelism plus one collective reduction is *exact* — no read ordering or
tie-breaking concerns.  The collective rides XLA:

1. each device expands + scatter-adds its shard of segment rows
   (``encoder.events.SegmentBatch``: flat start + uint8 code row per read)
   into a full-length local count tensor (pure DP over the flattened
   ("dp","sp") axes);
2. one ``lax.psum_scatter`` both sums the local tensors and leaves each
   device holding one contiguous block of the position axis — a
   reduce-scatter, bandwidth-optimal vs. all-reduce (factor n less traffic),
   and the result is already in the layout the vote wants;
3. the vote (elementwise per position) runs on the position-sharded blocks —
   sequence parallelism with zero extra communication;
4. results reach the host as one device-sharded array fetch.

On a single host the collectives ride ICI; on multi-host meshes the same
code rides DCN (JAX mesh abstraction covers both, no NCCL/MPI analogue is
needed).  The accumulator state stays position-sharded between chunks, so
streaming input and checkpoint/resume compose with sharding.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..constants import NUM_SYMBOLS, PAD_CODE
from ..encoder.events import SegmentBatch
from ..ops.pileup import expand_segment_positions, iter_row_slices
from .base import ALL, ShardedCountsBase, shard_map

__all__ = ["ShardedConsensus", "ALL"]


class ShardedConsensus(ShardedCountsBase):
    """Streaming sharded accumulate + vote over a ("dp", "sp") mesh."""

    def __init__(self, mesh: Mesh, total_len: int):
        # position axis padded so every device owns an equal block; the
        # sacrificial scatter row (index total_len) lives inside the pad.
        super().__init__(mesh, total_len)

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(ALL, None), P(ALL), P(ALL, None)),
                 out_specs=P(ALL, None))
        def accumulate(counts_blk, starts, codes):
            pos, code = expand_segment_positions(starts, codes, total_len)
            local = jnp.zeros((self.padded_len, NUM_SYMBOLS), dtype=jnp.int32)
            local = local.at[pos, code].add(1)
            # reduce over every device AND scatter position blocks: each
            # device leaves holding its own summed block (reduce-scatter).
            return counts_blk + jax.lax.psum_scatter(
                local, ALL, scatter_dimension=0, tiled=True)

        self._accumulate = jax.jit(accumulate, donate_argnums=0)

    # -- streaming input --------------------------------------------------
    def add(self, batch: SegmentBatch) -> None:
        for w, (starts, codes) in sorted(batch.buckets.items()):
            s = len(starts)
            # rows must shard evenly over the mesh (matters for
            # non-power-of-two device counts)
            target = -(-s // self.n) * self.n
            if target != s:
                starts = np.concatenate(
                    [starts, np.zeros(target - s, dtype=np.int32)])
                codes = np.concatenate(
                    [codes, np.full((target - s, codes.shape[1]), PAD_CODE,
                                    dtype=np.uint8)])
            for lo, hi in iter_row_slices(target, w, multiple_of=self.n):
                self._counts = self._accumulate(
                    self._counts,
                    jax.device_put(starts[lo:hi], self._row_spec),
                    jax.device_put(codes[lo:hi], self._mat_spec))
