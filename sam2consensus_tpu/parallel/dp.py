"""Sharded consensus pipeline: DP segment scatter → reduce-scatter → SP vote.

The distributed design (SURVEY.md §5 "Distributed communication backend"):
the count tensor is a sum-decomposable sufficient statistic, so data
parallelism plus one collective reduction is *exact* — no read ordering or
tie-breaking concerns.  The collective rides XLA:

1. each device expands + scatter-adds its shard of segment rows
   (``encoder.events.SegmentBatch``: flat start + uint8 code row per read)
   into a full-length local count tensor (pure DP over the flattened
   ("dp","sp") axes);
2. one ``lax.psum_scatter`` both sums the local tensors and leaves each
   device holding one contiguous block of the position axis — a
   reduce-scatter, bandwidth-optimal vs. all-reduce (factor n less traffic),
   and the result is already in the layout the vote wants;
3. the vote (elementwise per position) runs on the position-sharded blocks —
   sequence parallelism with zero extra communication;
4. results reach the host as one device-sharded array fetch.

On a single host the collectives ride ICI; on multi-host meshes the same
code rides DCN (JAX mesh abstraction covers both, no NCCL/MPI analogue is
needed).  The accumulator state stays position-sharded between chunks, so
streaming input and checkpoint/resume compose with sharding.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..constants import NUM_SYMBOLS, PAD_CODE
from ..encoder.events import SegmentBatch
from ..ops.pileup import (expand_segment_positions, iter_row_slices,
                          round_rows_grid, round_rows_pow2,
                          unpack_nibbles)
from ..wire import account_h2d
from ..wire.codec import canonicalize_rows
from .base import ALL, ShardedCountsBase, shard_map

__all__ = ["ShardedConsensus", "ALL"]


class ShardedConsensus(ShardedCountsBase):
    """Streaming sharded accumulate + vote over a ("dp", "sp") mesh.

    ``pileup`` picks the per-device accumulation strategy: ``"pallas"``
    runs the tile-CSR histogram kernel (``ops.pallas_pileup``) over the
    full position axis per device; ``"mxu"`` plans one tile-sorted
    chunk per device and runs the one-hot-matmul pileup
    (``ops.mxu_pileup``) locally before the reduce-scatter;
    ``"scatter"`` keeps the XLA scatter; ``"auto"`` runs the same
    measured scatter-vs-kernel trial as the single-device accumulator
    (``ops.pileup.PileupAutoTuner``: pallas on TPU, mxu elsewhere) and
    locks in the per-cell winner — the sharded promise of ``--pileup
    auto`` holds under ``--shards``.  Skewed slabs fall back to scatter
    per bucket, exactly as on a single device.

    Observability rides the shared slab driver
    (``ops.pileup.run_tuned_slab``): every slab emits a ``slab`` span
    and a ``pileup/slab_sec/<strategy>`` histogram sample, same as the
    single-device accumulator — the sp/dpsp routers record theirs via
    ``parallel.base.record_slab``.
    """

    def __init__(self, mesh: Mesh, total_len: int, pileup: str = "auto",
                 wire: str = "packed5"):
        # position axis padded so every device owns an equal block; the
        # sacrificial scatter row (index total_len) lives inside the pad.
        super().__init__(mesh, total_len, wire=wire)
        from ..ops import mxu_pileup
        from ..ops.pileup import PileupAutoTuner

        self.pileup = pileup
        self.strategy_used: dict = {}
        plat = jax.default_backend()
        self._pallas_interpret = plat != "tpu"
        self._tuner = PileupAutoTuner(
            kernel="pallas" if plat == "tpu" else "mxu") \
            if pileup == "auto" else None
        self._tile = mxu_pileup.TILE_POSITIONS
        self._tiles_len = -(-self.padded_len // self._tile) * self._tile
        self._n_tiles = self._tiles_len // self._tile
        self._mxu_cache: dict = {}
        self._pallas_cache: dict = {}

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(ALL, None), P(ALL), P(ALL, None)),
                 out_specs=P(ALL, None))
        def accumulate(counts_blk, starts, packed):
            # rows arrive 4-bit packed (ops.pileup.pack_nibbles): half the
            # host->device bytes on the tunneled link
            pos, code = expand_segment_positions(
                starts, unpack_nibbles(packed), total_len)
            local = jnp.zeros((self.padded_len, NUM_SYMBOLS), dtype=jnp.int32)
            local = local.at[pos, code].add(1)
            # reduce over every device AND scatter position blocks: each
            # device leaves holding its own summed block (reduce-scatter).
            return counts_blk + jax.lax.psum_scatter(
                local, ALL, scatter_dimension=0, tiled=True)

        self._accumulate = jax.jit(accumulate, donate_argnums=0)

    def _mxu_accumulate(self, rows_per_tile: int, width: int):
        """Per-(E, W) jitted sharded MXU accumulate (cached: the slab
        protocol keeps these shapes near-constant per run).  Rows ship
        compact (scatter-path bytes +4B/row slot); each device builds its
        padded tile layout locally (ops.mxu_pileup.build_padded_layout)."""
        key = (rows_per_tile, width)
        if key not in self._mxu_cache:
            from ..ops import mxu_pileup

            tile, n_tiles = self._tile, self._n_tiles
            tiles_len, padded_len = self._tiles_len, self.padded_len

            @partial(shard_map, mesh=self.mesh,
                     in_specs=(P(ALL, None), P(ALL), P(ALL, None), P(ALL)),
                     out_specs=P(ALL, None))
            def accumulate_mxu(counts_blk, starts, packed, slot):
                loc, cod = mxu_pileup.build_padded_layout(
                    starts, unpack_nibbles(packed), slot, tile=tile,
                    n_tiles=n_tiles,
                    rows_per_tile=rows_per_tile, width=width)
                local = mxu_pileup._accumulate_tiles(
                    jnp.zeros((tiles_len, NUM_SYMBOLS), dtype=jnp.int32),
                    loc, cod, tile=tile, n_tiles=n_tiles,
                    rows_per_tile=rows_per_tile, width=width)
                return counts_blk + jax.lax.psum_scatter(
                    local[:padded_len], ALL, scatter_dimension=0, tiled=True)

            self._mxu_cache[key] = jax.jit(accumulate_mxu, donate_argnums=0)
        return self._mxu_cache[key]

    def _plan_mxu(self, starts: np.ndarray, codes: np.ndarray):
        """Split rows into one contiguous chunk per device and slot-plan
        each with a common E; None on skew (scatter fallback)."""
        from ..ops import mxu_pileup

        total = len(starts)
        if total == 0:
            return None
        w = codes.shape[1]
        per = -(-total // self.n)
        if per * self.n != total:
            # equalize chunk lengths with PAD rows; they plan like real
            # rows into tile 0 and count nothing (codes one-hot to zero)
            starts = np.concatenate(
                [starts, np.zeros(per * self.n - total, dtype=starts.dtype)])
            codes = np.concatenate(
                [codes, np.full((per * self.n - total, w), PAD_CODE,
                                dtype=np.uint8)])
        bounds = [(i * per, (i + 1) * per) for i in range(self.n)]
        hists = []
        for lo, hi in bounds:
            tile_of = starts[lo:hi] // self._tile
            hists.append((tile_of, np.bincount(tile_of,
                                               minlength=self._n_tiles)))
        emax = max(int(pt.max(initial=1)) for _t, pt in hists)
        e_fine = round_rows_grid(emax)
        e = e_fine
        if self._tuner is not None and self._tuner.winner is None:
            # autotune timing phase: stay on the pow2 grid so warm and
            # timed slabs share one compiled shape (see _plan_prelude)
            e = round_rows_pow2(e_fine)
        # gate on the fine-grid economics (same rule as _plan_prelude)
        if self.n * self._n_tiles * e_fine / total > mxu_pileup.MAX_BLOWUP:
            return None
        slots = np.empty(per * self.n, dtype=np.int32)
        for (lo, hi), (tile_of, per_tile) in zip(bounds, hists):
            slots[lo:hi] = mxu_pileup.assign_slots(tile_of, per_tile, e)
        return starts, codes, slots, e

    def _pallas_accumulate(self, w: int, plan):
        """Cached shard_map'd Pallas accumulate: per-device tile-CSR
        histogram over the FULL padded position axis (dp's even row
        chunks carry global starts), then the same reduce-scatter as
        the scatter path."""
        from ..ops import pallas_pileup as pp

        key = (w, plan.row_block, plan.max_blocks, plan.n_rows_padded,
               plan.n_tiles)
        if key in self._pallas_cache:
            return self._pallas_cache[key]
        padded_len = self.padded_len
        interp = self._pallas_interpret

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(ALL, None), P(ALL), P(ALL, None), P(ALL),
                           P(ALL, None), P(ALL, None)),
                 out_specs=P(ALL, None), check_vma=False)
        def accumulate(counts_blk, starts, packed, rank, blk_lo, blk_n):
            local = pp.local_tile_counts(
                starts, packed, rank, blk_lo[0], blk_n[0],
                tile=pp.TILE_POSITIONS, n_tiles=plan.n_tiles, width=w,
                row_block=plan.row_block, max_blocks=plan.max_blocks,
                n_rows_padded=plan.n_rows_padded, out_len=padded_len,
                interpret=interp)
            return counts_blk + jax.lax.psum_scatter(
                local, ALL, scatter_dimension=0, tiled=True)

        fn = jax.jit(accumulate, donate_argnums=0)
        self._pallas_cache[key] = fn
        return fn

    def _plan_pallas(self, starts: np.ndarray, codes: np.ndarray):
        """Even per-device chunks + stacked CSR plans; None only for
        unsupported widths (odd halo-split or overhang > tile/2)."""
        from ..ops import pallas_pileup as pp

        total = len(starts)
        if total == 0:
            return None
        w = codes.shape[1]
        if w % 2 or pp._cw(w) * 2 > pp.TILE_POSITIONS:
            return None
        per = -(-total // self.n)
        if per * self.n != total:
            starts = np.concatenate(
                [starts, np.zeros(per * self.n - total,
                                  dtype=starts.dtype)])
            codes = np.concatenate(
                [codes, np.full((per * self.n - total, w), PAD_CODE,
                                dtype=np.uint8)])
        plan = pp.plan_rows_stacked(
            starts.reshape(self.n, per), w, self.padded_len,
            pp.TILE_POSITIONS)
        return starts, codes, plan

    # -- streaming input --------------------------------------------------
    def add(self, batch: SegmentBatch) -> None:
        from ..ops.pileup import run_tuned_slab
        from ..resilience.faultinject import fault_check

        fault_check("pileup_dispatch")
        kernel_name = (self._tuner.kernel if self._tuner is not None
                       else self.pileup)
        for w, (starts, codes) in sorted(batch.buckets.items()):
            if self.wire == "delta8":
                # canonical sorted order: what makes the per-chunk
                # delta chains uint8-tight (wire.codec.canonicalize_rows)
                starts, codes = canonicalize_rows(starts, codes)

            def plan_mxu():
                return self._plan_mxu(np.asarray(starts), np.asarray(codes))

            def plan_pallas():
                return self._plan_pallas(np.asarray(starts),
                                         np.asarray(codes))

            def exec_pallas(planned):
                p_starts, p_codes, plan = planned
                fn = self._pallas_accumulate(w, plan)
                self.bytes_h2d += (plan.rank.nbytes + plan.blk_lo.nbytes
                                   + plan.blk_n.nbytes)
                account_h2d(plan.rank.nbytes + plan.blk_lo.nbytes
                            + plan.blk_n.nbytes)
                st_dev, pk_dev = self.put_rows(
                    p_starts.astype(np.int32), p_codes)
                self._counts = fn(
                    self.counts, st_dev, pk_dev,
                    self.ship_kernel_operand(plan.rank.reshape(-1)),
                    self.ship_kernel_operand(plan.blk_lo),
                    self.ship_kernel_operand(plan.blk_n))

            def exec_mxu(plan):
                p_starts, p_codes, slots, e = plan
                fn = self._mxu_accumulate(e, w)
                self.bytes_h2d += slots.nbytes
                account_h2d(slots.nbytes)
                st_dev, pk_dev = self.put_rows(p_starts, p_codes)
                self._counts = fn(
                    self.counts, st_dev, pk_dev,
                    self.ship_kernel_operand(slots))

            def exec_scatter():
                s = len(starts)
                # rows must shard evenly over the mesh (matters for
                # non-power-of-two device counts)
                target = -(-s // self.n) * self.n
                sts, cds = starts, codes
                if target != s:
                    sts = np.concatenate(
                        [sts, np.zeros(target - s, dtype=np.int32)])
                    cds = np.concatenate(
                        [cds, np.full((target - s, cds.shape[1]),
                                      PAD_CODE, dtype=np.uint8)])
                for lo, hi in iter_row_slices(target, w, multiple_of=self.n):
                    # each slice ships through the run's wire codec,
                    # chunked to match the slice's n-way row sharding
                    st_dev, pk_dev = self.put_rows(sts[lo:hi],
                                                   cds[lo:hi])
                    self._counts = self._accumulate(
                        self.counts, st_dev, pk_dev)

            # one-element fetch, not block_until_ready: the latter returns
            # early over the tunneled runtime (tools/tunnel_probe.py)
            key = run_tuned_slab(
                self._tuner, self.pileup, len(starts), w,
                plan_pallas if kernel_name == "pallas" else plan_mxu,
                exec_pallas if kernel_name == "pallas" else exec_mxu,
                exec_scatter,
                lambda: np.asarray(self._counts[0, 0]))
            if self._tuner is not None and self._tuner.stats is not None:
                self.strategy_used["autotune"] = self._tuner.stats
            key = f"{key}_w{w}"
            self.strategy_used[key] = self.strategy_used.get(key, 0) + 1
