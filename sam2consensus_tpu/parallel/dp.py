"""Sharded consensus pipeline: DP segment scatter → reduce-scatter → SP vote.

The distributed design (SURVEY.md §5 "Distributed communication backend"):
the count tensor is a sum-decomposable sufficient statistic, so data
parallelism plus one collective reduction is *exact* — no read ordering or
tie-breaking concerns.  The collective rides XLA:

1. each device expands + scatter-adds its shard of segment rows
   (``encoder.events.SegmentBatch``: flat start + uint8 code row per read)
   into a full-length local count tensor (pure DP over the flattened
   ("dp","sp") axes);
2. one ``lax.psum_scatter`` both sums the local tensors and leaves each
   device holding one contiguous block of the position axis — a
   reduce-scatter, bandwidth-optimal vs. all-reduce (factor n less traffic),
   and the result is already in the layout the vote wants;
3. the vote (elementwise per position) runs on the position-sharded blocks —
   sequence parallelism with zero extra communication;
4. results reach the host as one device-sharded array fetch.

On a single host the collectives ride ICI; on multi-host meshes the same
code rides DCN (JAX mesh abstraction covers both, no NCCL/MPI analogue is
needed).  The accumulator state stays position-sharded between chunks, so
streaming input and checkpoint/resume compose with sharding.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..constants import NUM_SYMBOLS, PAD_CODE
from ..encoder.events import SegmentBatch
from ..ops.pileup import expand_segment_positions, iter_row_slices

ALL = ("dp", "sp")  # both mesh axes flattened: pure-DP / pure-SP phases


class ShardedConsensus:
    """Streaming sharded accumulate + vote over a ("dp", "sp") mesh."""

    def __init__(self, mesh: Mesh, total_len: int):
        self.mesh = mesh
        self.n = mesh.size
        self.total_len = total_len
        # position axis padded so every device owns an equal block; the
        # sacrificial scatter row (index total_len) lives inside the pad.
        self.block = -(-(total_len + 1) // self.n)
        self.padded_len = self.block * self.n

        counts_spec = NamedSharding(mesh, P(ALL, None))
        self._counts = jax.device_put(
            jnp.zeros((self.padded_len, NUM_SYMBOLS), dtype=jnp.int32),
            counts_spec)
        self._row_spec = NamedSharding(mesh, P(ALL))
        self._mat_spec = NamedSharding(mesh, P(ALL, None))

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(ALL, None), P(ALL), P(ALL, None)),
                 out_specs=P(ALL, None))
        def accumulate(counts_blk, starts, codes):
            pos, code = expand_segment_positions(starts, codes, total_len)
            local = jnp.zeros((self.padded_len, NUM_SYMBOLS), dtype=jnp.int32)
            local = local.at[pos, code].add(1)
            # reduce over every device AND scatter position blocks: each
            # device leaves holding its own summed block (reduce-scatter).
            return counts_blk + jax.lax.psum_scatter(
                local, ALL, scatter_dimension=0, tiled=True)

        self._accumulate = jax.jit(accumulate, donate_argnums=0)

    # -- streaming input --------------------------------------------------
    def add(self, batch: SegmentBatch) -> None:
        for w, (starts, codes) in sorted(batch.buckets.items()):
            s = len(starts)
            # rows must shard evenly over the mesh (matters for
            # non-power-of-two device counts)
            target = -(-s // self.n) * self.n
            if target != s:
                starts = np.concatenate(
                    [starts, np.zeros(target - s, dtype=np.int32)])
                codes = np.concatenate(
                    [codes, np.full((target - s, codes.shape[1]), PAD_CODE,
                                    dtype=np.uint8)])
            for lo, hi in iter_row_slices(target, w, multiple_of=self.n):
                self._counts = self._accumulate(
                    self._counts,
                    jax.device_put(starts[lo:hi], self._row_spec),
                    jax.device_put(codes[lo:hi], self._mat_spec))

    # -- state ------------------------------------------------------------
    @property
    def counts(self) -> jax.Array:
        """Position-sharded counts including the pad rows ([padded_len, 6])."""
        return self._counts

    def counts_host(self) -> np.ndarray:
        """Valid counts on host, ``[total_len, 6]``."""
        return np.asarray(self._counts)[: self.total_len]

    def restore(self, counts: np.ndarray) -> None:
        """Load checkpointed counts (``[total_len, 6]``), re-sharded."""
        padded = np.zeros((self.padded_len, NUM_SYMBOLS), dtype=np.int32)
        padded[: self.total_len] = counts
        self._counts = jax.device_put(
            jnp.asarray(padded), NamedSharding(self.mesh, P(ALL, None)))

    # -- vote -------------------------------------------------------------
    def vote(self, t_luts: np.ndarray, min_depth: int
             ) -> Tuple[np.ndarray, np.ndarray]:
        """Position-sharded vote; returns host (syms [T, total_len], cov)."""
        from ..ops.vote import vote_block

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(ALL, None), P(None, None)),
                 out_specs=(P(None, ALL), P(ALL)))
        def voted(counts_blk, luts):
            return vote_block(counts_blk, luts, min_depth)

        syms, cov = jax.jit(voted)(self._counts, jnp.asarray(t_luts))
        return (np.asarray(syms)[:, : self.total_len],
                np.asarray(cov, dtype=np.int64)[: self.total_len])
