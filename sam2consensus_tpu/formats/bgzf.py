"""BGZF container: block index, parallel inflate, file-like reassembly.

BGZF (the BAM/htslib container, SAM spec §4.1) is gzip with a twist that
matters enormously for ingest throughput: the stream is a concatenation of
independent deflate members, each ≤64 KiB of uncompressed payload, each
carrying its own compressed size (``BSIZE``) in a gzip FEXTRA subfield
(``SI1='B', SI2='C'``).  That makes every block an independently seekable,
independently inflatable decode shard — ``scan_blocks`` walks the headers
in ONE pass (a few bytes read per 64 KiB block), and :class:`BgzfReader`
then inflates blocks on a small thread pool (``zlib`` releases the GIL)
with ordered reassembly, so a multi-core host decompresses at N× the
serial ``gzip.open`` rate while the consumer still sees one ordered
byte stream.

Failure semantics (wired into the resilience ladder's vocabulary):

* a missing EOF marker (the canonical 28-byte empty block htslib writes
  last) or a header that does not parse ⇒ :class:`BgzfTruncation` /
  :class:`BgzfError` at OPEN time, with the precise byte offset — callers
  (``formats.open_alignment_input``) can fall back to a sibling SAM;
* a block whose payload fails to inflate or whose CRC32/ISIZE disagree ⇒
  :class:`BgzfCorruptBlock` mid-stream, carrying the block's compressed
  offset; classified TRANSIENT by ``resilience.policy.classify`` (it is
  OSError-shaped: storage/transport bitrot, worth one retry) and counted
  as ``format/bgzf_corrupt``;
* the ``bam_inflate`` fault-injection site fires per inflated block, so
  the chaos harness can rehearse all of the above deterministically.

Everything here is stdlib (``zlib``, ``struct``, ``concurrent.futures``)
— no htslib, no pysam.
"""

from __future__ import annotations

import io
import os
import struct
import zlib
from typing import BinaryIO, Iterator, List, Optional, Tuple

#: gzip magic + deflate method + FEXTRA flag — every BGZF block starts so
_BGZF_MAGIC = b"\x1f\x8b\x08\x04"

#: the canonical 28-byte EOF marker (an empty BGZF block), byte for byte
#: what htslib writes; its absence from a file tail means truncation
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000")

#: max uncompressed payload per block (spec: 2^16); writers cap input so
#: the compressed block also fits BSIZE's u16
MAX_BLOCK_UDATA = 65280


class BgzfError(ValueError):
    """Malformed BGZF container (header/structure level)."""

    def __init__(self, msg: str, offset: int = -1):
        super().__init__(msg)
        self.offset = offset


class BgzfTruncation(BgzfError):
    """The stream ends without the BGZF EOF marker (or mid-block)."""


class BgzfCorruptBlock(BgzfError):
    """A block inflated wrong (zlib error / CRC mismatch / ISIZE
    mismatch).  ``transient = True`` is the resilience vocabulary:
    storage-level bitrot is transport-shaped, so
    ``resilience.policy.classify`` rates it TRANSIENT (via this marker
    attribute — no import cycle) and retry policies give it one more
    chance before the format layer falls back or fails with the
    offset."""

    transient = True


def sniff_bgzf(head: bytes) -> bool:
    """True when ``head`` (>= 18 bytes) opens a BGZF member: gzip magic
    with FEXTRA set and a ``BC`` subfield of length 2 somewhere in the
    extra field (the spec allows other subfields alongside)."""
    if len(head) < 18 or head[:4] != _BGZF_MAGIC:
        return False
    xlen = struct.unpack_from("<H", head, 10)[0]
    extra = head[12:12 + xlen]
    pos = 0
    while pos + 4 <= len(extra):
        si1, si2, slen = extra[pos], extra[pos + 1], \
            struct.unpack_from("<H", extra, pos + 2)[0]
        if si1 == 66 and si2 == 67 and slen == 2:
            return True
        pos += 4 + slen
    return False


def is_bgzf(path: str) -> bool:
    """Sniff the file's first block header without consuming the handle."""
    try:
        with open(path, "rb") as fh:
            return sniff_bgzf(fh.read(64))
    except OSError:
        return False


def _block_bsize(head: bytes, offset: int) -> int:
    """Total compressed size of the block whose header bytes are ``head``
    (read at file ``offset``); raises BgzfError when it isn't one."""
    if len(head) < 18:
        raise BgzfTruncation(
            f"BGZF stream ends mid-header at offset {offset}", offset)
    if head[:4] != _BGZF_MAGIC:
        raise BgzfError(
            f"not a BGZF block at offset {offset} "
            f"(magic {head[:4]!r})", offset)
    xlen = struct.unpack_from("<H", head, 10)[0]
    extra = head[12:12 + xlen]
    pos = 0
    while pos + 4 <= len(extra):
        si1, si2, slen = extra[pos], extra[pos + 1], \
            struct.unpack_from("<H", extra, pos + 2)[0]
        if si1 == 66 and si2 == 67 and slen == 2:
            if pos + 6 > len(extra):
                raise BgzfTruncation(
                    f"BGZF BC subfield truncated at offset {offset}",
                    offset)
            return struct.unpack_from("<H", extra, pos + 4)[0] + 1
        pos += 4 + slen
    raise BgzfError(
        f"gzip member at offset {offset} has no BGZF BC subfield "
        "(plain gzip, not BGZF)", offset)


def scan_blocks(fh: BinaryIO, *, require_eof: bool = True
                ) -> List[Tuple[int, int]]:
    """One-pass virtual-offset block index: ``[(coffset, clen), ...]``.

    Reads only each block's header (18 bytes + seek), so indexing a
    multi-GB BAM costs one sweep of page-cache-friendly small reads.
    Validates the chain tiles the file exactly and (``require_eof``)
    that the stream ends with the EOF marker — the truncation check the
    issue's failure ladder keys on.  The handle is left at offset 0.
    """
    fh.seek(0, os.SEEK_END)
    size = fh.tell()
    blocks: List[Tuple[int, int]] = []
    offset = 0
    while offset < size:
        fh.seek(offset)
        head = fh.read(18 + 64)     # header + generous extra-field room
        bsize = _block_bsize(head, offset)
        if offset + bsize > size:
            raise BgzfTruncation(
                f"BGZF block at offset {offset} claims {bsize} bytes but "
                f"only {size - offset} remain (truncated download?)",
                offset)
        blocks.append((offset, bsize))
        offset += bsize
    if require_eof:
        if not blocks:
            raise BgzfTruncation("empty BGZF stream (no EOF marker)", 0)
        last_off, last_len = blocks[-1]
        fh.seek(last_off)
        if fh.read(last_len) != BGZF_EOF:
            raise BgzfTruncation(
                f"BGZF stream does not end with the EOF marker (last "
                f"block at offset {last_off}); file is likely truncated",
                last_off)
    fh.seek(0)
    return blocks


def inflate_block(data: bytes, offset: int = -1,
                  fault_check=None) -> bytes:
    """Inflate ONE complete BGZF block (header+payload+trailer bytes),
    verifying CRC32 and ISIZE; raises :class:`BgzfCorruptBlock` with the
    block's compressed offset on any disagreement."""
    if fault_check is not None:
        fault_check("bam_inflate")
    if len(data) < 26:
        raise BgzfCorruptBlock(
            f"BGZF block at offset {offset} too short ({len(data)} B)",
            offset)
    xlen = struct.unpack_from("<H", data, 10)[0]
    payload = data[12 + xlen:-8]
    crc_want, isize = struct.unpack_from("<II", data, len(data) - 8)
    try:
        out = zlib.decompress(payload, wbits=-15)
    except zlib.error as exc:
        raise BgzfCorruptBlock(
            f"BGZF block at offset {offset} failed to inflate: {exc}",
            offset) from exc
    if len(out) != isize:
        raise BgzfCorruptBlock(
            f"BGZF block at offset {offset} inflated to {len(out)} B, "
            f"ISIZE says {isize}", offset)
    crc_got = zlib.crc32(out) & 0xFFFFFFFF
    if crc_got != crc_want:
        raise BgzfCorruptBlock(
            f"BGZF block at offset {offset} CRC mismatch "
            f"(got {crc_got:#010x}, want {crc_want:#010x})", offset)
    return out


class BgzfReader(io.RawIOBase):
    """Ordered, optionally parallel BGZF decompressor with a file-like
    binary surface (``read``/``readline``/``readinto``/iteration), so it
    drops straight into :class:`io.sam.ReadStream` and the BAM decoder.

    ``threads > 1`` keeps a sliding window of ``4*threads`` STRIPES —
    runs of :data:`STRIPE_BLOCKS` consecutive blocks, inflated as one
    task so executor/future overhead amortizes over ~1 MB of output
    instead of 64 KiB — in flight on a shared
    :class:`~concurrent.futures.ThreadPoolExecutor` (zlib inflates with
    the GIL released); results are consumed strictly in file order, so
    downstream semantics are identical to serial decode.  ``tell()``
    reports the UNCOMPRESSED stream offset — what checkpoint resume and
    ``ReadStream.byte_offset`` expect.

    ``on_corrupt_retry``: one in-place re-read+re-inflate is attempted
    for a corrupt block (bitrot on the first read is transient by
    classification); a second failure propagates.
    """

    #: blocks inflated per pool task (~1 MB of output at the 64 KiB
    #: block ceiling): amortizes submit/result overhead, and the
    #: consumer joins 16x fewer chunks
    STRIPE_BLOCKS = 16

    def __init__(self, path_or_fh, threads: int = 1,
                 fault_check=None, metrics=None):
        super().__init__()
        if isinstance(path_or_fh, (str, os.PathLike)):
            self._fh: BinaryIO = open(path_or_fh, "rb")
            self._owns = True
            self.name = os.fspath(path_or_fh)
        else:
            self._fh = path_or_fh
            self._owns = False
            self.name = getattr(path_or_fh, "name", "<bgzf>")
        self._fault_check = fault_check
        self._metrics = metrics
        self.blocks = scan_blocks(self._fh)
        # pool workers read blocks CONCURRENTLY: pread(2) has no shared
        # seek state, so each worker addresses its block independently;
        # handles without a real fd (BytesIO) serialize under a lock
        try:
            self._fd: Optional[int] = self._fh.fileno()
        except (AttributeError, OSError, io.UnsupportedOperation):
            self._fd = None
        import threading

        self._read_lock = threading.Lock()
        self._threads = max(1, int(threads))
        self._pool = None
        self._inflight: List = []      # [(index, future)] in file order
        self._next_submit = 0
        self._next_block = 0
        self._buf = b""
        self._buf_pos = 0
        self._upos = 0                 # uncompressed offset of _buf start
        if self._threads > 1:
            # stripes run on the PROCESS-WIDE ingest pool (the same
            # scheduler budget as the byte-shard decode workers,
            # ingest.shared_pool): a serve queue opening many
            # containers no longer accumulates one idle pool per
            # reader, and the --decode-threads policy is the one
            # thread budget everywhere.  The pool is shared, so
            # close() must never shut it down — and submits go through
            # ingest.pool_submit (never a cached executor), because a
            # later open with a larger budget replaces the pool.
            from .. import ingest

            self._pool = ingest.shared_pool(self._threads)

    # -- block plumbing ----------------------------------------------------
    def _read_raw(self, index: int) -> bytes:
        off, length = self.blocks[index]
        if self._fd is not None:
            data = os.pread(self._fd, length, off)
        else:
            with self._read_lock:
                self._fh.seek(off)
                data = self._fh.read(length)
        if len(data) != length:
            raise BgzfTruncation(
                f"BGZF block at offset {off} shrank under us "
                f"({len(data)}/{length} B)", off)
        return data

    def _inflate(self, index: int) -> bytes:
        off = self.blocks[index][0]
        data = self._read_raw(index)
        try:
            return inflate_block(data, off, self._fault_check)
        except (BgzfCorruptBlock, ConnectionError, TimeoutError):
            # transient by classification (CRC/inflate bitrot, or an
            # injected bam_inflate rpc/timeout fault modeling it): one
            # re-read + re-inflate before giving up — a persistent
            # fault propagates with the block offset riding it
            if self._metrics is not None:
                self._metrics.add("format/bgzf_corrupt")
            return inflate_block(self._read_raw(index), off,
                                 self._fault_check)

    def _inflate_stripe(self, i0: int, count: int) -> bytes:
        if count == 1:
            return self._inflate(i0)
        return b"".join(self._inflate(i0 + k) for k in range(count))

    def _next_inflated(self) -> Optional[bytes]:
        """The next stripe's uncompressed bytes, in strict file order."""
        n = len(self.blocks)
        if self._next_block >= n:
            return None
        if self._pool is None:
            out = self._inflate(self._next_block)
            self._next_block += 1
            return out
        from .. import ingest

        window = self._threads * 4
        stripe = self.STRIPE_BLOCKS
        while self._next_submit < n and len(self._inflight) < window:
            count = min(stripe, n - self._next_submit)
            # via pool_submit, NOT a cached executor: a concurrent open
            # with a larger thread budget grows (replaces) the shared
            # pool, and a submit on the retired executor would raise
            self._inflight.append(
                (self._next_submit,
                 ingest.pool_submit(self._threads, self._inflate_stripe,
                                    self._next_submit, count)))
            self._next_submit += count
        index, fut = self._inflight.pop(0)
        assert index == self._next_block
        self._next_block = min(n, index + stripe)
        return fut.result()

    def read_blocks(self) -> Iterator[bytes]:
        """Yield each block's uncompressed payload in order (the
        bulk-consumer path: the BAM decoder batches over these without
        the line-orientated buffer below).  Resumes from the current
        stream position's block boundary."""
        while True:
            out = self._next_inflated()
            if out is None:
                return
            if out:
                yield out

    # -- file-like surface -------------------------------------------------
    def readable(self) -> bool:
        return True

    def _fill(self) -> bool:
        while True:
            nxt = self._next_inflated()
            if nxt is None:
                return False
            if nxt:
                self._upos += len(self._buf)
                self._buf = nxt
                self._buf_pos = 0
                return True

    def read(self, n: int = -1) -> bytes:
        parts = []
        want = n if n is not None and n >= 0 else None
        while want is None or want > 0:
            avail = len(self._buf) - self._buf_pos
            if avail == 0:
                if not self._fill():
                    break
                continue
            take = avail if want is None else min(avail, want)
            parts.append(self._buf[self._buf_pos:self._buf_pos + take])
            self._buf_pos += take
            if want is not None:
                want -= take
        return b"".join(parts)

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def readline(self, limit: int = -1) -> bytes:
        parts = []
        while True:
            nl = self._buf.find(b"\n", self._buf_pos)
            if nl >= 0:
                parts.append(self._buf[self._buf_pos:nl + 1])
                self._buf_pos = nl + 1
                return b"".join(parts)
            parts.append(self._buf[self._buf_pos:])
            self._buf_pos = len(self._buf)
            if not self._fill():
                return b"".join(parts)

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        line = self.readline()
        if not line:
            raise StopIteration
        return line

    def tell(self) -> int:
        """UNCOMPRESSED stream offset (checkpoint/resume coordinates)."""
        return self._upos + self._buf_pos

    def seek(self, offset: int, whence: int = os.SEEK_SET) -> int:
        """Seek in uncompressed coordinates.  Forward-only from 0 in the
        general case would be O(file); instead restart the block cursor
        and skip — fine for the two real callers (rewind; checkpoint
        resume to a recorded offset, which re-inflates only the prefix
        it skips and on a pool host does so in parallel)."""
        if whence == os.SEEK_CUR:
            offset += self.tell()
        elif whence == os.SEEK_END:
            raise io.UnsupportedOperation("BGZF: SEEK_END unsupported")
        if offset < 0:
            raise ValueError("negative seek position")
        # restart decode from block 0 and discard up to `offset`
        self._drain_pool()
        self._next_block = 0
        self._next_submit = 0
        self._buf = b""
        self._buf_pos = 0
        self._upos = 0
        remaining = offset
        while remaining > 0:
            if not self._fill():
                break
            take = min(remaining, len(self._buf))
            self._buf_pos = take
            remaining -= take
        return self.tell()

    def _drain_pool(self) -> None:
        for _i, fut in self._inflight:
            fut.cancel()
        self._inflight = []

    def close(self) -> None:
        if self.closed:
            return
        self._drain_pool()
        # the inflate pool is the shared ingest executor — other
        # readers (and future opens) keep using it; just drop the ref
        self._pool = None
        if self._owns:
            self._fh.close()
        super().close()


# -- writer (fixtures/tools; the reader is the hot path) -------------------
def compress_block(udata: bytes, level: int = 6) -> bytes:
    """One complete BGZF block for ≤``MAX_BLOCK_UDATA`` bytes of input."""
    if len(udata) > MAX_BLOCK_UDATA:
        raise ValueError(f"BGZF block payload {len(udata)} exceeds "
                         f"{MAX_BLOCK_UDATA}")
    c = zlib.compressobj(level, zlib.DEFLATED, -15)
    payload = c.compress(udata) + c.flush()
    # BSIZE field = total block length - 1: header(18) + payload + trailer(8)
    bsize_m1 = len(payload) + 18 + 8 - 1
    head = (_BGZF_MAGIC + b"\x00\x00\x00\x00\x00\xff"
            + struct.pack("<H", 6)            # XLEN
            + b"BC" + struct.pack("<H", 2)
            + struct.pack("<H", bsize_m1))
    trail = struct.pack("<II", zlib.crc32(udata) & 0xFFFFFFFF, len(udata))
    return head + payload + trail


def write_bgzf(data: bytes, path: str, level: int = 6,
               block_udata: int = MAX_BLOCK_UDATA) -> str:
    """Write ``data`` as a BGZF stream (blocks + EOF marker)."""
    with open(path, "wb") as fh:
        for off in range(0, len(data), block_udata):
            fh.write(compress_block(data[off:off + block_udata], level))
        fh.write(BGZF_EOF)
    return path
