"""Pluggable input formats: one open call, every alignment container.

``open_alignment_input(path, fmt="auto")`` is the single entry every
consumer routes through — the CLI, the serve runner's cold and
decode-ahead paths, the bench harness and the tests — returning an
:class:`AlignmentInput` whose ``contigs``/``stream`` pair drops into the
existing ``backend.run(contigs, stream, cfg)`` seam unchanged.

Formats and their decode routes:

==========  ==============================================================
``sam``     plain SAM text (``io/sam.py`` — mmap'd zero-copy blocks into
            the native C++ decoder)
``sam.gz``  gzip-compressed SAM.  Sniffed per FILE, not per suffix:
            htslib-written ``.sam.gz`` are really BGZF, whose ≤64 KiB
            independently-deflated blocks inflate on a ``--decode-threads``
            worker pool (``formats/bgzf.py``) with ordered reassembly;
            plain single-member gzip keeps the serial streaming path.
``bam``     BGZF container + binary records (``formats/bam.py``): the
            block-parallel inflate feeds a vectorized record decoder that
            emits the encoder's segment rows without ever materializing
            SAM text lines.
==========  ==============================================================

Failure semantics (the counters ride the run's metrics registry):

* BGZF truncation / structural damage is detected at OPEN time by the
  one-pass block scan (missing EOF marker, mid-block EOF, bad headers):
  counted ``format/bgzf_corrupt``; when a same-stem sibling SAM exists
  (``x.bam`` → ``x.sam``/``x.sam.gz``, ``x.sam.gz`` → ``x.sam``) the
  open FALLS BACK to it — the text rung of the decode ladder — counted
  ``format/fallback`` (and the chosen path recorded in the
  ``format/input`` gauge); with no sibling the error propagates with the
  precise block offset.
* A mid-stream corrupt block (CRC/ISIZE/inflate failure) is TRANSIENT:
  the reader re-reads and re-inflates it once (bitrot on the wire or a
  racing writer), counted ``format/bgzf_corrupt``; a second failure
  raises :class:`~.bgzf.BgzfCorruptBlock` carrying the block offset.
* The ``bam_inflate`` fault-injection site (``resilience/faultinject``)
  fires per inflated block, so chaos runs rehearse all of the above.
"""

from __future__ import annotations

import gzip
import io as _io
import os
from dataclasses import dataclass, field
from typing import List, Optional

from ..io.sam import Contig, ReadStream, read_header
from . import bgzf as _bgzf

FORMATS = ("auto", "sam", "sam.gz", "bam")

#: gzip magic (any flavor)
_GZ_MAGIC = b"\x1f\x8b"


class FormatError(ValueError):
    """Input does not match the requested/detected format."""


@dataclass
class AlignmentInput:
    """An opened alignment source, backend-ready.

    ``stream`` is a :class:`~..io.sam.ReadStream` (SAM flavors) or
    :class:`~.bam.BamReadStream` (BAM); both expose the counting surface
    the CLI and backends consume.  ``format`` is the RESOLVED format
    (``sam`` / ``sam.gz`` / ``sam.bgzf`` / ``bam``); ``fallback_from``
    records a corrupt-container fallback's original path."""

    path: str
    format: str
    contigs: List[Contig]
    stream: object
    handle: object = None
    fallback_from: Optional[str] = None

    def close(self) -> None:
        h = self.handle
        if h is not None:
            try:
                h.close()
            except OSError:
                pass


def detect_format(path: str) -> str:
    """Resolve a file's on-disk format by magic bytes, not suffix:
    ``sam`` | ``sam.gz`` (plain gzip) | ``sam.bgzf`` | ``bam``."""
    with open(path, "rb") as fh:
        head = fh.read(64)
    if head[:2] != _GZ_MAGIC:
        return "sam"
    if not _bgzf.sniff_bgzf(head):
        return "sam.gz"
    # BGZF: BAM iff the first inflated bytes open with the BAM magic
    with open(path, "rb") as fh:
        try:
            bsize = _bgzf._block_bsize(head, 0)
            first = _bgzf.inflate_block(fh.read(bsize), 0)
        except _bgzf.BgzfError:
            # damaged first block: defer to the opener, which runs the
            # full scan and owns the fallback path; suffix is the best
            # remaining hint
            return "bam" if path.endswith(".bam") else "sam.bgzf"
    return "bam" if first[:4] == b"BAM\x01" else "sam.bgzf"


def sibling_sam(path: str) -> Optional[str]:
    """A same-stem plain/gzip SAM next to ``path``, if one exists —
    the text fallback target for a damaged binary container."""
    stem = path
    for ext in (".bam", ".gz"):
        if stem.endswith(ext):
            stem = stem[: -len(ext)]
    if stem.endswith(".sam.bgzf"):
        stem = stem[: -len(".bgzf")]
    candidates = []
    if not stem.endswith(".sam"):
        candidates.append(stem + ".sam")
    else:
        candidates.append(stem)
    candidates.append(stem + ".gz" if stem.endswith(".sam")
                      else stem + ".sam.gz")
    for cand in candidates:
        if cand != path and os.path.exists(cand):
            return cand
    return None


def _metrics():
    try:
        from .. import observability as obs

        return obs.metrics()
    except Exception:  # pragma: no cover - observability always imports
        return None


def _fault_check(site: str) -> None:
    from ..resilience.faultinject import fault_check

    fault_check(site)


def open_alignment_input(path: str, fmt: str = "auto",
                         binary: bool = False, on_lines=None,
                         threads: int = 1,
                         fallback: bool = True) -> AlignmentInput:
    """Open ``path`` as ``fmt`` (``auto`` sniffs magic bytes) and return
    the backend-ready (contigs, stream) pair.

    ``threads`` sizes the BGZF inflate pool (callers pass the resolved
    ``--decode-threads``); ``binary`` keeps text-SAM handles in bytes
    mode (the native decoder's contract) and is ignored for formats that
    are inherently binary.  ``fallback=False`` disables the
    corrupt-container sibling-SAM fallback (tests pin exact errors)."""
    if fmt not in FORMATS:
        raise FormatError(
            f"unknown input format {fmt!r} (use one of {FORMATS})")
    resolved = detect_format(path) if fmt == "auto" else fmt
    reg = _metrics()

    if resolved == "bam":
        try:
            reader = _bgzf.BgzfReader(path, threads=threads,
                                      fault_check=_fault_check,
                                      metrics=reg)
        except _bgzf.BgzfError as exc:
            return _bgzf_open_failed(path, fmt, binary, on_lines,
                                     threads, fallback, exc, reg)
        from .bam import BamReadStream, read_bam_header

        try:
            contigs, _text = read_bam_header(reader)
        except Exception:
            # the reader owns an fd and (threads > 1) a live pool: a
            # corrupt first block / damaged BAM header must not leak
            # them until GC — serve queues survive such jobs and would
            # accumulate idle inflate threads otherwise
            reader.close()
            raise
        stream = BamReadStream(reader, [c.name for c in contigs],
                               on_lines=on_lines)
        if reg is not None:
            reg.gauge("format/input").set_info(
                {"path": path, "format": "bam",
                 "blocks": len(reader.blocks), "threads": threads})
        return AlignmentInput(path=path, format="bam", contigs=contigs,
                              stream=stream, handle=reader)

    if resolved in ("sam.gz", "sam.bgzf"):
        bgzf_file = resolved == "sam.bgzf" or (
            fmt == "sam.gz" and _bgzf.is_bgzf(path))
        if bgzf_file:
            try:
                handle = _bgzf.BgzfReader(path, threads=threads,
                                          fault_check=_fault_check,
                                          metrics=reg)
            except _bgzf.BgzfError as exc:
                return _bgzf_open_failed(path, fmt, binary, on_lines,
                                         threads, fallback, exc, reg)
            resolved = "sam.bgzf"
        else:
            handle = gzip.open(path, "rb")
            resolved = "sam.gz"
        if not binary:
            base = handle if isinstance(handle, gzip.GzipFile) \
                else _io.BufferedReader(handle)
            handle = _io.TextIOWrapper(base, encoding="ascii",
                                       errors="strict")
        try:
            contigs, _n, first = read_header(handle)
        except Exception:
            handle.close()      # see the bam branch: no fd/pool leak
            raise
        if reg is not None:
            reg.gauge("format/input").set_info(
                {"path": path, "format": resolved, "threads": threads})
        return AlignmentInput(
            path=path, format=resolved, contigs=contigs,
            stream=ReadStream(handle, first, on_lines=on_lines),
            handle=handle)

    # plain SAM text
    if resolved != "sam":  # pragma: no cover - FORMATS exhausts above
        raise FormatError(f"unhandled format {resolved!r}")
    handle = open(path, "rb") if binary else open(
        path, "r", encoding="ascii", errors="strict")
    contigs, _n, first = read_header(handle)
    if reg is not None:
        reg.gauge("format/input").set_info({"path": path, "format": "sam"})
    return AlignmentInput(
        path=path, format="sam", contigs=contigs,
        stream=ReadStream(handle, first, on_lines=on_lines),
        handle=handle)


def _bgzf_open_failed(path, fmt, binary, on_lines, threads, fallback,
                      exc, reg) -> AlignmentInput:
    """A BGZF container failed its open-time scan (truncation / bad
    blocks).  Count it, then take the text rung — a sibling SAM — when
    one exists; else re-raise with the block offset."""
    if reg is not None:
        reg.add("format/bgzf_corrupt")
    sib = sibling_sam(path) if fallback else None
    if sib is None:
        raise exc
    if reg is not None:
        reg.add("format/fallback")
        reg.gauge("format/input").set_info(
            {"path": sib, "format": "fallback",
             "fallback_from": path,
             "error": f"{type(exc).__name__}: {exc}"})
    import logging

    logging.getLogger("sam2consensus_tpu.formats").warning(
        "damaged BGZF container %s (%s); falling back to sibling %s",
        path, exc, sib)
    out = open_alignment_input(sib, "auto", binary=binary,
                               on_lines=on_lines, threads=threads,
                               fallback=False)
    out.fallback_from = path
    return out
