"""BAM record decode: binary alignment records → the encoder's event stream.

BAM (SAM spec §4) is the binary twin of SAM inside a BGZF container.  For
this system it is the better wire format twice over: the BGZF blocks are
free parallel-decode shards (``formats/bgzf.py``), and the records carry
CIGAR as packed ``u32`` ops and SEQ as 4-bit nibbles — so ingest skips SAM
text tokenization entirely.  Nothing here materializes a SAM text line:
records go straight into the encoder's segment-row event stream
(``encoder/events.py``), preserving the reference's exact
RNAME/POS/CIGAR/SEQ-only semantics (no FLAG/MAPQ filtering,
``sam2consensus.py:195-206``; a record with zero CIGAR ops is the binary
form of ``CIGAR == "*"`` and is skipped the same way).

Two decode lanes, split per record, merged per batch:

* **fast lane** (vectorized, numpy): single-op ``M`` reads — the dominant
  shape in short-read data — whose nibbles decode by one LUT gather into
  ready segment rows; invalid nibbles / out-of-bounds spans are re-routed
  to the slow lane so strict-mode errors keep oracle-identical
  type+message;
* **slow lane** (per record, python): multi-op CIGARs, negative/wrapped
  POS, refID ``-1`` — decoded into op tuples and handed to the golden
  :class:`~..encoder.events.ReadEncoder`, which owns validation, the
  maxdel gate, insertion events and (for long reads) row segmentation.

The CPU oracle consumes the same records via :meth:`BamReadStream.records`
— :class:`BamRecord` renders its CIGAR string lazily, only when the
oracle's text walker asks for it.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..constants import PAD_CODE
from ..core.cigar import BAM_OPS as CIGAR_OPS
from ..core.cigar import render_ops
from ..io.sam import Contig

BAM_MAGIC = b"BAM\x01"

#: BAM 4-bit seq nibble -> ASCII ("=ACMGRSVTWYHKDBN", spec table)
NIB_TO_CHAR = np.frombuffer(b"=ACMGRSVTWYHKDBN", dtype=np.uint8).copy()

#: BAM nibble -> consensus symbol code (constants.ALPHABET); anything
#: outside uppercase ACGTN is INVALID (255), which triggers the oracle's
#: exact strict-mode KeyError downstream — identical to how the same
#: character in SAM text would fail.
NIB_TO_CODE = np.full(16, 255, dtype=np.uint8)
NIB_TO_CODE[1] = 1   # A
NIB_TO_CODE[2] = 2   # C
NIB_TO_CODE[4] = 3   # G
NIB_TO_CODE[8] = 5   # T
NIB_TO_CODE[15] = 4  # N


class BamParseError(ValueError):
    """Structurally broken BAM payload (bad magic, impossible sizes)."""

    def __init__(self, msg: str, offset: int = -1):
        super().__init__(msg)
        self.offset = offset


@dataclass(frozen=True)
class BamRecord:
    """One mapped alignment, fields pre-split from the binary record.

    Quacks like :class:`~..io.sam.SamRecord` (``refname``/``pos``/
    ``cigar``/``seq``) for the oracle and the golden encoder, but carries
    ``ops`` pre-parsed so the encoder's binary fast path never rebuilds
    or re-regexes CIGAR text."""

    refname: str
    pos: int                              # 0-based leftmost position
    ops: Tuple[Tuple[int, str], ...]      # ((length, op), ...)
    seq: str

    @property
    def cigar(self) -> str:
        """CIGAR text, rendered on demand (oracle/walker compatibility)."""
        return render_ops(self.ops)


def read_bam_header(fh) -> Tuple[List[Contig], str]:
    """Parse the BAM header from a binary stream positioned at byte 0:
    magic, embedded SAM header text, and the binary reference table
    (the authoritative one — it is what refIDs index).  Returns
    (contigs, sam_header_text); the stream is left at the first
    alignment record."""
    magic = fh.read(4)
    if magic != BAM_MAGIC:
        raise BamParseError(
            f"not a BAM stream (magic {magic!r}, expected {BAM_MAGIC!r})")
    l_text = struct.unpack("<i", _read_exact(fh, 4, "l_text"))[0]
    if l_text < 0:
        raise BamParseError(f"negative header length {l_text}")
    text = _read_exact(fh, l_text, "header text").decode(
        "utf-8", errors="replace")
    n_ref = struct.unpack("<i", _read_exact(fh, 4, "n_ref"))[0]
    if n_ref < 0:
        raise BamParseError(f"negative reference count {n_ref}")
    contigs: List[Contig] = []
    for i in range(n_ref):
        l_name = struct.unpack("<i", _read_exact(fh, 4, "l_name"))[0]
        if not 0 < l_name <= 1 << 20:
            raise BamParseError(f"reference {i}: bad name length {l_name}")
        raw = _read_exact(fh, l_name, "ref name")
        name = raw.rstrip(b"\x00").decode("ascii", errors="replace")
        l_ref = struct.unpack("<i", _read_exact(fh, 4, "l_ref"))[0]
        contigs.append(Contig(name, l_ref))
    return contigs, text


def _read_exact(fh, n: int, what: str) -> bytes:
    data = fh.read(n)
    if len(data) != n:
        raise BamParseError(
            f"BAM stream truncated reading {what} "
            f"({len(data)}/{n} bytes)")
    return data


#: fixed BAM record prefix: block_size, refID, pos, l_read_name, mapq,
#: bin, n_cigar_op, flag, l_seq  (bin_mq_nl and flag_nc split into their
#: little-endian component fields)
_REC_FIXED = struct.Struct("<iiiBBHHHi")


class _RecordIndex:
    """Offsets + fixed fields for the complete records in one buffer.

    With ``collect_bad``, record-bounded structural damage (fields
    overrun a block_size whose extent IS known) becomes an index ENTRY
    flagged in ``bad`` (exception in ``bad_exc``) instead of a raise —
    keeping the index a faithful walk of the raw record stream, so
    checkpoint-resume record-count skips stay exact (the native lane's
    ``_skip_whole_records`` semantics).  Framing loss (block_size < 32)
    raises in every mode.
    """

    __slots__ = ("off", "refid", "pos", "l_rn", "n_cig", "l_seq",
                 "consumed", "n", "base", "bad", "bad_exc")

    def __init__(self, buf, base_offset: int, collect_bad: bool = False):
        off: List[int] = []
        refid: List[int] = []
        pos: List[int] = []
        l_rn: List[int] = []
        n_cig: List[int] = []
        l_seq: List[int] = []
        bad: List[bool] = []
        self.bad_exc: Dict[int, BamParseError] = {}
        p = 0
        size = len(buf)
        self.base = base_offset
        unpack = _REC_FIXED.unpack_from
        while p + 4 <= size:
            if p + 24 > size:
                break
            (block_size, rid, ps, lrn, _mapq, _bin, nc, _flag,
             lsq) = unpack(buf, p)
            if block_size < 32:
                raise BamParseError(
                    f"BAM record at offset {base_offset + p} claims "
                    f"block_size {block_size} (< 32)", base_offset + p)
            if p + 4 + block_size > size:
                break
            # fields must fit the record (the C lane's identical check,
            # decoder.cpp): without it a corrupt l_seq/n_cigar makes the
            # decode lanes read the NEXT record's bytes as SEQ
            is_bad = (lsq < 0 or 32 + lrn + 4 * nc + (lsq + 1) // 2 + lsq
                      > block_size)
            if is_bad:
                exc = BamParseError(
                    f"BAM record at offset {base_offset + p}: fields "
                    f"overrun the record (block_size {block_size}, "
                    f"l_read_name {lrn}, n_cigar {nc}, l_seq {lsq})",
                    base_offset + p)
                exc.rec_len = 4 + int(block_size)
                if not collect_bad:
                    raise exc
                self.bad_exc[len(off)] = exc
            off.append(p)
            refid.append(rid)
            pos.append(ps)
            l_rn.append(lrn)
            n_cig.append(nc)
            l_seq.append(lsq)
            bad.append(is_bad)
            p += 4 + block_size
        self.consumed = p
        self.n = len(off)
        self.off = np.asarray(off, dtype=np.int64)
        self.refid = np.asarray(refid, dtype=np.int64)
        self.pos = np.asarray(pos, dtype=np.int64)
        self.l_rn = np.asarray(l_rn, dtype=np.int64)
        self.n_cig = np.asarray(n_cig, dtype=np.int64)
        self.l_seq = np.asarray(l_seq, dtype=np.int64)
        self.bad = np.asarray(bad, dtype=bool)


def _gather(buf: np.ndarray, offs: np.ndarray, width: int) -> np.ndarray:
    """``buf[offs[i] : offs[i]+width]`` for all i, as an [n, width] array."""
    if len(offs) == 0:
        return np.zeros((0, width), dtype=np.uint8)
    return buf[offs[:, None] + np.arange(width, dtype=np.int64)[None, :]]


def decode_seq(buf: np.ndarray, seq_off: int, l_seq: int) -> str:
    """One record's SEQ as text (slow lane / oracle path)."""
    nb = (l_seq + 1) // 2
    packed = buf[seq_off:seq_off + nb]
    chars = np.empty(nb * 2, dtype=np.uint8)
    chars[0::2] = NIB_TO_CHAR[packed >> 4]
    chars[1::2] = NIB_TO_CHAR[packed & 0xF]
    return chars[:l_seq].tobytes().decode("ascii")


def decode_ops(buf: np.ndarray, cig_off: int,
               n_cig: int) -> Tuple[Tuple[int, str], ...]:
    """One record's CIGAR as ((length, op), ...) (slow lane path)."""
    raw = buf[cig_off:cig_off + 4 * n_cig]
    if len(raw) != 4 * n_cig:
        raise BamParseError(
            f"CIGAR runs past the record ({len(raw)}/{4 * n_cig} bytes)")
    arr = np.ascontiguousarray(raw).view("<u4")
    if len(arr):
        bad = int((arr & 0xF).max())
        if bad >= len(CIGAR_OPS):
            raise BamParseError(
                f"CIGAR op code {bad} outside MIDNSHP=X")
    return tuple((int(v >> 4), CIGAR_OPS[v & 0xF]) for v in arr)


class BamRecordReader:
    """Streaming BAM record iterator over an inflated byte source.

    ``source`` is any binary file-like already positioned past the BAM
    header (``read_bam_header``).  Iterates :class:`BamRecord` for
    mapped records (``n_cigar_op > 0``), counting EVERY record — the
    binary analogue of a SAM body line — through ``count_cb`` so
    progress totals match the text path's semantics."""

    CHUNK = 1 << 22

    def __init__(self, source, count_cb=None, bytes_cb=None):
        self._src = source
        self._count_cb = count_cb
        self._bytes_cb = bytes_cb

    def chunks(self) -> Iterator[Tuple[np.ndarray, "_RecordIndex"]]:
        """Yield (buffer, record-index) pairs spanning the whole stream;
        records never straddle a yielded buffer.  With ``on_bad`` set,
        record-bounded structural damage becomes flagged INDEX ENTRIES
        (``idx.bad``) — still counted, still skippable by position —
        instead of a raise."""
        pending = b""
        base = 0
        while True:
            data = self._src.read(self.CHUNK)
            if not data:
                if pending:
                    raise BamParseError(
                        f"BAM stream ends mid-record at offset {base} "
                        f"({len(pending)} dangling bytes)", base)
                return
            buf = pending + data if pending else data
            idx = _RecordIndex(buf, base,
                               collect_bad=self.on_bad is not None)
            if idx.consumed == 0 and len(buf) > self.CHUNK * 4:
                raise BamParseError(
                    f"BAM record at offset {base} larger than "
                    f"{len(buf)} bytes — corrupt block_size?", base)
            if idx.n:
                arr = np.frombuffer(buf, dtype=np.uint8,
                                    count=idx.consumed)
                if self._bytes_cb is not None:
                    self._bytes_cb(idx.consumed)
                yield arr, idx
            pending = buf[idx.consumed:]
            base += idx.consumed

    def __iter__(self) -> Iterator[BamRecord]:
        for buf, idx in self.chunks():
            cig_off = idx.off + 36 + idx.l_rn
            seq_off = cig_off + 4 * idx.n_cig
            for k in range(idx.n):
                if self._count_cb is not None:
                    self._count_cb(1)
                if idx.bad[k]:
                    # flagged at index time: fields overrun the record's
                    # block_size, so decoding would read the NEXT
                    # record's bytes — absorb the INDEX exception, never
                    # walk the entry (idx.bad is always all-False in
                    # strict mode: the index raised instead)
                    self.on_bad(int(idx.base + idx.off[k]),
                                idx.bad_exc[k])
                    continue
                if idx.n_cig[k] == 0:
                    continue                      # CIGAR "*" analogue
                try:
                    rec = record_at(buf, idx, k, int(cig_off[k]),
                                    int(seq_off[k]), self.refname_fn)
                except BamParseError as exc:
                    # bad CIGAR op / refID outside the table: bounded
                    # to this indexed record, so tolerant mode skips
                    # exactly it
                    if self.on_bad is not None:
                        self.on_bad(int(idx.base + idx.off[k]), exc)
                        continue
                    raise
                yield rec
            del buf

    #: patched by the owning stream: refid -> display name ("*" for -1)
    refname_fn = staticmethod(lambda refid: "*")

    #: tolerant hook: ``on_bad(abs_offset, exc)`` absorbs record-bounded
    #: damage (None = strict raise, the default)
    on_bad = None


def record_at(buf: np.ndarray, idx: "_RecordIndex", k: int,
              cig_off: int, seq_off: int, refname_fn) -> BamRecord:
    return BamRecord(
        refname=refname_fn(int(idx.refid[k])),
        pos=int(idx.pos[k]),
        ops=decode_ops(buf, cig_off, int(idx.n_cig[k])),
        seq=decode_seq(buf, seq_off, int(idx.l_seq[k])))


class BamReadStream:
    """BAM-side twin of :class:`~..io.sam.ReadStream`.

    Same counting surface (``n_lines``/``n_bytes``/``add_lines``/
    ``on_lines``) so the CLI's progress accounting and the backends'
    stats work unchanged; ``records()`` feeds the oracle / pure-python
    encoder, and ``make_encoder`` (consumed by
    ``JaxBackend._make_encoder``) builds the vectorized
    :class:`BamSegmentEncoder` over the raw record stream.  Checkpoint
    resume (``skip_to``) is a record-count skip — BGZF reads are
    re-inflated up to the resume point, in parallel on a pool host.
    """

    format = "bam"

    def __init__(self, handle, refnames: List[str], on_lines=None):
        self.handle = handle
        self.refnames = list(refnames)
        self.on_lines = on_lines
        self.n_lines = 0
        self.n_bytes = 0
        self._skip_records = 0

    def refname(self, refid: int) -> str:
        if refid < 0:
            return "*"
        if refid >= len(self.refnames):
            raise BamParseError(
                f"record refID {refid} outside the reference table "
                f"(n_ref={len(self.refnames)})")
        return self.refnames[refid]

    def add_lines(self, k: int) -> None:
        if k:
            self.n_lines += k
            if self.on_lines is not None:
                self.on_lines(self.n_lines)

    def add_bytes(self, k: int) -> None:
        if k:
            self.n_bytes += k

    def byte_offset(self) -> int:
        """Uncompressed BAM offset matching ``n_lines`` — not meaningful
        across the fast-lane batching, so checkpoint resume uses record
        counts (-1 = use ``skip_lines``)."""
        return -1

    def skip_to(self, byte_offset: int, k: int) -> str:
        self.skip_lines(k)
        return "lines" if k > 0 else "none"

    def skip_lines(self, k: int) -> None:
        """Arrange for the next ``records()`` / encoder pass to drop the
        first ``k`` records (they still count toward ``n_lines``)."""
        if k > 0:
            self._skip_records = k
            self.n_lines = 0

    def _reader(self) -> BamRecordReader:
        rd = BamRecordReader(self.handle, count_cb=self.add_lines,
                             bytes_cb=self.add_bytes)
        rd.refname_fn = self.refname
        return rd

    def records(self, on_bad=None) -> Iterator[BamRecord]:
        """Mapped records in file order (oracle / python-encoder lane).

        ``on_bad(raw, exc)``: tolerant hook matching the text
        ``ReadStream.records`` signature — record-bounded structural
        damage reports a rendered placeholder instead of raising."""
        skip = self._skip_records
        self._skip_records = 0
        rd = self._reader()
        if on_bad is not None:
            rd.on_bad = lambda abs_off, exc: on_bad(
                f"<bam record at offset {abs_off}>", exc)
        for rec in rd:
            if skip > 0:
                skip -= 1
                continue
            yield rec

    def make_encoder(self, layout, cfg, acc=None, bad_sink=None):
        """The jax backend's decode hook.

        Preferred path: the C++ binary record decoder
        (``native/decoder.cpp s2c_decode_bam`` via
        :class:`NativeBamEncoder`) — same slab protocol and fused
        host-counting as the native SAM text path, minus the text
        tokenization it never needed.  Falls back to the pure-python
        :class:`BamSegmentEncoder` (the portable semantics twin) when
        the native library is unavailable or ``--decoder py`` forces it.
        """
        from .. import native as _native
        from ..encoder.events import resolve_segment_width
        from ..ops.pileup import HostPileupAccumulator

        decoder = getattr(cfg, "decoder", "auto")
        lib = _native.load() if decoder != "py" else None
        if lib is not None and hasattr(lib, "s2c_decode_bam"):
            fuse = (isinstance(acc, HostPileupAccumulator)
                    and not getattr(cfg, "paranoid", False))
            enc = NativeBamEncoder(
                layout, self, maxdel=cfg.maxdel, strict=cfg.strict,
                segment_width=resolve_segment_width(
                    getattr(cfg, "segment_width", 0)),
                accumulate_into=acc.counts_host() if fuse else None,
                bad_sink=bad_sink)
            return enc, enc.encode_batches()
        if decoder == "native":
            raise RuntimeError(
                "--decoder native requested but the C++ decoder is "
                f"unavailable: {_native.load_error()}")
        enc = BamSegmentEncoder(
            layout, self, maxdel=cfg.maxdel, strict=cfg.strict,
            chunk_reads=getattr(cfg, "chunk_reads", 262144),
            segment_width=getattr(cfg, "segment_width", 0),
            bad_sink=bad_sink)
        return enc, enc.encode_batches()


class BamSegmentEncoder:
    """Vectorized BAM → :class:`SegmentBatch` encoder.

    The fast lane turns a whole chunk's single-op-M reads into segment
    rows with numpy gathers (no per-read python); everything else —
    indels, clips, wrapped POS, invalid nibbles, unknown refs — replays
    per record through the golden :class:`ReadEncoder`, which is the
    single owner of validation semantics, the maxdel gate, insertion
    events and long-read segmentation.  Output batches are
    bucket-compatible with the SAM paths, so every accumulator and
    wire codec downstream runs unchanged.
    """

    def __init__(self, layout, stream: BamReadStream,
                 maxdel: Optional[int] = 150, strict: bool = True,
                 chunk_reads: int = 262144, segment_width: int = 0,
                 bad_sink=None):
        from ..encoder.events import ReadEncoder, resolve_segment_width

        self.layout = layout
        self.stream = stream
        self.strict = strict
        self.chunk_reads = max(1, chunk_reads)
        #: tolerant decode: absorbed in _encode_slow (the replay lane
        #: every malformed record routes through; the fast lane's
        #: filters re-route to slow before anything could raise)
        self.bad_sink = bad_sink
        # config policy -> concrete width (0 = segmentation off)
        seg_w = resolve_segment_width(segment_width)
        self._py = ReadEncoder(layout, maxdel=maxdel, strict=strict,
                               segment_width=seg_w)
        self.insertions = self._py.insertions
        self._seg_w = seg_w
        # refid -> (flat offset, length) over the BAM reference table,
        # routed through the layout's name index so duplicate-name
        # semantics (last LN wins) match the SAM text path exactly
        offs = []
        lens = []
        for name in stream.refnames:
            ci = layout.index.get(name)
            if ci is None:          # dup name pruned — cannot happen for
                offs.append(-1)     # layout built from this same table,
                lens.append(-1)     # but stay total
            else:
                offs.append(int(layout.offsets[ci]))
                lens.append(int(layout.lengths[ci]))
        self._ref_off = np.asarray(offs, dtype=np.int64)
        self._ref_len = np.asarray(lens, dtype=np.int64)

    @property
    def n_reads(self) -> int:
        return self._py.n_reads

    @property
    def n_skipped(self) -> int:
        return self._py.n_skipped

    counts_fused = False

    def encode_batches(self):
        """Yield SegmentBatches of ≲``chunk_reads`` reads each."""
        skip = self.stream._skip_records
        self.stream._skip_records = 0

        mats: List[Tuple[np.ndarray, np.ndarray, int]] = []  # (starts, mat, n_real_cells)
        rows: List[Tuple[int, np.ndarray]] = []
        batch_reads = 0
        reader = self.stream._reader()
        if self.bad_sink is not None:
            reader.on_bad = self._absorb_record
        for buf, idx in reader.chunks():
            self.stream.add_lines(idx.n)
            lo = 0
            if skip > 0:
                lo = min(skip, idx.n)
                skip -= lo
            sel = np.arange(lo, idx.n, dtype=np.int64)
            if len(sel) == 0:
                continue
            bad = idx.bad[sel]
            if bad.any():
                # index-flagged structural damage (fields overrun the
                # record): absorb the INDEX exception and drop the entry
                # before the lane split — walking it would read the next
                # record's bytes as CIGAR/SEQ (strict mode never gets
                # here: the index raised at build time)
                for k in sel[bad]:
                    self._absorb_record(int(idx.base + idx.off[k]),
                                        idx.bad_exc[int(k)])
                sel = sel[~bad]
                if len(sel) == 0:
                    continue
            n_cig = idx.n_cig[sel]
            mapped = sel[n_cig > 0]          # CIGAR "*" analogue dropped
            if len(mapped) == 0:
                continue
            cig_off = idx.off[mapped] + 36 + idx.l_rn[mapped]
            seq_off = cig_off + 4 * idx.n_cig[mapped]

            fast, slow = self._split_fast(buf, idx, mapped, cig_off)
            if len(fast):
                f_sel = np.searchsorted(mapped, fast)
                n_rows, n_cells, extra_slow = self._encode_fast(
                    buf, idx, fast, seq_off[f_sel], mats)
                batch_reads += len(fast) - len(extra_slow)
                if len(extra_slow):
                    slow = np.sort(np.concatenate([slow, extra_slow]))
            for k in slow:
                ks = int(np.searchsorted(mapped, k))
                abs_off = int(idx.base + idx.off[k])
                try:
                    rec = record_at(buf, idx, int(k), int(cig_off[ks]),
                                    int(seq_off[ks]), self.stream.refname)
                except BamParseError as exc:
                    # bad CIGAR op / refID outside the table — bounded
                    # to this already-indexed (and already-counted)
                    # record
                    self._absorb_record(abs_off, exc)
                    continue
                if self._encode_slow(rec, rows, offset=abs_off):
                    batch_reads += 1
            if batch_reads >= self.chunk_reads:
                yield self._flush(mats, rows, batch_reads)
                mats, rows, batch_reads = [], [], 0
        if mats or rows or batch_reads:
            yield self._flush(mats, rows, batch_reads)

    # -- lanes -------------------------------------------------------------
    def _split_fast(self, buf, idx, mapped, cig_off):
        """Partition mapped record indices into (fast, slow) lanes."""
        n_cig = idx.n_cig[mapped]
        cand = n_cig == 1
        if cand.any():
            first = np.ascontiguousarray(
                _gather(buf, cig_off[cand], 4)).view("<u4").reshape(-1)
            op_m = (first & 0xF) == 0
            len_ok = (first >> 4) == idx.l_seq[mapped][cand]
            good = np.zeros(len(mapped), dtype=bool)
            good[np.nonzero(cand)[0]] = op_m & len_ok
        else:
            good = np.zeros(len(mapped), dtype=bool)
        refid = idx.refid[mapped]
        pos = idx.pos[mapped]
        in_table = (refid >= 0) & (refid < len(self._ref_off))
        good &= in_table
        if good.any():
            safe = np.clip(refid, 0, len(self._ref_len) - 1)
            rl = np.where(in_table, self._ref_len[safe], -1)
            good &= (pos >= 0) & (pos + idx.l_seq[mapped] <= rl) \
                & (idx.l_seq[mapped] > 0)
        return mapped[good], mapped[~good]

    def _encode_fast(self, buf, idx, fast, seq_off, mats):
        """Vectorized nibble decode for same-length groups; returns
        (rows_emitted, cells, indices re-routed to the slow lane)."""
        l_seq = idx.l_seq[fast]
        extra_slow: List[int] = []
        n_rows = n_cells = 0
        for L in np.unique(l_seq):
            grp = l_seq == L
            g_idx = fast[grp]
            nb = (int(L) + 1) // 2
            packed = _gather(buf, seq_off[grp], nb)
            codes = np.empty((len(g_idx), nb * 2), dtype=np.uint8)
            codes[:, 0::2] = NIB_TO_CODE[packed >> 4]
            codes[:, 1::2] = NIB_TO_CODE[packed & 0xF]
            codes = codes[:, :int(L)]
            bad = (codes == 255).any(axis=1)
            if bad.any():
                # invalid nibble → slow-lane replay raises the oracle's
                # exact KeyError (strict) / counts a skip (permissive)
                extra_slow.extend(int(i) for i in g_idx[bad])
                good = ~bad
                g_idx = g_idx[good]
                codes = codes[good]
                if len(g_idx) == 0:
                    continue
            starts = (self._ref_off[idx.refid[g_idx]]
                      + idx.pos[g_idx]).astype(np.int64)
            self._py.n_reads += len(g_idx)
            if self._seg_w and int(L) > self._seg_w:
                starts, codes = _segment_matrix(starts, codes,
                                                self._seg_w)
            mats.append((starts, codes, len(g_idx) * int(L)))
            n_rows += len(codes)
            n_cells += len(g_idx) * int(L)
        return n_rows, n_cells, np.asarray(sorted(extra_slow),
                                           dtype=np.int64)

    def _absorb_record(self, abs_off: int, exc: BaseException) -> None:
        """One record-bounded BAM failure (structural overrun, bad
        CIGAR op, refID outside the table): quarantine / skip /
        strict-raise — the python twin of the native lane's
        ``_fallback_record`` tolerance protocol."""
        from ..ingest.badrecords import mark_offset

        if self.bad_sink is not None:
            self.bad_sink.record(f"<bam record at offset {abs_off}>",
                                 exc, offset=abs_off)
            self._py.n_skipped += 1
            return
        # no sink: structural parse damage raises in BOTH modes —
        # legacy permissive mode tolerates encode-level contract errors
        # only, matching the native lane's _fallback_record
        mark_offset(exc, abs_off)
        raise exc

    def _encode_slow(self, rec: BamRecord,
                     rows: List[Tuple[int, np.ndarray]],
                     offset: Optional[int] = None) -> bool:
        from ..encoder.events import EncodeError, render_record
        from ..ingest.badrecords import mark_offset

        try:
            new_rows = self._py.encode_record(rec)
        except (EncodeError, KeyError, IndexError) as exc:
            if self.bad_sink is not None:
                self.bad_sink.record(render_record(rec), exc,
                                     offset=offset)
                self._py.n_skipped += 1
                return False
            if self.strict:
                mark_offset(exc, offset)
                raise
            self._py.n_skipped += 1
            return False
        rows.extend(new_rows)
        self._py.n_reads += 1
        return True

    # -- batch assembly ----------------------------------------------------
    def _flush(self, mats, rows, batch_reads):
        """Merge fast matrices + slow rows into one padded SegmentBatch
        (same bucket invariants as ``pack_rows``)."""
        from ..encoder.events import SegmentBatch, _bucket_width

        per_w = {}
        n_events = 0
        for starts, mat, cells in mats:
            per_w.setdefault(_bucket_width(mat.shape[1]),
                             []).append((starts, mat))
            n_events += cells
        for start, row in rows:
            w = _bucket_width(len(row))
            per_w.setdefault(w, []).append((start, row))
            n_events += len(row) - int((row == PAD_CODE).sum())

        buckets = {}
        for w, items in per_w.items():
            total = sum(len(it[0]) if isinstance(it[0], np.ndarray) else 1
                        for it in items)
            s_pad = max(1024, 1 << (total - 1).bit_length())
            mat = np.full((s_pad, w), PAD_CODE, dtype=np.uint8)
            st = np.zeros(s_pad, dtype=np.int32)
            r = 0
            for it in items:
                if isinstance(it[0], np.ndarray):
                    starts, m = it
                    st[r:r + len(starts)] = starts
                    mat[r:r + len(starts), : m.shape[1]] = m
                    r += len(starts)
                else:
                    start, row = it
                    st[r] = start
                    mat[r, : len(row)] = row
                    r += 1
            buckets[w] = (st, mat)
        return SegmentBatch(buckets=buckets, n_reads=batch_reads,
                            n_events=n_events)


from ..encoder.native_encoder import NativeReadEncoder  # noqa: E402


class NativeBamEncoder(NativeReadEncoder):
    """C++ binary record decode: BGZF-inflated bytes → SegmentBatches.

    A :class:`~..encoder.native_encoder.NativeReadEncoder` whose byte
    feed is whole BAM records instead of text lines: slab persistence,
    width adaptation, fused uint8-shadow counting, the python twin and
    batch assembly are all inherited, with ``s2c_decode_bam`` doing the
    per-record work and three replay lanes handled here:

    * ``status 2`` (flagged record): the ONE record replays through the
      golden python encoder, so strict-mode exception type/message are
      oracle-identical (corrupt framing raises :class:`BamParseError`
      with the record offset);
    * overflow records (``span > width`` — the segmented long-read
      lane — and negative-POS wraps): replayed per record through the
      python twin, whose segmentation splits them into W-wide rows;
    * trailing partial record at stream end: :class:`BamParseError`
      (mid-record truncation, precise offset).
    """

    #: bytes pulled per read() from the (block-parallel) BGZF reader
    CHUNK = 1 << 22

    def __init__(self, layout, stream: BamReadStream,
                 maxdel: Optional[int] = 150, strict: bool = True,
                 segment_width: int = 0, accumulate_into=None,
                 bad_sink=None):
        super().__init__(layout, maxdel=maxdel, strict=strict,
                         on_lines=stream.add_lines,
                         on_bytes=stream.add_bytes,
                         accumulate_into=accumulate_into,
                         segment_width=segment_width,
                         bad_sink=bad_sink)
        self.stream = stream
        ci = []
        off = []
        ln = []
        for name in stream.refnames:
            k = layout.index.get(name)
            if k is None:       # unreachable for layouts built from this
                ci.append(-1)   # table; stay total
                off.append(0)
                ln.append(0)
            else:
                ci.append(int(k))
                off.append(int(layout.offsets[k]))
                ln.append(int(layout.lengths[k]))
        self._ref_ci = np.asarray(ci, dtype=np.int32)
        self._ref_off = np.asarray(off, dtype=np.int64)
        self._ref_lenv = np.asarray(ln, dtype=np.int64)

    def encode_batches(self) -> Iterator["SegmentBatch"]:
        self._probed = False
        self._new_slab()
        self._fallback_rows = []
        self._batch_reads = 0
        self._batch_events = 0

        ins_cap = 1 << 16
        chars_cap = 1 << 20
        ovf_cap = 4096
        out = np.zeros(16, dtype=np.int64)
        skip = self.stream._skip_records
        self.stream._skip_records = 0

        pending = b""
        src = self.stream.handle
        stream_off = 0          # absolute offset of `pending`'s start
        eof = False
        while not eof or pending:
            data_b = src.read(self.CHUNK)
            if not data_b:
                eof = True
                if not pending:
                    break
                buf = pending
            else:
                buf = pending + data_b if pending else data_b
            data = np.frombuffer(buf, dtype=np.uint8)
            offset = 0
            while offset < len(data):
                if skip > 0:
                    adv, skip = self._skip_whole_records(data, offset,
                                                         skip)
                    if adv == 0:
                        break               # need more bytes
                    offset += adv
                    continue
                chunk = data[offset:]
                ic = np.empty(ins_cap, dtype=np.int32)
                il = np.empty(ins_cap, dtype=np.int32)
                im = np.empty(ins_cap, dtype=np.int32)
                ich = np.empty(chars_cap, dtype=np.uint8)
                ovf = np.empty(ovf_cap, dtype=np.int64)

                fill = self._fill
                self._lib.s2c_decode_bam(
                    np.ascontiguousarray(chunk), len(chunk),
                    self._ref_ci, self._ref_off, self._ref_lenv,
                    len(self._ref_ci),
                    -1 if self.maxdel is None else self.maxdel,
                    self._c_strict,
                    self._slab_w,
                    self._starts[fill:], self._codes[fill:],
                    len(self._starts) - fill,
                    ic, il, im, ins_cap,
                    ich, chars_cap,
                    ovf, ovf_cap,
                    out,
                    self._acc_u8, self._acc_ovf, self._acc_len,
                    1 if self._acc_direct else 0)

                (n_rows, n_reads, n_skipped, consumed, n_ins, n_chars,
                 status, err_off, n_events, n_lines, n_overflow,
                 _max_span) = out[:12]
                self._banked += int(out[12])

                self._fill = 0 if self._acc is not None \
                    else fill + int(n_rows)
                if n_ins:
                    self.insertions.array_chunks.append(
                        (ic[:n_ins].copy(), il[:n_ins].copy(),
                         im[:n_ins].copy(), ich[:n_chars].copy()))
                self._py.n_reads += int(n_reads)
                self._py.n_skipped += int(n_skipped)
                self._batch_reads += int(n_reads)
                self._batch_events += int(n_events)
                self._count_lines(int(n_lines))

                for k in range(int(n_overflow)):
                    # negative-POS wrap lane: python replay (segmented
                    # there too; wide positive reads are segmented in C)
                    self._fallback_record(
                        data, int(ovf[k]) + offset,
                        flagged_at=stream_off + int(ovf[k]) + offset)
                if int(out[13]) + n_overflow > max(64, n_reads // 64):
                    # many segmented/wrapped reads: widen future slabs
                    # toward the cap so each read needs fewer rows
                    self.width = min(self._width_cap, self.width * 2)
                elif (not self._probed and n_reads > 256
                      and _max_span > 0 and not n_overflow):
                    self._probed = True
                    from ..encoder.events import (MIN_BUCKET_W,
                                                  _bucket_width)

                    self.width = min(self._width_cap,
                                     max(MIN_BUCKET_W,
                                         _bucket_width(int(_max_span))))

                offset += int(consumed)
                self._count_bytes(int(consumed))
                if status == 2:
                    rec_len = self._fallback_record(
                        data, offset, flagged_at=stream_off + offset,
                        c_reason=int(out[14]))
                    self._count_lines(1)
                    self._count_bytes(rec_len)
                    offset += rec_len
                elif status == 1:
                    # capacity: a segmented wide read may need MANY free
                    # rows (ceil(span/width), not <=2 like the text
                    # path), so any partially-filled slab flushes —
                    # growing the insertion buffers instead would spin
                    # forever against the row constraint
                    if self._fill > 0:
                        batch = self._flush()
                        if batch is not None:
                            yield batch
                    elif consumed == 0:
                        if ins_cap >= (1 << 22):
                            # empty slab, generous buffers, still stuck:
                            # one record wider than the whole slab —
                            # replay it through the python twin (its
                            # row list is unbounded)
                            rec_len = self._fallback_record(
                                data, offset,
                                flagged_at=stream_off + offset)
                            self._count_lines(1)
                            self._count_bytes(rec_len)
                            offset += rec_len
                        else:
                            ins_cap *= 2
                            chars_cap *= 2
                            ovf_cap *= 2
                elif consumed == 0 or offset >= len(data):
                    break                   # partial record: need bytes

            stream_off += offset
            pending = bytes(buf[offset:]) if offset < len(buf) else b""
            if len(pending) > self.CHUNK * 4:
                # same guard as the python twin: a "partial record" that
                # keeps growing past 4 chunks is a corrupt block_size,
                # not a long read — fail with the offset instead of
                # buffering the rest of the file quadratically
                raise BamParseError(
                    f"BAM record at offset {stream_off} larger than "
                    f"{len(pending)} bytes — corrupt block_size?",
                    stream_off)
            if eof and pending:
                raise BamParseError(
                    f"BAM stream ends mid-record at offset {stream_off} "
                    f"({len(pending)} dangling bytes)", stream_off)
            if self._acc is not None and self._batch_reads:
                batch = self._flush()
                if batch is not None:
                    yield batch

        self.merge_shadow()
        batch = self._flush()
        if batch is not None:
            yield batch

    # -- replay lanes ------------------------------------------------------
    def _record_at_offset(self, data: np.ndarray, off: int,
                          flagged_at: Optional[int] = None
                          ) -> Tuple[BamRecord, int]:
        """Parse ONE record at ``off`` for python replay; raises
        :class:`BamParseError` (with the stream offset when known) on
        structural damage — the same surface a pure-python decode of
        this record would hit."""
        where = off if flagged_at is None else flagged_at
        if off + 24 > len(data):
            raise BamParseError(
                f"BAM record at offset {where} truncated", where)
        (block_size, refid, pos, l_rn, _mapq, _bin, n_cig, _flag,
         l_seq) = _REC_FIXED.unpack_from(data, off)
        if block_size < 32 or off + 4 + block_size > len(data):
            raise BamParseError(
                f"BAM record at offset {where} claims block_size "
                f"{block_size} past the stream", where)
        # from here the record's extent IS known (4 + block_size): any
        # damage below is bounded to this one record, so tolerant mode
        # can skip exactly it — mark the errors with rec_len so
        # _fallback_record knows how far to advance
        rec_len = 4 + int(block_size)
        cig_off = off + 36 + l_rn
        seq_off = cig_off + 4 * n_cig
        try:
            if l_seq < 0 or 32 + l_rn + 4 * n_cig + (l_seq + 1) // 2 \
                    + l_seq > block_size:
                raise BamParseError(
                    f"BAM record at offset {where}: fields overrun the "
                    f"record (block_size {block_size}, l_read_name "
                    f"{l_rn}, n_cigar {n_cig}, l_seq {l_seq})", where)
            rec = BamRecord(
                refname=self.stream.refname(int(refid)),
                pos=int(pos),
                ops=decode_ops(data, cig_off, int(n_cig)),
                seq=decode_seq(data, seq_off, int(l_seq)))
        except BamParseError as exc:
            exc.rec_len = rec_len
            raise
        return rec, rec_len

    def _fallback_record(self, data: np.ndarray, off: int,
                         flagged_at: Optional[int] = None,
                         c_reason: int = 0) -> int:
        """Replay one record through the golden python encoder (error
        parity / wrap split / segmentation); returns the record's total
        byte length.

        The BAM rung's tolerance point: with a sink attached
        (``--on-bad-record skip|quarantine``), any record-bounded
        failure — a replay-raised oracle error, or structural damage
        whose extent is still known (``BamParseError.rec_len``) — is
        absorbed per record; framing loss (truncation, a block_size
        past the stream) stays job-level in every mode."""
        from ..encoder.events import EncodeError, render_record
        from ..ingest.badrecords import mark_offset

        sink = self.bad_sink
        where = off if flagged_at is None else flagged_at
        try:
            rec, rec_len = self._record_at_offset(data, off, flagged_at)
        except BamParseError as exc:
            bounded_len = getattr(exc, "rec_len", None)
            if sink is not None and bounded_len is not None:
                self._quarantine(
                    sink, f"<bam record at offset {where}>", exc,
                    where, c_reason)
                return bounded_len
            raise
        try:
            rows = self._py.encode_record(rec)
        except (EncodeError, KeyError, IndexError) as exc:
            if sink is not None:
                self._quarantine(sink, render_record(rec), exc,
                                 where, c_reason)
                return rec_len
            if self.strict:
                mark_offset(exc, where)
                raise
            self._py.n_skipped += 1
            return rec_len
        self._py.n_reads += 1
        self._batch_reads += 1
        for start_flat, row in rows:
            if self._acc is not None:
                cols = np.nonzero(row < 6)[0]
                pos = start_flat + cols
                ok = (pos >= 0) & (pos < self._acc_len)
                np.add.at(self._acc, (pos[ok], row[cols[ok]]), 1)
                self._batch_events += len(cols)
            else:
                self._fallback_rows.append((start_flat, row))
                self._batch_events += (len(row)
                                       - int((row == PAD_CODE).sum()))
        return rec_len

    def _skip_whole_records(self, data: np.ndarray, off: int,
                            skip: int) -> Tuple[int, int]:
        """Checkpoint-resume record skipping: advance over up to
        ``skip`` complete records; returns (bytes advanced, skip left).
        Skipped records still count as lines."""
        adv = 0
        while skip > 0 and off + adv + 4 <= len(data):
            bs = int.from_bytes(
                bytes(data[off + adv:off + adv + 4]), "little",
                signed=True)
            if bs < 32 or off + adv + 4 + bs > len(data):
                break
            adv += 4 + bs
            skip -= 1
            self._count_lines(1)
        return adv, skip


# -- writer (fixtures / format-conversion tooling; pure stdlib) ------------
#: ASCII char -> BAM seq nibble (strict: only the 16 spec chars)
CHAR_TO_NIB = {chr(c): i for i, c in enumerate(NIB_TO_CHAR)}

_OP_TO_CODE = {op: i for i, op in enumerate(CIGAR_OPS)}


def encode_bam_record(refid: int, pos: int, cigar: str, seq: str,
                      read_name: bytes = b"r") -> bytes:
    """One binary alignment record (no BGZF framing)."""
    from ..core.cigar import split_ops

    ops = [] if cigar == "*" else split_ops(cigar)
    seq_s = "" if seq == "*" else seq
    l_seq = len(seq_s)
    name = read_name + b"\x00"
    cig = b"".join(struct.pack("<I", (n << 4) | _OP_TO_CODE[op])
                   for n, op in ops)
    nibs = bytearray((l_seq + 1) // 2)
    for i, ch in enumerate(seq_s):
        try:
            v = CHAR_TO_NIB[ch]
        except KeyError:
            raise ValueError(
                f"SEQ char {ch!r} has no BAM nibble encoding") from None
        if i % 2 == 0:
            nibs[i // 2] |= v << 4
        else:
            nibs[i // 2] |= v
    qual = b"\xff" * l_seq           # 0xff = unavailable, like "*"
    body = (struct.pack("<iiBBHHHiiii", refid, pos, len(name), 0, 0,
                        len(ops), 0, l_seq, -1, -1, 0)
            + name + cig + bytes(nibs) + qual)
    return struct.pack("<i", len(body)) + body


def bam_payload(contigs, records, header_text: str = "") -> bytes:
    """The complete UNCOMPRESSED BAM stream (header + records).

    ``records`` iterates (refname, pos0, cigar, seq); refnames index the
    ``contigs`` table ((name, length) pairs or Contig objects)."""
    pairs = [(c.name, c.length) if isinstance(c, Contig) else tuple(c)
             for c in contigs]
    if not header_text:
        header_text = "".join(
            f"@SQ\tSN:{n}\tLN:{ln}\n" for n, ln in pairs)
    text = header_text.encode("utf-8")
    out = [BAM_MAGIC, struct.pack("<i", len(text)), text,
           struct.pack("<i", len(pairs))]
    index = {}
    for i, (n, ln) in enumerate(pairs):
        raw = n.encode("ascii") + b"\x00"
        out.append(struct.pack("<i", len(raw)))
        out.append(raw)
        out.append(struct.pack("<i", ln))
        index.setdefault(n, i)
    for k, (refname, pos0, cigar, seq) in enumerate(records):
        refid = index[refname] if refname != "*" else -1
        out.append(encode_bam_record(refid, pos0, cigar, seq,
                                     read_name=b"r%d" % k))
    return b"".join(out)


def write_bam(contigs, records, path: str, level: int = 6) -> str:
    """Write a BGZF-framed BAM file (fixtures/bench conversion)."""
    from .bgzf import write_bgzf

    return write_bgzf(bam_payload(contigs, records), path, level=level)


def sam_text_to_records(text: str):
    """Parse SAM text into ``(contigs, [(refname, pos0, cigar, seq)])``
    — the shared conversion front end for :func:`sam_text_to_bam` and
    the fixture/bench tooling (one definition, so committed fixtures
    can never drift from what the bench converter produces).  EVERY
    body line is kept, mapped or not (CIGAR ``"*"`` becomes the zero-op
    record), so progress totals stay identical across containers."""
    from ..io.sam import parse_sq_line

    contigs = []
    records = []
    for line in text.splitlines():
        if line.startswith("@"):
            if line.startswith("@SQ"):
                contigs.append(parse_sq_line(line))
            continue
        if not line:
            continue
        f = line.split("\t")
        records.append((f[2].split()[0], int(f[3]) - 1, f[5], f[9]))
    return contigs, records


def sam_text_to_bam(text: str, path: str, level: int = 6) -> str:
    """Convert in-memory SAM text to a BAM file — the fixture/bench
    bridge (oracle reads the SAM, the system under test reads the BAM)."""
    contigs, records = sam_text_to_records(text)
    return write_bam(contigs, records, path, level=level)


def _segment_matrix(starts: np.ndarray, codes: np.ndarray,
                    seg_w: int) -> Tuple[np.ndarray, np.ndarray]:
    """Split an [n, L] row matrix into [(n*ceil(L/W)), W] segments with
    starts advanced per segment — the fast-lane form of the encoder's
    long-read segmentation (pileup addition commutes, so splitting a
    row at any boundary is exact)."""
    n, L = codes.shape
    n_seg = -(-L // seg_w)
    pad_to = n_seg * seg_w
    if pad_to != L:
        padded = np.full((n, pad_to), PAD_CODE, dtype=np.uint8)
        padded[:, :L] = codes
        codes = padded
    seg_codes = codes.reshape(n * n_seg, seg_w)
    seg_starts = (starts[:, None]
                  + (np.arange(n_seg, dtype=np.int64) * seg_w)[None, :]
                  ).reshape(-1)
    # drop all-PAD tail segments (possible when L % seg_w leaves a
    # segment entirely past the read) — none exist here because the pad
    # is < seg_w by construction, but keep the invariant explicit
    return seg_starts, seg_codes
