"""The flagship "model": pileup + threshold vote as one jittable step.

There is no neural network in this workload; the framework's model is the
consensus caller itself (SURVEY.md north star).  ``make_consensus_model``
closes over the static genome geometry and returns a pure function

    forward(starts, codes, thr_enc) -> (syms, cov)

that expands one batch of read segment rows (flat-genome start + uint8 code
row, ``encoder.events.SegmentBatch``), scatter-adds them into a fresh count
tensor and votes every position for every threshold — the fused single-chip
step the driver compile-checks (``__graft_entry__.entry``).  The
streaming/sharded production paths decompose the same two ops
(``ops/pileup.py``, ``parallel/dp.py``).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from ..constants import NUM_SYMBOLS
from ..ops.vote import vote_block


def make_consensus_model(total_len: int, min_depth: int = 1) -> Callable:
    """Return the jittable forward step for a genome of ``total_len``."""

    def forward(starts: jax.Array, codes: jax.Array,
                thr_enc: jax.Array) -> Tuple[jax.Array, jax.Array]:
        from ..ops.pileup import expand_segment_positions

        pos, code = expand_segment_positions(starts, codes, total_len)
        counts = jnp.zeros((total_len + 1, NUM_SYMBOLS), dtype=jnp.int32)
        counts = counts.at[pos, code].add(1)[:-1]
        return vote_block(counts, thr_enc, min_depth)

    return forward
