"""Shared constants: the consensus alphabet and the IUPAC ambiguity mapping.

The reference hard-codes a 6-symbol per-position count alphabet
(``/root/reference/sam2consensus.py:167``) and a literal ambiguity dictionary
(``sam2consensus.py:317-329``).  Here both are *derived* from first principles:

* ``ALPHABET`` is the 6 symbols in ASCII-sorted order — ``-`` < ``A`` < ``C``
  < ``G`` < ``N`` < ``T`` — which is exactly the order produced by
  ``"".join(sorted(nucs))`` in the reference's emit step
  (``sam2consensus.py:367``).  Symbol index therefore doubles as a bit position
  in the 6-bit called-set mask used by the TPU vote kernel.

* ``AMB`` maps every non-empty called subset to its output character using the
  rule the reference's table encodes:

    - the nucleotide part ``B = S ∩ {A,C,G,T}`` picks the standard IUPAC code;
    - if ``B == {A,C,G,T}`` the call is always uppercase ``"N"`` (so is the
      all-six set ``-ACGNT``, per ``sam2consensus.py:328-329``);
    - otherwise the code is lowercased when ``-`` or ``N`` is in the set
      (the reference uses lowercase to flag "gap or N participated");
    - sets with no real nucleotide: ``{-}`` → ``-``, ``{N}`` → ``N``,
      ``{-,N}`` → ``n``.

  The reference's literal table has 62 entries; the rule reproduces every one
  (pinned by ``tests/test_iupac.py``) and additionally defines the one subset
  the reference forgot — ``ACGNT`` (five-way tie without gap), which raises
  ``KeyError`` there — as ``"N"``.  That single deliberate fix is documented
  as quirk-7-adjacent behavior in SURVEY.md §2.
"""

from __future__ import annotations

import numpy as np

#: Count-lane alphabet in ASCII-sorted order; index == bit position in masks.
ALPHABET = "-ACGNT"
GAP, A, C, G, N, T = range(6)
NUM_SYMBOLS = 6

#: Standard IUPAC codes keyed by frozenset of nucleotides.
_IUPAC_CORE = {
    frozenset("A"): "A", frozenset("C"): "C", frozenset("G"): "G",
    frozenset("T"): "T",
    frozenset("AC"): "M", frozenset("AG"): "R", frozenset("AT"): "W",
    frozenset("CG"): "S", frozenset("CT"): "Y", frozenset("GT"): "K",
    frozenset("ACG"): "V", frozenset("ACT"): "H", frozenset("AGT"): "D",
    frozenset("CGT"): "B", frozenset("ACGT"): "N",
}


def _call_for_subset(subset: frozenset) -> str:
    """Output character for a called set of symbols (subset of ALPHABET)."""
    nucs = subset & frozenset("ACGT")
    if nucs == frozenset("ACGT"):
        # Reference emits uppercase "N" for ACGT, -ACGT and -ACGNT alike
        # (sam2consensus.py:327-329); ACGNT is the entry it forgot.
        return "N"
    if nucs:
        code = _IUPAC_CORE[nucs]
        if subset & frozenset("-N"):
            return code.lower()
        return code
    if subset == frozenset("-"):
        return "-"
    if subset == frozenset("N"):
        return "N"
    if subset == frozenset("-N"):
        return "n"
    # Empty set: unreachable from the callers (a voted position always has at
    # least one nonzero lane); use gap so the LUT below is total.
    return "-"


def build_amb_table() -> dict:
    """Ambiguity dict keyed like the reference: sorted-concatenated subset."""
    table = {}
    for mask in range(1, 1 << NUM_SYMBOLS):
        subset = frozenset(ALPHABET[i] for i in range(NUM_SYMBOLS) if mask & (1 << i))
        key = "".join(sorted(subset))
        table[key] = _call_for_subset(subset)
    return table


#: ``AMB["".join(sorted(called_symbols))] -> output char`` — the drop-in
#: equivalent of the reference's ``amb`` dict (sam2consensus.py:317-329).
AMB = build_amb_table()

#: 64-entry uint8 LUT: 6-bit called-set mask (bit i == ALPHABET[i]) -> ASCII.
#: This is the device-side form consumed by the JAX/Pallas vote kernels.
IUPAC_MASK_LUT = np.zeros(1 << NUM_SYMBOLS, dtype=np.uint8)
for _mask in range(1 << NUM_SYMBOLS):
    _subset = frozenset(ALPHABET[i] for i in range(NUM_SYMBOLS) if _mask & (1 << i))
    IUPAC_MASK_LUT[_mask] = ord(_call_for_subset(_subset))

#: 256-entry uint8 LUT: ASCII base -> symbol index; 255 marks invalid input.
#: The reference's input contract is uppercase ACGTN only (quirk 7): any other
#: base raises KeyError there, so 255 triggers strict-mode errors here.
INVALID_SYMBOL = 255
BASE_TO_CODE = np.full(256, INVALID_SYMBOL, dtype=np.uint8)
for _i, _ch in enumerate(ALPHABET):
    BASE_TO_CODE[ord(_ch)] = _i

#: Symbol index -> ASCII, for rendering.
CODE_TO_BASE = np.frombuffer(ALPHABET.encode("ascii"), dtype=np.uint8).copy()

#: Padding code in segment rows (``encoder.events.SegmentBatch``): marks
#: row positions that contribute no pileup event (beyond the read span, or
#: gap bases dropped by the maxdel gate).  Shares the value of
#: INVALID_SYMBOL on purpose — both mean "no countable symbol here", and
#: invalid input bases never reach a committed row (strict mode raises,
#: permissive mode skips the read).
PAD_CODE = 255

#: largest position window the sp window strategy will materialize per
#: device ([Wp, 6] int32 local + one psum of the same size over ICI).
#: Lives here — the package's jax-free constants module — because it is
#: shared by ``parallel.sp.PositionShardedConsensus`` (the strategy) and
#: ``parallel.auto`` (the pure cost model, which must mirror the window
#: gate without importing sp's jax machinery; ADVICE r5 #4).
SP_WINDOW_CAP = 1 << 21

# -- 5-bit output symbol space -------------------------------------------
#
# The vote emits exactly 32 distinct bytes: the FILL sentinel (0), '-',
# the 15 uppercase IUPAC codes, and the 15 lowercase forms (a called set
# that mixes nucleotides with gap/N lowers the code; {-,N} gives 'n').
# That is 5 bits of information per position, which the fused tail
# exploits to ship the dense consensus at 5/8 of a byte per character
# over the slow host link (ops/fused.py "packed5"): a nibble plane
# (codes 0-15) plus a high-bit plane.  The LOW half holds the sentinel,
# '-', and the frequent uppercase calls so the host can decode the
# common case with one 256-entry pair-LUT gather and touch the high
# plane only where a bit is set ('B' — the rarest call, needing C,G,T
# to pass without A — rides with the lowercase half).
SYM32_ASCII = np.frombuffer(
    b"\x00-ACGTNMRWSYKVHD" + b"Bacgtnmrwsykvhdb", dtype=np.uint8).copy()
assert len(SYM32_ASCII) == 32 and len(set(SYM32_ASCII)) == 32
