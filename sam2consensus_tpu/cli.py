"""Command-line interface.

Drop-in compatible with the reference CLI (``/root/reference/
sam2consensus.py:87-104``): the eight flags ``-i -c -n -o -p -m -f -d`` keep
their names, defaults and post-processing (``:108-138``), and the progress
messages match (``:143,:174,:225,:227,:419-426``).  New-framework flags are
long-form only so they cannot collide with reference invocations.

``--maxdel`` is ``type=int`` here — the reference omits the type so any
user-supplied value silently disables the deletion filter under Python 2
(quirk 1, SURVEY.md §2); pass ``--py2-compat`` to reproduce that behavior.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from typing import List, Optional

from .config import RunConfig, default_prefix, normalize_outfolder
from .io.fasta import write_outputs


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sam2consensus-tpu",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("-i", "--input", dest="filename", required=True,
                   help="alignment file: SAM (optionally gzip/BGZF-"
                        "compressed) or BAM; need not be sorted "
                        "(format sniffed by magic bytes, see --format)")
    p.add_argument("-c", "--consensus-thresholds", dest="thresholds",
                   type=str, default="0.25",
                   help="comma-separated consensus threshold(s), e.g. 0.25,0.75; default=0.25")
    p.add_argument("-n", dest="n", type=int, default=0,
                   help="wrap FASTA sequences every n characters; default=no wrapping")
    p.add_argument("-o", "--outfolder", dest="outfolder", default="./",
                   help="output folder; default=current folder")
    p.add_argument("-p", "--prefix", dest="prefix", default="",
                   help="output name prefix; default=input filename without extension")
    p.add_argument("-m", "--min-depth", dest="min_depth", type=int, default=1,
                   help="minimum depth to call a consensus base; default=1")
    p.add_argument("-f", "--fill", dest="fill", default="-",
                   help="padding character for uncovered regions; default=-")
    # default=None is a "not supplied" sentinel resolved to 150 in
    # config_from_args; it lets --py2-compat detect an explicit -d reliably
    # (including -d150 joined and --maxd abbreviated spellings).
    p.add_argument("-d", "--maxdel", dest="maxdel", type=int, default=None,
                   help="ignore deletions longer than this; default=150")
    # --- new-framework flags ---
    p.add_argument("--backend", choices=["cpu", "jax"], default="cpu",
                   help="consensus backend: cpu (golden oracle) or jax (TPU)")
    # NOTE: long-form only — the reference already owns -f for --fill
    p.add_argument("--format", dest="input_format",
                   choices=["auto", "sam", "sam.gz", "bam"],
                   default="auto",
                   help="input format (sam2consensus_tpu/formats): auto "
                        "(default) sniffs magic bytes — plain SAM, "
                        "gzip SAM, BGZF SAM (htslib .sam.gz; inflated "
                        "block-parallel on --decode-threads workers) or "
                        "BAM (block-parallel BGZF + binary record "
                        "decode, no SAM text materialized)")
    p.add_argument("--segment-width", dest="segment_width", type=int,
                   default=0,
                   help="long-read segmented slab layout: reads whose "
                        "reference span exceeds this split into "
                        "W-wide segment rows (byte-exact; pileup "
                        "addition commutes) instead of widening the "
                        "slab bucket toward the span. 0 = auto "
                        "(4096), negative = off, positive = explicit "
                        "width (rounded up to a power of two)")
    p.add_argument("--py2-compat", action="store_true",
                   help="reproduce the reference's Python-2 maxdel quirk: any "
                        "explicit -d value disables deletion filtering")
    p.add_argument("--permissive", action="store_true",
                   help="skip-and-count malformed/out-of-contract records "
                        "instead of erroring like the reference")
    p.add_argument("--on-bad-record", dest="on_bad_record",
                   choices=["fail", "skip", "quarantine"], default="fail",
                   help="per-record malformation policy "
                        "(ingest/badrecords.py): fail (default; strict "
                        "reference semantics — first bad record kills the "
                        "job with a typed error carrying the file offset), "
                        "skip (drop + count as ingest/bad_records with a "
                        "per-reason taxonomy), quarantine (skip + write "
                        "the raw record and classified reason to a "
                        "bounded JSONL sidecar).  Identical consensus "
                        "bytes on every decode rung (serial/sharded/"
                        "streaming/BAM)")
    p.add_argument("--max-bad-records", dest="max_bad_records", default="",
                   help="error budget for tolerant modes: N (absolute — "
                        "the Nth bad record fails the job immediately) or "
                        "x%% (fraction of all records, checked at stream "
                        "end).  A blown budget is a clean job-level "
                        "failure with a precise summary (DATA resilience "
                        "class: never retried, never demotes a rung, "
                        "never pins a serve tenant)")
    p.add_argument("--quarantine-out", dest="quarantine_out", default=None,
                   help="quarantine sidecar path (s2c-quarantine/1 JSONL; "
                        "default <outfolder>/<prefix>_quarantine.jsonl); "
                        "bounded by S2C_QUARANTINE_MAX stored records")
    p.add_argument("--quiet", action="store_true", help="suppress progress output")
    p.add_argument("--json-metrics", dest="json_metrics", default=None,
                   help="write run metrics as JSON to this path ('-' = stdout)")
    p.add_argument("--profile-dir", dest="profile_dir", default=None,
                   help="write a jax.profiler trace to this directory")
    p.add_argument("--trace-out", dest="trace_out", default=None,
                   help="write a Chrome/Perfetto trace-event JSON of the "
                        "run's span tree (decode/stage/pileup dispatch/"
                        "accumulate/vote/insertions/render, device spans "
                        "closed under a device barrier) to this path; "
                        "open at https://ui.perfetto.dev")
    p.add_argument("--metrics-out", dest="metrics_out", default=None,
                   help="write the run's metrics registry (phase seconds, "
                        "wire bytes, dispatch decisions, histograms with "
                        "p50/p95/p99) as JSONL to this path")
    p.add_argument("--log-level", dest="log_level", default=None,
                   choices=["debug", "info", "warning", "error"],
                   help="enable package logging to stderr at this level")
    p.add_argument("--log-format", dest="log_format",
                   choices=["text", "json"], default="text",
                   help="log record shape: text (default) or json — "
                        "one JSON object per record carrying "
                        "job_id/tenant/rung and the innermost open "
                        "trace span as correlation IDs "
                        "(observability/telemetry.py; json implies "
                        "--log-level info when none is given)")
    p.add_argument("--checkpoint-dir", dest="checkpoint_dir", default=None,
                   help="persist count-tensor checkpoints here and resume "
                        "from them if present (jax backend)")
    p.add_argument("--checkpoint-every", dest="checkpoint_every", type=int,
                   default=2_000_000,
                   help="reads between checkpoint writes; default=2000000")
    p.add_argument("--incremental", action="store_true",
                   help="treat the checkpoint as an accumulated base: a new "
                        "input file ADDS its reads on top (and the final "
                        "state is persisted for the next shard) instead of "
                        "resuming the same file; requires --checkpoint-dir")
    p.add_argument("--paranoid", action="store_true",
                   help="re-validate device inputs and outputs every batch "
                        "(index bounds, symbol codes, count invariants)")
    p.add_argument("--pileup",
                   choices=["auto", "pallas", "mxu", "scatter", "host"],
                   default="auto",
                   help="pileup strategy: auto (host-counts on genomes up "
                        "to ~2M positions — least wire on a tunneled chip "
                        "— else online autotune between scatter and the "
                        "device kernel), pallas (tile-CSR VMEM histogram "
                        "kernel — the measured TPU winner), XLA "
                        "scatter-add, MXU one-hot matmul (retired from "
                        "auto on TPU — PERF.md; falls back to scatter on "
                        "skewed coverage), or host (accumulate counts in "
                        "native code, ship the tensor once; "
                        "single-device). scatter/mxu compose with "
                        "--shards in the dp shard layout")
    p.add_argument("--wire", choices=["auto", "packed5", "delta8"],
                   default="auto",
                   help="host->device row wire codec (jax backend): "
                        "packed5 (the legacy packed lanes: int32 starts "
                        "+ 4-bit code nibbles), delta8 (delta-compressed "
                        "starts with an escape lane + 2-bit ACGT planes "
                        "+ trailing-pad elision; a device-side unpack "
                        "stage reconstitutes identical operands, so "
                        "counts are byte-identical), or auto (default: "
                        "delta8 below the modeled ~71 MB/s link "
                        "crossover, packed5 on fast/link-free paths — "
                        "same link constants as the tail placement "
                        "model). Env S2C_WIRE overrides")
    p.add_argument("--insertion-kernel", dest="ins_kernel",
                   choices=["auto", "scatter", "pallas"], default="auto",
                   help="insertion-table build on device: XLA scatter or "
                        "the Pallas segmented-reduce kernel. auto "
                        "(default) picks pallas only for chip-resident "
                        "tails inside its measured winning event-count "
                        "window (TPU sweep, PERF.md) and never preempts "
                        "the link-free native tail")
    p.add_argument("--decode-threads", dest="decode_threads", type=int,
                   default=1,
                   help="host worker threads (multi-core hosts; 0 = auto, "
                        "up to 4): parallel fused host-pileup decode "
                        "(host-counts strategy without --checkpoint-dir; "
                        "per-worker count tensors sum exactly at the end) "
                        "AND the native C++ tail vote's position ranges")
    p.add_argument("--decoder", choices=["auto", "native", "py"],
                   default="auto",
                   help="host SAM decode path for the jax backend: the C++ "
                        "decoder when available (auto), required (native), "
                        "or pure python (py)")
    p.add_argument("--shard-mode", dest="shard_mode",
                   choices=["auto", "dp", "sp", "dpsp"], default="auto",
                   help="sharded accumulator layout: full-length local "
                        "scatter + reduce-scatter (dp), position-sharded "
                        "blocks with halo exchange for huge genomes (sp), "
                        "or the dp x sp product — read shards x macro "
                        "position blocks on the 2-D mesh, for huge-genome "
                        "+ deep-coverage workloads (dpsp; needs a mesh "
                        "with both axes > 1); auto prices all three from "
                        "the first decoded slab's shape, the mesh, and "
                        "the calibrated link/ICI constants "
                        "(sam2consensus_tpu/parallel/auto.py)")
    p.add_argument("--shards", type=int, default=0,
                   help="data-parallel shards for the jax backend; 0 = all devices")
    p.add_argument("--chunk-reads", dest="chunk_reads", type=int, default=262144,
                   help="reads per host->device batch (jax backend)")
    # --- resilience (sam2consensus_tpu/resilience/) ---
    p.add_argument("--retries", type=int, default=3,
                   help="transient device-failure re-attempts per dispatch "
                        "(RPC/link/timeout errors; exponential backoff + "
                        "seeded jitter); default=3")
    p.add_argument("--retry-backoff", dest="retry_backoff", type=float,
                   default=0.25,
                   help="base backoff seconds between retries (doubles per "
                        "attempt, capped at 8 s); default=0.25")
    p.add_argument("--on-device-error", dest="on_device_error",
                   choices=["fail", "retry", "fallback"], default="retry",
                   help="mid-run device failure policy: fail (raise "
                        "immediately), retry (transient errors retry, OOM "
                        "splits the slab, then raise), or fallback (after "
                        "retries, step down the degradation ladder — device "
                        "kernel -> scatter -> host pileup, device tail -> "
                        "host tail — writing an emergency checkpoint at "
                        "each demotion; counts are never lost). Env "
                        "S2C_ON_DEVICE_ERROR overrides. default=retry")
    p.add_argument("--fault-inject", dest="fault_inject", default="",
                   help="deterministic fault injection for the device path "
                        "(tests/chaos): comma-separated "
                        "site:kind:after_n[:times] specs — sites "
                        "device_put|pileup_dispatch|accumulate|vote|"
                        "insertion_build|link_probe|wire_encode|"
                        "serve_decode_ahead|journal_write|job_hang, kinds "
                        "rpc|timeout|oom|"
                        "fatal|trace, after_n an integer call count or "
                        "pP probability (seeded by S2C_FAULT_SEED), times "
                        "an integer or inf. job_hang SLEEPS "
                        "S2C_FAULT_HANG_S before raising (a wedged "
                        "dispatch); serve_decode_ahead/journal_write are "
                        "serve-runner-scope sites. Env S2C_FAULT_INJECT "
                        "also activates it")
    return p


def config_from_args(args: argparse.Namespace) -> RunConfig:
    # The reference crashes on any unusable threshold — ValueError in the
    # float parse, or amb[""] KeyError (sam2consensus.py:367) at the first
    # covered position for t <= 0 / nan; reject all of them up front.
    try:
        thresholds = [float(i) for i in args.thresholds.split(",")]
    except ValueError:
        raise SystemExit(
            f"error: could not parse consensus thresholds {args.thresholds!r}"
            " (expected comma-separated numbers, e.g. 0.25,0.75)") from None
    # Upper bound: t is a fraction in (0, 1]; anything above 1 behaves like
    # t=1 (the greedy vote takes every group).  100 leaves headroom for
    # percent-style inputs the reference also tolerated, while keeping the
    # header's int(t*100) and the jax backend's int32 cutoff LUTs finite.
    if not all(math.isfinite(t) and 0 < t <= 100 for t in thresholds):
        raise SystemExit(
            "error: consensus thresholds must be finite, > 0 and <= 100, "
            f"got {args.thresholds}")
    prefix = args.prefix if args.prefix != "" else default_prefix(args.filename)
    # --on-bad-record / --max-bad-records / --quarantine-out cross-
    # checks are validated up front (a typo'd budget must fail the run
    # at parse time, not after the decode warmed up) by the ONE
    # authority — policy_from_config — which API callers hit with the
    # same ValueError at run start
    from .ingest.badrecords import policy_from_config

    try:
        policy_from_config(args)
    except ValueError as exc:
        raise SystemExit(f"error: {exc}") from None
    if args.maxdel is None:
        maxdel: Optional[int] = 150
    elif args.py2_compat:
        # quirk 1: a user-supplied -d/--maxdel under Python 2 compares as a
        # string and the gate `gaps <= maxdel` is then always True.
        maxdel = None
    else:
        maxdel = args.maxdel
    return RunConfig(
        thresholds=thresholds,
        min_depth=args.min_depth,
        fill=args.fill,
        maxdel=maxdel,
        prefix=prefix,
        nchar=args.n,
        outfolder=normalize_outfolder(args.outfolder),
        backend=args.backend,
        strict=not args.permissive,
        py2_compat=args.py2_compat,
        input_format=getattr(args, "input_format", "auto"),
        segment_width=getattr(args, "segment_width", 0),
        decoder=args.decoder,
        pileup=args.pileup,
        wire=args.wire,
        decode_threads=args.decode_threads,
        ins_kernel=args.ins_kernel,
        chunk_reads=args.chunk_reads,
        profile_dir=args.profile_dir,
        json_metrics=args.json_metrics,
        trace_out=args.trace_out,
        metrics_out=args.metrics_out,
        log_level=args.log_level,
        log_format=getattr(args, "log_format", "text"),
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        paranoid=args.paranoid,
        incremental=args.incremental,
        source_id=os.path.abspath(args.filename),
        shards=args.shards,
        shard_mode=args.shard_mode,
        retries=args.retries,
        retry_backoff=args.retry_backoff,
        on_device_error=args.on_device_error,
        fault_inject=args.fault_inject,
        on_bad_record=getattr(args, "on_bad_record", "fail"),
        max_bad_records=getattr(args, "max_bad_records", ""),
        quarantine_out=getattr(args, "quarantine_out", None),
    )


def get_backend(name: str):
    if name == "cpu":
        from .backends.cpu import CpuBackend
        return CpuBackend()
    if name == "jax":
        try:
            from .backends.jax_backend import JaxBackend
        except ImportError as exc:
            raise SystemExit(
                "the jax backend failed to import (is jax installed?): "
                f"{exc}") from exc
        return JaxBackend()
    raise ValueError(f"unknown backend {name!r}")


def build_serve_parser() -> argparse.ArgumentParser:
    """The ``serve`` subcommand's surface: many ``-i`` inputs sharing one
    flag set, run through a persistent warm backend
    (sam2consensus_tpu/serve).  Job-shared flags mirror the one-shot
    CLI; checkpoint/incremental flags are absent by design (their
    serial-decode contract does not compose with decode-ahead)."""
    p = argparse.ArgumentParser(
        prog="sam2consensus-tpu serve",
        description="persistent multi-job serving: one warm jax "
                    "backend across every input (jit reuse + cross-job "
                    "pipelining); outputs per job like N one-shot runs")
    p.add_argument("-i", "--input", dest="inputs", action="append",
                   default=None,
                   help="SAM input (repeatable; one job per input, run "
                        "in order).  Required unless --ingest-port "
                        "starts a streaming-session server instead")
    p.add_argument("-c", "--consensus-thresholds", dest="thresholds",
                   type=str, default="0.25")
    p.add_argument("-n", dest="n", type=int, default=0)
    p.add_argument("-o", "--outfolder", dest="outfolder", default="./")
    p.add_argument("-m", "--min-depth", dest="min_depth", type=int,
                   default=1)
    p.add_argument("-f", "--fill", dest="fill", default="-")
    p.add_argument("-d", "--maxdel", dest="maxdel", type=int, default=None)
    p.add_argument("--py2-compat", action="store_true")
    p.add_argument("--permissive", action="store_true")
    p.add_argument("--on-bad-record", dest="on_bad_record",
                   choices=["fail", "skip", "quarantine"], default="fail",
                   help="per-record malformation policy shared by every "
                        "job (see the one-shot CLI); a blown "
                        "--max-bad-records budget fails ONLY that job "
                        "(DATA class: no retry, no rung demotion, no "
                        "tenant pinning) while the queue keeps draining "
                        "warm")
    p.add_argument("--max-bad-records", dest="max_bad_records", default="",
                   help="per-job bad-record error budget: N or x%%")
    p.add_argument("--quarantine-out", dest="quarantine_out", default=None,
                   help="quarantine sidecar base path: job k writes "
                        "<base>.job<k>.jsonl (default per-job "
                        "<outfolder>/<prefix>_quarantine.jsonl)")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--format", dest="input_format",
                   choices=["auto", "sam", "sam.gz", "bam"],
                   default="auto")
    p.add_argument("--segment-width", dest="segment_width", type=int,
                   default=0)
    p.add_argument("--pileup",
                   choices=["auto", "pallas", "mxu", "scatter", "host"],
                   default="auto")
    p.add_argument("--wire", choices=["auto", "packed5", "delta8"],
                   default="auto")
    p.add_argument("--insertion-kernel", dest="ins_kernel",
                   choices=["auto", "scatter", "pallas"], default="auto")
    p.add_argument("--decode-threads", dest="decode_threads", type=int,
                   default=1)
    p.add_argument("--decoder", choices=["auto", "native", "py"],
                   default="auto")
    p.add_argument("--shard-mode", dest="shard_mode",
                   choices=["auto", "dp", "sp", "dpsp"], default="auto")
    p.add_argument("--shards", type=int, default=0)
    p.add_argument("--chunk-reads", dest="chunk_reads", type=int,
                   default=262144)
    p.add_argument("--retries", type=int, default=3)
    p.add_argument("--retry-backoff", dest="retry_backoff", type=float,
                   default=0.25)
    p.add_argument("--on-device-error", dest="on_device_error",
                   choices=["fail", "retry", "fallback"], default="retry")
    p.add_argument("--fault-inject", dest="fault_inject", default="")
    p.add_argument("--log-level", dest="log_level", default=None,
                   choices=["debug", "info", "warning", "error"])
    p.add_argument("--metrics-out", dest="metrics_out", default=None,
                   help="per-job metrics JSONL base path: job k writes "
                        "<base>.job<k>.jsonl (+ its .manifest.json)")
    p.add_argument("--trace-out", dest="trace_out", default=None,
                   help="per-job trace base path: job k writes "
                        "<base>.job<k>.json")
    p.add_argument("--prewarm", choices=["auto", "off"], default="auto",
                   help="compile the layout's canonical slab shapes "
                        "behind the first job's decode (auto; engages "
                        "for explicitly device-pinned pileups — "
                        "--pileup scatter/pallas/mxu — since --pileup "
                        "auto may route host-side where there is "
                        "nothing to warm)")
    p.add_argument("--no-decode-ahead", dest="decode_ahead",
                   action="store_false",
                   help="disable cross-job pipelining (job N+1's host "
                        "decode normally overlaps job N's device work)")
    # --- continuous batching (sam2consensus_tpu/serve/scheduler.py) ---
    p.add_argument("--batch", dest="batch", default="off",
                   help="continuous batching: pack up to N eligible "
                        "small jobs (--pileup auto/scatter, genome <= "
                        "S2C_BATCH_MAX_MEMBER_LEN positions) into "
                        "shared slabs riding ONE device dispatch "
                        "sequence, with per-job count partitions "
                        "extracted for byte-identical per-job outputs. "
                        "off (default) | auto (tuned batch size, env "
                        "S2C_BATCH_AUTO_JOBS) | N.  A tenant burning "
                        "its --slo objective flushes the filling batch "
                        "immediately (latency over occupancy); any "
                        "fault inside a packed phase demotes only that "
                        "batch back to the serial path")
    p.add_argument("--batch-window", dest="batch_window", type=float,
                   default=None,
                   help="max milliseconds a filling batch waits for "
                        "more eligible jobs before flushing (default "
                        "50; live-arrival queues only — a pre-planned "
                        "queue arrives at once)")
    # --- cohort serving (sam2consensus_tpu/serve/cohort.py) ---
    p.add_argument("--cohort-manifest", dest="cohort_manifest",
                   default=None,
                   help="cohort mode: stream EVERY sample named by "
                        "this manifest (a directory of .sam/.sam.gz/"
                        ".bam files, a text file of paths/globs, or a "
                        ".jsonl object-store-style listing with a "
                        "'path' per row) through packed shared-panel "
                        "waves — one submission, not N.  Implies "
                        "--batch auto unless --batch is set; the "
                        "shared reference layout is planned once and "
                        "reused every wave, wave size follows the "
                        "learned packed rate under --mem-budget/"
                        "--max-queue caps, and --journal resumes an "
                        "interrupted cohort at its last committed "
                        "wave.  Does not compose with -i/--input or "
                        "--ingest-port")
    p.add_argument("--cohort-wave", dest="cohort_wave", type=int,
                   default=0,
                   help="fixed cohort wave size (members per packed "
                        "wave); 0 (default) sizes waves from the "
                        "learned cohort_jobs_per_sec rate card x "
                        "S2C_COHORT_WAVE_SEC, clamped to the length/"
                        "queue/memory caps")
    p.add_argument("--cohort-summary", dest="cohort_summary",
                   default=None,
                   help="write the cohort summary JSON (waves, "
                        "panel-plan reuse evidence, per-wave "
                        "cohort_wave decisions, per-position call "
                        "concordance) to this path")
    # --- incremental consensus (sam2consensus_tpu/serve/countcache.py) ---
    p.add_argument("--count-cache", dest="count_cache", default=None,
                   help="per-reference count cache byte budget (e.g. "
                        "'512M', '2G'; 'off' disables; env "
                        "S2C_COUNT_CACHE).  Keeps each reference "
                        "set's accumulated count tensor + insertion "
                        "log resident across jobs (LRU under the "
                        "budget) so an --incremental job against a "
                        "warm reference pays only delta decode + "
                        "scatter + re-vote — byte-identical to a cold "
                        "run over the concatenated inputs")
    p.add_argument("--incremental", action="store_true",
                   help="treat every input as an incremental shard "
                        "against its reference's warm count state "
                        "(requires --count-cache): outputs cover ALL "
                        "reads absorbed for that reference so far, "
                        "and re-submitting an already-absorbed input "
                        "adds nothing (keyed by absolute path)")
    # --- survivability (sam2consensus_tpu/serve/{journal,health,admission}) ---
    p.add_argument("--journal", dest="journal", default=None,
                   help="crash-safe job journal directory: every job's "
                        "lifecycle is durably recorded (atomic "
                        "tmp+rename segments) and each job gets a "
                        "per-job checkpoint home there, so a killed "
                        "server restarted with the SAME command resumes "
                        "the queue — committed jobs are skipped by "
                        "output fingerprint, the in-flight job resumes "
                        "from its checkpoint; zero lost, zero "
                        "duplicated jobs.  Implies --no-decode-ahead "
                        "(checkpoints need serial decode).  Outputs are "
                        "written per job at commit time, not at queue "
                        "end")
    p.add_argument("--worker-id", dest="worker_id", default="",
                   help="fleet mode (sam2consensus_tpu/serve/fleet.py; "
                        "requires --journal): join the journal as a "
                        "work-stealing worker under this UNIQUE id — "
                        "N processes launched with the same --journal "
                        "and the same inputs share the queue: each job "
                        "is claimed (atomic journal event, first "
                        "writer wins) before it runs, leases carry a "
                        "TTL renewed while the worker lives, and a "
                        "dead/frozen worker's expired lease is reaped "
                        "by a peer which re-claims the job from its "
                        "checkpoint — zero lost, zero duplicated.  Two "
                        "live processes sharing one id is operator "
                        "error (the id IS the lease identity)")
    p.add_argument("--lease-ttl", dest="lease_ttl", type=float,
                   default=None,
                   help="fleet lease TTL seconds (env S2C_LEASE_TTL, "
                        "default 30): a worker silent this long is "
                        "presumed dead and its in-flight job becomes "
                        "re-claimable; recovery latency is ~TTL + one "
                        "reap-scan period, so smaller = faster "
                        "takeover, larger = more tolerance for "
                        "stop-the-world pauses.  Renewals ride the "
                        "0.1 s watchdog poll at half-TTL margin")
    p.add_argument("--verify-outputs", dest="verify_outputs",
                   choices=["fast", "full"], default="fast",
                   help="journal-resume output verification: fast "
                        "(default) accepts a committed file whose "
                        "size+mtime still match the commit-time stat "
                        "and re-hashes only on drift — resume over a "
                        "large committed queue is O(stat); full "
                        "re-hashes every committed output "
                        "unconditionally")
    # --- streaming sessions (serve/{session,stream_server}.py) ---
    p.add_argument("--ingest-port", dest="ingest_port", type=int,
                   default=None,
                   help="streaming-session mode (requires --journal; "
                        "serve/stream_server.py): serve the live wave "
                        "ingest API on 127.0.0.1:PORT (0 = ephemeral, "
                        "logged at startup) instead of draining a "
                        "fixed -i queue.  Sessions are journal "
                        "entities under claim/lease semantics: a "
                        "killed worker's open sessions are stolen by "
                        "a peer sharing the journal, replaying every "
                        "journaled-but-unabsorbed wave — zero lost, "
                        "zero double-counted reads")
    p.add_argument("--stability-waves", dest="stability_waves",
                   type=int, default=3,
                   help="consecutive waves the consensus digest must "
                        "survive unchanged before the session emits "
                        "its stability verdict (the read-until "
                        "signal; default 3, must be >= 1)")
    p.add_argument("--revote-debounce", dest="revote_debounce",
                   type=float, default=0.0,
                   help="seconds to coalesce arriving waves before "
                        "re-voting (default 0 = re-vote on every "
                        "wave; must be >= 0).  Debounced waves are "
                        "journaled + ACKed 202 immediately and "
                        "absorbed in arrival order on the cadence")
    p.add_argument("--ingest-max-body", dest="ingest_max_body",
                   type=int, default=None,
                   help="max wave body bytes the ingest endpoint "
                        "accepts (default 64 MiB); larger uploads "
                        "answer 413 before buffering")
    p.add_argument("--ingest-timeout", dest="ingest_timeout",
                   type=float, default=None,
                   help="per-request socket deadline seconds on the "
                        "ingest endpoint (default 10); a client "
                        "silent this long mid-body answers 408 and "
                        "frees the handler thread")
    p.add_argument("--ingest-max-pending", dest="ingest_max_pending",
                   type=int, default=None,
                   help="per-session journaled-but-unabsorbed wave "
                        "bound (default 64): a session at its bound "
                        "answers 429 + Retry-After (admission "
                        "backpressure) instead of buffering without "
                        "limit")
    p.add_argument("--job-timeout", dest="job_timeout", type=float,
                   default=None,
                   help="per-job wall-clock deadline in seconds "
                        "(env S2C_JOB_TIMEOUT): a job that overruns is "
                        "abandoned and failed (under --on-device-error "
                        "fallback it retries once on the ladder's host "
                        "rung) while the server keeps draining the "
                        "queue")
    p.add_argument("--stall-timeout", dest="stall_timeout", type=float,
                   default=None,
                   help="hung-dispatch watchdog in seconds (env "
                        "S2C_STALL_TIMEOUT): fail the in-flight job "
                        "when no device dispatch completes for this "
                        "long — catches a wedged XLA dispatch or a "
                        "stuck decode thread long before a generous "
                        "--job-timeout would.  Set it ABOVE the "
                        "worst-case cold jit compile of one slab shape "
                        "(compilation is silence to this watchdog; the "
                        "persistent compile cache and --prewarm keep "
                        "that small on warm servers)")
    p.add_argument("--checkpoint-every", dest="checkpoint_every",
                   type=int, default=2_000_000,
                   help="journal mode: reads between a job's periodic "
                        "checkpoint writes (bounds how much of the "
                        "in-flight job a kill -9 re-runs); "
                        "default=2000000")
    p.add_argument("--max-queue", dest="max_queue", type=int, default=0,
                   help="admission control: max jobs admitted per "
                        "submission (0 = unbounded); overflow is "
                        "rejected with reason queue_full "
                        "(serve/admission_* counters)")
    p.add_argument("--tenant", dest="tenant", default="",
                   help="tenant label for every job of this invocation "
                        "(admission quotas + degraded-tenant isolation; "
                        "the API sets it per JobSpec)")
    p.add_argument("--tenant-quota", dest="tenant_quota", type=int,
                   default=0,
                   help="admission control: max admitted jobs per "
                        "tenant per submission (0 = unbounded)")
    p.add_argument("--mem-budget", dest="mem_budget", default=None,
                   help="capacity-priced admission (observability/"
                        "memplane.py): a job whose predicted peak "
                        "host+device bytes (from its header-probed "
                        "genome length, threshold grid and slab "
                        "geometry) exceeds this budget is shed with "
                        "reason 'capacity' instead of OOMing the warm "
                        "server.  Size grammar like --count-cache "
                        "('4G', '512M'); 'off'/unset disables; env "
                        "S2C_MEM_BUDGET")
    p.add_argument("--health-out", dest="health_out", default=None,
                   help="write an atomic health/readiness snapshot "
                        "(queue depth, in-flight job, heartbeat age, "
                        "tenant rungs, journal position, SLO burn) to "
                        "this path — rewritten at every job boundary "
                        "AND on the watchdog heartbeat cadence, so it "
                        "stays fresh while a job hangs")
    # --- telemetry plane (sam2consensus_tpu/observability/telemetry) ---
    p.add_argument("--telemetry-out", dest="telemetry_out", default=None,
                   help="write the server-lifetime OpenMetrics/"
                        "Prometheus text exposition (folded per-job "
                        "counters, per-tenant SLO summaries, "
                        "heartbeat-aged liveness gauges) to this path, "
                        "rewritten atomically on the telemetry "
                        "cadence — scrapeable with a plain file read, "
                        "no agent required")
    p.add_argument("--telemetry-port", dest="telemetry_port", type=int,
                   default=None,
                   help="serve /metrics (OpenMetrics text) and "
                        "/healthz (the health snapshot JSON) on "
                        "127.0.0.1:PORT via a stdlib-only endpoint "
                        "(0 = ephemeral port, logged at startup); "
                        "scrapes compute fresh heartbeat ages per "
                        "request")
    p.add_argument("--telemetry-interval", dest="telemetry_interval",
                   type=float, default=None,
                   help="seconds between exposition/health rewrites "
                        "(default 2.0; env S2C_TELEMETRY_INTERVAL); "
                        "the same cadence drives the mid-hang health "
                        "refresh")
    p.add_argument("--slo", dest="slo", default=None,
                   help="per-phase latency objectives, e.g. "
                        "'e2e=5s,queue=1s' (phases: queue|queue_wait, "
                        "decode, dispatch, vote, e2e; values in s or "
                        "ms; env S2C_SLO).  Breaches burn "
                        "slo/violations/<tenant>/<phase> counters "
                        "surfaced in the exposition, the health "
                        "snapshot and each job's manifest serve.slo "
                        "verdict")
    p.add_argument("--profile-capture-dir", dest="profile_capture_dir",
                   default=None,
                   help="where on-demand profiler captures land "
                        "(default: the journal dir, else next to "
                        "--telemetry-out).  Arm a capture with "
                        "SIGUSR2 or by touching <dir>/capture_profile "
                        "— a bounded jax.profiler window (pure-Python "
                        "span/stack dump on cpu) taken WHILE the "
                        "current job runs, no restart needed")
    p.add_argument("--log-format", dest="log_format",
                   choices=["text", "json"], default="text",
                   help="log record shape (see the one-shot CLI); "
                        "json records carry job_id/tenant/rung/span "
                        "correlation IDs across every serve thread")
    # shared-flag defaults config_from_args expects but serve never
    # exposes (one-shot-only features)
    p.set_defaults(backend="jax", prefix="", profile_dir=None,
                   json_metrics=None, checkpoint_dir=None,
                   paranoid=False, filename="")
    return p


def _serve_sessions(args: argparse.Namespace, echo) -> int:
    """``s2c serve --journal DIR --ingest-port P``: host streaming
    consensus sessions behind the live ingest endpoint until told to
    stop (SIGTERM / SIGINT / ctrl-C) — there is no fixed queue to
    drain.  Open sessions survive the stop: their journaled waves are
    replayed by whichever worker (this one restarted, or a fleet peer)
    claims them next."""
    import copy
    import logging
    import signal
    import time

    from .serve import ServeRunner
    from .serve.session import (DEFAULT_MAX_PENDING, SessionManager)
    from .serve.stream_server import (DEFAULT_MAX_BODY,
                                      DEFAULT_TIMEOUT_S, IngestServer)

    base_args = copy.copy(args)
    base_args.filename = ""             # per-session prefix, not per-job
    base_args.prefix = ""
    base_cfg = config_from_args(base_args)

    runner = ServeRunner(prewarm=args.prewarm,
                         decode_ahead=args.decode_ahead, echo=echo,
                         journal_dir=args.journal,
                         job_timeout=args.job_timeout,
                         stall_timeout=args.stall_timeout,
                         max_queue=args.max_queue,
                         tenant_quota=args.tenant_quota,
                         health_out=args.health_out,
                         fault_inject=args.fault_inject,
                         telemetry_out=args.telemetry_out,
                         telemetry_port=args.telemetry_port,
                         telemetry_interval=args.telemetry_interval,
                         slo=args.slo,
                         profile_capture_dir=args.profile_capture_dir,
                         mem_budget=args.mem_budget,
                         worker_id=args.worker_id,
                         lease_ttl=args.lease_ttl,
                         verify_outputs=args.verify_outputs)
    manager = SessionManager(
        runner, base_cfg,
        stability_waves=args.stability_waves,
        revote_debounce=args.revote_debounce,
        max_pending=(args.ingest_max_pending
                     if args.ingest_max_pending is not None
                     else DEFAULT_MAX_PENDING))
    runner.sessions = manager           # health snapshot `sessions` gate
    server = IngestServer(
        manager, port=args.ingest_port,
        max_body=(args.ingest_max_body
                  if args.ingest_max_body is not None
                  else DEFAULT_MAX_BODY),
        timeout=(args.ingest_timeout
                 if args.ingest_timeout is not None
                 else DEFAULT_TIMEOUT_S))
    echo(f"\nStreaming sessions on 127.0.0.1:{server.port}"
         + (f" as fleet worker {args.worker_id!r}"
            if args.worker_id else "")
         + f" (journal: {runner.journal.root})\n")

    stop = {"flag": False}

    def _stop(signum, frame):
        stop["flag"] = True

    prev = signal.signal(signal.SIGTERM, _stop)
    try:
        while not stop["flag"]:
            try:
                manager.tick()
                runner.telemetry_tick()
            except Exception as exc:    # the loop must outlive anything
                logging.getLogger("sam2consensus_tpu.serve").warning(
                    "session tick failed (%s: %s)",
                    type(exc).__name__, exc)
            time.sleep(0.1)
    except KeyboardInterrupt:
        pass
    finally:
        signal.signal(signal.SIGTERM, prev)
        server.close()
        runner.close()
    n_open = len(manager.sessions)
    echo(f"Ingest stopped; {n_open} open session(s) remain journaled "
         f"for takeover.\n")
    return 0


def _serve_cohort(args: argparse.Namespace, echo) -> int:
    """``s2c serve --cohort-manifest M --batch auto``: stream one
    manifest's samples through packed shared-panel waves
    (serve/cohort.py).  Exit 0 iff every sample succeeded (resumed
    samples count as succeeded — the journal already proved their
    outputs)."""
    import copy
    import sys as _sys

    from .serve import ServeRunner
    from .serve.cohort import CohortRunner, load_manifest

    try:
        paths = load_manifest(args.cohort_manifest)
    except (OSError, ValueError) as exc:
        raise SystemExit(f"error: {exc}") from None
    base_args = copy.copy(args)
    base_args.filename = ""             # per-sample prefix, not per-job
    base_args.prefix = ""
    base_cfg = config_from_args(base_args)

    runner = ServeRunner(prewarm=args.prewarm,
                         decode_ahead=args.decode_ahead, echo=echo,
                         journal_dir=args.journal,
                         job_timeout=args.job_timeout,
                         stall_timeout=args.stall_timeout,
                         max_queue=args.max_queue,
                         tenant_quota=args.tenant_quota,
                         health_out=args.health_out,
                         fault_inject=args.fault_inject,
                         telemetry_out=args.telemetry_out,
                         telemetry_port=args.telemetry_port,
                         telemetry_interval=args.telemetry_interval,
                         slo=args.slo,
                         profile_capture_dir=args.profile_capture_dir,
                         batch=args.batch if args.batch != "off"
                         else "auto",
                         batch_window=args.batch_window,
                         mem_budget=args.mem_budget,
                         verify_outputs=args.verify_outputs)
    echo(f"\nCohort of {len(paths)} sample(s) from "
         f"{args.cohort_manifest}"
         + (f" (jit cache: {runner.cache_dir})" if runner.cache_dir
            else "")
         + (f" (journal: {runner.journal.root})" if runner.journal
            else "") + "\n")
    try:
        cohort = CohortRunner(runner, paths, base_cfg,
                              wave=args.cohort_wave,
                              tenant=args.tenant,
                              summary_out=args.cohort_summary,
                              echo=echo)
        summary = cohort.run()
    finally:
        runner.close()
    for res in cohort.results:
        if not res.ok:
            print(f"job {res.job_id} FAILED: {res.error}",
                  file=_sys.stderr)
    conc = summary.get("concordance") or {}
    echo(f"Cohort done: {summary['samples_ok']} ok + "
         f"{summary['resumed']} resumed / {summary['samples_total']} "
         f"sample(s) in {summary['waves']} wave(s), "
         f"{summary['jobs_per_sec']} jobs/s"
         + (f", mean concordance {conc['mean_concordance']}"
            if conc else "") + ".\n")
    if args.cohort_summary:
        echo(f"Cohort summary at {args.cohort_summary}")
    return 1 if summary["failed"] else 0


def serve_main(argv: List[str]) -> int:
    """``s2c serve -i a.sam -i b.sam [...]``: run every input through
    one warm server; exit 0 iff every job succeeded."""
    import copy

    args = build_serve_parser().parse_args(argv)
    echo = (lambda *a, **k: None) if args.quiet else print

    from . import observability
    from .serve import JobSpec, ServeRunner
    from .utils.platform import pin_platform_from_env

    observability.configure_logging(args.log_level, args.log_format)
    pin_platform_from_env()
    # same non-composable combos the one-shot main rejects up front —
    # a deep per-job failure would be a worse error surface
    if args.pileup == "host" and args.shards > 1:
        raise SystemExit("--pileup host accumulates on the single host; "
                         "it does not compose with --shards")
    if args.shards > 1:
        # typed capacity check BEFORE the server warms: a --shards over
        # the runtime's device count rejects here, not as a late
        # XLA/mesh failure on the first admitted job
        from .parallel.mesh import MeshCapacityError, validate_shards

        try:
            validate_shards(args.shards, pileup=args.pileup)
        except MeshCapacityError as exc:
            raise SystemExit(f"error: {exc}") from None
    # a typo'd SLO objective must fail the server start, not silently
    # never fire (same up-front discipline as --fault-inject)
    from .observability.telemetry import parse_slo

    try:
        parse_slo(args.slo)
    except ValueError as exc:
        raise SystemExit(f"error: {exc}") from None
    from .serve.scheduler import parse_batch_mode

    try:
        parse_batch_mode(args.batch)
    except ValueError as exc:
        raise SystemExit(f"error: {exc}") from None
    from .serve.countcache import parse_budget

    try:
        cache_on = parse_budget(
            args.count_cache if args.count_cache is not None
            else os.environ.get("S2C_COUNT_CACHE")) > 0
    except ValueError as exc:
        raise SystemExit(f"error: {exc}") from None
    try:
        parse_budget(args.mem_budget if args.mem_budget is not None
                     else os.environ.get("S2C_MEM_BUDGET"))
    except ValueError as exc:
        raise SystemExit("error: " + str(exc).replace(
            "--count-cache", "--mem-budget")) from None
    if args.incremental and not cache_on:
        raise SystemExit(
            "error: --incremental serve jobs need --count-cache SIZE "
            "(or S2C_COUNT_CACHE) — the warm per-reference count state "
            "lives there")
    if args.incremental and args.journal:
        raise SystemExit(
            "error: --incremental does not compose with --journal "
            "(the journal injects per-job checkpoint homes, a second "
            "source of resumable state)")
    if args.worker_id and not args.journal:
        raise SystemExit(
            "error: --worker-id requires --journal (the shared "
            "journal IS the fleet's work-stealing queue)")
    if args.worker_id and args.batch != "off":
        raise SystemExit(
            "error: --worker-id does not compose with --batch "
            "(packed batches would need batch-level leases; the "
            "fleet IS the parallelism)")
    if args.worker_id and cache_on:
        raise SystemExit(
            "error: --worker-id does not compose with --count-cache "
            "(incremental jobs are rejected on a journaled server, "
            "so the cache would be a silent no-op)")
    if args.lease_ttl is not None and not args.lease_ttl > 0:
        raise SystemExit("error: --lease-ttl must be > 0")
    # --- streaming-session cross-checks: a typo'd session flag must
    # fail the server start, not surface as a deep mid-wave error
    # (same up-front discipline as parse_slo / --fault-inject)
    session_mode = args.ingest_port is not None
    # --- cohort cross-checks (serve/cohort.py): same fail-the-start
    # discipline — a cohort flag combination that cannot work must
    # reject before the server warms, not mid-manifest
    cohort_mode = args.cohort_manifest is not None
    if cohort_mode and session_mode:
        raise SystemExit(
            "error: --cohort-manifest does not compose with "
            "--ingest-port (a cohort is a pre-planned manifest; "
            "sessions are a live wave stream)")
    if cohort_mode and args.inputs:
        raise SystemExit(
            "error: --cohort-manifest does not compose with "
            "-i/--input (the manifest IS the input list — one "
            "submission for the whole cohort)")
    if cohort_mode and args.worker_id:
        raise SystemExit(
            "error: --cohort-manifest does not compose with "
            "--worker-id (cohort waves ride packed batches, which "
            "fleet workers exclude; shard cohorts by manifest "
            "instead)")
    if cohort_mode and args.incremental:
        raise SystemExit(
            "error: --cohort-manifest does not compose with "
            "--incremental (incremental jobs are ineligible for "
            "packing, so every wave would serialize)")
    if cohort_mode and args.batch.strip().lower() in ("0", "1"):
        raise SystemExit(
            "error: --cohort-manifest needs packed waves: use "
            "--batch auto or --batch N with N >= 2 (or omit --batch "
            "— cohort mode defaults it to auto)")
    if args.cohort_wave < 0 or args.cohort_wave == 1:
        raise SystemExit(
            "error: --cohort-wave must be 0 (rate-sized) or >= 2 "
            "(a wave of one cannot pack)")
    if session_mode and not args.journal:
        raise SystemExit(
            "error: --ingest-port requires --journal (sessions are "
            "journal entities — the durable wave intent log IS the "
            "crash-safety story)")
    if session_mode and args.inputs:
        raise SystemExit(
            "error: --ingest-port does not compose with -i/--input "
            "(waves arrive over the ingest API, not a fixed queue)")
    if not session_mode and not cohort_mode and not args.inputs:
        raise SystemExit(
            "error: at least one -i/--input is required (or "
            "--ingest-port to serve streaming sessions, or "
            "--cohort-manifest to serve a cohort)")
    if session_mode and args.batch != "off":
        raise SystemExit(
            "error: --ingest-port does not compose with --batch "
            "(waves of one session must absorb serially in arrival "
            "order; packed batches would break the count-bank rule)")
    if session_mode and args.incremental:
        raise SystemExit(
            "error: --ingest-port does not compose with --incremental "
            "(sessions ARE the incremental path — per-wave "
            "checkpoint-seeded absorption, journal-fenced)")
    if session_mode and cache_on:
        raise SystemExit(
            "error: --ingest-port does not compose with --count-cache "
            "(session count state lives in per-session checkpoint "
            "homes under the journal, not the LRU cache)")
    if args.stability_waves < 1:
        raise SystemExit("error: --stability-waves must be >= 1")
    if args.revote_debounce < 0:
        raise SystemExit("error: --revote-debounce must be >= 0")
    if args.ingest_max_body is not None and args.ingest_max_body <= 0:
        raise SystemExit("error: --ingest-max-body must be > 0")
    if args.ingest_timeout is not None and not args.ingest_timeout > 0:
        raise SystemExit("error: --ingest-timeout must be > 0")
    if args.ingest_max_pending is not None \
            and args.ingest_max_pending < 1:
        raise SystemExit("error: --ingest-max-pending must be >= 1")
    if args.fault_inject:
        from .resilience.faultinject import parse_spec

        try:
            parse_spec(args.fault_inject)
        except ValueError as exc:
            raise SystemExit(f"error: {exc}") from None

    if session_mode:
        return _serve_sessions(args, echo)
    if cohort_mode:
        return _serve_cohort(args, echo)

    specs = []
    for k, path in enumerate(args.inputs):
        job_args = copy.copy(args)
        job_args.filename = path
        job_args.prefix = ""            # per-job default: input basename
        if args.metrics_out:
            job_args.metrics_out = f"{args.metrics_out}.job{k}.jsonl"
        if args.trace_out:
            job_args.trace_out = f"{args.trace_out}.job{k}.json"
        if args.quarantine_out:
            # per-job sidecars, same .jobN discipline as metrics/trace
            # (N jobs sharing one sidecar would interleave evidence)
            job_args.quarantine_out = f"{args.quarantine_out}.job{k}.jsonl"
        cfg = config_from_args(job_args)
        if cfg.on_bad_record == "quarantine" and not cfg.quarantine_out:
            # the DEFAULT sidecar derives from prefix = input basename,
            # so two jobs over the same upload (the retrying-tenant
            # case) would clobber each other's evidence — stamp the
            # job index into the default too
            cfg.quarantine_out = os.path.join(
                cfg.outfolder,
                f"{cfg.prefix}_quarantine.job{k}.jsonl")
        specs.append(JobSpec(filename=path, config=cfg,
                             job_id=f"job{k}:{os.path.basename(path)}",
                             tenant=args.tenant))

    runner = ServeRunner(prewarm=args.prewarm,
                         decode_ahead=args.decode_ahead, echo=echo,
                         journal_dir=args.journal,
                         job_timeout=args.job_timeout,
                         stall_timeout=args.stall_timeout,
                         max_queue=args.max_queue,
                         tenant_quota=args.tenant_quota,
                         health_out=args.health_out,
                         fault_inject=args.fault_inject,
                         telemetry_out=args.telemetry_out,
                         telemetry_port=args.telemetry_port,
                         telemetry_interval=args.telemetry_interval,
                         slo=args.slo,
                         profile_capture_dir=args.profile_capture_dir,
                         batch=args.batch,
                         batch_window=args.batch_window,
                         count_cache=args.count_cache,
                         mem_budget=args.mem_budget,
                         worker_id=args.worker_id,
                         lease_ttl=args.lease_ttl,
                         verify_outputs=args.verify_outputs)
    echo(f"\nServing {len(specs)} job(s) on one warm backend"
         + (f" as fleet worker {args.worker_id!r}"
            if args.worker_id else "")
         + (f" (jit cache: {runner.cache_dir})" if runner.cache_dir
            else "")
         + (f" (journal: {runner.journal.root})" if runner.journal
            else "") + "\n")
    results = runner.submit_jobs(specs)
    failed = 0
    for spec, res in zip(specs, results):
        if not res.ok:
            failed += 1
            print(f"job {res.job_id} FAILED: {res.error}",
                  file=sys.stderr)
            continue
        if res.resumed or res.output_paths:
            # journal mode: the runner wrote (or a previous process
            # already committed) this job's outputs at commit time
            continue
        write_outputs(res.fastas, spec.config.outfolder,
                      spec.config.prefix, spec.config.nchar,
                      spec.config.thresholds, echo=echo)
        if spec.config.metrics_out:
            from .observability.manifest import manifest_path_for

            echo("Run manifest written to "
                 + manifest_path_for(spec.config.metrics_out) + "\n")
    ov = runner.registry.value("serve/overlap_sec")
    if args.health_out:
        echo(f"Health snapshot at {args.health_out}")
    if args.telemetry_out:
        echo(f"Telemetry exposition at {args.telemetry_out}")
    nv = int(runner.registry.value("slo/violations"))
    if nv:
        echo(f"SLO: {nv} objective breach(es) — see slo/violations/* "
             f"in the exposition / health snapshot")
    echo(f"Done: {len(results) - failed}/{len(results)} job(s) ok, "
         f"cross-job overlap {ov:.3f}s.\n")
    return 1 if failed else 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "serve":
        return serve_main(argv[1:])
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    echo = (lambda *a, **k: None) if args.quiet else print

    from . import observability

    observability.configure_logging(cfg.log_level, cfg.log_format)

    # A user's JAX_PLATFORMS must win even where a sitecustomize hook
    # pre-registered a remote accelerator and overrode jax.config (the
    # config trumps the env var; utils/platform.py) — without this,
    # JAX_PLATFORMS=cpu against the CLI silently dials the remote chip
    from .utils.platform import pin_platform_from_env

    pin_platform_from_env()

    if cfg.shards and cfg.backend != "jax":
        raise SystemExit("--shards requires --backend jax")
    if cfg.pileup == "host" and cfg.shards > 1:
        raise SystemExit("--pileup host accumulates on the single host; "
                         "it does not compose with --shards")
    if cfg.shards > 1:
        # typed up-front rejection (parallel.mesh.MeshCapacityError):
        # over-device --shards must fail HERE with the remedy in the
        # message, not as a late mesh/XLA error mid-run
        from .parallel.mesh import MeshCapacityError, validate_shards

        try:
            validate_shards(cfg.shards, pileup=cfg.pileup)
        except MeshCapacityError as exc:
            raise SystemExit(f"error: {exc}") from None
    if cfg.checkpoint_dir and cfg.backend != "jax":
        raise SystemExit("--checkpoint-dir requires --backend jax")
    if cfg.incremental and not cfg.checkpoint_dir:
        raise SystemExit("--incremental requires --checkpoint-dir")
    if cfg.fault_inject:
        # validate up front: a typo'd spec must fail the run, not
        # silently inject nothing
        from .resilience.faultinject import parse_spec

        try:
            parse_spec(cfg.fault_inject)
        except ValueError as exc:
            raise SystemExit(f"error: {exc}") from None

    t0 = time.perf_counter()
    echo("\nProcessing file " + args.filename + ":\n")

    # Mirrors the reference's progress accounting: every non-leading-header
    # line counts toward reads_total (sam2consensus.py:182,194,224-225).
    # The native decoder reports lines per block, so emit one message per
    # 500k multiple crossed (identical lines, batched timing).
    progress = [0]

    def on_lines(total: int) -> None:
        for k in range(progress[0] // 500000 + 1, total // 500000 + 1):
            echo(str(k * 500000) + " reads processed.")
        progress[0] = total

    # one open call for every container (sam2consensus_tpu/formats):
    # format sniffed/forced, BGZF blocks inflated on the decode-threads
    # pool, BAM records decoded binary; jax backend gets binary handles
    # so the native decoder parses raw bytes (no whole-file str decode/
    # encode round trip on the hot path)
    from .config import resolve_decode_threads
    from .formats import open_alignment_input

    ai = open_alignment_input(args.filename, cfg.input_format,
                              binary=cfg.backend == "jax",
                              on_lines=on_lines,
                              threads=resolve_decode_threads(cfg))
    contigs, stream = ai.contigs, ai.stream
    echo("SAM header processed, " + str(len(contigs)) + " references found.\n")
    backend = get_backend(cfg.backend)
    if cfg.backend == "jax":
        # persistent compilation cache: a COLD process start skips XLA
        # re-compiles of programs any earlier run (one-shot or serve)
        # already built; S2C_JIT_CACHE overrides the default dir, empty
        # disables (observability/jitcache.py; consults are counted
        # compile/persist_{hit,miss})
        from .observability.jitcache import setup_persistent_cache

        setup_persistent_cache()
    from .ingest.badrecords import BadRecordBudgetExceeded

    try:
        if cfg.profile_dir:
            import jax

            with jax.profiler.trace(cfg.profile_dir):
                result = backend.run(contigs, stream, cfg)
        else:
            result = backend.run(contigs, stream, cfg)
    except BadRecordBudgetExceeded as exc:
        # rotten input: a clean job-level failure with the precise
        # summary (counts per reason + sidecar path), not a traceback —
        # the budget is the user's own contract with their data
        ai.close()
        s = exc.summary
        lines = [f"error: {exc}"]
        if s.get("reasons"):
            lines.append("  reasons: " + ", ".join(
                f"{why}={n}" for why, n in s["reasons"].items()))
        if s.get("sidecar"):
            lines.append(f"  quarantine sidecar: {s['sidecar']}")
        raise SystemExit("\n".join(lines)) from None
    ai.close()
    reads_total = stream.n_lines

    echo("A total of " + str(reads_total) + " reads were processed, out of "
         "which, " + str(result.stats.reads_mapped) + " reads were mapped.\n")
    n_bad = result.stats.extra.get("bad_records", 0)
    if n_bad:
        msg = (f"{n_bad} malformed record(s) "
               + ("quarantined" if cfg.on_bad_record == "quarantine"
                  else "skipped") + f" (--on-bad-record {cfg.on_bad_record})")
        sidecar = result.stats.extra.get("quarantine_sidecar")
        if sidecar:
            msg += f"; sidecar: {sidecar}"
        echo(msg + "\n")

    write_outputs(result.fastas, cfg.outfolder, cfg.prefix, cfg.nchar,
                  cfg.thresholds, echo=echo)
    echo("Done.\n")

    if cfg.metrics_out and cfg.backend == "jax":
        # the run manifest (observability/manifest.py) rides alongside
        # the metrics sink: config + env overrides + link provenance +
        # every model decision with its residual/drift verdict
        from .observability.manifest import manifest_path_for

        echo("Run manifest written to "
             + manifest_path_for(cfg.metrics_out) + "\n")

    elapsed = time.perf_counter() - t0
    if cfg.json_metrics:
        metrics = {
            "backend": cfg.backend,
            "reads_mapped": result.stats.reads_mapped,
            "reads_skipped": result.stats.reads_skipped,
            "aligned_bases": result.stats.aligned_bases,
            "consensus_bases": result.stats.consensus_bases,
            "references": len(contigs),
            "references_with_output": len(result.fastas),
            "elapsed_sec": elapsed,
            "consensus_bases_per_sec":
                result.stats.consensus_bases / elapsed if elapsed > 0 else 0.0,
            **result.stats.extra,
        }
        blob = json.dumps(metrics)
        if cfg.json_metrics == "-":
            print(blob)
        else:
            with open(cfg.json_metrics, "w") as fh:
                fh.write(blob + "\n")
    return 0


def _accelerator_client_live() -> bool:
    """True when a (possibly tunneled) non-CPU accelerator client was
    actually initialized this process — the only case where interpreter
    teardown can abort in the client's C++ destructors ("FATAL: exception
    not rethrown", exit 134).  Introspects jax's backend cache without
    triggering initialization: public accessors first (``jax.extend.
    backend`` — nothing there enumerates without initializing today,
    but ``backends_are_initialized`` may surface publicly; probing the
    public namespace first means a future jax keeps working when the
    private module moves), then ``jax._src.xla_bridge``'s
    ``backends_are_initialized()`` + ``_backends`` cache.  The private
    attribute is pinned by tests/test_cli.py
    ``test_xla_bridge_private_surface_still_exists`` so a jax upgrade
    that drops it fails the suite loudly instead of silently flipping
    CPU-only runs onto the conservative ``os._exit`` branch (ADVICE r5
    #3).  An unreadable cache counts as live (the conservative side is
    skipping destructors, not crashing).  Override with S2C_SAFE_EXIT=0
    (never os._exit) / =1 (always)."""
    import os as _os

    env = _os.environ.get("S2C_SAFE_EXIT")
    if env is not None:
        return env != "0"
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return False
    try:
        inited = None
        try:                          # public namespace first
            from jax.extend import backend as jex_backend

            inited = getattr(jex_backend, "backends_are_initialized",
                             None)
        except ImportError:
            pass
        from jax._src import xla_bridge

        if inited is None:
            inited = getattr(xla_bridge, "backends_are_initialized",
                             None)
        if inited is not None and not inited():
            return False              # no client exists at all
        return any(p != "cpu" for p in xla_bridge._backends)
    except Exception:
        return True


if __name__ == "__main__":
    rc = main()
    # a tunneled accelerator client can abort in C++ teardown at
    # interpreter exit ("terminate called ... FATAL: exception not
    # rethrown", exit 134) AFTER every output file is closed and the
    # Done message printed; successful runs that touched the accelerator
    # skip those destructors so the exit code reflects the run, not the
    # remote client's shutdown.  CPU-only runs (the default backend; also
    # coverage/profiling hosts) exit normally so atexit handlers and
    # non-std stream flushes still run (ADVICE r4).  Error paths still
    # raise out of main() as bare tracebacks (reference parity).
    if _accelerator_client_live():
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    sys.exit(rc)
