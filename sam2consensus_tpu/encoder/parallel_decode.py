"""Multi-threaded fused decode+accumulate for multi-core hosts.

The fused host-counts path (``native_encoder.NativeReadEncoder`` with
``accumulate_into``) is a single pass over the SAM text at ~500 MB/s per
core.  The measurement host fronting the tunneled chip has ONE core, but
production TPU-VM hosts have many — and the count tensor is
sum-decomposable, so the pass parallelizes exactly:

* the input stream's line-aligned blocks round-robin into bounded
  per-worker queues;
* each worker owns a full fused decoder — its own slab scratch, its own
  insertion store, its own ``[L, 6]`` count tensor — and the C decode
  releases the GIL, so workers run truly parallel;
* counts sum at the end (addition commutes: same guarantee the dp
  reduce-scatter relies on, SURVEY.md §5); insertion stores concatenate
  (grouping sorts by site key, so inter-store order is irrelevant);
* strict-mode error parity: the serial path raises at the FIRST bad
  input line.  Blocks are fed in stream order and processed in order
  within each worker, so when workers fail the smallest failing block
  index is exactly the first bad line of the stream; its exception is
  re-raised after the join.  Feeding stops at the first observed
  failure (the serial path would not have read further either).

Not composable with checkpointing (checkpoints need ordered consumption
offsets) or paranoid mode (which wants row batches); the backend gates
accordingly.  With one worker the class degrades to the serial fused
path plus one queue hop.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator, List, Optional

import numpy as np

from .. import observability as obs
from .events import GenomeLayout, InsertionEvents, SegmentBatch
from .native_encoder import NativeReadEncoder


class ParallelFusedDecoder:
    """Same surface as NativeReadEncoder for the backend's accumulate loop
    (``insertions`` / ``n_reads`` / ``n_skipped`` / ``encode_blocks``)."""

    _DONE = object()

    #: per-worker count tensors are capped to this much extra memory in
    #: total; workers clamp down on huge genomes rather than OOM the
    #: large-genome runs the feature exists to speed up
    EXTRA_COUNTS_BUDGET = 512 << 20

    def __init__(self, layout: GenomeLayout, counts: np.ndarray,
                 n_threads: int, maxdel: Optional[int] = 150,
                 strict: bool = True, on_lines=None, on_bytes=None,
                 segment_width: int = 0):
        self._segment_width = segment_width
        self.layout = layout
        self._counts = counts                 # worker 0 writes here
        # per-extra-worker memory: its int32 count tensor, plus — in
        # shadow mode only — the fused decoder's uint8 shadow and (worst
        # case, deep coverage) int32 overflow bank, 2.25x the tensor
        # alone.  Direct mode (huge genomes) allocates neither, and is
        # exactly where under-capping would hurt most.
        from .native_encoder import fused_direct_mode

        if fused_direct_mode(layout.total_len):
            extra_each = max(1, counts.nbytes)
        else:
            extra_each = max(1, counts.nbytes + (counts.nbytes * 5) // 4)
        cap = 1 + self.EXTRA_COUNTS_BUDGET // extra_each
        self.n_threads = max(1, min(n_threads, cap))
        #: counting is fused into the worker decode passes (batches are
        #: counters-only), and the workers already overlap — the
        #: backend's extra prefetch thread would be pure overhead
        self.counts_fused = True
        self.insertions = InsertionEvents()
        self.n_reads = 0
        self.n_skipped = 0
        self._on_lines = on_lines
        self._on_bytes = on_bytes
        self._workers: List[dict] = []
        for w in range(self.n_threads):
            target = counts if w == 0 else np.zeros_like(counts)
            state = {
                "counts": target, "q": queue.Queue(maxsize=2),
                "batches": [], "error": None, "lines": 0, "bytes": 0,
                "idx": w,
            }

            def _count(key, st=state):
                def cb(k):
                    st[key] += k
                return cb

            enc = NativeReadEncoder(layout, maxdel=maxdel, strict=strict,
                                    accumulate_into=target,
                                    on_lines=_count("lines"),
                                    on_bytes=_count("bytes"),
                                    segment_width=segment_width)
            state["enc"] = enc
            self._workers.append(state)

    def _any_error(self) -> bool:
        return any(st["error"] is not None for st in self._workers)

    # -- worker ------------------------------------------------------------
    def _work(self, state: dict) -> None:
        enc: NativeReadEncoder = state["enc"]
        current_idx = [None]
        # capture the RUN's tracer and registry at thread start: a
        # worker that outlives the run (consumer aborted mid-stream)
        # must not record into whatever registry is current at its exit
        tr = obs.tracer()
        reg = obs.metrics()
        tr.name_thread(f"decode-worker-{state['idx']}")
        t0 = time.perf_counter()

        def feed():
            while True:
                item = state["q"].get()
                if item is self._DONE:
                    return
                current_idx[0] = item[0]
                yield item[1]

        try:
            for batch in enc.encode_blocks(feed()):
                state["batches"].append(batch)
        except BaseException as exc:
            state["error"] = (current_idx[0], exc)
        # one span per worker lifetime (block-level spans would be
        # noise: the fused C decode runs ~500 MB/s/core); the bytes/lines
        # args make per-worker balance visible in the trace
        tr.complete("decode_worker", t0, worker=state["idx"],
                    lines=state["lines"], bytes=state["bytes"])
        reg.add("decode/worker_sec", time.perf_counter() - t0)

    # -- coordinator -------------------------------------------------------
    def encode_blocks(self, blocks) -> Iterator[SegmentBatch]:
        threads = [threading.Thread(target=self._work, args=(st,),
                                    daemon=True)
                   for st in self._workers]
        for t in threads:
            t.start()

        def tolerant_put(st, thread, item) -> bool:
            """Bounded put that gives up if the worker died."""
            while thread.is_alive():
                try:
                    st["q"].put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            for idx, block in enumerate(blocks):
                if self._any_error():
                    break                 # serial parity: stop reading
                w = idx % self.n_threads
                tolerant_put(self._workers[w], threads[w], (idx, block))
                # drain finished batches opportunistically so the
                # backend's stats cadence ticks while decoding continues
                for st in self._workers:
                    while st["batches"]:
                        yield st["batches"].pop(0)
        finally:
            for st, t in zip(self._workers, threads):
                tolerant_put(st, t, self._DONE)
            for t in threads:
                t.join()

        # error parity: smallest failing block index == first bad line
        errors = [st["error"] for st in self._workers
                  if st["error"] is not None]
        if errors:
            errors.sort(key=lambda e: (e[0] is None, e[0]))
            raise errors[0][1]

        # merge: counts sum into worker 0's tensor (the accumulator's
        # buffer), insertion stores concatenate, counters total
        n_lines = n_bytes = 0
        for w, st in enumerate(self._workers):
            enc: NativeReadEncoder = st["enc"]
            if w > 0:
                self._counts += st["counts"]
            self.insertions.extend(enc.insertions)
            self.n_reads += enc.n_reads
            self.n_skipped += enc.n_skipped
            n_lines += st["lines"]
            n_bytes += st["bytes"]
            for batch in st["batches"]:
                yield batch
        if self._on_lines is not None and n_lines:
            self._on_lines(n_lines)
        if self._on_bytes is not None and n_bytes:
            self._on_bytes(n_bytes)
