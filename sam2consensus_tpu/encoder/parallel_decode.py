"""Sharded multi-core ingest: byte-range workers own the decode.

The first multi-threaded decoder fed workers from a Python coordinator
thread — the stream's blocks round-robined into bounded per-worker
queues.  Measured on a 2-core host that design scaled 1.1x where the
embarrassingly-parallel native vote scaled 2.6x: the feed thread's
block slicing, queue puts and drain polling all run under the GIL,
serializing against the workers' Python-side slab bookkeeping.  This
rewrite removes the coordinator from the hot path entirely:

* the input is split ONCE into record-aligned byte ranges
  (``ingest.plan_byte_shards``: mmap + line-boundary snapping — every
  SAM line starts in exactly one shard);
* each worker OWNS a shard: it slices zero-copy ``memoryview`` windows
  off the map and runs the native decoder GIL-free over them — no
  queue, no feed thread, no shared mutable state during decode;
* counts land in per-worker partitions — the fused decoder's private
  uint8 shadow + int32 overflow bank (``NativeReadEncoder
  private_counts=True``), 1.25 count-tensor-equivalents per extra
  worker instead of the old 2.25 — and merge into the run's single
  int32 tensor through the existing ``s2c_merge_u8`` SIMD fold, only
  after EVERY shard has succeeded (a failing shard can therefore retry
  or demote without ever corrupting the merge);
* error parity with the serial path is structural: shards are disjoint
  and ordered, so the earliest-SHARD error is the earliest-offset
  error; within a shard the worker's sequential decode surfaces its
  first error first.  Workers past a failed shard stop at the next
  sub-block boundary (the serial path would not have read further);
  workers before it run to completion so an even-earlier error still
  wins.  Decode-semantics errors (the replayed Python exception types)
  re-raise exactly; anything else — an injected ``ingest_decode_shard``
  fault, MemoryError — retries the shard once on a fresh encoder and
  then demotes the WHOLE ingest to the serial rung (fresh pass over the
  full input against zeroed counts), counted as ``ingest/demoted``.

Two output modes share the machinery:

* **fused** (``counts`` given — the host-pileup path): batches are
  counters-only; each worker holds its batches until its shard commits
  so a retry/demotion never double-counts, then the coordinator yields
  them all after the merge;
* **slab** (``counts=None`` — the device path): workers emit real
  row slabs into a bounded hand-off queue as they fill, and the
  consumer (the backend's prefetch thread) wire-encodes and stages
  them while later shards are still decoding — decode → encode →
  ``device_put`` as one overlapped pipeline.  Addition commutes, so
  inter-shard batch order is irrelevant to the counts.

Inputs that cannot be byte-sharded — gzip streams (non-splittable),
BGZF text (parallel at the inflate layer already), in-memory handles —
degrade to the STREAMING rung: the original queue-feed coordinator,
kept as ``encode_blocks``, counted as ``ingest/fallback``.

Not composable with checkpointing (checkpoints need ordered consumption
offsets) or paranoid mode (which re-validates ordered row batches); the
backend gates accordingly.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from .. import observability as obs
from ..ingest import DEFAULT_MIN_SHARD_BYTES, ShardPlan, snap_line_start
from ..ingest.badrecords import is_data_error
from ..resilience.faultinject import fault_check
from .events import (EncodeError, GenomeLayout, InsertionEvents,
                     SegmentBatch)
from .native_encoder import NativeReadEncoder

#: decode-semantics exceptions (the replayed python parser/encoder
#: errors whose type+message parity with the serial path is contract);
#: everything else is infrastructure and goes to the retry/demote path
PARITY_ERRORS = (EncodeError, KeyError, IndexError, ValueError,
                 OverflowError, UnicodeDecodeError)

#: sub-block feed granularity inside a shard: line-snapped windows this
#: size bound the abort-check latency and keep the fused stats cadence
SHARD_BLOCK_BYTES = 1 << 23


class ParallelFusedDecoder:
    """Same surface as NativeReadEncoder for the backend's accumulate
    loop (``insertions`` / ``n_reads`` / ``n_skipped`` / ``counts_fused``
    / ``encode_blocks``), plus the shard scheduler (``encode_input`` /
    ``encode_shards``).  ``counts=None`` selects slab mode."""

    _DONE = object()

    #: per-worker count partitions are capped to this much extra memory
    #: in total; workers clamp down on huge genomes rather than OOM the
    #: large-genome runs the feature exists to speed up
    EXTRA_COUNTS_BUDGET = 512 << 20

    def __init__(self, layout: GenomeLayout,
                 counts: Optional[np.ndarray], n_threads: int,
                 maxdel: Optional[int] = 150,
                 strict: bool = True, on_lines=None, on_bytes=None,
                 segment_width: int = 0, bad_sink=None):
        self._segment_width = segment_width
        #: tolerant decode (--on-bad-record): ONE run-wide sink shared by
        #: every worker encoder.  Rung invariance is partition keying:
        #: shard workers record into partition ``(shard_idx,)`` (cleared
        #: whole on a shard retry, reset whole on an ingest demotion —
        #: the count-bank discipline), streaming workers re-key per
        #: block index; ``entries()``'s sorted-partition merge is stream
        #: order on both rungs.
        self.bad_sink = bad_sink
        self.layout = layout
        self._counts = counts
        self.maxdel = maxdel
        self.strict = strict
        self._direct = False
        self._merge_lock = threading.Lock()
        if counts is None:
            self.n_threads = max(1, n_threads)
        else:
            # per-extra-worker memory: shadow mode holds a uint8 shadow
            # + int32 bank (1.25x the count tensor — the old design's
            # private int32 tensor on top of those is gone: workers
            # merge straight into the shared tensor via s2c_merge_u8);
            # direct mode (huge genomes) holds one private int32
            # partition.  Worker 0 always writes the shared tensor.
            from .native_encoder import fused_direct_mode

            self._direct = fused_direct_mode(layout.total_len)
            if self._direct:
                extra_each = max(1, counts.nbytes)
            else:
                extra_each = max(1, (counts.nbytes * 5) // 4)
            cap = 1 + self.EXTRA_COUNTS_BUDGET // extra_each
            self.n_threads = max(1, min(n_threads, cap))
        #: fused mode: counting rides the worker decode passes (batches
        #: are counters-only) and the workers already overlap — the
        #: backend's extra prefetch thread would be pure overhead
        self.counts_fused = counts is not None
        self.insertions = InsertionEvents()
        self.n_reads = 0
        self.n_skipped = 0
        self._on_lines = on_lines
        self._on_bytes = on_bytes

    # ------------------------------------------------------------------
    def _private_for(self, idx: int) -> bool:
        """Shard-worker count-partition policy.  Shadow mode: EVERY
        worker is private (the shadow+bank cost the same either way)
        and merges its partition at its own stream end under the shared
        merge lock — merges overlap slower workers' decode, and the
        shared tensor is only ever touched lock-serialized.  Direct
        mode (huge genomes): a private partition is a full int32
        tensor, so worker 0 writes the shared tensor in place (its
        retry scrubs it) and the private partitions fold post-join."""
        if self._counts is None:
            return False
        return not self._direct or idx > 0

    # ------------------------------------------------------------------
    def _mk_encoder(self, st: dict, private: bool,
                    partition=(0,)) -> NativeReadEncoder:
        """A fresh worker encoder counting lines/bytes into ``st``."""

        def _count(key):
            def cb(k):
                st[key] += k
            return cb

        return NativeReadEncoder(
            self.layout, maxdel=self.maxdel, strict=self.strict,
            accumulate_into=self._counts,
            on_lines=_count("lines"), on_bytes=_count("bytes"),
            segment_width=self._segment_width,
            private_counts=private and self._counts is not None,
            bad_sink=self.bad_sink, bad_partition=partition)

    def _finish(self, encoders: List[NativeReadEncoder],
                n_lines: int, n_bytes: int) -> None:
        """Commit worker results: counts merge (coordinator-serialized,
        so the shared tensor only ever has one writer), insertion stores
        concatenate (grouping sorts by site key, so inter-store order is
        irrelevant), counters total."""
        for enc in encoders:
            enc.merge_shadow()          # no-op for non-private/direct
            self.insertions.extend(enc.insertions)
            self.n_reads += enc.n_reads
            self.n_skipped += enc.n_skipped
        if self._on_lines is not None and n_lines:
            self._on_lines(n_lines)
        if self._on_bytes is not None and n_bytes:
            self._on_bytes(n_bytes)

    # -- rung selection ----------------------------------------------------
    def encode_input(self, stream,
                     min_shard_bytes: int = DEFAULT_MIN_SHARD_BYTES
                     ) -> Iterator[SegmentBatch]:
        """Decode ``stream`` (io.sam.ReadStream) on the best rung: byte
        shards when the input mmaps (plain files), else the streaming
        rung with a counted ``ingest/fallback``."""
        plan = None
        if self.n_threads > 1:
            plan = stream.shard_plan(self.n_threads,
                                     min_bytes=min_shard_bytes)
        if plan is not None and plan.ranges:
            return self.encode_shards(plan)
        reg = obs.metrics()
        if self.n_threads > 1:
            reg.add("ingest/fallback", 1)
        reg.gauge("ingest/mode").set_info(
            {"rung": "stream", "threads": self.n_threads,
             "input": type(stream.handle).__name__,
             "fused": self.counts_fused})
        return self.encode_blocks(stream.blocks(), stream=stream)

    # -- shard rung --------------------------------------------------------
    def encode_shards(self, plan: ShardPlan) -> Iterator[SegmentBatch]:
        """Decode a byte-sharded input; see the module docstring for the
        ownership/merge/error protocol."""
        reg = obs.metrics()
        ranges = list(plan.ranges)
        nw = min(self.n_threads, len(ranges))
        reg.gauge("ingest/mode").set_info(
            {"rung": "shards", "threads": nw, "shards": len(ranges),
             "bytes": plan.nbytes, "fused": self.counts_fused})
        reg.add("ingest/shards", len(ranges))
        if self.counts_fused:
            return self._run_shards_fused(plan, ranges, nw)
        return self._run_shards_slab(plan, ranges, nw)

    def _shard_blocks(self, data, lo: int, hi: int, shard_idx: int,
                      horizon: List[int], enc: NativeReadEncoder):
        """Zero-copy line-snapped windows of one shard.  Between windows
        the worker checks the error horizon: a shard EARLIER than this
        one failed, so nothing from here on can matter (serial parity:
        the stream would have stopped there) — stop feeding.

        ``enc.block_base`` is stamped with each window's absolute file
        offset before the yield, so a strict decode error (and every
        quarantine entry) carries the SAME offset the serial rung
        would report — including for a record straddling a shard snap
        boundary, whose line lives whole in exactly one shard."""
        import mmap as _mmap

        try:
            # one readahead hint per shard: the map's pages fault on
            # this worker's thread otherwise, at whatever per-fault
            # cost the host's kernel/sandbox charges
            lo_pg = lo & ~(_mmap.PAGESIZE - 1)
            data.madvise(_mmap.MADV_WILLNEED, lo_pg, hi - lo_pg)
        except (AttributeError, ValueError, OSError):
            pass
        pos = lo
        view = memoryview(data)
        while pos < hi:
            if horizon[0] < shard_idx:
                return
            end = snap_line_start(data, min(pos + SHARD_BLOCK_BYTES, hi),
                                  lo, hi)
            if end <= pos:      # one line longer than the window
                end = hi
            enc.block_base = pos
            yield view[pos:end]
            pos = end

    def _shard_work(self, st: dict, data, horizon: List[int],
                    hlock: threading.Lock, emit, tr, reg) -> None:
        """One worker: decode the owned shard, GIL-free in the C core.

        ``emit(batch)`` is rung-specific (collect vs queue-put).
        Attempt protocol: a decode-semantics error records
        ``(shard_idx, exc)`` and advances the horizon; any other
        failure retries ONCE on a fresh encoder (the failed attempt's
        private partitions and held batches are discarded whole, so
        nothing can double-count), then flags the shard for demotion.

        ``tr``/``reg`` are the RUN's instruments, captured on the
        spawning thread: worker threads are never thread-bound, so
        resolving them here would read whatever run is process-current
        — the wrong job under serve's decode-ahead overlap.
        """
        shard_idx, (lo, hi) = st["idx"], st["range"]
        tr.name_thread(f"decode-shard-{shard_idx}")
        t0 = time.perf_counter()
        attempts = 0
        while True:
            attempts += 1
            held: List[SegmentBatch] = []
            st["lines"] = st["bytes"] = 0
            # attempt 1 uses the coordinator-prebuilt encoder (its
            # tensor allocations would otherwise contend the GIL with
            # the other workers' chunk bookkeeping); retries build
            # fresh — the failed attempt's private partitions are
            # discarded whole, so nothing can double-count
            enc = st.pop("enc0", None)
            try:
                if enc is None:
                    # INSIDE the try: a retry-attempt allocation failure
                    # (the fresh shadow+bank is ~1.25 count tensors) is
                    # itself an infrastructure fault — it must take the
                    # retry/demote protocol, not kill the worker thread
                    # with st['fault'] unset
                    enc = self._mk_encoder(st, self._private_for(shard_idx),
                                           partition=(shard_idx,))
                if self.counts_fused:
                    fault_check("ingest_decode_shard")
                for batch in enc.encode_blocks(
                        self._shard_blocks(data, lo, hi, shard_idx,
                                           horizon, enc)):
                    if self.counts_fused:
                        # counters-only: held until the shard commits,
                        # so a retry/demotion never double-counts
                        held.append(batch)
                    elif not emit(batch):
                        break           # consumer gone
                if self.counts_fused and not self._direct:
                    # shadow mode: fold this worker's private partition
                    # now, lock-serialized — merges overlap the slower
                    # workers' decode instead of queueing post-join.  A
                    # later shard's demotion zeroes the shared tensor,
                    # so an early merge is never a corruption hazard.
                    with self._merge_lock:
                        enc.merge_shadow()
                st["enc"] = enc
                st["held"] = held
                break
            except PARITY_ERRORS as exc:
                st["error"] = (shard_idx, exc)
                with hlock:
                    horizon[0] = min(horizon[0], shard_idx)
                break
            except BaseException as exc:
                if is_data_error(exc):
                    # the run's bad-record budget blew on this worker's
                    # records: a property of the INPUT, not of this
                    # shard's attempt — never retried, never demoted
                    # (the serial rung would fail on the same bytes)
                    st["error"] = (shard_idx, exc)
                    with hlock:
                        horizon[0] = min(horizon[0], shard_idx)
                    break
                # infrastructure fault (injected ingest_decode_shard,
                # MemoryError, ...): retry the shard once on a fresh
                # encoder, then hand the decision to the coordinator
                if (shard_idx == 0 and self._direct
                        and self._counts is not None):
                    # direct-mode worker 0 writes the SHARED tensor in
                    # place: scrub its partial contribution before any
                    # retry/demotion — no other writer exists outside
                    # the merge lock, and nothing has merged yet
                    with self._merge_lock:
                        self._counts[:] = 0
                if attempts >= 2 or not self.counts_fused:
                    st["fault"] = exc
                    with hlock:
                        horizon[0] = min(horizon[0], shard_idx)
                    break
                if self.bad_sink is not None:
                    # the failed attempt's quarantine partition rolls
                    # back whole with its count partition — the fresh
                    # attempt re-records, so nothing double-counts
                    self.bad_sink.clear_partition((shard_idx,))
                reg.add("ingest/shard_retries", 1)
                tr.event("ingest/shard_retry", shard=shard_idx,
                         error=f"{type(exc).__name__}: {exc}")
        dt = time.perf_counter() - t0
        tr.complete("decode_shard", t0, shard=shard_idx,
                    lines=st["lines"], bytes=st["bytes"])
        reg.add("decode/worker_sec", dt)
        reg.add("ingest/worker_sec", dt)

    def _spawn_shards(self, ranges, nw: int, data, emit):
        """Start one worker per shard (round-robined when shards exceed
        the thread budget) and return (states, threads, horizon)."""
        horizon = [len(ranges)]
        hlock = threading.Lock()
        # instruments resolved HERE (the spawning thread, which serve's
        # decode-ahead binds to its job) and passed into the workers
        tr = obs.tracer()
        reg = obs.metrics()
        states = [{"idx": i, "range": r, "lines": 0, "bytes": 0,
                   "enc": None, "held": [], "error": None, "fault": None}
                  for i, r in enumerate(ranges)]
        for st in states:
            # attempt-1 encoders built HERE, before any worker runs:
            # their shadow/bank allocations and name-table builds would
            # otherwise serialize against the other workers under the
            # GIL right at the start of the parallel phase
            st["enc0"] = self._mk_encoder(st, self._private_for(st["idx"]),
                                          partition=(st["idx"],))
        # one thread per shard up to nw at a time: a simple claim queue
        # (shards are sized ~equal, so static round-robin is fine too;
        # the claim queue additionally absorbs snap-size imbalance)
        claims: "queue.Queue" = queue.Queue()
        for st in states:
            claims.put(st)

        def runner():
            while True:
                try:
                    st = claims.get_nowait()
                except queue.Empty:
                    return
                self._shard_work(st, data, horizon, hlock, emit, tr, reg)

        threads = [threading.Thread(target=runner, daemon=True,
                                    name=f"decode-worker-{w}")
                   for w in range(nw)]
        for t in threads:
            t.start()
        return states, threads, horizon

    @staticmethod
    def _first_failure(states):
        """The stream-order-first failure: ``(idx, kind, exc)`` or None.
        Shards are disjoint and ordered, so the smallest shard index is
        the earliest stream offset regardless of which worker hit it."""
        failures = []
        for st in states:
            if st["error"] is not None:
                failures.append((st["error"][0], "error", st["error"][1]))
            if st["fault"] is not None:
                failures.append((st["idx"], "fault", st["fault"]))
        if not failures:
            return None
        failures.sort(key=lambda f: f[0])
        return failures[0]

    def _run_shards_fused(self, plan: ShardPlan, ranges, nw: int
                          ) -> Iterator[SegmentBatch]:
        reg = obs.metrics()
        states, threads, _horizon = self._spawn_shards(
            ranges, nw, plan.data, emit=None)
        for t in threads:
            t.join()
        first = self._first_failure(states)
        if first is not None and first[1] == "error":
            # a decode-semantics error EARLIER than any fault: serial
            # would have raised it before reaching the faulted region
            raise first[2]
        if first is not None:
            # demotion: the serial rung, whole input, zeroed counts —
            # by construction nothing has merged yet and nothing was
            # yielded, so the fresh pass is exactly the serial path
            reg.add("ingest/demoted", 1)
            obs.tracer().event(
                "ingest/demoted",
                error=f"{type(first[2]).__name__}: {first[2]}")
            self._counts[:] = 0
            if self.bad_sink is not None:
                # demotion replays the WHOLE input on the serial rung:
                # every shard partition rolls back so the fresh pass's
                # records (partition (0,)) are the only ones counted
                self.bad_sink.reset()
            st = {"lines": 0, "bytes": 0}
            enc = self._mk_encoder(st, private=False)
            enc.block_base = plan.start
            view = memoryview(plan.data)
            for batch in enc.encode_blocks(
                    iter([view[plan.start:plan.end]])):
                yield batch
            self._finish([enc], st["lines"], st["bytes"])
            return
        self._finish([st["enc"] for st in states],
                     sum(st["lines"] for st in states),
                     sum(st["bytes"] for st in states))
        for st in states:
            for batch in st["held"]:
                yield batch

    def _run_shards_slab(self, plan: ShardPlan, ranges, nw: int
                         ) -> Iterator[SegmentBatch]:
        out_q: "queue.Queue" = queue.Queue(maxsize=2 * nw)
        stop = threading.Event()

        def emit(batch) -> bool:
            while not stop.is_set():
                try:
                    out_q.put(batch, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        states, threads, _horizon = self._spawn_shards(
            ranges, nw, plan.data, emit)

        def alive() -> bool:
            return any(t.is_alive() for t in threads)

        try:
            while True:
                try:
                    batch = out_q.get(timeout=0.1)
                except queue.Empty:
                    if not alive():
                        break
                    continue
                yield batch
        finally:
            stop.set()
            for t in threads:
                t.join()
        # drain anything emitted between the last get and the joins
        while True:
            try:
                yield out_q.get_nowait()
            except queue.Empty:
                break
        first = self._first_failure(states)
        if first is not None:
            # slab mode has no retry rung: emitted slabs may already be
            # accumulated device-side, so a clean replay is impossible —
            # surface the stream-order-first failure (parity error, or
            # the fault for the run's retry policy/ladder to own)
            raise first[2]
        self._finish([st["enc"] for st in states],
                     sum(st["lines"] for st in states),
                     sum(st["bytes"] for st in states))

    # -- streaming rung ----------------------------------------------------
    def encode_blocks(self, blocks, stream=None) -> Iterator[SegmentBatch]:
        """The queue-feed rung for non-shardable inputs: the stream's
        line-aligned blocks round-robin into bounded per-worker queues;
        workers process blocks in order within each worker, so when
        workers fail the smallest failing block index is exactly the
        first bad line of the stream.  Feeding stops at the first
        observed failure (the serial path would not have read further
        either).  With one worker this degrades to the serial fused
        path plus one queue hop.

        ``stream`` (when given) supplies per-block input offsets
        (``ReadStream.block_offset`` — uncompressed offsets on gzip
        handles) and keys each block's quarantine partition by block
        index, so tolerant-mode entries merge in stream order exactly
        like the shard rung's."""
        workers: List[dict] = []
        for w in range(self.n_threads):
            st = {"idx": w, "q": queue.Queue(maxsize=2), "batches": [],
                  "error": None, "fault": None, "lines": 0, "bytes": 0,
                  "enc": None}
            st["enc"] = self._mk_encoder(st, private=w > 0)
            workers.append(st)

        def any_error() -> bool:
            return any(st["error"] is not None or st["fault"] is not None
                       for st in workers)

        # instruments resolved on the consuming thread (thread-bound in
        # serve's decode-ahead) and passed into the workers
        tr = obs.tracer()
        reg = obs.metrics()
        threads = [threading.Thread(target=self._stream_work,
                                    args=(st, tr, reg), daemon=True)
                   for st in workers]
        for t in threads:
            t.start()

        def tolerant_put(st, thread, item) -> bool:
            """Bounded put that gives up if the worker died."""
            while thread.is_alive():
                try:
                    st["q"].put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            for idx, block in enumerate(blocks):
                if any_error():
                    break                 # serial parity: stop reading
                off = getattr(stream, "block_offset", None) \
                    if stream is not None else None
                w = idx % self.n_threads
                tolerant_put(workers[w], threads[w], (idx, block, off))
                # drain finished batches opportunistically so the
                # backend's stats cadence ticks while decoding continues
                for st in workers:
                    while st["batches"]:
                        yield st["batches"].pop(0)
        finally:
            for st, t in zip(workers, threads):
                tolerant_put(st, t, self._DONE)
            for t in threads:
                t.join()

        # error parity: smallest failing block index == first bad line
        errors = [st["error"] for st in workers if st["error"] is not None]
        if errors:
            errors.sort(key=lambda e: (e[0] is None, e[0]))
            raise errors[0][1]
        faults = [st["fault"] for st in workers if st["fault"] is not None]
        if faults:
            raise faults[0]

        self._finish([st["enc"] for st in workers],
                     sum(st["lines"] for st in workers),
                     sum(st["bytes"] for st in workers))
        for st in workers:
            for batch in st["batches"]:
                yield batch

    def _stream_work(self, st: dict, tr, reg) -> None:
        enc: NativeReadEncoder = st["enc"]
        current_idx = [None]
        # tr/reg are the RUN's instruments captured on the consuming
        # thread: a worker that outlives the run (consumer aborted
        # mid-stream) must not record into whatever registry is current
        # at its exit, and an unbound worker thread must not read a
        # different job's process-current registry under serve overlap
        tr.name_thread(f"decode-worker-{st['idx']}")
        t0 = time.perf_counter()

        def feed():
            while True:
                item = st["q"].get()
                if item is self._DONE:
                    return
                current_idx[0] = item[0]
                # per-block re-key: quarantine partition = block index
                # (sorted-partition merge == stream order) and the
                # block's absolute input offset for error marking
                enc.bad_partition = (item[0],)
                enc.block_base = item[2]
                yield item[1]

        try:
            for batch in enc.encode_blocks(feed()):
                st["batches"].append(batch)
        except PARITY_ERRORS as exc:
            st["error"] = (current_idx[0], exc)
        except BaseException as exc:
            if is_data_error(exc):
                # budget blown mid-block: input-shaped, takes the
                # parity path (smallest block index wins) not the
                # infrastructure-fault path
                st["error"] = (current_idx[0], exc)
            else:
                st["fault"] = exc
        # one span per worker lifetime (block-level spans would be
        # noise: the fused C decode runs ~500 MB/s/core); the bytes/lines
        # args make per-worker balance visible in the trace
        tr.complete("decode_worker", t0, worker=st["idx"],
                    lines=st["lines"], bytes=st["bytes"])
        reg.add("decode/worker_sec", time.perf_counter() - t0)
