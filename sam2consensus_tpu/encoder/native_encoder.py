"""Native-decode path: raw SAM text blocks → SegmentBatch via the C++ core.

Wraps ``native/decoder.cpp`` (ctypes) with the orchestration the C side
deliberately doesn't do:

* buffer sizing/growth and resume-after-capacity (the C call commits whole
  lines and reports consumed bytes);
* width adaptation: rows wider than the current bucket width W are reported
  as overflow lines, fall back to the Python encoder for this block, and
  double W for subsequent blocks when they stop being rare;
* error parity: a line the C decoder flags is REPLAYED through the Python
  parser/encoder, so the exception type and message are identical to the
  pure-Python path (and if the replay disagrees and succeeds — e.g. exotic
  int literals Python accepts — the read is committed via the Python
  fallback and decoding continues);
* merging native row matrices with Python-fallback rows into one
  power-of-two-padded SegmentBatch per block.

Byte-for-byte output equivalence with the Python encoder over the fixture
corpus is pinned by tests/test_native.py.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..constants import PAD_CODE
from ..ingest.badrecords import (C_REASONS, RECORD_ERRORS, classify_reason,
                                 mark_offset)
from ..io.sam import iter_records
from .. import native
from .events import (EncodeError, GenomeLayout, MIN_BUCKET_W, ReadEncoder,
                     SegmentBatch, _bucket_width)


def available() -> bool:
    return native.load() is not None


def fused_direct_mode(total_len: int) -> bool:
    """True when the fused pileup counts straight into the int32 tensor
    (huge genomes: sparse per-line coverage, and the uint8 shadow's
    L-proportional merge would dominate).  One definition shared by the
    encoder and ParallelFusedDecoder's memory cap."""
    return total_len >= int(os.environ.get(
        "S2C_FUSED_DIRECT_MIN_LEN", str(1 << 23)))


def _line_end(data: np.ndarray, start: int) -> int:
    """Index of the newline ending the line at ``start`` (or end of data)."""
    seg = data[start:start + (1 << 20)]
    nl = np.nonzero(seg == 10)[0]
    if len(nl):
        return start + int(nl[0])
    if start + len(seg) < len(data):  # pragma: no cover - >1MB line
        nl = np.nonzero(data[start:] == 10)[0]
        return start + int(nl[0]) if len(nl) else len(data)
    return len(data)


class NativeReadEncoder:
    """Streaming encoder over raw text blocks; same surface as ReadEncoder."""

    def __init__(self, layout: GenomeLayout, maxdel: Optional[int] = 150,
                 strict: bool = True, width: int = 256,
                 on_lines=None, on_bytes=None,
                 accumulate_into: Optional[np.ndarray] = None,
                 segment_width: int = 0, private_counts: bool = False,
                 bad_sink=None, bad_partition=(0,)):
        lib = native.load()
        if lib is None:  # pragma: no cover - callers check available()
            raise RuntimeError(f"native decoder unavailable: "
                               f"{native.load_error()}")
        self._lib = lib
        self.layout = layout
        self.maxdel = maxdel
        self.strict = strict
        #: tolerant decode (--on-bad-record): when a sink is attached,
        #: the C decoder runs in line-FLAGGING mode (strict=1 on the C
        #: side — its clean fast path is byte-identical to strict runs,
        #: which is why tolerant-mode overhead on clean input is ~zero)
        #: and the python replay below absorbs each flagged record into
        #: the sink instead of raising.  ``bad_partition`` keys this
        #: encoder's records in the sink's deterministic merge order;
        #: the rung schedulers re-key it (shard index / block index).
        self.bad_sink = bad_sink
        self.bad_partition = tuple(bad_partition)
        self._c_strict = 1 if (strict or bad_sink is not None) else 0
        #: absolute input offset of the block currently being decoded
        #: (set by the feeding rung; None = offsets unknown) — the base
        #: for strict-error offset marking and quarantine entries
        self.block_base = None
        #: slab-width ceiling: with the segmented layout active, a long
        #: read is an overflow line that the python twin splits into
        #: <=segment_width rows — so the native slab never widens past W
        #: (one 100 kb read would otherwise push every subsequent slab
        #: to a 65536-wide, ~97%-padding shape)
        self._width_cap = segment_width if segment_width else 1 << 16
        self.width = min(width, self._width_cap)
        self.on_lines = on_lines
        self.on_bytes = on_bytes
        # fused host pileup: the C decoder counts each committed row into
        # a uint8 shadow tensor (4x fewer cache lines than int32 on the
        # hot random-access increments; SIMD one-hot adds where the ISA
        # allows) with saturation wraps banked as +256 in a lazily-paged
        # int32 tensor; ``merge_shadow`` folds both into ``accumulate_into``
        # at stream end / checkpoint boundaries.  Rows become scratch and
        # batches carry only counters.  Python-fallback reads accumulate
        # into ``accumulate_into`` directly via numpy.
        self._acc = accumulate_into
        #: shard-worker mode (encoder/parallel_decode.py): the decode
        #: pass must never touch ``accumulate_into`` directly — counts
        #: stay in this encoder's PRIVATE uint8 shadow / int32 bank
        #: partitions until the coordinator calls :meth:`merge_shadow`
        #: after every shard succeeded, so a failed shard can be
        #: retried (or the whole ingest demoted) without ever having
        #: corrupted the shared tensor
        self._private = bool(private_counts)
        if accumulate_into is not None:
            if accumulate_into.shape != (layout.total_len, 6) \
                    or accumulate_into.dtype != np.int32 \
                    or not accumulate_into.flags.c_contiguous:
                raise ValueError("accumulate_into must be C-contiguous "
                                 "int32 [total_len, 6]")
            self._acc_flat = accumulate_into.reshape(-1)
            self._acc_len = layout.total_len
            # counting mode by genome size: the uint8 shadow wins when
            # coverage is deep (count lines revisited many times) but
            # pays an L-proportional merge; huge genomes are sparse per
            # line, so counts go STRAIGHT into the int32 pileup (passed
            # as the C side's acc_ovf) — no shadow, no merge
            self._acc_direct = fused_direct_mode(layout.total_len)
            if self._acc_direct:
                self._acc_u8 = np.zeros(6, dtype=np.uint8)   # unused
                # private direct mode: a full private int32 partition
                # stands in for the shared tensor until merge time
                self._acc_ovf = np.zeros(layout.total_len * 6,
                                         dtype=np.int32) \
                    if self._private else self._acc_flat
            else:
                # np.zeros -> calloc: the overflow bank's pages only
                # materialize where depth actually passes 255
                self._acc_u8 = np.zeros(layout.total_len * 6,
                                        dtype=np.uint8)
                self._acc_ovf = np.zeros(layout.total_len * 6,
                                         dtype=np.int32)
            # where python-replayed fallback lines count: the shared
            # tensor normally; the private int32 bank/partition in
            # shard-worker mode (the bank is exact — merge adds it)
            self._fb_acc = self._acc if not self._private \
                else self._acc_ovf.reshape(layout.total_len, 6)
        else:
            self._acc_direct = False
            self._acc_flat = np.zeros(6, dtype=np.int32)   # dummy, len 0
            self._acc_u8 = np.zeros(6, dtype=np.uint8)
            self._acc_ovf = np.zeros(6, dtype=np.int32)
            self._acc_len = 0
            self._fb_acc = None
        #: saturation wraps the C side banked into ``_acc_ovf`` since the
        #: last merge — 0 means the bank is all zeros and its fold is a
        #: no-op merge_shadow can skip
        self._banked = 0
        # python twin for overflow/error-replay fallback; shares counters
        # and the insertion store so fallback reads land in the same place
        # (NOT the sink: _fallback_line/_fallback_record own the tolerant
        # catch around encode_record, so the twin never double-records)
        self._py = ReadEncoder(layout, maxdel=maxdel, strict=strict,
                               segment_width=segment_width)
        self.insertions = self._py.insertions

        names_blob = "".join(layout.names).encode("ascii")
        name_off = np.zeros(len(layout.names) + 1, dtype=np.int64)
        np.cumsum([len(n.encode("ascii")) for n in layout.names],
                  out=name_off[1:])
        self._names = names_blob
        self._name_off = name_off
        self._ctg_offset = layout.offsets[:-1].astype(np.int64).copy()
        self._ctg_len = layout.lengths.astype(np.int64).copy()

    @property
    def counts_fused(self) -> bool:
        """True when counting is fused into the decode pass — batches
        are counters-only and the backend's consumer loop is stats-only
        (it skips the prefetch thread then)."""
        return self._acc is not None

    @property
    def n_reads(self) -> int:
        return self._py.n_reads

    @property
    def n_skipped(self) -> int:
        return self._py.n_skipped

    #: expanded scatter cells per emitted slab (rows = SLAB_CELLS // width);
    #: matches ops.pileup.SCATTER_CELL_BUDGET so one slab = one scatter call
    SLAB_CELLS = 1 << 23

    def encode_blocks(self, blocks: Iterable[str]) -> Iterator[SegmentBatch]:
        """Yield SegmentBatches as fixed-size row slabs fill.

        Slabs persist across text blocks, so the steady state is one
        (rows, width) shape per run — one jit compilation, near-zero row
        padding — and only the final partial slab pads up to a power of
        two.
        """
        # slab state
        self._probed = False
        self._new_slab()
        self._fallback_rows: List[Tuple[int, np.ndarray]] = []
        self._batch_reads = 0
        self._batch_events = 0

        # persistent insertion/overflow buffers, allocated ONCE and
        # reused across calls (contents are copied out per call below).
        # They used to be allocated per chunk iteration; at ~1.3 MB a
        # set that is an mmap+munmap pair per chunk through glibc,
        # whose mmap_sem write locks serialize the OTHER decode
        # workers' page faults — measured as most of the gap between
        # raw-C and full-path shard scaling on the 2-core rig
        ins_cap = 1 << 16
        chars_cap = 1 << 20
        ovf_cap = 4096
        out = np.zeros(16, dtype=np.int64)
        ic = np.empty(ins_cap, dtype=np.int32)
        il = np.empty(ins_cap, dtype=np.int32)
        im = np.empty(ins_cap, dtype=np.int32)
        ich = np.empty(chars_cap, dtype=np.uint8)
        ovf = np.empty(ovf_cap, dtype=np.int64)

        for text in blocks:
            if isinstance(text, str):
                text = text.encode("ascii")
            data = np.frombuffer(text, dtype=np.uint8)
            base = self.block_base       # set by the feeding rung
            offset = 0
            while offset < len(data):
                chunk = data[offset:]
                # NOT dead code: the status==1/consumed==0 branch below
                # doubles the caps when a single line overruns the
                # insertion buffers — these guards are where the arrays
                # actually grow before the retry call (the C decoder is
                # told the cap, so cap > len(array) would write past
                # the end)
                if len(ic) < ins_cap:
                    ic = np.empty(ins_cap, dtype=np.int32)
                    il = np.empty(ins_cap, dtype=np.int32)
                    im = np.empty(ins_cap, dtype=np.int32)
                if len(ich) < chars_cap:
                    ich = np.empty(chars_cap, dtype=np.uint8)
                if len(ovf) < ovf_cap:
                    ovf = np.empty(ovf_cap, dtype=np.int64)
                fill = self._fill
                self._lib.s2c_decode(
                    chunk, len(chunk),
                    self._names, self._name_off, len(self._ctg_len),
                    self._ctg_offset, self._ctg_len,
                    -1 if self.maxdel is None else self.maxdel,
                    self._c_strict,
                    self._slab_w,
                    self._starts[fill:], self._codes[fill:],
                    len(self._starts) - fill,
                    ic, il, im, ins_cap,
                    ich, chars_cap,
                    ovf, ovf_cap,
                    out,
                    self._acc_u8, self._acc_ovf, self._acc_len,
                    1 if self._acc_direct else 0)

                (n_rows, n_reads, n_skipped, consumed, n_ins, n_chars,
                 status, _err_off, n_events, n_lines, n_overflow,
                 _max_span) = out[:12]
                self._banked += int(out[12])

                # fused pileup: rows were counted inside the C pass; the
                # slab is scratch, reuse it from the top
                self._fill = 0 if self._acc is not None \
                    else fill + int(n_rows)
                if n_ins:
                    self.insertions.array_chunks.append(
                        (ic[:n_ins].copy(), il[:n_ins].copy(),
                         im[:n_ins].copy(), ich[:n_chars].copy()))
                self._py.n_reads += int(n_reads)
                self._py.n_skipped += int(n_skipped)
                self._batch_reads += int(n_reads)
                self._batch_events += int(n_events)
                self._count_lines(int(n_lines))

                # overflow lines (span > width): python fallback, whole read
                for k in range(int(n_overflow)):
                    self._fallback_line(
                        chunk, int(ovf[k]),
                        abs_off=None if base is None
                        else base + offset + int(ovf[k]))
                if n_overflow > max(64, n_reads // 64):
                    # widen future slabs; the current slab keeps its
                    # width.  Capped at the segmented layout's W when
                    # active — overflow reads come back segmented via
                    # the python twin instead of widening every slab.
                    self.width = min(self._width_cap, self.width * 2)
                elif (not self._probed and n_reads > 256 and _max_span > 0
                      and not n_overflow):
                    # one-shot shrink to the observed span profile: padding
                    # bytes are wire bytes on the host->device link
                    self._probed = True
                    self.width = min(self._width_cap,
                                     max(MIN_BUCKET_W,
                                         _bucket_width(int(_max_span))))

                offset += int(consumed)
                self._count_bytes(int(consumed))
                if status == 2:
                    # flagged line: python replay for identical errors; if
                    # the replay succeeds instead (python being more lenient
                    # than the C parser), commit it via the fallback path
                    line_end = _line_end(data, offset)
                    self._fallback_line(
                        data, offset, line_end=line_end,
                        abs_off=None if base is None else base + offset,
                        c_reason=int(out[14]))
                    self._count_lines(1)
                    self._count_bytes(min(line_end + 1, len(data)) - offset)
                    offset = line_end + 1
                elif status == 1:
                    if len(self._starts) - self._fill < 2:
                        # slab full: emit and start fresh
                        batch = self._flush()
                        if batch is not None:
                            yield batch
                    elif consumed == 0:
                        # a single line overran the insertion buffers
                        ins_cap *= 2
                        chars_cap *= 2
                        ovf_cap *= 2
                    # else: per-call insertion buffers were the constraint;
                    # they were copied out above, so just keep going

            if self._acc is not None and self._batch_reads:
                # fused pileup: the slab never fills (it is scratch), so
                # yield a counters-only batch per text block to keep the
                # backend's checkpoint cadence and stats ticking
                batch = self._flush()
                if batch is not None:
                    yield batch

        if not self._private:
            # shard workers defer the merge to the coordinator (after
            # every shard succeeded); everyone else folds at stream end
            self.merge_shadow()
        batch = self._flush()
        if batch is not None:
            yield batch

    def encode_blocks_from(self, stream) -> Iterator[SegmentBatch]:
        """``encode_blocks`` over a ReadStream, tracking each block's
        absolute input offset (``stream.block_offset`` →
        ``self.block_base``) so strict errors and quarantine entries
        carry real file offsets on the serial rung too."""
        def feed():
            for block in stream.blocks():
                self.block_base = getattr(stream, "block_offset", None)
                yield block

        return self.encode_blocks(feed())

    def merge_shadow(self) -> None:
        """Fold the C decoder's uint8 shadow counts + overflow bank into
        the int32 pileup, then reset both (idempotent; exact — cell + bank
        always equals the true count).  Runs automatically at stream end;
        the backend also calls it before snapshotting a checkpoint, whose
        contract is that ``accumulate_into`` reflects every committed
        batch.  Direct-mode runs (huge genomes) counted straight into
        the pileup — nothing to merge.

        The shadow fold is a single C pass (``s2c_merge_u8``: SIMD
        widen-add + clear, zero blocks skipped) and the +256 bank is
        folded only when the decoder actually banked a saturation wrap
        (``out[oBanked]``) — at typical coverage the bank is untouched
        and its two full-tensor passes were the dominant merge cost
        (measured ~100 ms of the ~200 ms merge at 4.6 Mbp)."""
        if self._acc is None:
            return
        if self._acc_direct:
            if not self._private:
                return          # counts went straight into the pileup
            # private direct partition: one widen-add into the shared
            # tensor (the coordinator serializes these across workers)
            np.add(self._acc_flat, self._acc_ovf, out=self._acc_flat)
            self._acc_ovf[:] = 0
            return
        # the .so is source-hash-keyed (native/_build_so), so the symbol
        # always matches this file's expectations — no fallback branch
        self._lib.s2c_merge_u8(self._acc_flat, self._acc_u8,
                               self._acc_len * 6)
        if self._banked:
            np.add(self._acc_flat, self._acc_ovf, out=self._acc_flat)
            self._acc_ovf[:] = 0
            self._banked = 0

    # ------------------------------------------------------------------
    def _new_slab(self) -> None:
        self._slab_w = self.width
        rows = max(1024, self.SLAB_CELLS // self._slab_w)
        self._starts = np.empty(rows, dtype=np.int32)
        self._codes = np.empty((rows, self._slab_w), dtype=np.uint8)
        self._fill = 0

    def _flush(self) -> Optional[SegmentBatch]:
        batch = self._build_batch(
            [(self._starts, self._codes, self._fill)] if self._fill else [],
            self._fallback_rows, self._batch_reads, self._batch_events)
        self._new_slab()
        self._fallback_rows = []
        self._batch_reads = 0
        self._batch_events = 0
        return batch

    def _count_lines(self, k: int) -> None:
        if self.on_lines is not None and k:
            self.on_lines(k)

    def _count_bytes(self, k: int) -> None:
        if self.on_bytes is not None and k > 0:
            self.on_bytes(k)

    def _fallback_line(self, data: np.ndarray, start: int,
                       line_end: Optional[int] = None,
                       abs_off: Optional[int] = None,
                       c_reason: int = 0) -> None:
        """Encode one raw line via the Python path into the pending batch.

        This is THE tolerance point of every native text rung: a line
        the C decoder flagged (or a wide/overflow read) replays through
        the golden encoder; with a sink attached, any strict-mode error
        the replay raises — parse OR encode level, the exact oracle
        types — is classified and absorbed per record.  Strict mode
        additionally stamps the line's absolute input offset onto the
        raised exception (``s2c_offset``), identically on the serial,
        sharded and streaming rungs.
        """
        if line_end is None:
            line_end = _line_end(data, start)
        raw = bytes(data[start:min(line_end + 1, len(data))])
        sink = self.bad_sink
        try:
            # include the trailing newline so even an empty line replays
            # as the truthy "\n" string the pure-python path would have
            # seen; the record iterator raises IndexError on malformed
            # lines in every mode, exactly like the pure-python path
            line = raw.decode("ascii")
            recs = list(iter_records(iter(()), line))
        except RECORD_ERRORS as exc:
            if sink is not None:
                self._quarantine(sink, raw, exc, abs_off, c_reason)
                return
            mark_offset(exc, abs_off)
            raise
        for rec in recs:
            try:
                rows = self._py.encode_record(rec)
            except (EncodeError, KeyError, IndexError) as exc:
                if sink is not None:
                    self._quarantine(sink, raw, exc, abs_off, c_reason)
                    continue
                if self.strict:
                    mark_offset(exc, abs_off)
                    raise
                self._py.n_skipped += 1
                continue
            self._py.n_reads += 1
            self._batch_reads += 1
            for start_flat, row in rows:
                if self._acc is not None:
                    # fused pileup: count the replayed row immediately
                    # (into the private bank in shard-worker mode, so
                    # the shared tensor stays untouched until merge —
                    # the bank is exact, so marking it dirty via
                    # ``_banked`` folds it like a saturation wrap)
                    cols = np.nonzero(row < 6)[0]
                    pos = start_flat + cols
                    ok = (pos >= 0) & (pos < self._acc_len)
                    np.add.at(self._fb_acc, (pos[ok], row[cols[ok]]), 1)
                    if self._private and not self._acc_direct and len(cols):
                        self._banked += 1
                    self._batch_events += len(cols)
                else:
                    self._fallback_rows.append((start_flat, row))
                    self._batch_events += (len(row)
                                           - int((row == PAD_CODE).sum()))

    def _quarantine(self, sink, raw: bytes, exc: BaseException,
                    abs_off: Optional[int], c_reason: int) -> None:
        """Absorb one flagged record into the sink (counts a skip like
        legacy permissive mode).  The C decoder's reason-code hint
        refines classification only when the python-side classifier
        cannot name the failure — python classification is the
        authority, so the pure-python rung can never disagree."""
        reason = classify_reason(exc)
        if reason == "malformed":
            reason = C_REASONS.get(int(c_reason), reason)
        sink.record(raw, exc, partition=self.bad_partition,
                    offset=abs_off, reason=reason)
        self._py.n_skipped += 1

    def _build_batch(self, native_parts, fallback_rows, n_reads, n_events
                     ) -> Optional[SegmentBatch]:
        """Merge native matrices + fallback rows into one padded batch.

        Common case (one native part per width, no fallback rows): the
        decode buffer is padded *in place* — only the pad tail is written,
        no bulk copy.
        """
        per_w: Dict[int, List] = {}
        for starts, codes, n in native_parts:
            per_w.setdefault(codes.shape[1], []).append((starts, codes, n))
        for start_flat, row in fallback_rows:
            w = _bucket_width(len(row))
            per_w.setdefault(w, []).append((start_flat, row))

        buckets: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for w, items in per_w.items():
            if len(items) == 1 and len(items[0]) == 3:
                starts, codes, n = items[0]
                s_pad = max(1024, 1 << (n - 1).bit_length())
                if s_pad <= len(starts):   # buffer big enough: pad in place
                    starts[n:s_pad] = 0
                    codes[n:s_pad] = PAD_CODE
                    buckets[w] = (starts[:s_pad], codes[:s_pad])
                    continue
            total = sum(it[2] if len(it) == 3 else 1 for it in items)
            s_pad = max(1024, 1 << (total - 1).bit_length())
            mat = np.full((s_pad, w), PAD_CODE, dtype=np.uint8)
            st = np.zeros(s_pad, dtype=np.int32)
            r = 0
            for it in items:
                if len(it) == 3:
                    starts, codes, n = it
                    st[r:r + n] = starts[:n]
                    mat[r:r + n] = codes[:n]
                    r += n
                else:
                    start_flat, row = it
                    st[r] = start_flat
                    mat[r, : len(row)] = row
                    r += 1
            buckets[w] = (st, mat)
        if not buckets and n_reads == 0:
            return None
        return SegmentBatch(buckets=buckets, n_reads=n_reads,
                            n_events=n_events,
                            accumulated=self._acc is not None)
