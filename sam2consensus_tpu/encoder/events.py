"""Read → tensor encoder: host-side CIGAR decode into scatter-ready segments.

This is the keystone of the TPU formulation (SURVEY.md §7 step 3): each read
becomes ONE contiguous reference-coordinate segment — a flat-genome start plus
a uint8 code row (read bases for M/=/X, GAP for D/N/P runs, PAD_CODE for gap
bases dropped by the maxdel gate) — because every reference-consuming CIGAR op
is contiguous in reference coordinates.  Position indices are *not* expanded
on the host: the device computes ``start + iota`` and scatter-adds, so the
host→device transfer is ~1 byte per aligned base instead of 8+
(positions int32 + codes int32 in a flat COO stream), which profiling showed
was the pipeline bottleneck (the TPU scatter itself is ~free).

Semantics are identical to the golden CIGAR walker (``core/cigar.py``,
spec ``/root/reference/sam2consensus.py:46-82,195-221``):

* M/=/X bases become read-base codes at their reference positions;
* D/N/P bases become GAP codes, subject to the per-read maxdel gate
  (total gap length > maxdel ⇒ gap codes become PAD, positions still
  advance) — the gate counts literal ``-`` characters in SEQ too, exactly
  like the reference's ``seqout.count("-")``;
* I records an insertion event keyed by (contig, index of next ref base);
* S skips read bases, H is a no-op;
* POS-1 may be negative: local indices in [-reflen, 0) wrap Python-style,
  splitting the read into (at most) two segment rows.

The genome is laid out as ONE flat position axis — contigs concatenated with
per-contig offsets — rather than a padded [contig, max_len] matrix.  The vote
is per-position, so nothing needs the contig structure on device; a flat
layout wastes zero padding FLOPs/HBM and makes position-axis sharding a plain
1-D sharding (SURVEY.md §5 long-context).

Rows are bucketed by power-of-two width and row counts padded to powers of
two, so the jitted device scatter compiles O(log²) distinct shapes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..constants import BASE_TO_CODE, GAP, INVALID_SYMBOL, PAD_CODE
from ..core.cigar import split_ops
from ..io.sam import Contig, SamRecord

#: smallest segment-row bucket width
MIN_BUCKET_W = 32

#: auto-resolved long-read segment width: reads whose reference span
#: exceeds this split into W-wide rows at exact W boundaries (pileup
#: addition commutes, so the split is semantically free) instead of
#: inflating the slab bucket width toward the span.  4096 keeps every
#: short-read workload untouched (typical spans are 10-100x smaller)
#: while a 100 kb ONT read becomes ~25 dense rows rather than one row
#: in a 131072-wide bucket that is ~97% padding (and wire bytes).
DEFAULT_SEGMENT_W = 4096


def resolve_segment_width(value: int) -> int:
    """``RunConfig.segment_width`` policy: 0 = auto (DEFAULT_SEGMENT_W),
    negative = segmentation off, positive = that width rounded up to a
    power of two (>= MIN_BUCKET_W) so bucket invariants hold."""
    if value == 0:
        return DEFAULT_SEGMENT_W
    if value < 0:
        return 0
    return max(MIN_BUCKET_W, 1 << (int(value) - 1).bit_length())


class GenomeLayout:
    """Flat concatenated coordinate system over the declared contigs.

    Duplicate @SQ names follow the reference's dict-overwrite (last LN wins,
    first position in iteration order).
    """

    def __init__(self, contigs: Sequence[Contig]):
        lengths: Dict[str, int] = {}
        for c in contigs:
            lengths[c.name] = c.length
        self.names: List[str] = list(lengths)
        self.lengths = np.array([lengths[n] for n in self.names], dtype=np.int64)
        self.offsets = np.zeros(len(self.names) + 1, dtype=np.int64)
        np.cumsum(self.lengths, out=self.offsets[1:])
        self.total_len = int(self.offsets[-1])
        self.index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}

    def contig_slice(self, name: str) -> slice:
        i = self.index[name]
        return slice(int(self.offsets[i]), int(self.offsets[i + 1]))


@dataclass
class StagedSlab:
    """One bucket's device-staged wire payload.

    Placed by the decode prefetch thread (``PileupAccumulator.stage``)
    so this batch's h2d transfer overlaps the previous batch's dispatch
    instead of serializing with it on the link.  ``codec`` names the
    wire format the operands travelled in (``sam2consensus_tpu/wire``):
    ``"packed5"`` operands are the legacy ``(starts_dev, packed_dev)``
    pair; ``"delta8"`` operands are the compressed lanes, reconstituted
    on device by ``wire.device.decode_to_packed`` using ``meta``
    ``(width, sentinel)``.  ``nbytes`` is what actually crossed the
    link; ``raw_nbytes`` the packed5-equivalent bill (the compression
    ratio's denominator/numerator in the ``wire/*`` metrics).
    """
    codec: str
    operands: Tuple
    nbytes: int
    raw_nbytes: int
    meta: Optional[Tuple] = None
    #: set once the slab's wire bytes have been billed — a retry/ladder
    #: replay re-consumes the SAME device operands without re-crossing
    #: the link, and must not re-bill them
    billed: bool = False


@dataclass
class SegmentBatch:
    """One host→device batch of per-read pileup segments.

    ``buckets`` maps row width W to ``(starts int32 [S], codes uint8 [S, W])``
    where row r contributes one pileup event per column c with
    ``codes[r, c] != PAD_CODE`` at flat position ``starts[r] + c``.  S is
    padded to a power of two with all-PAD rows (start 0), W is a power of two.
    """
    buckets: Dict[int, Tuple[np.ndarray, np.ndarray]]
    n_reads: int = 0
    n_events: int = 0          # countable (non-PAD) symbols in the batch
    #: True when the fused decode path already counted this batch's cells
    #: into the host count tensor (encoder/native_encoder.py): buckets are
    #: empty and consumers must not re-accumulate
    accumulated: bool = False
    #: optional device-staged operands ``{w: StagedSlab}`` placed by the
    #: decode prefetch thread (``PileupAccumulator.stage``); a staging
    #: failure clears this dict and the batch replays unstaged through
    #: the consumer's retry policy / ladder (resilience/)
    staged: Dict[int, StagedSlab] = field(default_factory=dict)


@dataclass
class InsertionEvents:
    """Raw insertion observations, grouped later by (contig, local position).

    Two storage forms coexist: per-read Python lists (the Python encoder
    appends one entry per I op) and bulk array chunks
    ``(contig int32, local int32, motif_len int32, motif_chars uint8)``
    appended by the native decoder.  ``to_arrays`` merges both; ordering
    between forms is irrelevant (grouping sorts by site key).
    """
    contig_ids: List[int] = field(default_factory=list)
    local_pos: List[int] = field(default_factory=list)
    motifs: List[str] = field(default_factory=list)
    array_chunks: List[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]
                       ] = field(default_factory=list)

    def extend(self, other: "InsertionEvents") -> None:
        self.contig_ids.extend(other.contig_ids)
        self.local_pos.extend(other.local_pos)
        self.motifs.extend(other.motifs)
        self.array_chunks.extend(other.array_chunks)

    def __len__(self) -> int:
        return len(self.motifs) + sum(len(c[0]) for c in self.array_chunks)

    def to_arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray]:
        """Merged ``(contig i64, local i64, motif_lens i64, motif_chars u8)``
        — motif_chars is raw ASCII, one motif after another."""
        contigs = [np.asarray(self.contig_ids, dtype=np.int64)]
        locals_ = [np.asarray(self.local_pos, dtype=np.int64)]
        mlens = [np.array([len(m) for m in self.motifs], dtype=np.int64)]
        chars = [np.frombuffer("".join(self.motifs).encode("ascii"),
                               dtype=np.uint8)]
        for c, l, ml, ch in self.array_chunks:
            contigs.append(c.astype(np.int64))
            locals_.append(l.astype(np.int64))
            mlens.append(ml.astype(np.int64))
            chars.append(ch)
        return (np.concatenate(contigs), np.concatenate(locals_),
                np.concatenate(mlens), np.concatenate(chars))


def render_record(rec) -> str:
    """Canonical raw-record rendering for quarantine sidecars when the
    original line/bytes are not in hand (parsed-record paths: the pure-
    python rung, the BAM slow lane): the four consensus-relevant fields
    as a minimal SAM-ish line.  Raw-line paths store the real line."""
    try:
        return (f"{rec.refname}\t{rec.pos + 1}\t{rec.cigar}\t{rec.seq}")
    except Exception:       # a record too broken to render still counts
        return repr(rec)


class EncodeError(ValueError):
    """Base for encoder-contract violations.

    Strict-mode validation failures raise the ORACLE's exact exception
    types and messages (KeyError / IndexError, backends/cpu.py) so the
    jax backend's tracebacks match the reference's; permissive-mode
    catch sites accept ``(EncodeError, KeyError, IndexError)``.
    """


def _bucket_width(span: int) -> int:
    return max(MIN_BUCKET_W, 1 << (span - 1).bit_length())


def pack_rows(rows: List[Tuple[int, np.ndarray]]) -> SegmentBatch:
    """Bucket (flat_start, code_row) pairs into padded SegmentBatch arrays."""
    by_w: Dict[int, Tuple[List[int], List[np.ndarray]]] = {}
    n_events = 0
    for start, row in rows:
        w = _bucket_width(len(row))
        starts, codes = by_w.setdefault(w, ([], []))
        starts.append(start)
        codes.append(row)
        n_events += len(row) - int((row == PAD_CODE).sum())
    buckets: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    for w, (starts, code_rows) in by_w.items():
        s = len(starts)
        s_pad = max(1024, 1 << (s - 1).bit_length())
        mat = np.full((s_pad, w), PAD_CODE, dtype=np.uint8)
        for r, row in enumerate(code_rows):
            mat[r, : len(row)] = row
        st = np.zeros(s_pad, dtype=np.int32)
        st[:s] = starts
        buckets[w] = (st, mat)
    return SegmentBatch(buckets=buckets, n_events=n_events)


class ReadEncoder:
    """Streaming encoder: SamRecords in, SegmentBatches + InsertionEvents out."""

    def __init__(self, layout: GenomeLayout, maxdel: Optional[int] = 150,
                 strict: bool = True, segment_width: int = 0,
                 bad_sink=None, bad_partition=(0,)):
        self.layout = layout
        self.maxdel = maxdel
        self.strict = strict
        #: >0 = split rows wider than this at exact W boundaries (the
        #: long-read segmented layout); 0 = off (legacy fixed buckets).
        #: Callers resolve config policy via :func:`resolve_segment_width`.
        self.segment_width = segment_width
        #: tolerant decode (``--on-bad-record skip|quarantine``): a
        #: :class:`~..ingest.badrecords.QuarantineSink` shared run-wide.
        #: When set, :meth:`encode_segments` absorbs per-record failures
        #: into it instead of raising (or silently counting, in legacy
        #: permissive mode).  ``bad_partition`` keys this encoder's
        #: records in the sink's deterministic merge order (mutable:
        #: the streaming rung re-keys it per block).
        self.bad_sink = bad_sink
        self.bad_partition = tuple(bad_partition)
        self.n_reads = 0
        self.n_skipped = 0
        self.insertions = InsertionEvents()

    def encode_segments(self, records: Iterable[SamRecord],
                        chunk_reads: int = 262144) -> Iterator[SegmentBatch]:
        """Yield segment batches of at most ``chunk_reads`` reads each."""
        rows: List[Tuple[int, np.ndarray]] = []
        in_chunk = 0
        for rec in records:
            try:
                # encode_record validates fully before committing anything,
                # so a raise here leaves the pending rows untouched.
                new_rows = self.encode_record(rec)
            except (EncodeError, KeyError, IndexError) as exc:
                if self.bad_sink is not None:
                    # tolerant decode: quarantine/count the record (the
                    # sink raises the budget error when it is spent)
                    self.bad_sink.record(render_record(rec), exc,
                                         partition=self.bad_partition)
                    self.n_skipped += 1
                    continue
                if self.strict:
                    raise
                self.n_skipped += 1
                continue
            rows.extend(new_rows)
            self.n_reads += 1
            in_chunk += 1
            if in_chunk >= chunk_reads:
                batch = pack_rows(rows)
                batch.n_reads = in_chunk
                rows, in_chunk = [], 0
                yield batch
        if rows or in_chunk:
            batch = pack_rows(rows)
            batch.n_reads = in_chunk
            yield batch

    # -- single read ------------------------------------------------------
    def encode_record(self, rec: SamRecord) -> List[Tuple[int, np.ndarray]]:
        """Encode one record into (flat_start, code_row) segment rows.

        Raises the oracle's exact KeyError/IndexError (before any side
        effect) on contract violations;
        on success also appends the read's insertion events.
        """
        layout = self.layout
        ci = layout.index.get(rec.refname)
        if ci is None:
            # oracle-identical type AND message (backends/cpu.py): the jax
            # backend's strict errors must match the reference's
            raise KeyError(
                f"read mapped to unknown reference {rec.refname!r} "
                "(reference would KeyError here too)")
        reflen = int(layout.lengths[ci])
        offset = int(layout.offsets[ci])

        seq_codes = BASE_TO_CODE[
            np.frombuffer(rec.seq.encode("ascii"), dtype=np.uint8)]

        # walk ops, collecting runs by OUTPUT offset (validation before
        # commit).  The reference builds ``seqout`` by string
        # CONCATENATION (sam2consensus.py:46-82): an M op shorter than
        # its claim (SEQ exhausted — out-of-contract input) shifts every
        # later op left, and the read's span is len(seqout), not the
        # CIGAR-claimed sum.  For in-contract reads the two are equal.
        # NOTE the reference's MIXED semantics: seqout is concatenated
        # (short M ops shift later BASE/GAP cells left), but its insertion
        # keys use the reference cursor, which advances by the CLAIMED op
        # lengths (core/cigar.py walk) — so a short-SEQ read can key an
        # insertion past its emitted span.  Both cursors are tracked; they
        # agree for in-contract reads.
        my_base: List[Tuple[int, np.ndarray]] = []    # (out_offset, codes)
        my_gaps: List[Tuple[int, int]] = []           # (out_offset, length)
        my_ins: List[Tuple[int, str]] = []
        rc = 0
        out = 0
        claim = rec.pos
        # pre-split ops ride with binary records (formats/bam.py), so the
        # BAM path never rebuilds or re-regexes CIGAR text
        ops = getattr(rec, "ops", None)
        if ops is None:
            ops = split_ops(rec.cigar)
        for length, op in ops:
            if op in "M=X":
                codes = seq_codes[rc:rc + length]
                my_base.append((out, codes))
                rc += length
                out += len(codes)
                claim += length
            elif op in "DNP":
                my_gaps.append((out, length))
                out += length
                claim += length
            elif op == "I":
                my_ins.append((claim, rec.seq[rc:rc + length]))
                rc += length
            elif op == "S":
                rc += length
            # H: no-op

        # validation (quirk 7 contract): bounds incl. negative-wrap, alphabet.
        # A zero-span read (all S/H/I ops) touches no position and is accepted
        # at any POS, like the reference's zero-iteration pileup loop.
        span = out
        if span > 0 and (rec.pos < -reflen or rec.pos + span > reflen):
            raise IndexError(
                f"read at pos {rec.pos} spans [{rec.pos}, {rec.pos + span})"
                f" outside reference {rec.refname!r} of length {reflen} "
                "(reference would IndexError here too)")
        def bad_alphabet():
            # constructed lazily: valid reads (the hot path) pay nothing
            raise KeyError(
                f"read at pos {rec.pos} contains an out-of-alphabet base "
                "(input contract is uppercase ACGTN; the reference would "
                "KeyError here too, though for insertion motifs only "
                "later, in its reformat pass)")

        for _start, codes in my_base:
            if codes.size and codes.max() == INVALID_SYMBOL:
                bad_alphabet()
        for _local, motif in my_ins:
            mcodes = BASE_TO_CODE[
                np.frombuffer(motif.encode("ascii"), dtype=np.uint8)]
            if mcodes.size and mcodes.max() == INVALID_SYMBOL:
                bad_alphabet()

        # commit: insertion side channel
        for local, motif in my_ins:
            self.insertions.contig_ids.append(ci)
            self.insertions.local_pos.append(local)
            self.insertions.motifs.append(motif)
        if span == 0:
            return []

        # build the span row: M runs + GAP runs partition [0, span) by
        # construction (concatenation leaves no holes)
        if len(my_base) == 1 and not my_gaps:
            row = my_base[0][1]
        else:
            row = np.empty(span, dtype=np.uint8)
            for start, codes in my_base:
                row[start: start + len(codes)] = codes
            for start, length in my_gaps:
                row[start: start + length] = GAP

        # maxdel gate (sam2consensus.py:210-218): the reference counts
        # seqout's "-" characters — D/N/P runs AND literal '-' in SEQ alike —
        # and when the gate trips, skips those bases but still advances.
        n_gap_syms = int((row == GAP).sum())
        if self.maxdel is not None and n_gap_syms > self.maxdel:
            row = np.where(row == GAP, np.uint8(PAD_CODE), row)

        # flat coordinates, wrapping negatives Python-style (quirk 7 contract)
        if rec.pos >= 0:
            return self._segmented(offset + rec.pos, row)
        neg = min(span, -rec.pos)          # bases in the wrapped tail
        out = self._segmented(offset + reflen + rec.pos, row[:neg])
        if span > neg:
            out.extend(self._segmented(offset, row[neg:]))
        return out

    def _segmented(self, start: int, row: np.ndarray
                   ) -> List[Tuple[int, np.ndarray]]:
        """Long-read segmented layout: rows wider than ``segment_width``
        split at exact W boundaries into independent scatter rows —
        pileup addition commutes, so the split is byte-exact while the
        slab bucket width stays bounded by W instead of the read span."""
        w = self.segment_width
        if w <= 0 or len(row) <= w:
            return [(start, row)] if len(row) else []
        return [(start + off, row[off:off + w])
                for off in range(0, len(row), w)]


def _expand_segments(starts: List[int], lengths: List[int]) -> np.ndarray:
    """Concatenate ``arange(start, start+len)`` for all segments, vectorized."""
    if not starts:
        return np.zeros(0, dtype=np.int64)
    starts_a = np.asarray(starts, dtype=np.int64)
    lens_a = np.asarray(lengths, dtype=np.int64)
    total = int(lens_a.sum())
    ends = np.cumsum(lens_a)
    # position within the concatenation minus segment base, plus start
    idx = np.arange(total, dtype=np.int64)
    seg_base = np.repeat(ends - lens_a, lens_a)
    return idx - seg_base + np.repeat(starts_a, lens_a)


def group_insertions(events: InsertionEvents, layout: GenomeLayout):
    """Group raw insertion events into the dense per-key column table inputs.

    Returns ``None`` when there are no events, else a dict with:

    * ``key_contig`` int32 [K], ``key_local`` int32 [K] — unique insertion
      sites, ordered by (contig, local position);
    * ``key_flat`` int64 [K] — flat genome position of the site, or -1 when
      ``local == reflen`` (end-of-contig site: exists in the table, never
      emitted, coverage treated as 0 — see cpu.py for the matching oracle
      behavior);
    * ``max_cols`` int — longest motif overall (table width);
    * ``n_cols`` int32 [K] — longest motif per site (valid column count);
    * ``ev_key`` int32 [E], ``ev_col`` int32 [E], ``ev_code`` int32 [E] —
      one row per (motif occurrence, column), ready for scatter-add.
    """
    if len(events) == 0:
        return None
    contig, local, motif_lens, motif_chars = events.to_arrays()
    all_codes = BASE_TO_CODE[motif_chars]

    # composite sort key: (contig, local); local may be negative (reads with
    # POS=0 insert before wrap), so bias it into [0, 2^41) before packing.
    bias = 1 << 40
    composite = (contig << 41) + (local + bias)
    uniq, inverse = np.unique(composite, return_inverse=True)
    key_contig = (uniq >> 41).astype(np.int32)
    key_local = ((uniq & ((1 << 41) - 1)) - bias).astype(np.int32)

    n_cols = np.zeros(len(uniq), dtype=np.int64)
    np.maximum.at(n_cols, inverse, motif_lens)
    max_cols = int(n_cols.max())

    # expand each motif occurrence into one event per column
    ev_key = np.repeat(inverse, motif_lens).astype(np.int32)
    ev_col = _expand_segments([0] * len(motif_lens),
                              list(motif_lens)).astype(np.int32)
    ev_code = all_codes.astype(np.int32)

    reflens = layout.lengths[key_contig]
    flat = layout.offsets[key_contig] + key_local
    key_flat = np.where(key_local < reflens, flat, -1).astype(np.int64)
    # negative local keys (possible via pos=0 reads): wrap like Python lists
    neg = key_local < 0
    if neg.any():
        key_flat = np.where(
            neg, layout.offsets[key_contig] + reflens + key_local, key_flat)

    return {
        "key_contig": key_contig,
        "key_local": key_local,
        "key_flat": key_flat,
        "max_cols": max_cols,
        "n_cols": n_cols.astype(np.int32),
        "ev_key": ev_key,
        "ev_col": ev_col,
        "ev_code": ev_code,
    }
