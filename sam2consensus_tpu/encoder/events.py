"""Read → tensor encoder: host-side CIGAR decode into scatter-ready events.

This is the keystone of the TPU formulation (SURVEY.md §7 step 3): once reads
become flat integer event arrays, the whole pileup is one scatter-add and the
vote is a per-position reduction — no raggedness survives to the device.

Semantics are identical to the golden CIGAR walker (``core/cigar.py``,
spec ``/root/reference/sam2consensus.py:46-82,195-221``):

* M/=/X bases become (position, base_code) events;
* D/N/P bases become (position, GAP) events, subject to the per-read maxdel
  gate (total gap length > maxdel ⇒ gap events dropped, positions still
  advance);
* I records an insertion event keyed by (contig, index of next ref base);
* S skips read bases, H is a no-op;
* POS-1 may be negative: local indices in [-reflen, 0) wrap Python-style.

The genome is laid out as ONE flat position axis — contigs concatenated with
per-contig offsets — rather than a padded [contig, max_len] matrix.  The vote
is per-position, so nothing needs the contig structure on device; a flat
layout wastes zero padding FLOPs/HBM and makes position-axis sharding a plain
1-D sharding (SURVEY.md §5 long-context).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..constants import BASE_TO_CODE, GAP, INVALID_SYMBOL
from ..core.cigar import split_ops
from ..io.sam import Contig, SamRecord


class GenomeLayout:
    """Flat concatenated coordinate system over the declared contigs.

    Duplicate @SQ names follow the reference's dict-overwrite (last LN wins,
    first position in iteration order).
    """

    def __init__(self, contigs: Sequence[Contig]):
        lengths: Dict[str, int] = {}
        for c in contigs:
            lengths[c.name] = c.length
        self.names: List[str] = list(lengths)
        self.lengths = np.array([lengths[n] for n in self.names], dtype=np.int64)
        self.offsets = np.zeros(len(self.names) + 1, dtype=np.int64)
        np.cumsum(self.lengths, out=self.offsets[1:])
        self.total_len = int(self.offsets[-1])
        self.index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}

    def contig_slice(self, name: str) -> slice:
        i = self.index[name]
        return slice(int(self.offsets[i]), int(self.offsets[i + 1]))


@dataclass
class PileupChunk:
    """One host→device batch of per-base pileup events."""
    positions: np.ndarray          # int32 [n] flat genome position
    codes: np.ndarray              # int32 [n] symbol code 0..5
    n_reads: int = 0


@dataclass
class InsertionEvents:
    """Raw insertion observations, grouped later by (contig, local position)."""
    contig_ids: List[int] = field(default_factory=list)
    local_pos: List[int] = field(default_factory=list)
    motifs: List[str] = field(default_factory=list)

    def extend(self, other: "InsertionEvents") -> None:
        self.contig_ids.extend(other.contig_ids)
        self.local_pos.extend(other.local_pos)
        self.motifs.extend(other.motifs)

    def __len__(self) -> int:
        return len(self.motifs)


class EncodeError(ValueError):
    pass


def _expand_segments(starts: List[int], lengths: List[int]) -> np.ndarray:
    """Concatenate ``arange(start, start+len)`` for all segments, vectorized."""
    if not starts:
        return np.zeros(0, dtype=np.int64)
    starts_a = np.asarray(starts, dtype=np.int64)
    lens_a = np.asarray(lengths, dtype=np.int64)
    total = int(lens_a.sum())
    ends = np.cumsum(lens_a)
    # position within the concatenation minus segment base, plus start
    idx = np.arange(total, dtype=np.int64)
    seg_base = np.repeat(ends - lens_a, lens_a)
    return idx - seg_base + np.repeat(starts_a, lens_a)


class ReadEncoder:
    """Streaming encoder: SamRecords in, PileupChunks + InsertionEvents out."""

    def __init__(self, layout: GenomeLayout, maxdel: Optional[int] = 150,
                 strict: bool = True):
        self.layout = layout
        self.maxdel = maxdel
        self.strict = strict
        self.n_reads = 0
        self.n_skipped = 0
        self.insertions = InsertionEvents()

    def encode_chunks(self, records: Iterable[SamRecord],
                      chunk_reads: int = 262144) -> Iterator[PileupChunk]:
        """Yield pileup chunks of at most ``chunk_reads`` reads each."""
        base_starts: List[int] = []      # flat-genome starts of M-run segments
        base_codes: List[np.ndarray] = []
        gap_starts: List[int] = []
        gap_lens: List[int] = []
        irr_pos: List[np.ndarray] = []   # pre-expanded irregular events
        irr_codes: List[np.ndarray] = []
        in_chunk = 0

        def flush() -> PileupChunk:
            nonlocal base_starts, base_codes, gap_starts, gap_lens
            nonlocal irr_pos, irr_codes, in_chunk
            lens = [len(c) for c in base_codes]
            pos_bases = _expand_segments(base_starts, lens)
            pos_gaps = _expand_segments(gap_starts, gap_lens)
            parts_codes = ([c.astype(np.int32) for c in base_codes]
                           + [np.full(len(pos_gaps), GAP, dtype=np.int32)]
                           + [c.astype(np.int32) for c in irr_codes])
            parts_pos = [pos_bases, pos_gaps] + [p for p in irr_pos]
            positions = np.concatenate(parts_pos).astype(np.int32) \
                if parts_pos else np.zeros(0, dtype=np.int32)
            codes = np.concatenate(parts_codes) \
                if parts_codes else np.zeros(0, dtype=np.int32)
            chunk = PileupChunk(positions=positions, codes=codes,
                                n_reads=in_chunk)
            base_starts, base_codes, gap_starts, gap_lens = [], [], [], []
            irr_pos, irr_codes = [], []
            in_chunk = 0
            return chunk

        for rec in records:
            try:
                # _encode_one validates fully before committing any segment,
                # so a raise here leaves the chunk lists untouched.
                self._encode_one(rec, base_starts, base_codes,
                                 gap_starts, gap_lens, irr_pos, irr_codes)
            except EncodeError:
                if self.strict:
                    raise
                self.n_skipped += 1
                continue
            self.n_reads += 1
            in_chunk += 1
            if in_chunk >= chunk_reads:
                yield flush()
        if in_chunk or base_codes or gap_lens or irr_codes:
            yield flush()

    # -- single read ------------------------------------------------------
    def _encode_one(self, rec: SamRecord,
                    base_starts: List[int], base_codes: List[np.ndarray],
                    gap_starts: List[int], gap_lens: List[int],
                    irr_pos: List[np.ndarray], irr_codes: List[np.ndarray]
                    ) -> None:
        layout = self.layout
        ci = layout.index.get(rec.refname)
        if ci is None:
            raise EncodeError(f"unknown reference {rec.refname!r}")
        reflen = int(layout.lengths[ci])
        offset = int(layout.offsets[ci])

        seq_codes = BASE_TO_CODE[
            np.frombuffer(rec.seq.encode("ascii"), dtype=np.uint8)]

        # walk ops, collecting local segments first (validation before commit)
        my_base: List[Tuple[int, np.ndarray]] = []
        my_gaps: List[Tuple[int, int]] = []
        my_ins: List[Tuple[int, str]] = []
        rc = 0
        ref_cursor = rec.pos
        gap_total = 0
        for length, op in split_ops(rec.cigar):
            if op in "M=X":
                my_base.append((ref_cursor, seq_codes[rc:rc + length]))
                rc += length
                ref_cursor += length
            elif op in "DNP":
                my_gaps.append((ref_cursor, length))
                gap_total += length
                ref_cursor += length
            elif op == "I":
                my_ins.append((ref_cursor, rec.seq[rc:rc + length]))
                rc += length
            elif op == "S":
                rc += length
            # H: no-op

        # validation (quirk 7 contract): bounds incl. negative-wrap, alphabet.
        # A zero-span read (all S/H/I ops) touches no position and is accepted
        # at any POS, like the reference's zero-iteration pileup loop.
        span = ref_cursor - rec.pos
        if span > 0 and (rec.pos < -reflen or ref_cursor > reflen):
            raise EncodeError(
                f"read at pos {rec.pos} spans [{rec.pos}, {ref_cursor}) "
                f"outside reference {rec.refname!r} of length {reflen}")
        for _start, codes in my_base:
            if codes.size and codes.max() == INVALID_SYMBOL:
                raise EncodeError(
                    "read contains out-of-alphabet base "
                    "(input contract is uppercase ACGTN)")
        for _local, motif in my_ins:
            mcodes = BASE_TO_CODE[
                np.frombuffer(motif.encode("ascii"), dtype=np.uint8)]
            if mcodes.size and mcodes.max() == INVALID_SYMBOL:
                raise EncodeError(
                    "insertion motif contains out-of-alphabet base "
                    "(the reference KeyErrors on these in its reformat pass)")

        # commit: translate to flat coordinates (wrapping negatives)
        def flat(local_start: int, length: int) -> List[Tuple[int, int]]:
            """Split a local run into flat-genome runs, wrapping negatives."""
            if local_start >= 0:
                return [(offset + local_start, length)]
            neg = min(length, -local_start)   # bases in the wrapped tail
            runs = [(offset + reflen + local_start, neg)]
            if length > neg:
                runs.append((offset, length - neg))
            return runs

        # The reference gates on seqout.count("-"), which counts D/N/P gap
        # runs AND literal '-' characters appearing in SEQ itself ('-' is in
        # the count alphabet); both kinds are skipped when the gate trips.
        dash_in_m = sum(int((codes == GAP).sum()) for _s, codes in my_base)
        count_gaps = (self.maxdel is None
                      or (gap_total + dash_in_m) <= self.maxdel)
        for start, codes in my_base:
            if not count_gaps and (codes == GAP).any():
                local = start + np.arange(len(codes), dtype=np.int64)
                keep = codes != GAP
                local, kept = local[keep], codes[keep]
                flatpos = np.where(local < 0, offset + reflen + local,
                                   offset + local)
                irr_pos.append(flatpos)
                irr_codes.append(kept)
                continue
            pieces = flat(start, len(codes))
            consumed = 0
            for fstart, flen in pieces:
                base_starts.append(fstart)
                base_codes.append(codes[consumed:consumed + flen])
                consumed += flen
        if count_gaps:
            for start, length in my_gaps:
                for fstart, flen in flat(start, length):
                    gap_starts.append(fstart)
                    gap_lens.append(flen)
        for local, motif in my_ins:
            self.insertions.contig_ids.append(ci)
            self.insertions.local_pos.append(local)
            self.insertions.motifs.append(motif)


def group_insertions(events: InsertionEvents, layout: GenomeLayout):
    """Group raw insertion events into the dense per-key column table inputs.

    Returns ``None`` when there are no events, else a dict with:

    * ``key_contig`` int32 [K], ``key_local`` int32 [K] — unique insertion
      sites, ordered by (contig, local position);
    * ``key_flat`` int64 [K] — flat genome position of the site, or -1 when
      ``local == reflen`` (end-of-contig site: exists in the table, never
      emitted, coverage treated as 0 — see cpu.py for the matching oracle
      behavior);
    * ``max_cols`` int — longest motif overall (table width);
    * ``n_cols`` int32 [K] — longest motif per site (valid column count);
    * ``ev_key`` int32 [E], ``ev_col`` int32 [E], ``ev_code`` int32 [E] —
      one row per (motif occurrence, column), ready for scatter-add.
    """
    if len(events) == 0:
        return None
    contig = np.asarray(events.contig_ids, dtype=np.int64)
    local = np.asarray(events.local_pos, dtype=np.int64)
    motif_lens = np.array([len(m) for m in events.motifs], dtype=np.int64)
    all_codes = BASE_TO_CODE[np.frombuffer(
        "".join(events.motifs).encode("ascii"), dtype=np.uint8)]

    # composite sort key: (contig, local); local may be negative (reads with
    # POS=0 insert before wrap), so bias it into [0, 2^41) before packing.
    bias = 1 << 40
    composite = (contig << 41) + (local + bias)
    uniq, inverse = np.unique(composite, return_inverse=True)
    key_contig = (uniq >> 41).astype(np.int32)
    key_local = ((uniq & ((1 << 41) - 1)) - bias).astype(np.int32)

    n_cols = np.zeros(len(uniq), dtype=np.int64)
    np.maximum.at(n_cols, inverse, motif_lens)
    max_cols = int(n_cols.max())

    # expand each motif occurrence into one event per column
    ev_key = np.repeat(inverse, motif_lens).astype(np.int32)
    ev_col = _expand_segments([0] * len(motif_lens),
                              list(motif_lens)).astype(np.int32)
    ev_code = all_codes.astype(np.int32)

    reflens = layout.lengths[key_contig]
    flat = layout.offsets[key_contig] + key_local
    key_flat = np.where(key_local < reflens, flat, -1).astype(np.int64)
    # negative local keys (possible via pos=0 reads): wrap like Python lists
    neg = key_local < 0
    if neg.any():
        key_flat = np.where(
            neg, layout.offsets[key_contig] + reflens + key_local, key_flat)

    return {
        "key_contig": key_contig,
        "key_local": key_local,
        "key_flat": key_flat,
        "max_cols": max_cols,
        "n_cols": n_cols.astype(np.int32),
        "ev_key": ev_key,
        "ev_col": ev_col,
        "ev_code": ev_code,
    }
