"""sam2consensus-tpu: a TPU-native consensus-calling framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
``zoujiayun/sam2consensus`` (reference at ``/root/reference``, analyzed in
SURVEY.md): SAM pileup → Geneious-style threshold consensus with IUPAC
ambiguity codes, one FASTA per reference.

Two backends sit behind the ``ConsensusBackend`` boundary:

* ``cpu`` — the golden oracle, a spec-faithful Python 3 implementation of the
  reference algorithm (quirks included);
* ``jax`` — the TPU path: vectorized read→event encoding, scatter-add pileup
  into a flat ``[total_positions, 6]`` count tensor, a closed-form threshold
  vote vmapped over thresholds, shard_map data parallelism with ``psum`` over
  ICI, and a Pallas segmented-reduce kernel for the insertion table.

Both produce byte-identical FASTA output — that is the framework's
correctness gate.
"""

__version__ = "0.1.0"

from .config import RunConfig  # noqa: F401
from .backends.base import BackendResult, ConsensusBackend, FastaRecord  # noqa: F401
