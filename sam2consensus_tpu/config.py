"""Run configuration shared by every backend.

Mirrors the reference's flag surface (``/root/reference/sam2consensus.py:87-104``)
with the post-processing it applies at ``:108-138``, plus the new-framework
extensions (``--backend`` etc.) called out in SURVEY.md §5.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class RunConfig:
    """Everything a backend needs to turn records into FASTA records.

    ``maxdel`` follows the *fixed* semantics (``type=int``); ``maxdel=None``
    means the deletion gate is disabled (gaps always counted), which is what
    the reference's quirk 1 silently does for any user-supplied ``-d`` value
    under Python 2 (``sam2consensus.py:102-103,210``; str/int comparison).
    ``--py2-compat`` maps a user-supplied ``-d`` to ``None`` to reproduce it.
    """

    thresholds: List[float] = field(default_factory=lambda: [0.25])
    min_depth: int = 1
    fill: str = "-"
    maxdel: Optional[int] = 150
    prefix: str = ""
    nchar: int = 0
    outfolder: str = "./"
    backend: str = "cpu"
    # --- non-reference extensions ---
    strict: bool = True          # strict: error on invalid bases / out-of-range
    py2_compat: bool = False
    input_format: str = "auto"   # auto | sam | sam.gz | bam (formats/;
    #                              auto sniffs magic bytes, not suffixes)
    segment_width: int = 0       # long-read segmented slab layout: 0 = auto
    #                              (encoder/events.DEFAULT_SEGMENT_W), <0 =
    #                              off, >0 = explicit width (pow2-rounded)
    decoder: str = "auto"        # auto | native | py (jax backend host decode)
    pileup: str = "auto"         # auto | mxu | scatter | host (pileup strategy)
    wire: str = "auto"           # auto | packed5 | delta8 (h2d row wire codec,
    #                              sam2consensus_tpu/wire; auto prices the
    #                              measured link rate)
    decode_threads: int = 1      # ingest decode workers; 0 = auto (all
    #                              cores, S2C_DECODE_THREADS_CAP pins)
    ins_kernel: str = "auto"  # auto | scatter | pallas (insertion table)
    shard_mode: str = "auto"     # auto | dp | sp | dpsp (accumulator layout)
    incremental: bool = False    # keep/extend checkpoints across input files
    source_id: str = ""          # identity of the input (for incremental)
    # --- resilience (sam2consensus_tpu/resilience/) ---
    retries: int = 3             # transient-failure re-attempts per dispatch
    retry_backoff: float = 0.25  # base backoff seconds (exp + jitter)
    on_device_error: str = "retry"   # fail | retry | fallback (ladder)
    fault_inject: str = ""       # fault spec (tests/chaos; also env
    #                              S2C_FAULT_INJECT), see resilience/faultinject
    chunk_reads: int = 262144    # reads per host->device batch (jax backend)
    profile_dir: Optional[str] = None
    json_metrics: Optional[str] = None
    trace_out: Optional[str] = None      # Chrome/Perfetto trace JSON path
    metrics_out: Optional[str] = None    # metrics-registry JSONL path
    log_level: Optional[str] = None      # package logger level (CLI)
    log_format: str = "text"     # text | json (structured records with
    #                              job/tenant/rung/span correlation IDs
    #                              — observability/telemetry.py)
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 2_000_000  # reads between checkpoint writes
    paranoid: bool = False       # re-validate device inputs/outputs per batch
    shards: int = 0              # 0 = use all local devices for DP
    # --- tolerant decode (sam2consensus_tpu/ingest/badrecords.py) ---
    on_bad_record: str = "fail"  # fail | skip | quarantine (per-record
    #                              malformation policy; fail = strict
    #                              reference semantics, byte-identical)
    max_bad_records: str = ""    # error budget: "" (none), N, or x%
    quarantine_out: Optional[str] = None  # sidecar path (quarantine mode;
    #                              default <outfolder>/<prefix>_quarantine.jsonl)

    @staticmethod
    def threshold_labels(thresholds: List[float]) -> List[str]:
        """Percent labels, matching ``int(t*100)`` (sam2consensus.py:394)."""
        return [str(int(t * 100)) for t in thresholds]


def resolve_decode_threads(cfg) -> int:
    """``--decode-threads`` with 0 = auto; ONE policy shared by the
    shard scheduler (encoder/parallel_decode.py), the native vote tail
    and the BGZF inflate pool (formats/bgzf.py + ingest.shared_pool) —
    "shared with the native decoder" by construction.

    Auto means ALL cores.  The old hard cap of 4 was an unmeasured
    guess from a one-core bench host; the committed scaling artifact
    (``perf/thread_scaling_r08.jsonl``) shows the shard-owned decode
    tracking core count on the hosts we can measure (1.9x at 2 threads
    on the 2-core rig, where the retired feed-thread design managed
    1.1x), with no knee below the host's core count — so the policy cap
    is the core count itself.  The real guards are elsewhere: the
    sharded decoder's ``EXTRA_COUNTS_BUDGET`` clamps workers on huge
    genomes (memory, the one measured failure mode), and
    ``S2C_DECODE_THREADS_CAP`` lets shared hosts pin a smaller budget
    without touching per-run flags."""
    threads = getattr(cfg, "decode_threads", 1)
    if threads == 0:
        threads = os.cpu_count() or 1
        try:
            cap = int(os.environ.get("S2C_DECODE_THREADS_CAP", "0"))
        except ValueError:
            cap = 0
        if cap > 0:
            threads = min(threads, cap)
    return max(1, threads)


def default_prefix(filename: str) -> str:
    """Input basename up to the first dot (sam2consensus.py:121-124)."""
    return "".join(filename.split("/")[-1]).split(".")[0]


def normalize_outfolder(outfolder: str) -> str:
    """rstrip slash + ensure exists + trailing slash (sam2consensus.py:127-130)."""
    out = outfolder.rstrip("/")
    if out == "":
        out = "/"  # pathological "-o /" case; reference would makedirs("")->error
    if not os.path.exists(out):
        os.makedirs(out)
    return out + "/"
