"""Host-side ``delta8`` slab codec: what actually crosses the link.

A segment-row slab is ``(starts int32 [S], codes uint8 [S, W])``.  The
legacy wire (codec ``"packed5"`` — the packed-lane format every round
shipped so far) moves ``4 + W/2`` bytes per row: int32 starts plus the
4-bit nibble code lanes (``ops.pileup.pack_nibbles``).  ``delta8``
exploits three measured regularities of real slabs:

* **starts are near-sorted** — the encoder emits reads in input order
  and real inputs are coordinate-sorted or close, so consecutive start
  deltas are small.  Deltas ride one uint8 each; value 255 marks an
  escape whose exact int32 delta (negative for unsorted tails, large
  for sparse jumps, and always the first row of a chunk, whose delta is
  from 0) rides the escape lane;
* **rows are mostly ACGT** — codes A/C/G/T (1/2/3/5 in the count-lane
  alphabet) remap to 2 bits; gap, N and interior-pad cells are listed
  sparsely as (flat cell index, code) escape pairs;
* **bucket pad tails are long** — a span-``s`` row sits in a
  power-of-two bucket of width up to ``2s``, so up to half of every
  code row is trailing PAD.  One per-row trailing-pad count (uint8 when
  it fits — real rows trail < W/2 by the bucket invariant — widening
  per-slab when it doesn't) elides the tail instead of shipping it.

``chunks`` splits the slab into equal contiguous chunks whose delta
chains restart from zero: the sharded accumulators ship ``n`` device
chunks per slab (parallel/{dp,sp,dpsp}), and a per-chunk chain makes the
device-side prefix sum local to each device — no cross-device decode
dependency.

Encoding is refused (``None`` / :func:`worthwhile` False) rather than
forced when a slab would not shrink — escape-dense adversarial slabs
fall back to the packed5 lanes, recorded per slab, and the
self-describing header keeps a mixed stream decodable.

Byte identity: :func:`decode_slab_host` is the exact inverse (pinned by
tests/test_wire.py round-trip properties), and the device decode
(:mod:`.device`) reproduces the same operands bit-for-bit, so counts —
and therefore FASTA output — cannot differ from the uncompressed path.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from ..constants import NUM_SYMBOLS, PAD_CODE

#: wire codecs, by self-describing header id
CODECS = ("packed5", "delta8")

#: escape marker in the uint8 delta lane
DELTA_ESCAPE = 255

#: 2-bit wire value -> count-lane code (A=1, C=2, G=3, T=5)
WIRE2_TO_CODE = np.array([1, 2, 3, 5], dtype=np.uint8)

#: count-lane code -> 2-bit wire value (non-ACGT cells escape; their
#: primary-lane bits are zero and ignored on decode)
CODE_TO_WIRE2 = np.zeros(256, dtype=np.uint8)
CODE_TO_WIRE2[[1, 2, 3, 5]] = np.arange(4, dtype=np.uint8)

#: True for codes the 2-bit primary lane can carry
IS_ACGT = np.zeros(256, dtype=bool)
IS_ACGT[[1, 2, 3, 5]] = True

#: trailing-pad lane dtypes, narrowest first; the max value of each is
#: the "whole row is PAD" sentinel (real rows always trail strictly
#: less, enforced by the encoder's dtype widening)
_TRAIL_DTYPES = (np.uint8, np.uint16, np.int32)


def _pow2(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


@dataclass
class WireSlab:
    """One encoded slab + its self-describing header.

    Array shapes (``C`` chunks of ``R`` rows, ``S = C * R``):

    ========= ===================== =====================================
    field     shape / dtype         meaning
    ========= ===================== =====================================
    d8        ``[C, R] uint8``      start deltas; 255 = escape
    esc_delta ``[C, Ep] int32``     exact deltas of escaped rows, in row
                                    order per chunk (pad entries 0)
    trail     ``[C, R] uintX``      trailing-PAD cells per row; the
                                    dtype max is the all-PAD-row sentinel
    base2     ``[C, R, ⌈W/4⌉] u8``  2-bit ACGT planes, 4 cells per byte
    esc_idx   ``[C, Ec] int32``     chunk-local flat cell index
                                    (``r*W + c``) of non-ACGT cells; pad
                                    entries ``R*W`` (dropped on decode)
    esc_code  ``[C, Ec] uint8``     the escaped cells' exact codes
    ========= ===================== =====================================
    """

    codec: str
    n_rows: int
    width: int
    chunks: int
    sentinel: int                  # trail lane's all-PAD sentinel
    d8: np.ndarray
    esc_delta: np.ndarray
    trail: np.ndarray
    base2: np.ndarray
    esc_idx: np.ndarray
    esc_code: np.ndarray
    n_esc_rows: int
    n_esc_cells: int

    def header(self) -> np.ndarray:
        """Self-describing slab header: shipped ahead of the lanes so a
        consumer (or a future on-disk spool) can size and route the
        decode without out-of-band state, and so ``--wire auto`` bills
        exact per-slab bytes."""
        return np.array(
            [CODECS.index(self.codec), self.n_rows, self.width,
             self.chunks, self.esc_delta.shape[1], self.esc_idx.shape[1],
             self.sentinel, self.n_esc_rows, self.n_esc_cells],
            dtype=np.int32)

    def arrays(self) -> Tuple[np.ndarray, ...]:
        """The device-bound lanes, in decode-argument order."""
        return (self.d8, self.esc_delta, self.trail, self.base2,
                self.esc_idx, self.esc_code)

    @property
    def wire_bytes(self) -> int:
        """Exact bytes this slab puts on the link (lanes + header)."""
        return (sum(a.nbytes for a in self.arrays())
                + self.header().nbytes)


def packed5_slab_bytes(n_rows: int, width: int) -> int:
    """Wire bytes of the legacy packed-lane format for the same slab."""
    return n_rows * (4 + (width + 1) // 2)


def row_bytes_estimate(width: int, codec: str) -> float:
    """Modeled wire bytes per row, for link pricing that runs BEFORE a
    slab is encoded (``parallel.auto.slab_stats`` post-codec row bytes,
    the shard-mode model's grid-inflation term).  ``delta8`` prices the
    clean-slab shape — 1 delta + 1 trail + 2-bit lanes — because
    escape-dense slabs fall back to packed5 and are billed as such."""
    if codec == "delta8":
        return 2 + -(-width // 4)
    return 4 + (width + 1) // 2


def encode_slab(starts: np.ndarray, codes: np.ndarray,
                chunks: int = 1) -> Optional["WireSlab"]:
    """Encode one slab; ``None`` when the shape cannot chunk evenly.

    Exactness contract: ``decode_slab_host(encode_slab(s, c)) == (s, c)``
    for every uint8 code matrix and non-negative int32 starts — unsorted
    tails, >254 deltas, all-PAD rows, interior PAD/gap/N cells and
    single-row slabs all round-trip through the escape lanes.
    """
    S, W = codes.shape
    if S == 0 or chunks < 1 or S % chunks:
        return None
    R = S // chunks

    # -- start deltas ----------------------------------------------------
    s64 = np.ascontiguousarray(starts, dtype=np.int64).reshape(chunks, R)
    prev = np.roll(s64, 1, axis=1)
    prev[:, 0] = 0                       # chain restarts at each chunk
    delta = s64 - prev
    esc_row = (delta < 0) | (delta >= DELTA_ESCAPE)
    n_esc_rows = int(esc_row.sum())
    ep = _pow2(max(1, int(esc_row.sum(axis=1).max(initial=1))))
    # escape-lane fallback width: uint16 rows when every escaped delta
    # fits (the sparse-but-sorted common case — deltas of a few thousand
    # on a shallow slab), int32 only for negative/huge jumps.  This is
    # what keeps sparse sorted slabs at ~3 B/row instead of 5.
    esc_vals = delta[esc_row]
    esc_dt = np.uint16 if (len(esc_vals) == 0
                           or (esc_vals.min(initial=0) >= 0
                               and esc_vals.max(initial=0) < (1 << 16))
                           ) else np.int32
    esc_delta = np.zeros((chunks, ep), dtype=esc_dt)
    ci, ri = np.nonzero(esc_row)
    if len(ci):
        k = (np.cumsum(esc_row, axis=1) - 1)[ci, ri]
        esc_delta[ci, k] = delta[ci, ri].astype(esc_dt)
    d8 = np.where(esc_row, DELTA_ESCAPE, delta).astype(np.uint8)

    # -- trailing-pad lane ----------------------------------------------
    nonpad = codes != PAD_CODE
    anyrow = nonpad.any(axis=1)
    nlen = np.where(anyrow, W - nonpad[:, ::-1].argmax(axis=1), 0)
    trail_real = W - nlen
    max_trail = int(trail_real[anyrow].max(initial=0))
    for dt in _TRAIL_DTYPES:
        sentinel = int(np.iinfo(dt).max)
        if max_trail < sentinel:
            break
    trail = np.where(anyrow, trail_real, sentinel).astype(dt) \
        .reshape(chunks, R)

    # -- 2-bit ACGT planes ----------------------------------------------
    # the lane is only as wide as the slab's LONGEST row payload: a
    # span-s row sits in a power-of-two bucket up to width 2s, so the
    # shared trailing-PAD region past max(nlen) — up to half the bucket
    # — ships zero bytes (the per-row trail lane restores it exactly).
    # The width quantizes to a sixteenth-pow2 grid (finer sibling of
    # ops.pileup.round_rows_grid): decode shapes are jit trace keys, so
    # a raw per-slab max would compile per slab; the grid caps the
    # cache at O(log) entries for <=6.25% lane waste.
    wire2 = CODE_TO_WIRE2[codes]
    lane_bytes = max(1, -(-int(nlen.max(initial=0)) // 4))
    shift = max(0, (lane_bytes - 1).bit_length() - 4)
    lane_bytes = -(-lane_bytes >> shift) << shift
    wq = min(-(-W // 4), lane_bytes) * 4
    if wq < W:
        wire2 = wire2[:, :wq]
    elif wq != W:
        wire2 = np.concatenate(
            [wire2, np.zeros((S, wq - W), dtype=np.uint8)], axis=1)
    q = wire2.reshape(S, wq // 4, 4)
    base2 = (q[:, :, 0] | (q[:, :, 1] << 2) | (q[:, :, 2] << 4)
             | (q[:, :, 3] << 6)).astype(np.uint8).reshape(chunks, R,
                                                           wq // 4)

    # -- cell escapes (non-ACGT within the row payload) ------------------
    cols = np.arange(W)
    escm = (cols[None, :] < nlen[:, None]) & ~IS_ACGT[codes]
    n_esc_cells = int(escm.sum())
    rg, cg = np.nonzero(escm)
    ci2 = rg // R
    per_chunk = np.bincount(ci2, minlength=chunks)
    ec = _pow2(max(1, int(per_chunk.max(initial=1))))
    # cell-index lane narrows too: chunk-local flat indices (and the
    # R*W drop sentinel) fit uint16 for every bucket up to 64k cells
    idx_dt = np.uint16 if R * W <= np.iinfo(np.uint16).max else np.int32
    esc_idx = np.full((chunks, ec), R * W, dtype=idx_dt)
    esc_code = np.zeros((chunks, ec), dtype=np.uint8)
    if len(rg):
        offs = np.concatenate([[0], np.cumsum(per_chunk)])[ci2]
        kk = np.arange(len(rg)) - offs
        esc_idx[ci2, kk] = ((rg % R) * W + cg).astype(idx_dt)
        esc_code[ci2, kk] = codes[rg, cg]

    return WireSlab(codec="delta8", n_rows=S, width=W, chunks=chunks,
                    sentinel=sentinel, d8=d8, esc_delta=esc_delta,
                    trail=trail, base2=base2, esc_idx=esc_idx,
                    esc_code=esc_code, n_esc_rows=n_esc_rows,
                    n_esc_cells=n_esc_cells)


def canonicalize_rows(starts: np.ndarray,
                      codes: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Stable-sort a slab's real rows by start position, in encode order.

    Pileup accumulation is order-invariant (addition commutes; every
    consumer scatter-adds), so sorting is free correctness-wise — and
    it is what makes delta8 effective on UNSORTED inputs: random read
    order turns every delta into an escape, while sorted rows over an
    ``L``-position genome delta at ``~L/S`` per row (uint8 territory
    for any slab with ≥ L/254 rows).  The encoder's all-PAD pow2 pad
    tail stays in place (kernel planners detect it as a suffix), and
    the sort is deterministic (stable), so the staging thread and the
    consumer derive the SAME canonical slab from the same arrays —
    device kernel plans built host-side always match the decoded
    operands.  Already-sorted slabs return the inputs untouched.
    """
    s = np.asarray(starts)
    c = np.asarray(codes)
    # trailing all-PAD pad block (encoder pow2 padding) stays a suffix
    nonpad = (c != PAD_CODE).any(axis=1)
    nz = np.nonzero(nonpad)[0]
    n_real = int(nz[-1]) + 1 if len(nz) else 0
    pre = s[:n_real]
    if len(pre) > 1 and np.any(pre[1:] < pre[:-1]):
        order = np.argsort(pre, kind="stable")
        s = s.copy()
        c = c.copy()
        s[:n_real] = pre[order]
        c[:n_real] = c[:n_real][order]
    return s, c


def worthwhile(slab: "WireSlab") -> bool:
    """True when the encoded slab actually beats the packed5 lanes —
    escape-dense slabs (adversarial inputs, deep unsorted tails) ship
    legacy instead, per slab, recorded by the caller."""
    return slab.wire_bytes < packed5_slab_bytes(slab.n_rows, slab.width)


def decode_slab_host(slab: "WireSlab") -> Tuple[np.ndarray, np.ndarray]:
    """Exact numpy inverse of :func:`encode_slab` — the codec's oracle
    (the device decode in :mod:`.device` is pinned against it)."""
    C, R = slab.d8.shape
    W = slab.width
    esc = slab.d8 == DELTA_ESCAPE
    rank = np.cumsum(esc, axis=1) - 1
    ci = np.arange(C)[:, None]
    delta = np.where(
        esc, slab.esc_delta[ci, np.clip(rank, 0, slab.esc_delta.shape[1]
                                        - 1)],
        slab.d8.astype(np.int64))
    starts = np.cumsum(delta, axis=1).reshape(-1).astype(np.int32)

    shifts = np.array([0, 2, 4, 6], dtype=np.uint8)
    two = (slab.base2.reshape(C * R, -1)[:, :, None] >> shifts) & 3
    lane = WIRE2_TO_CODE[two.reshape(C * R, -1)[:, :W]]
    codes = np.full((C * R, W), PAD_CODE, dtype=np.uint8)
    codes[:, :lane.shape[1]] = lane
    nlen = np.where(slab.trail == slab.sentinel, 0,
                    W - slab.trail.astype(np.int64)).reshape(-1)
    codes[np.arange(W)[None, :] >= nlen[:, None]] = PAD_CODE
    flat = codes.reshape(C, R * W)
    idx = slab.esc_idx.astype(np.int64)
    ok = idx < R * W
    cc, kk = np.nonzero(ok)
    flat[cc, idx[cc, kk]] = slab.esc_code[cc, kk]
    return starts, flat.reshape(C * R, W)


# -- run-level codec choice ---------------------------------------------

#: modeled wire bytes SAVED per pileup cell by delta8 at representative
#: slab shapes (W=128, ~100 bp reads: 68 B -> ~34 B per row); the auto
#: gate compares the link seconds this saves against the host encode +
#: device decode it costs
SAVED_BYTES_PER_CELL = float(os.environ.get("S2C_WIRE_SAVED_BPC", "0.25"))


#: packed5 wire bytes per cell at the representative slab shape the
#: auto gate prices (W=128, ~100 bp reads: 68 B/row)
_PACKED5_BPC = 68.0 / 128.0


def modeled_wire_ratio(codec: str) -> float:
    """The compression ratio (packed5-equivalent bytes / shipped bytes)
    the auto gate's pricing ASSUMES for ``codec`` — the decision
    ledger's prediction, joined at run end against the measured
    ``wire/raw_bytes / wire/bytes`` (observability/ledger.py).  packed5
    is the reference encoding, ratio 1; delta8's modeled saving is
    ``SAVED_BYTES_PER_CELL`` off the packed5 bill."""
    if codec != "delta8":
        return 1.0
    return _PACKED5_BPC / max(_PACKED5_BPC - SAVED_BYTES_PER_CELL, 1e-9)


def wire_auto_cutoff_bps() -> float:
    """Link rate below which ``--wire auto`` picks delta8.

    The codec pays ~S2C_WIRE_DEV_NS of device unpack (prefix sum +
    2-bit expand, VPU-bound) and ~S2C_WIRE_HOST_NS of host encode per
    cell (vectorized numpy; overlapped by the staging pipeline, priced
    at full cost to stay conservative), and saves
    ``SAVED_BYTES_PER_CELL`` of link.  With the defaults the crossover
    sits at ~71 MB/s: the 40 MB/s tunnel compresses, a PCIe-class link
    (~GB/s) ships packed5 — the decode passes would cost more than the
    saved wire, the same shape as the packed5 OUTPUT encoding gate
    (backends.jax_backend._fetch_costs).
    """
    dev_ns = float(os.environ.get("S2C_WIRE_DEV_NS", "1.5"))
    host_ns = float(os.environ.get("S2C_WIRE_HOST_NS", "2.0"))
    return SAVED_BYTES_PER_CELL / ((dev_ns + host_ns) * 1e-9)


def resolve_codec(mode: str, link_bps: Optional[float],
                  link_free: bool = False) -> Tuple[str, str]:
    """``(codec, reason)`` for one run — THE ``--wire`` decision.

    Explicit modes win unconditionally (the cpu-mesh byte-identity
    tests force delta8 with no link at all).  ``auto`` ships packed5
    when the tail is link-free (the "saved" wire would be a memcpy
    while the encode/decode costs stay real) and otherwise prices the
    measured link rate against :func:`wire_auto_cutoff_bps`.  Env
    ``S2C_WIRE`` overrides the requested mode (campaign A/B legs).
    Pinned by tests/test_wire.py decision tests.
    """
    env = os.environ.get("S2C_WIRE")
    if env:
        mode = env
    if mode not in ("auto",) + CODECS:
        raise ValueError(
            f"--wire {mode!r}: use auto|{'|'.join(CODECS)}")
    if mode != "auto":
        return mode, "forced"
    if link_free:
        return "packed5", "link_free"
    if link_bps is not None and link_bps < wire_auto_cutoff_bps():
        return "delta8", "slow_link"
    return "packed5", "fast_link"
