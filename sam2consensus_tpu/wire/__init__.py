"""Compressed event wire format + streaming H2D pipeline (round 6).

The device pileup is LINK-bound on the bench rig (PERF.md: 71 MB h2d at
78% utilization of the modeled 40 MB/s tunnel for the north-star
config), so this package attacks both sides of the wire bill:

* :mod:`.codec` — the host-side ``delta8`` slab codec: start positions
  travel as uint8 deltas (rows arrive position-sorted from the encoder,
  so consecutive deltas are small; an escape lane carries the unsorted
  tails and >254 jumps exactly), base codes travel 2-bit-packed ACGT
  planes with a per-row trailing-pad count eliding the bucket pad tail,
  and rare non-ACGT cells (gaps, N, interior pad) ride a sparse escape
  list.  Every slab carries a self-describing header (codec id, row
  count, escape counts) so ``--wire auto`` is priced by the same link
  model that routes tail placement, and a mixed-codec stream stays
  decodable.
* :mod:`.device` — the device-side unpack stage: one jitted prefix-sum
  + gather + 2-bit unpack reconstituting EXACTLY the operands every
  existing pileup kernel consumes (absolute int32 starts + the 4-bit
  packed code lanes), so scatter / Pallas tile-CSR / MXU and all three
  shard layouts run unchanged downstream.  Counts are byte-identical
  to the uncompressed path by construction (the decode is exact, and
  the kernels see identical operands).
* :mod:`.pipeline` — double-buffered async staging: two pinned staging
  slots let the decode-prefetch thread encode + ``device_put`` slab
  N+1 while slab N accumulates on device, with backpressure when both
  slots are in flight, and interval accounting that surfaces the
  measured stage/accumulate overlap (``pipeline/overlap_sec``).
"""

from .codec import (CODECS, WireSlab, decode_slab_host, encode_slab,
                    modeled_wire_ratio, packed5_slab_bytes, resolve_codec,
                    row_bytes_estimate, wire_auto_cutoff_bps, worthwhile)


def link_free_default() -> bool:
    """True when the default backend shares host memory (no wire to
    bill).  Import-guarded so jax-free consumers (the cpu backend's
    paranoid path) can still call the accounting helpers."""
    try:
        import jax

        return jax.default_backend() == "cpu"
    except Exception:
        return True


def account_d2h(nbytes: int, link_free=None) -> None:
    """THE device→host accounting choke point: every fetch that crosses
    the link bills ``wire/d2h_bytes`` here — the fused tail's packed
    buffer, the sharded (gather-based) tail's symbol/stat fetches
    (``parallel.base.fetch_host``), and full count-tensor pulls
    (checkpoint snapshots, ladder demotions, paranoid cross-checks,
    overflow fallbacks via ``counts_host``).  Before this, the
    gather-based and counts-pull routes bypassed the accounting
    entirely and ``wire/d2h_bytes`` was a tail-output model, not a
    measurement.  ``link_free`` skips the bill when the fetch is a host
    memcpy (the default backend IS the cpu, or a tail explicitly
    committed to the local cpu device — callers that know pass it)."""
    if link_free is None:
        link_free = link_free_default()
    if link_free or nbytes <= 0:
        return
    from .. import observability as obs

    obs.metrics().add("wire/d2h_bytes", int(nbytes))


def account_h2d(nbytes: int) -> None:
    """THE host→device accounting choke point, mirroring
    :func:`account_d2h` for the other direction: every staged
    ``device_put`` (slab operands, kernel plans, counts uploads,
    prewarm compiles) bills ``wire/h2d_bytes`` here, so
    ``stats.extra["h2d_bytes"]`` and the manifests read the registry
    instead of re-summing per-accumulator attributes.  Unlike d2h
    there is NO link-free skip: the legacy ``bytes_h2d`` attributes
    always counted staged bytes even on a shared-memory backend (the
    encode + copy work is real, and the wire-codec A/B tests compare
    exactly those totals) — the registry must mirror them exactly."""
    if nbytes <= 0:
        return
    from .. import observability as obs

    obs.metrics().add("wire/h2d_bytes", int(nbytes))


def fetch_d2h(x, link_free=None):
    """``np.asarray`` with the transfer billed through
    :func:`account_d2h`; returns the host array."""
    import numpy as np

    arr = np.asarray(x)
    account_d2h(arr.nbytes, link_free)
    return arr


__all__ = [
    "CODECS", "WireSlab", "encode_slab", "decode_slab_host",
    "modeled_wire_ratio", "packed5_slab_bytes", "resolve_codec",
    "row_bytes_estimate", "wire_auto_cutoff_bps", "worthwhile",
    "account_d2h", "account_h2d", "fetch_d2h", "link_free_default",
]
