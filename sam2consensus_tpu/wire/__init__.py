"""Compressed event wire format + streaming H2D pipeline (round 6).

The device pileup is LINK-bound on the bench rig (PERF.md: 71 MB h2d at
78% utilization of the modeled 40 MB/s tunnel for the north-star
config), so this package attacks both sides of the wire bill:

* :mod:`.codec` — the host-side ``delta8`` slab codec: start positions
  travel as uint8 deltas (rows arrive position-sorted from the encoder,
  so consecutive deltas are small; an escape lane carries the unsorted
  tails and >254 jumps exactly), base codes travel 2-bit-packed ACGT
  planes with a per-row trailing-pad count eliding the bucket pad tail,
  and rare non-ACGT cells (gaps, N, interior pad) ride a sparse escape
  list.  Every slab carries a self-describing header (codec id, row
  count, escape counts) so ``--wire auto`` is priced by the same link
  model that routes tail placement, and a mixed-codec stream stays
  decodable.
* :mod:`.device` — the device-side unpack stage: one jitted prefix-sum
  + gather + 2-bit unpack reconstituting EXACTLY the operands every
  existing pileup kernel consumes (absolute int32 starts + the 4-bit
  packed code lanes), so scatter / Pallas tile-CSR / MXU and all three
  shard layouts run unchanged downstream.  Counts are byte-identical
  to the uncompressed path by construction (the decode is exact, and
  the kernels see identical operands).
* :mod:`.pipeline` — double-buffered async staging: two pinned staging
  slots let the decode-prefetch thread encode + ``device_put`` slab
  N+1 while slab N accumulates on device, with backpressure when both
  slots are in flight, and interval accounting that surfaces the
  measured stage/accumulate overlap (``pipeline/overlap_sec``).
"""

from .codec import (CODECS, WireSlab, decode_slab_host, encode_slab,
                    modeled_wire_ratio, packed5_slab_bytes, resolve_codec,
                    row_bytes_estimate, wire_auto_cutoff_bps, worthwhile)

__all__ = [
    "CODECS", "WireSlab", "encode_slab", "decode_slab_host",
    "modeled_wire_ratio", "packed5_slab_bytes", "resolve_codec",
    "row_bytes_estimate", "wire_auto_cutoff_bps", "worthwhile",
]
