"""Device-side ``delta8`` unpack: wire lanes → the legacy kernel operands.

One jitted stage reconstitutes EXACTLY what every existing pileup
consumer eats — absolute int32 starts plus the 4-bit packed code lanes
(``ops.pileup.pack_nibbles`` bytes, bit-for-bit) — so the XLA scatter,
the Pallas tile-CSR histogram, the MXU matmul and all three shard
layouts run unchanged downstream of the decode.  The work is a per-chunk
prefix sum over the delta lane (escapes gathered from the escape lane by
their running rank), a 2-bit shift/mask expand of the ACGT planes, an
iota-vs-trailing-length mask restoring the bucket PAD tail, and one
sparse scatter restoring non-ACGT cells; all VPU-shaped, ~ns/cell,
against the ~0.25 B/cell of link it saves on a tunnel-class link
(codec.wire_auto_cutoff_bps).

Chunked decode (``C > 1``) vmaps the chunk axis, so each chunk's prefix
sum is independent — the sharded accumulators device_put the lanes with
the chunk axis sharded over the mesh and decode with sharded
out-shardings, keeping the unpack local to the device that owns the
rows (no cross-device decode dependency by construction).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..constants import NUM_SYMBOLS, PAD_CODE
from .codec import DELTA_ESCAPE


def pack_nibbles_jnp(codes: jax.Array) -> jax.Array:
    """Traceable twin of ``ops.pileup.pack_nibbles`` (PAD → 15, odd
    widths pad one PAD column) so decoded operands are byte-identical
    to the host-packed lanes every kernel was compiled against."""
    nib = jnp.where(codes < NUM_SYMBOLS, codes,
                    jnp.uint8(15)).astype(jnp.uint8)
    s, w = nib.shape
    if w % 2:
        nib = jnp.concatenate(
            [nib, jnp.full((s, 1), 15, dtype=jnp.uint8)], axis=1)
    return nib[:, 0::2] | (nib[:, 1::2] << 4)


#: 2-bit wire value -> count-lane code, as a traceable constant
_WIRE2_TO_CODE = jnp.array([1, 2, 3, 5], dtype=jnp.uint8)


def _decode_chunk(d8, esc_delta, trail, base2, esc_idx, esc_code,
                  width: int, sentinel: int):
    """Decode ONE chunk's lanes to (starts int32 [R], codes u8 [R, W])."""
    r = d8.shape[0]
    esc = d8 == jnp.uint8(DELTA_ESCAPE)
    rank = jnp.cumsum(esc.astype(jnp.int32)) - 1
    ep = esc_delta.shape[0]
    # the escape lanes ship dtype-narrowed (uint16 rows when they fit,
    # codec.encode_slab); widen on chip before arithmetic
    esc_delta = esc_delta.astype(jnp.int32)
    esc_idx = esc_idx.astype(jnp.int32)
    delta = jnp.where(esc, esc_delta[jnp.clip(rank, 0, ep - 1)],
                      d8.astype(jnp.int32))
    starts = jnp.cumsum(delta).astype(jnp.int32)

    shifts = jnp.array([0, 2, 4, 6], dtype=jnp.uint8)
    two = (base2[:, :, None] >> shifts[None, None, :]) & 3
    lane = _WIRE2_TO_CODE[two.reshape(r, -1)[:, :width]]
    if lane.shape[1] < width:
        # the 2-bit lane is only as wide as the slab's longest payload;
        # the shared trailing-PAD region reconstitutes here
        lane = jnp.concatenate(
            [lane, jnp.full((r, width - lane.shape[1]), PAD_CODE,
                            dtype=jnp.uint8)], axis=1)
    codes = lane
    nlen = jnp.where(trail == sentinel, 0,
                     width - trail.astype(jnp.int32))
    col = jax.lax.iota(jnp.int32, width)
    codes = jnp.where(col[None, :] < nlen[:, None], codes,
                      jnp.uint8(PAD_CODE))
    # restore non-ACGT cells; pad escape entries carry index R*W, which
    # is out of range and dropped
    flat = codes.reshape(-1).at[esc_idx].set(esc_code, mode="drop")
    return starts, flat.reshape(r, width)


def _decode_to_packed(d8, esc_delta, trail, base2, esc_idx, esc_code,
                      width: int, sentinel: int):
    """Chunk-vmapped decode → (starts [S] i32, packed [S, ⌈W/2⌉] u8)."""
    f = partial(_decode_chunk, width=width, sentinel=sentinel)
    starts, codes = jax.vmap(f)(d8, esc_delta, trail, base2, esc_idx,
                                esc_code)
    c, r = d8.shape
    return (starts.reshape(-1),
            pack_nibbles_jnp(codes.reshape(c * r, width)))


#: single-device decode entry (the sharded accumulators build their own
#: jit with sharded out-shardings via :func:`decode_fn`)
decode_to_packed = jax.jit(_decode_to_packed,
                           static_argnames=("width", "sentinel"))


def decode_fn(out_shardings=None):
    """A jitted decode with explicit output shardings — the sharded
    accumulators pass their (row_spec, mat_spec) pair so the decoded
    operands land exactly where the legacy ``device_put`` would have
    placed them."""
    if out_shardings is None:
        return decode_to_packed
    return jax.jit(_decode_to_packed,
                   static_argnames=("width", "sentinel"),
                   out_shardings=out_shardings)
