"""Double-buffered async H2D staging + stage/accumulate overlap metering.

The decode prefetch thread (backends.jax_backend._Prefetcher) already
overlaps HOST DECODE with device work; this module makes the TRANSFER
overlap explicit and bounded: two pinned staging slots let slab N+1 be
wire-encoded and ``device_put`` in flight while slab N accumulates on
device, with BACKPRESSURE (the producer blocks) when both slots hold
staged-but-unconsumed slabs — so staging can never run unboundedly
ahead of the device queue, and a failed in-flight slab is at most one
slot of work to invalidate and replay.

Overlap is MEASURED, not assumed: the stager logs every staging
interval, the consumer logs every dispatch interval, and
:meth:`StageSlots.overlap_sec` reports their exact intersection — the
``pipeline/overlap_sec`` metric the bench rows carry (a serialized
pipeline reports ~0 even when both phases are busy; a healthy one
reports stage_sec ≈ overlap_sec).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

#: pinned staging slots: slab N consuming + slab N+1 in flight
DEFAULT_SLOTS = 2


def intersect_sec(a: List[Tuple[float, float]],
                  b: List[Tuple[float, float]]) -> float:
    """Total overlap between two interval lists (merge sweep).  Shared
    by :meth:`StageSlots.overlap_sec` (stage∩dispatch within a run) and
    the serve runner (job N+1 decode ∩ job N dispatch across runs —
    ``serve/overlap_sec``)."""
    a = sorted(a)
    b = sorted(b)
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


#: pre-rename alias (tests/test_wire.py pins the merge-sweep math)
_intersect_sec = intersect_sec


class StageSlots:
    """Two pinned staging slots around an accumulator's ``stage``.

    Producer side (the decode prefetch thread) calls :meth:`stage`,
    which blocks while both slots are in flight (backpressure) and
    re-raises any staging failure AFTER releasing the batch's slot —
    the caller invalidates the batch's staged operands and delivers it
    unstaged, so the failure replays through the consumer's retry
    policy / degradation ladder (resilience/).  Consumer side calls
    :meth:`consumed` after dispatching each batch (releasing its slot)
    and :meth:`note_consume` with the dispatch interval.  ``stage_fn``
    is rebindable: a ladder demotion re-routes (or drops) staging
    without tearing the pipeline down.
    """

    def __init__(self, stage_fn: Optional[Callable],
                 slots: int = DEFAULT_SLOTS):
        self.stage_fn = stage_fn
        self.slots = slots
        self._sem = threading.Semaphore(slots)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._held: set = set()
        self._stage_iv: List[Tuple[float, float]] = []
        self._consume_iv: List[Tuple[float, float]] = []
        self.backpressure_sec = 0.0
        self.staged_batches = 0

    # -- producer side (prefetch thread) --------------------------------
    def acquire(self, batch) -> bool:
        """Claim a staging slot for ``batch``, blocking under
        backpressure.  SPLIT from :meth:`run` so the caller's
        ``phase/stage_sec`` clock can exclude the wait — backpressure
        is the consumer's dispatch time, already billed there, and
        folding it into the stage phase would both double-bill it and
        deflate the overlap fraction computed against stage seconds.
        False = staging unavailable (closed, or no stage_fn bound)."""
        if self.stage_fn is None:
            return False
        t_wait = time.perf_counter()
        while not self._stop.is_set():
            if self._sem.acquire(timeout=0.05):
                self.backpressure_sec += time.perf_counter() - t_wait
                with self._lock:
                    self._held.add(id(batch))
                return True
        return False                    # consumer gone; drop staging

    def run(self, batch) -> None:
        """Stage an acquired batch (encode + device_put).  A failure
        invalidates the batch's slot here (released) and re-raises —
        the caller clears ``batch.staged`` and delivers it unstaged, so
        the slab replays through the consumer's retry policy/ladder."""
        fn = self.stage_fn
        if fn is None:                  # rebound to None after acquire
            self._release(batch)
            return
        t0 = time.perf_counter()
        try:
            fn(batch)
            self.staged_batches += 1
        except BaseException:
            self._release(batch)
            raise
        finally:
            with self._lock:
                self._stage_iv.append((t0, time.perf_counter()))

    def stage(self, batch) -> None:
        """acquire + run in one call (unit tests / simple callers)."""
        if self.acquire(batch):
            self.run(batch)

    # -- consumer side ---------------------------------------------------
    def consumed(self, batch) -> None:
        self._release(batch)

    def note_consume(self, t0: float, t1: float) -> None:
        with self._lock:
            self._consume_iv.append((t0, t1))

    def _release(self, batch) -> None:
        with self._lock:
            if id(batch) in self._held:
                self._held.discard(id(batch))
                self._sem.release()

    def close(self) -> None:
        """Unblock any backpressured producer (consumer exited)."""
        self._stop.set()

    # -- accounting ------------------------------------------------------
    def stage_sec(self) -> float:
        with self._lock:
            return sum(t1 - t0 for t0, t1 in self._stage_iv)

    def overlap_sec(self) -> float:
        """Exact seconds the staging thread's transfer work co-ran with
        the consumer's accumulate dispatches."""
        with self._lock:
            return intersect_sec(list(self._stage_iv),
                                 list(self._consume_iv))
