"""The threshold consensus vote as a closed-form per-position reduction.

The reference's caller (``/root/reference/sam2consensus.py:359-367``) walks
count groups in descending order, taking whole tie-groups while the running
total stays below ``t * coverage``.  That sequential greedy loop has an exact
per-lane closed form, which is what makes it a TPU-friendly elementwise op:

    lane i is included  ⟺  c_i != 0  AND  S_i < t * cov,
    where S_i = Σ_j c_j over lanes j with c_j > c_i.

Proof sketch: groups share a count value, so "all lanes with strictly greater
count" is exactly the set of groups taken before lane i's group, and the
greedy prefix is monotone (the only possibly-negative lane — the completed
insertion gap lane, quirk 4 — sorts last, so prefix sums are non-decreasing
until the final group).  Tie-group all-or-nothing inclusion and the
break-at-first-failure are both captured.  Pinned against the oracle by the
differential tests.

Float fidelity: the reference compares an integer running total against the
Python float ``t * coverage``.  The device reproduces that float64 product's
value — including its rounding — with pure int32 limb arithmetic
(``ops.cutoff.exact_cutoff``), so the whole vote is elementwise integer math
with NO table gathers: ``S < t*cov ⟺ S < ceil(fl64(t*cov))`` for integer S.
(The earlier host-LUT formulation was equally exact but cost a ~65 ms
max-coverage round trip plus a ~46 ms [L]-wide gather per vote on the
tunneled chip — see ops/cutoff.py for the measurements.)

The called set becomes a 6-bit mask (bit i = ALPHABET[i], ASCII-sorted order)
mapped through the 64-entry IUPAC LUT — the tensor form of the reference's
``amb["".join(sorted(nucs))]``.  The LUT lookup runs as a one-hot select
(64 elementwise compares), measured ~free where the gather was not.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import IUPAC_MASK_LUT, SYM32_ASCII
from .cutoff import exact_cutoff

#: 64-entry LUT mapping the called-set mask straight to the 5-bit symbol
#: code (index into ``SYM32_ASCII``) — the packed5 output encoding
#: replaces the ASCII select with this one, so re-encoding costs ZERO
#: extra device work (ops/fused.py ``_pack5_planes``).
IUPAC_MASK_LUT5 = np.array(
    [{int(b): i for i, b in enumerate(SYM32_ASCII)}[int(v)]
     for v in IUPAC_MASK_LUT], dtype=np.uint8)

#: device output byte marking "fill this position on host" (cov==0 or
#: cov<min_depth); never collides with real output chars (all >= ord('-')).
FILL_SENTINEL = 0


def device_fill_code(fill: str, sym_space: str = "ascii"):
    """The device-resident epilogue's fill substitution code, or None
    when the fill string cannot be substituted on device.

    The reference substitutes the ``-f`` fill character for unemitted
    positions on the host (``sam2consensus.py:345``); the fused epilogue
    does it inside the vote instead — ``jnp.where(emit, syms, fill)`` is
    the SAME select that placed the FILL sentinel, so the substitution
    is free device work and the fetched buffer is final FASTA body
    bytes.  Representability depends on the wire symbol space:

    * ``ascii``: any single latin-1 character (the select emits raw
      bytes);
    * ``code5``: only fill characters inside the 32-symbol vote
      alphabet (``constants.SYM32_ASCII``) — the packed planes carry 5
      bits, nothing else fits.

    Multi-character (or non-latin) fills return None and the host
    render keeps the sentinel path, exactly as before."""
    if len(fill) != 1 or ord(fill) > 255:
        return None
    if sym_space == "code5":
        hits = np.nonzero(SYM32_ASCII == ord(fill))[0]
        return int(hits[0]) if len(hits) else None
    return ord(fill)


def threshold_luts(thresholds: Sequence[float], max_cov: int) -> np.ndarray:
    """Integer cutoffs ``lut[t, cov] = ceil(float64(t)*cov)`` as int32.

    For integer S: ``S < t*cov`` (the reference's float comparison at
    sam2consensus.py:362) ⟺ ``S < lut[t, cov]``.  The production vote now
    computes the same value on device (``ops.cutoff``); this host builder
    remains as the independent oracle the device math is tested against
    (tests/test_cutoff.py) and for numpy-side consumers.
    """
    t = np.asarray(thresholds, dtype=np.float64)[:, None]
    cov = np.arange(max_cov + 1, dtype=np.float64)[None, :]
    prod = t * cov
    lut = np.ceil(prod)
    if lut.max() > np.iinfo(np.int32).max:
        raise OverflowError("threshold*coverage exceeds int32")
    return lut.astype(np.int32)


def iupac_select(mask: jax.Array, table=IUPAC_MASK_LUT) -> jax.Array:
    """Map 6-bit called-set masks to output bytes, gather-free.

    One-hot select over a 64-entry LUT (ASCII by default; the packed5
    encoding passes ``IUPAC_MASK_LUT5``): elementwise compares fuse into
    the vote for ~free where a table gather measured ~46 ms at L = 4.6 M
    (tools/tunnel_probe.py).
    """
    lut = jnp.asarray(table).astype(jnp.int32)
    onehot = mask[..., None] == jnp.arange(64, dtype=jnp.int32)
    return jnp.sum(jnp.where(onehot, lut, 0), axis=-1).astype(jnp.uint8)


def emit_gate(cov: jax.Array, min_depth: int) -> jax.Array:
    """Positions the reference emits a real character for (others get the
    fill char): ``cov > 0 ∧ cov >= min_depth``.  Single definition shared
    by the vote's FILL sentinel and the sparse-output bitmask
    (ops/fused.py) so the two can never drift apart."""
    return (cov > 0) & (cov >= min_depth)


def vote_block(counts: jax.Array, thr_enc: jax.Array,
               min_depth: int, sym_space: str = "ascii",
               fill_code: int = FILL_SENTINEL) -> tuple:
    """Vote every position of a counts block for every threshold.

    Pure traceable function (no jit) so it can run inside ``jax.jit``,
    ``shard_map`` blocks (position-sharded vote) and Pallas comparisons
    alike.

    Args:
      counts: int32 ``[L, 6]`` pileup counts.
      thr_enc: int32 ``[T, 5]`` encoded thresholds
        (``ops.cutoff.encode_thresholds``).
      min_depth: static minimum depth gate.
      sym_space: ``"ascii"`` (output bytes) or ``"code5"`` (5-bit symbol
        codes, ``constants.SYM32_ASCII`` order) — the same one-hot
        select through a different table, so the packed5 wire encoding
        costs no extra device work.  The FILL sentinel is 0 in both
        spaces (``SYM32_ASCII[0] == 0``).
      fill_code: what unemitted positions carry — FILL_SENTINEL (the
        host substitutes later) or a :func:`device_fill_code` value
        (the device-resident epilogue: the fetched bytes are final).

    Returns:
      syms: uint8 ``[T, L]`` symbol per position (``fill_code`` where
        the reference emits the fill character), and cov: int32 ``[L]``.
    """
    table = IUPAC_MASK_LUT if sym_space == "ascii" else IUPAC_MASK_LUT5
    # widen on chip: the host-counts path uploads uint8/uint16 to spare the
    # ~40 MB/s link (ops/pileup.py HostPileupAccumulator)
    counts = counts.astype(jnp.int32)
    cov = counts.sum(axis=-1)                                  # [L]
    # S[l, i] = sum_j counts[l, j] * (counts[l, j] > counts[l, i]); the
    # [L, 6, 6] broadcast fuses into the reduction under XLA.
    greater = counts[:, None, :] > counts[:, :, None]
    strictly_greater_sum = jnp.sum(
        jnp.where(greater, counts[:, None, :], 0), axis=-1)    # [L, 6]
    nonzero = counts != 0
    bit = (1 << jnp.arange(6, dtype=jnp.int32))[None, :]

    emit = emit_gate(cov, min_depth)                           # [L]

    def per_threshold(enc_row):
        cutoff = exact_cutoff(cov, enc_row)                    # [L]
        included = nonzero & (strictly_greater_sum < cutoff[:, None])
        mask = jnp.sum(jnp.where(included, bit, 0), axis=-1)   # [L]
        syms = iupac_select(mask, table)
        return jnp.where(emit, syms, jnp.uint8(fill_code))

    return jax.vmap(per_threshold)(thr_enc), cov


#: jitted single-device entry point over a full counts tensor
vote_positions = partial(jax.jit, static_argnames=(
    "min_depth", "sym_space", "fill_code"))(vote_block)


def vote_positions_native(counts: np.ndarray, thresholds: Sequence[float],
                          min_depth: int, threads: int = 1):
    """C++ vote over host-resident counts (``native/decoder.cpp
    s2c_vote``), or None when the native library is unavailable.

    Same closed form and the same 64-entry mask LUT as the device vote;
    the float64 ``ceil(t * cov)`` cutoff is computed directly (the host
    has float64 — only the chip needed ops/cutoff.py's limb arithmetic).
    Used by the backend for link-free tails, where the XLA CPU vote's
    ~5 M positions/s/threshold was the measured bottleneck.  Position
    ranges split across ``threads`` workers on multi-core hosts (the
    ranges are independent; below 1M positions the C side stays serial).

    Returns (syms uint8 [T, L] with FILL sentinel, cov int32 [L]).
    """
    from .. import native

    lib = native.load()
    if lib is None:
        return None
    counts = np.ascontiguousarray(counts, dtype=np.int32)
    length = counts.shape[0]
    n_thr = len(thresholds)
    syms = np.empty(n_thr * length, np.uint8)
    cov = np.empty(length, np.int32)
    lib.s2c_vote(counts.reshape(-1), length,
                 np.asarray(thresholds, np.float64), n_thr, min_depth,
                 IUPAC_MASK_LUT, syms, cov, max(1, threads))
    return syms.reshape(n_thr, length), cov
