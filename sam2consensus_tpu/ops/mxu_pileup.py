"""MXU pileup: the hot scatter re-cast as one-hot matmuls + overlap-add.

XLA's ``scatter_add`` serializes duplicate indices on TPU; measured on a
v5e chip it runs at ~150M cells/s and was the device-side bottleneck of
the pipeline (the pileup is THE hot op — the reference's per-base dict
increment, ``/root/reference/sam2consensus.py:211-218``, SURVEY.md CS3).
This module reformulates the pileup so the FLOPs land on the MXU (the
design mandate: put the hot loop where the hardware is):

* the host counting-sorts segment rows by **position tile**
  (``start // TP``) and pads each tile's rows to a common count ``E``;
* per tile, two one-hot matrices — ``M[r, d] = [local_start_r == d]``
  (int8 ``[E, TP]``) and ``C[r, j*6+b] = [codes_r[j] == b]`` (int8
  ``[E, W*6]``) — contract over rows on the MXU:
  ``T = Mᵀ @ C`` (int32 ``[TP, W*6]``), which is exactly
  ``T[d, j, b] = #{rows starting at d whose j-th cell is base b}``;
* the diagonal fold ``counts[d+j, b] += T[d, j, b]`` is a pure-reshape
  skew (pad each j-plane by W, flatten, re-view shifted by one) plus one
  column sum — no gather, no scatter;
* tile overhangs (rows extend ≤ W-1 past their tile) are overlap-added
  into the next tile's range with one small scatter of NT*W rows.

Everything is integer-exact (int8 one-hots, int32 accumulation).

**RETIRED from the TPU autotuner (round 5, PERF.md R5.1)**: the start
one-hot's density is ``1/TP``, so every counted cell structurally costs
``6*TP`` MACs (12k at TP=2048) — the formulation measured ~3x slower
than the plain scatter end-to-end on the chip, and the Pallas tile-CSR
histogram (``ops.pallas_pileup``) supersedes it at ~9x the scatter
rate.  It stays available as ``--pileup mxu`` (the one formulation
whose FLOPs land on the systolic array; the CPU-mesh tests pin its
semantics, and it remains the autotune trial kernel off-TPU).  The
scatter path remains both the semantics oracle
(tests/test_mxu_pileup.py) and the fallback when coverage skew makes
per-tile padding explode (``plan.blowup``).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import NUM_SYMBOLS

#: positions per tile.  MXU work scales as R * TP * W * 6 MACs, so smaller
#: tiles mean less redundant compute but smaller (less efficient) matmuls;
#: 2048 measured best on v5e for W=128.
TILE_POSITIONS = 2048

#: fall back to scatter when per-tile padding would inflate rows this much
MAX_BLOWUP = 4.0

#: tiles processed per lax.map step: bounds the live matmul intermediate
#: to TILE_CHUNK * tile * W * 6 int32 (~200MB at W=128) on any genome size
TILE_CHUNK = 32


class TilePlan(NamedTuple):
    """Host-side plan: rows tile-sorted and densely padded per tile."""
    loc: np.ndarray        # [NT*E] int32 tile-local starts, flat
    codes: np.ndarray      # [NT*E*W] uint8 code rows, flat (PAD-filled)
    n_tiles: int
    rows_per_tile: int     # E
    width: int
    blowup: float          # padded rows / real rows


def _plan_prelude(starts: np.ndarray, padded_len: int, tile: int,
                  max_blowup: float, rows_per_tile: Optional[int],
                  coarse: bool = False):
    """Shared planning prelude: tile histogram, E selection, blowup gate.

    Returns ``(n_tiles, tile_of, per_tile, e, blowup)`` or ``None`` when
    there are no rows OR when per-tile padding would inflate the row count
    beyond ``max_blowup`` (skewed coverage) — checked BEFORE any padded
    array is allocated.  ``rows_per_tile`` forces E instead of deriving it
    from this slab's fullest tile — the sharded pipeline plans one chunk
    per device and SPMD needs a uniform shape across them (parallel/dp.py).
    """
    n = len(starts)
    if n == 0:
        return None
    n_tiles = max(1, -(-padded_len // tile))
    tile_of = starts // tile
    per_tile = np.bincount(tile_of, minlength=n_tiles)
    if rows_per_tile is None:
        # eighth-power-of-two rounding (ops.pileup.round_rows_grid):
        # measured occupancy 42-52% -> 52-70%+ across slab densities;
        # the remainder is per-tile Poisson skew (max vs mean), which
        # uniform heights cannot remove.  ``coarse`` keeps the old full
        # power-of-two grid — the autotuner uses it while still TIMING
        # so its warm and timed slabs share one compiled shape whenever
        # their tile maxima fall in the same octave (the fine grid's 8x
        # more E values would routinely bill jit compilation to the mxu
        # sample and mis-lock scatter); once locked, fine grid.
        from .pileup import round_rows_grid, round_rows_pow2

        e_fine = round_rows_grid(int(per_tile.max()))
        e = round_rows_pow2(e_fine) if coarse else e_fine
        # the blowup GATE always prices the fine grid: a coarse trial
        # layout must not disqualify (skew-lock to scatter) a workload
        # the production fine grid would serve
        if n_tiles * e_fine / n > max_blowup:
            return None
        # the REPORTED blowup is likewise the fine-grid (gated) economics:
        # a coarse timing-phase layout pads the device up to 2x more, but
        # that waste is transient (two trial slabs) and the actual padded
        # rows stay derivable from n_tiles * rows_per_tile — reporting the
        # coarse figure would let callers observe blowup > max_blowup and
        # misread the production layout's cost (ADVICE r4)
        blowup = n_tiles * e_fine / n
    else:
        e = rows_per_tile
        if int(per_tile.max(initial=0)) > e:
            return None
        if n_tiles * e / n > max_blowup:
            return None
        blowup = n_tiles * e / n
    return n_tiles, tile_of, per_tile, e, blowup


def plan_tiles(starts: np.ndarray, codes: np.ndarray, padded_len: int,
               tile: int = TILE_POSITIONS,
               max_blowup: float = MAX_BLOWUP,
               rows_per_tile: Optional[int] = None) -> Optional[TilePlan]:
    """Counting-sort rows by position tile into host-padded arrays
    (the padded-transfer layout; see :func:`plan_slots` for production)."""
    pre = _plan_prelude(starts, padded_len, tile, max_blowup, rows_per_tile)
    if pre is None:
        return None
    n_tiles, tile_of, per_tile, e, blowup = pre
    n = len(starts)
    width = codes.shape[1]

    order = np.argsort(tile_of, kind="stable")
    s_sorted = starts[order]
    c_sorted = codes[order]
    loc = np.zeros(n_tiles * e, dtype=np.int32)
    cod = np.full((n_tiles * e, width), 255, dtype=np.uint8)
    hi = np.cumsum(per_tile)
    lo = hi - per_tile
    tile_sorted = tile_of[order]
    slot = tile_sorted * e + (np.arange(n) - lo[tile_sorted])
    loc[slot] = (s_sorted - tile_sorted * tile).astype(np.int32)
    cod[slot] = c_sorted
    return TilePlan(loc, cod.reshape(-1), n_tiles, e, width, blowup)


def _skew_fold(t3: jax.Array) -> jax.Array:
    """[TP, W, 6] -> [TP+W, 6]: out[q] = sum_j t3[q-j, j] (reshape trick)."""
    tp, w, c = t3.shape
    a = jnp.moveaxis(t3, 1, 0)                               # [W, TP, 6]
    a = jnp.concatenate([a, jnp.zeros((w, w, c), a.dtype)], axis=1)
    m = tp + w
    d = a.reshape(w * m, c)[: w * (m - 1)].reshape(w, m - 1, c)
    out = d.sum(axis=0)                                      # [TP+W-1, 6]
    return jnp.concatenate([out, jnp.zeros((1, c), out.dtype)], axis=0)


class SlotPlan(NamedTuple):
    """Host-side compact plan: one int32 slot per row, nothing padded.

    The padded tile layout is materialized ON DEVICE (a row scatter by
    ``slot``), so the host->device transfer stays at the scatter path's
    compact bytes (+4B/row for the slot) instead of shipping up to
    ``MAX_BLOWUP``x padded rows over the (tunnel-bottlenecked) link —
    the prime suspect for round 1's end-to-end MXU regression.
    """
    slot: np.ndarray       # [N] int32, unique: tile_of * E + rank-in-tile
    n_tiles: int
    rows_per_tile: int     # E
    width: int
    blowup: float          # device-side padded rows / real rows


def assign_slots(tile_of: np.ndarray, per_tile: np.ndarray,
                 e: int) -> np.ndarray:
    """Rank each row within its tile: slot = tile_of * E + rank."""
    n = len(tile_of)
    order = np.argsort(tile_of, kind="stable")
    hi = np.cumsum(per_tile)
    rank = np.empty(n, dtype=np.int64)
    rank[order] = np.arange(n) - (hi - per_tile)[tile_of[order]]
    return (tile_of * e + rank).astype(np.int32)


def plan_slots(starts: np.ndarray, width: int, padded_len: int,
               tile: int = TILE_POSITIONS,
               max_blowup: float = MAX_BLOWUP,
               rows_per_tile: Optional[int] = None,
               coarse: bool = False) -> Optional[SlotPlan]:
    """Assign each row its padded-layout slot (counting sort, no copies).

    Same fallback contract as :func:`plan_tiles`; ``rows_per_tile`` forces
    E for SPMD-uniform sharded planning (parallel/dp.py); ``coarse``
    keeps E on the pow2 grid (autotune timing phase, see _plan_prelude).
    """
    pre = _plan_prelude(starts, padded_len, tile, max_blowup, rows_per_tile,
                        coarse)
    if pre is None:
        return None
    n_tiles, tile_of, per_tile, e, blowup = pre
    return SlotPlan(assign_slots(tile_of, per_tile, e),
                    n_tiles, e, width, blowup)


def _accumulate_tiles(counts: jax.Array, loc: jax.Array, cod: jax.Array,
                      *, tile: int, n_tiles: int, rows_per_tile: int,
                      width: int) -> jax.Array:
    """Traceable tile body shared by both transfer layouts:
    ``loc`` [NT, E] tile-local starts, ``cod`` [NT, E, W] code rows."""

    def per_tile(locs, codes):
        d = jax.lax.iota(jnp.int32, tile)[None, :]
        m = (locs[:, None] == d).astype(jnp.int8)            # [E, TP]
        c6 = jax.lax.iota(jnp.int32, NUM_SYMBOLS)[None, None, :]
        c = (codes[:, :, None].astype(jnp.int32) == c6)
        c = c.reshape(rows_per_tile, width * NUM_SYMBOLS).astype(jnp.int8)
        t = jax.lax.dot_general(m, c, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.int32)
        return _skew_fold(t.reshape(tile, width, NUM_SYMBOLS))

    # chunk the tile axis: a flat vmap would materialize the matmul output
    # for EVERY tile at once — [n_tiles, tile, W*6] int32 scales with
    # genome length and OOMs HBM on multi-Mbp genomes.  lax.map over
    # fixed-size tile chunks caps the live intermediate at
    # TILE_CHUNK * tile * W * 6 * 4B regardless of n_tiles.
    if n_tiles <= TILE_CHUNK:
        tiles = jax.vmap(per_tile)(loc, cod)                 # [NT, TP+W, 6]
    else:
        n_chunks = -(-n_tiles // TILE_CHUNK)
        pad = n_chunks * TILE_CHUNK - n_tiles
        loc_p = jnp.pad(loc, ((0, pad), (0, 0)))
        cod_p = jnp.pad(cod, ((0, pad), (0, 0), (0, 0)),
                        constant_values=255)
        tiles = jax.lax.map(
            lambda xs: jax.vmap(per_tile)(*xs),
            (loc_p.reshape(n_chunks, TILE_CHUNK, rows_per_tile),
             cod_p.reshape(n_chunks, TILE_CHUNK, rows_per_tile, width)))
        tiles = tiles.reshape(n_chunks * TILE_CHUNK, tile + width,
                              NUM_SYMBOLS)[:n_tiles]
    main = tiles[:, :tile, :].reshape(-1, NUM_SYMBOLS)
    # overhang of tile t covers [(t+1)*TP, (t+1)*TP + W): one tiny scatter
    pad = jnp.zeros(((n_tiles + 1) * tile + width, NUM_SYMBOLS),
                    tiles.dtype)
    idx = ((jnp.arange(n_tiles) + 1) * tile)[:, None] \
        + jnp.arange(width)[None, :]
    pad = pad.at[idx.reshape(-1)].add(
        tiles[:, tile:, :].reshape(-1, NUM_SYMBOLS))
    return counts + main + pad[: n_tiles * tile]


def build_padded_layout(starts: jax.Array, codes: jax.Array,
                        slot: jax.Array, *, tile: int, n_tiles: int,
                        rows_per_tile: int, width: int):
    """Traceable device-side padding: compact rows + slot -> (loc, cod).

    One row scatter (N indices for whole W-byte rows — far fewer indices
    than the N*W cell scatter of the scatter pileup, and with no duplicate
    accumulation).  Slots are unique by construction, so ``.set`` is
    deterministic.

    Only even widths may reach this layout: the 4-bit wire packing
    (ops.pileup.pack_nibbles) widens ODD rows to W+1 columns on unpack,
    which would silently mis-lay rows against the static pre-pack width
    (safe for scatter consumers, whose PAD cells self-redirect).  Encoder
    buckets are even by construction; this guard turns a future odd-width
    (halo-split) routing mistake into an immediate error (ADVICE r4).
    """
    assert width % 2 == 0, (
        f"MXU packed layout requires an even row width, got {width}: "
        f"odd (halo-split) rows unpack to width+1 and must stay on the "
        f"scatter path")
    e = rows_per_tile
    tile_of = slot // e
    loc = jnp.zeros(n_tiles * e, dtype=jnp.int32).at[slot].set(
        (starts - tile_of * tile).astype(jnp.int32))
    cod = jnp.full((n_tiles * e, width), 255, dtype=jnp.uint8).at[slot].set(
        codes)
    return loc.reshape(n_tiles, e), cod.reshape(n_tiles, e, width)


@functools.partial(jax.jit, donate_argnums=0,
                   static_argnames=("tile", "n_tiles", "rows_per_tile",
                                    "width"))
def pileup_mxu(counts: jax.Array, loc_flat: jax.Array, codes_flat: jax.Array,
               *, tile: int, n_tiles: int, rows_per_tile: int,
               width: int) -> jax.Array:
    """Padded-transfer layout (TilePlan): accumulate into ``counts``
    ([n_tiles*tile, 6]).  Flat inputs are reshaped on device:
    multi-dimensional host->device transfers of non-native shapes are
    pathologically slow through a tunneled runtime, flat byte streams are
    not.  Kept as the semantics twin for tests; production uses the
    compact layout below.
    """
    loc = loc_flat.reshape(n_tiles, rows_per_tile)
    cod = codes_flat.reshape(n_tiles, rows_per_tile, width)
    return _accumulate_tiles(counts, loc, cod, tile=tile, n_tiles=n_tiles,
                             rows_per_tile=rows_per_tile, width=width)


@functools.partial(jax.jit, donate_argnums=0,
                   static_argnames=("tile", "n_tiles", "rows_per_tile",
                                    "width"))
def pileup_mxu_compact(counts: jax.Array, starts: jax.Array,
                       codes: jax.Array, slot: jax.Array, *, tile: int,
                       n_tiles: int, rows_per_tile: int,
                       width: int) -> jax.Array:
    """Compact-transfer layout (SlotPlan): rows ship exactly as the
    scatter path ships them (+4B/row slot); the padded tile layout is
    built on device, keeping the tunnel link at compact bytes."""
    loc, cod = build_padded_layout(starts, codes, slot, tile=tile,
                                   n_tiles=n_tiles,
                                   rows_per_tile=rows_per_tile, width=width)
    return _accumulate_tiles(counts, loc, cod, tile=tile, n_tiles=n_tiles,
                             rows_per_tile=rows_per_tile, width=width)


@functools.partial(jax.jit, donate_argnums=0,
                   static_argnames=("tile", "n_tiles", "rows_per_tile",
                                    "width"))
def pileup_mxu_packed(counts: jax.Array, starts: jax.Array,
                      packed: jax.Array, slot: jax.Array, *, tile: int,
                      n_tiles: int, rows_per_tile: int,
                      width: int) -> jax.Array:
    """Compact layout fed by the 4-bit wire format (ops.pileup
    pack_nibbles): half the code bytes on the link.  The unpacked PAD
    nibble (15) one-hots to zero exactly like the uint8 PAD, so no
    translation is needed before the tile matmuls."""
    from .pileup import unpack_nibbles

    loc, cod = build_padded_layout(starts, unpack_nibbles(packed), slot,
                                   tile=tile, n_tiles=n_tiles,
                                   rows_per_tile=rows_per_tile, width=width)
    return _accumulate_tiles(counts, loc, cod, tile=tile, n_tiles=n_tiles,
                             rows_per_tile=rows_per_tile, width=width)
