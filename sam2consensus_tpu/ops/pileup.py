"""Device-side pileup accumulation: the reference's hot loop as one scatter.

The reference spends ~all wall-clock doing one Python dict increment per
aligned base (``/root/reference/sam2consensus.py:211-218``, SURVEY.md CS3).
Here reads arrive as segment rows — flat-genome start + uint8 code row
(``encoder.events.SegmentBatch``) — and the device expands positions with an
iota and scatter-adds into a flat ``[total_len + 1, 6]`` int32 tensor.  XLA's
scatter accumulates duplicate indices exactly, so read order and sharding
cannot change the result (addition commutes; SURVEY.md §5).

Design note: an earlier COO formulation (one int32 position + one int32 code
per aligned base, expanded on host) was host-transfer-bound — ~8 bytes/base
over the PCIe/tunnel link dominated end-to-end time while the TPU scatter
itself was ~free.  Segment rows move ~1 byte/base and push the expansion
into the compiled program, where it fuses into the scatter's index
computation.

Rows are padded (PAD_CODE) and bucketed to power-of-two shapes so the jit
cache holds O(log²) entries; PAD positions are redirected to the sacrificial
row ``total_len``, which is dropped at read time.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as obs
from ..constants import NUM_SYMBOLS, PAD_CODE
from ..encoder.events import MIN_BUCKET_W, SegmentBatch, StagedSlab
from ..observability import jitcache, memplane
from ..resilience.faultinject import fault_check
from ..wire import account_h2d
from ..wire import codec as wire_codec


def account_wire(codec: str, nbytes: int, raw_nbytes: int) -> None:
    """One slab's wire bill into the run's registry: ``wire/bytes`` is
    what crossed the link, ``wire/raw_bytes`` the packed5-equivalent
    bill — their ratio is the compression the bench rows report.
    Shared by the single-device and sharded accumulators so the
    accounting cannot drift between paths."""
    reg = obs.metrics()
    reg.add("wire/bytes", nbytes)
    reg.add("wire/raw_bytes", raw_nbytes)
    reg.add(f"wire/slabs/{codec}", 1)


def encode_wire_slab(wire: str, starts, codes, chunks: int = 1):
    """The delta8 encode gate shared by every row-shipping path:
    ``None`` means ship the packed5 lanes (codec off, shape cannot
    chunk, or the encoded slab would not shrink — escape-dense slabs
    are billed honestly, per slab).  The ``wire_encode`` fault site
    fires here, on whichever thread is encoding (staging or consumer
    fallback)."""
    if wire != "delta8":
        return None
    fault_check("wire_encode")
    slab = wire_codec.encode_slab(np.asarray(starts), np.asarray(codes),
                                  chunks=chunks)
    if slab is None or not wire_codec.worthwhile(slab):
        obs.metrics().add("wire/fallback_slabs", 1)
        return None
    return slab


#: cap on expanded scatter cells (rows x width) per device call, bounding the
#: int32 position/code temporaries to ~32MB each even if XLA materializes them
SCATTER_CELL_BUDGET = 1 << 23


def round_rows_grid(m: int) -> int:
    """Round a row capacity up to an eighth-power-of-two grid.

    Shared by every slab/slot planner that pads row counts to a static
    shape (MXU tile plans, the sp/dpsp routing grids): 8 mantissa steps
    per octave keep each jit cache O(log) across slabs (x8 vs pure
    powers of two) while the pad waste — wasted MXU lanes, wasted wire
    bytes on routed grids — stays <=12.5% instead of <=2x.  Values
    <=16 round exactly (shift 0); floor 8.
    """
    m = max(8, int(m))
    shift = max(0, (m - 1).bit_length() - 4)
    return -(-m >> shift) << shift


def round_rows_pow2(m: int) -> int:
    """Full power-of-two row-capacity rounding (floor 8) — the COARSE
    grid the autotuner's timing phase stays on so its warm and timed
    slabs share one compiled shape (see mxu_pileup._plan_prelude)."""
    return 1 << max(3, (max(1, int(m)) - 1).bit_length())


def pack_nibbles(codes: np.ndarray) -> np.ndarray:
    """Host-side 4-bit wire packing: ``[S, W]`` codes → ``[S, ⌈W/2⌉]`` bytes.

    Symbol codes are 0..5 and PAD is 255; a nibble holds both (PAD → 15,
    still ``>= NUM_SYMBOLS`` so validity tests are unchanged after unpack).
    Halves the dominant host→device transfer on the ~40 MB/s tunneled link
    (tools/tunnel_probe.py).  Encoder buckets are even (powers of two
    ≥ 32), but the sp/dpsp halo splits can produce an ODD width (halo =
    min(block, cap) with an odd position block): those pad one extra PAD
    column, so ``unpack_nibbles`` returns W+1 columns.  That is safe for
    every scatter consumer — they expand via
    ``expand_segment_positions``, which redirects PAD cells to the
    sacrificial slot — but NOT for the MXU packed layout
    (``ops.mxu_pileup.build_padded_layout`` allocates at the static
    pre-pack width): only even encoder buckets may take the MXU path.
    Even columns ride the low nibble.
    """
    nib = np.where(codes < NUM_SYMBOLS, codes, 15).astype(np.uint8)
    if nib.shape[1] % 2:
        nib = np.concatenate(
            [nib, np.full((len(nib), 1), 15, dtype=np.uint8)], axis=1)
    return nib[:, 0::2] | (nib[:, 1::2] << 4)


def unpack_nibbles(packed: jax.Array) -> jax.Array:
    """Device-side inverse of :func:`pack_nibbles` (PAD comes back as 15)."""
    lo = packed & 0xF
    hi = packed >> 4
    s, half = packed.shape
    return jnp.stack([lo, hi], axis=-1).reshape(s, half * 2)


def expand_segment_positions(starts: jax.Array, codes: jax.Array,
                             sacrificial) -> tuple:
    """Expand segment rows to flat (pos, code) scatter operands.

    Pure traceable function shared by every consumer of SegmentBatch rows
    (single-device scatter here, the fused model step, the shard_map DP path)
    so PAD/validity semantics cannot drift between them.  PAD cells are
    redirected to the ``sacrificial`` position with code 0.
    """
    w = codes.shape[1]
    pos = starts[:, None] + jax.lax.iota(jnp.int32, w)[None, :]
    valid = codes < NUM_SYMBOLS
    pos = jnp.where(valid, pos, sacrificial)
    code = jnp.where(valid, codes, 0).astype(jnp.int32)
    return pos.reshape(-1), code.reshape(-1)


@partial(jax.jit, donate_argnums=0, static_argnums=3)
def _scatter_segments(counts: jax.Array, starts: jax.Array,
                      codes: jax.Array, sacrificial: int) -> jax.Array:
    # trace-time side effect: bumps compile/* in the CURRENT registry
    # exactly once per compiled shape (observability/jitcache.py) —
    # the serve-mode warm-path evidence
    jitcache.note_trace("scatter", rows=starts.shape[0],
                        width=codes.shape[1])
    pos, code = expand_segment_positions(starts, codes, sacrificial)
    return counts.at[pos, code].add(1)


@partial(jax.jit, donate_argnums=0, static_argnums=3)
def _scatter_segments_packed(counts: jax.Array, starts: jax.Array,
                             packed: jax.Array, sacrificial: int
                             ) -> jax.Array:
    """Scatter path fed by the 4-bit wire format (pack_nibbles)."""
    jitcache.note_trace("scatter_packed", rows=starts.shape[0],
                        width=packed.shape[1] * 2)
    pos, code = expand_segment_positions(starts, unpack_nibbles(packed),
                                         sacrificial)
    return counts.at[pos, code].add(1)


def iter_row_slices(n_rows: int, width: int, multiple_of: int = 1):
    """Yield (lo, hi) row slices capping hi-lo at SCATTER_CELL_BUDGET cells.

    The step stays a power of two (assuming the budget and width are), so
    pre-padded power-of-two batches stay power-of-two per slice and the jit
    cache stays small; ``multiple_of`` additionally aligns the step for
    even sharding over a device mesh.
    """
    step = max(multiple_of, (SCATTER_CELL_BUDGET // width)
               // multiple_of * multiple_of)
    for lo in range(0, n_rows, step):
        yield lo, min(n_rows, lo + step)


def padded_total_len(total_len: int) -> int:
    """Position-axis padding shared by :class:`PileupAccumulator` and
    the serve-mode prewarm (the scatter's counts operand is
    ``[padded, 6]``, so a prewarm against a different padding would
    compile a shape no job ever dispatches)."""
    from . import mxu_pileup

    tile = mxu_pileup.TILE_POSITIONS
    return -(-(total_len + 1) // tile) * tile


def canonical_slab_shapes(total_len: int, read_len: int = 150,
                          chunk_reads: int = 262144,
                          n_reads: Optional[int] = None,
                          segment_width: int = 0) -> list:
    """The (rows, width) scatter shapes a job over this genome layout is
    expected to dispatch — the serve-mode prewarm enumeration.

    Widths: the power-of-two bucket of ``read_len`` plus its double
    (deletion runs widen a read's reference span past its length;
    encoder/events._bucket_width), both clamped to ``segment_width``
    when the long-read segmented layout is active — segmentation bounds
    every row at W, so wider shapes can never be dispatched.  Rows: the
    power-of-two row paddings a chunk of ``min(n_reads, chunk_reads)``
    reads produces (the accumulator rounds the real row count to a
    power of two and ``iter_row_slices`` caps a slice at
    SCATTER_CELL_BUDGET cells), plus one level down for
    partially-filled tail chunks.  Deliberately a SMALL set — a handful
    of compiles hidden behind the first job's decode — not an
    exhaustive sweep; shapes outside it simply compile on first
    dispatch like today.
    """
    w0 = max(MIN_BUCKET_W, 1 << max(0, (max(1, read_len) - 1).bit_length()))
    widths = [w0, w0 * 2]
    if segment_width:
        widths = sorted({min(w, int(segment_width)) for w in widths})
    shapes = []
    for w in widths:
        step = max(1, SCATTER_CELL_BUDGET // w)
        if n_reads is not None:
            # per-job hint: the row paddings this job's chunks produce,
            # plus one level down for skipped-read shrink / tail chunks
            r_top = min(1 << max(3, (min(n_reads, chunk_reads) - 1)
                                 .bit_length()), step)
            levels = {r_top, max(8, r_top // 2)}
        else:
            # server startup: every power-of-two level a >=~1k-read job
            # can dispatch (the encoder's row floor is 1024; buckets
            # with fewer real rows compile cheaply on first touch)
            r_top = min(1 << max(3, (min(chunk_reads, 1 << 62) - 1)
                                 .bit_length()), step)
            levels = {1 << b for b in range(10, r_top.bit_length())}
            levels.add(r_top)
        for r in sorted(levels):
            shapes.append((int(r), int(w)))
    return sorted(set(shapes))


def canonical_panel_shapes(panel_len: int, wave_jobs: int,
                           read_len: int = 150,
                           chunk_reads: int = 262144,
                           n_reads: Optional[int] = None,
                           segment_width: int = 0) -> list:
    """The (rows, width) scatter shapes a shared-reference COHORT wave
    dispatches — :func:`canonical_slab_shapes` over the combined panel
    axis (``panel_len * wave_jobs`` positions; per-member read counts
    sum across the wave).  A cohort driver prewarms this set once
    before wave 1 (serve/cohort.py), so every wave of the cohort —
    including the first — dispatches shapes the jit cache already
    holds: the dedup story's compile half (the offset-table half lives
    in serve/packing.PanelGeometry)."""
    return canonical_slab_shapes(
        int(panel_len) * max(1, int(wave_jobs)),
        read_len=read_len, chunk_reads=chunk_reads,
        n_reads=None if n_reads is None
        else int(n_reads) * max(1, int(wave_jobs)),
        segment_width=segment_width)


def prewarm_scatter(total_len: int, shapes, device=None) -> int:
    """Compile the packed segment scatter for each ``(rows, width)`` in
    ``shapes`` without accumulating anything: all-PAD operands redirect
    every cell to the sacrificial row, so the count tensor the jobs
    later allocate is untouched and the jit cache entries are REAL (the
    same counts/starts/packed shapes and the same static sacrificial a
    job over this layout dispatches).  Returns the number of shapes
    compiled; trace-time counters land in the CURRENT registry (the
    serve runner binds its server registry, so per-job registries show
    the prewarmed shapes as pure cache hits)."""
    padded = padded_total_len(total_len)
    # the counts tensor is DEVICE-born (jnp.zeros) — nothing crosses
    # the link for it, so nothing bills; only the host-built operand
    # uploads below are real h2d traffic
    counts = jnp.zeros((padded, NUM_SYMBOLS), dtype=jnp.int32)
    if device is not None:
        counts = jax.device_put(counts, device)
    n = 0
    for rows, width in sorted(set((int(r), int(w)) for r, w in shapes)):
        if width % 2 or rows <= 0:
            continue
        # host-built operands + device_put: the same real upload a
        # job's first slab would pay, billed at the same h2d choke
        # point (they land in the SERVER registry — the serve runner
        # binds it around prewarm — so the fleet ledger is complete
        # without polluting any job's bill)
        starts = jax.device_put(np.zeros(rows, dtype=np.int32), device)
        packed = jax.device_put(
            np.full((rows, width // 2), 255, dtype=np.uint8), device)
        account_h2d(int(starts.nbytes) + int(packed.nbytes))
        # donated counts chain through every shape (same array shape)
        counts = _scatter_segments_packed(counts, starts, packed,
                                          total_len)
        n += 1
    if n:
        np.asarray(counts[0, 0])       # force compile + run completion
    return n


class PileupAutoTuner:
    """Online-autotune state machine shared by the single-device and dp
    accumulators (see PileupAccumulator's docstring for the rationale).

    Protocol per slab: ``choose(n_rows, width)`` -> (strategy, timing);
    execute the slab; then call exactly one of ``report_skew()`` (the mxu
    plan fell back) or ``complete(sec_per_cell)`` (pass the measured
    per-cell seconds iff ``timing`` was True, else no argument).
    ``stats`` is a dict once a winner is locked, else None.
    """

    MAX_SKEW_RETRIES = 3

    def __init__(self, min_cells: int = SCATTER_CELL_BUDGET >> 3,
                 kernel: str = "mxu"):
        #: which device kernel the trial races against scatter: the
        #: Pallas tile-CSR histogram on real TPUs (ops.pallas_pileup —
        #: measured 5-9x the scatter rate on v5e), the MXU matmul
        #: formulation elsewhere (kept for the CPU-mesh test surface;
        #: retired from TPU auto — PERF.md "MXU retirement")
        self.STAGES = (("scatter", False), ("scatter", True),
                       (kernel, False), (kernel, True))
        self.kernel = kernel
        self.min_cells = min_cells
        self.times: dict = {}
        self.stats = None
        self._stage = 0
        self._warm_shape = None
        self._skew = 0
        self._chosen = "scatter"
        self._timing = False
        self._advance = False

    @property
    def winner(self):
        return self.times.get("winner")

    def _lock(self, winner: str, **extra) -> None:
        self.times["winner"] = winner
        self.stats = {
            "scatter_sec_per_mcell": round(
                self.times.get("scatter", 0.0) * 1e6, 5),
            f"{self.kernel}_sec_per_mcell": round(
                self.times.get(self.kernel, 0.0) * 1e6, 5),
            "winner": winner, **extra}

    def choose(self, n_rows: int, width: int):
        self._timing = self._advance = False
        if self.winner is not None:
            self._chosen = self.winner
        elif n_rows * width < self.min_cells:
            # tiny slab: timing would be noise, cost is negligible
            self._chosen = "scatter"
        else:
            self._chosen, is_timing_stage = self.STAGES[self._stage]
            shape = (n_rows, width)
            if not is_timing_stage:
                self._warm_shape = shape        # warm slab
                self._advance = True
            elif shape != self._warm_shape:
                # shape changed since the warm slab: this run would
                # include jit compilation — re-warm, stay in stage
                self._warm_shape = shape
            else:
                self._timing = self._advance = True
        return self._chosen, self._timing

    def report_skew(self) -> None:
        """The kernel plan fell back to scatter on this slab."""
        if self.winner is not None:
            return
        self._timing = self._advance = False
        self._skew += 1
        if self._skew >= self.MAX_SKEW_RETRIES:
            # persistent skew: the kernel would rarely engage anyway, and
            # each retry pays the host planning scan — settle for scatter
            self._lock("scatter", reason=f"{self.kernel}_skew")

    def complete(self, sec_per_cell=None) -> None:
        if self.winner is not None:
            return
        if self._timing:
            self.times[self._chosen] = sec_per_cell
            if "scatter" in self.times and self.kernel in self.times:
                self._lock(min(("scatter", self.kernel),
                               key=self.times.get))
        if self._advance:
            self._stage += 1


#: "auto" picks the host-counts strategy when the genome is at most this
#: many positions: the count tensor's one-time wire cost (<= L*6*2 bytes,
#: dtype-narrowed) is then bounded by ~24 MB while the row stream costs
#: ~1 byte per aligned base — at any depth >= ~12x the counts win, and at
#: low depth on a genome this small both are cheap.  Larger genomes keep
#: the device pileup, whose wire bill scales with aligned bases, not L.
HOST_PILEUP_MAX_LEN = 1 << 21


def host_pileup_max_len(native_tail: bool = False,
                        link_free: bool = False,
                        link_bps=None) -> int:
    """The auto gate's genome-length bound, by what the tail would cost.

    When the caller can actually serve the tail with the native C++ vote
    (``native_tail`` — the library loads AND nothing forces the tail
    onto the device or a fused wire encoding; the backend computes
    this), a host-counts run never touches the link at all: the tail
    votes at ~7 ns/position locally, while the device path's FLOOR is
    two link round trips plus ~0.5 B/aligned-base of rows up and the
    symbols back.  Up to ~2^23 positions the local vote stays under
    that floor for any read depth, so the gate widens 4x.  When the
    default backend additionally IS the local cpu (``link_free`` — the
    "device" shares the host's memory), the bound vanishes entirely:
    there is no wire to bill at any genome size, and the fused C++
    decode+count runs at memory speed where the XLA-CPU scatter pays
    ~100 ns/cell (measured: the 40 Mbp config's accumulate fell ~1 s →
    ~0.1 s).

    The bound also vanishes on a slow enough LINK (``link_bps``, the
    placement model's probed/modeled rate): below
    S2C_HOST_ALWAYS_LINK_MBPS (default 80 MB/s — tunnel-class), the
    device pileup's wire floor beats the host at no genome size.  Rows
    up cost >= 0.5 B/aligned base (>= 6 ns at 80 MB/s) against ~0.9 ns
    fused host counting, and the output fetch costs >= 0.625 B/position
    (packed5, >= 7.8 ns at 80 MB/s) against the ~7 ns/position SIMD
    native vote — both terms favor the host at every L and depth (the
    measured round-4 wide-genome mis-route: host 1.2 s vs chip 3.5 s on
    the ~8-40 MB/s tunnel).  On a PCIe-class link (~GB/s) both
    inequalities flip and the narrow 2^23 bound below applies.

    Otherwise the tail would fall to the XLA CPU vote or a counts
    upload, and the narrow bound is the measured choice (PERF.md).
    Override with S2C_HOST_PILEUP_MAX_LEN.
    """
    import os

    def _record(bound: int, reason: str) -> int:
        # decision ledger (observability/ledger.py): the gate's bound
        # and WHY — joined into the run manifest so a mis-sized bound
        # (the round-4 wide-genome mis-route's shape) is visible from
        # the artifact; no prediction (the bound is a threshold, not a
        # priced cost), so no residual/drift
        inputs = {"reason": reason, "native_tail": bool(native_tail),
                  "link_free": bool(link_free)}
        if link_bps is not None:
            inputs["link_bps"] = int(link_bps)
        obs.record_decision("host_pileup_bound", str(bound),
                            inputs=inputs)
        return bound

    env = os.environ.get("S2C_HOST_PILEUP_MAX_LEN")
    if env:
        try:
            return _record(int(env), "env")
        except ValueError:
            raise RuntimeError(
                f"S2C_HOST_PILEUP_MAX_LEN={env!r}: expected a plain "
                f"integer position count (e.g. 8388608)") from None
    if native_tail and link_free:
        return _record(1 << 62, "link_free")
    if native_tail and link_bps is not None:
        slow = float(os.environ.get(
            "S2C_HOST_ALWAYS_LINK_MBPS", "80")) * 1e6
        if link_bps < slow:
            return _record(1 << 62, "slow_link")
    return _record((1 << 23) if native_tail else HOST_PILEUP_MAX_LEN,
                   "native_tail" if native_tail else "default")


class HostPileupAccumulator:
    """Host-side counts accumulation: ship the count tensor, not the reads.

    Measured rationale (tools/tunnel_probe.py): the tunneled chip moves
    ~40 MB/s each way with ~65 ms round-trip latency, so the device pileup
    pays ~1 byte per aligned base on the wire while the count tensor is
    only ``L*6`` cells.  Whenever aligned bases >> L*6 (deep coverage,
    small genomes — e.g. amplicon at 100k depth: 8 MB of rows vs 9.6 KB of
    counts), accumulating on host and shipping COUNTS once is strictly
    less wire, and the host pass (native C++ slab walk, memory-speed)
    rides with decode.  The TPU still runs the whole tail: vote, insertion
    table, stats (ops/fused.py).

    The count tensor is the same sum-decomposable state as the device
    accumulator's, so checkpoint / resume / incremental / paranoid
    semantics are unchanged (SURVEY.md §5); ``counts`` uploads with the
    narrowest dtype that holds ``max(counts)`` (uint8/uint16/int32) and
    the device vote widens to int32 on arrival.
    """

    def __init__(self, total_len: int):
        from .. import native

        self.total_len = total_len
        self._counts = np.zeros((total_len, NUM_SYMBOLS), dtype=np.int32)
        # residency accounting (observability/memplane.py): released
        # with the accumulator.  No mem_alloc fault site here — the
        # host rung is the ladder's bottom by construction, same
        # contract as every other injection site.
        memplane.track_obj("counts_host", self, self._counts.nbytes)
        self._lib = native.load()              # None -> numpy fallback
        self._device_counts = None
        self._wire_itemsize = None
        self.strategy_used: dict = {"host": 0}
        self.bytes_h2d = 0                     # wire accounting for bench
        #: when set (backends/jax_backend.py small-genome gate), counts
        #: upload COMMITS to this device and the whole fused tail follows
        #: it — e.g. the local XLA CPU backend, whose dispatch costs ~ms
        #: where the tunneled chip costs ~65 ms per round trip
        self.tail_device = None

    def add(self, batch: SegmentBatch) -> None:
        self._device_counts = None
        self._wire_itemsize = None
        if batch.accumulated:
            # fused decode path: the C++ decoder already counted this
            # batch's rows in-pass (encoder/native_encoder.py); nothing to
            # walk, just record that the fused path ran
            self.strategy_used["host_fused"] = (
                self.strategy_used.get("host_fused", 0) + 1)
            return
        flat = self._counts.reshape(-1)
        for w, (starts, codes) in sorted(batch.buckets.items()):
            t0 = time.perf_counter()
            if self._lib is not None:
                self._lib.s2c_accumulate_rows(
                    np.ascontiguousarray(starts),
                    np.ascontiguousarray(codes),
                    len(starts), w, flat, self.total_len)
            else:
                rows, cols = np.nonzero(codes < NUM_SYMBOLS)
                pos = starts[rows].astype(np.int64) + cols
                ok = (pos >= 0) & (pos < self.total_len)
                np.add.at(self._counts,
                          (pos[ok], codes[rows[ok], cols[ok]]), 1)
            self.strategy_used["host"] += 1
            obs.tracer().complete("slab", t0, strategy="host",
                                  n_rows=len(starts), width=w)
            obs.metrics().observe("pileup/slab_sec/host",
                                  time.perf_counter() - t0)

    def wire_itemsize(self) -> int:
        """Bytes/cell of the narrowed upload dtype (cached one-pass max);
        the tail-placement cost model needs the wire bill before the
        upload happens."""
        if self._wire_itemsize is None:
            m = int(self._counts.max(initial=0))
            self._wire_itemsize = 1 if m < (1 << 8) else \
                2 if m < (1 << 16) else 4
        return self._wire_itemsize

    @property
    def counts(self):
        """Device copy of the counts, wire-narrowed; vote widens on chip."""
        import jax

        if self._device_counts is None:
            with obs.tracer().span("counts_upload"):
                fault_check("device_put")
                it = self.wire_itemsize()
                if it == 4:    # already int32: ship the buffer, no copy
                    arr = self._counts
                else:
                    arr = self._counts.astype(np.uint8 if it == 1
                                              else np.uint16)
                self.strategy_used["host_wire_dtype"] = str(arr.dtype)
                self._device_counts = jax.device_put(arr,
                                                     self.tail_device)
                if self.tail_device is None:
                    # bill the wire AFTER the put: a retried upload
                    # (transient transfer failure under the resilience
                    # policy) must not double-count the tensor
                    self.bytes_h2d += arr.nbytes   # real wire bytes
                    account_h2d(arr.nbytes)
        return self._device_counts

    def counts_host(self) -> np.ndarray:
        return self._counts

    def set_counts(self, counts) -> None:
        # in place: the fused decode path (encoder/native_encoder.py)
        # captures this buffer by reference; rebinding would orphan it
        self._counts[:] = np.asarray(counts, dtype=np.int32)
        self._device_counts = None
        self._wire_itemsize = None

    def invalidate_upload(self) -> None:
        """Drop any cached device copy of the counts — a tail demotion
        (resilience/ladder.py) re-routes the upload to ``tail_device``,
        and a cached default-device array would silently pin the fused
        tail back on the path that just failed."""
        self._device_counts = None


def run_tuned_slab(tuner, static_choice: str, n_rows: int, width: int,
                   plan_kernel, exec_kernel, exec_scatter, block) -> str:
    """Shared driver for one slab of the autotune protocol.

    Used by both the single-device and the dp-sharded accumulators so the
    choose → execute → report_skew/complete sequencing (subtle: timing
    must start before host planning, a skewed kernel plan must clear the
    timing flag, stats publish after every slab) lives in exactly one
    place.  ``plan_kernel() -> plan | None`` (None = skew; only the MXU
    matmul layout can skew — the Pallas CSR plan pads nothing),
    ``exec_kernel(plan)`` / ``exec_scatter()`` run the slab, ``block()``
    forces completion for an honest timing sample.  Returns the strategy
    key actually used.
    """
    if tuner is not None:
        chosen, timing = tuner.choose(n_rows, width)
    else:
        chosen, timing = static_choice, False
    t0 = time.perf_counter()           # before host planning: the kernel
    plan = None                        # number must be end-to-end
    skewed = False
    if chosen != "scatter":
        plan = plan_kernel()
        if plan is None:               # skew (padding blowup): scatter
            skewed = True
            if tuner is not None:
                tuner.report_skew()
                timing = False
    if plan is not None:
        exec_kernel(plan)
        key = chosen
    else:
        exec_scatter()
        key = "scatter"
    if tuner is not None and not skewed:
        if timing:
            block()
            tuner.complete((time.perf_counter() - t0) / (n_rows * width))
        else:
            tuner.complete()
    # per-slab observability: a child span under the backend's
    # pileup_dispatch span (same thread), a slab-seconds histogram per
    # strategy, and — once the autotuner locks — the trial's verdict as
    # a structured gauge.  Non-timing slabs measure dispatch, not device
    # compute (dispatches are async); the timed slabs blocked above.
    dt = time.perf_counter() - t0
    obs.tracer().complete("slab", t0, strategy=key, n_rows=n_rows,
                          width=width, skewed=skewed, timed=timing)
    reg = obs.metrics()
    reg.observe(f"pileup/slab_sec/{key}", dt)
    reg.add("pileup/slabs", 1)
    if tuner is not None and tuner.stats is not None:
        reg.gauge("pileup/autotune").set_info(dict(tuner.stats))
    return key


class PileupAccumulator:
    """Streaming accumulator for one device (sharded use lives in parallel/).

    Four strategies (``strategy``):

    * ``"scatter"``: XLA scatter-add — the semantics oracle, and the
      automatic fallback when per-tile padding would explode (skewed
      coverage) or a bucket is tiny;
    * ``"pallas"``: the tile-CSR VMEM histogram kernel
      (``ops.pallas_pileup``) — duplicate positions accumulate at VPU
      speed instead of serializing an HBM scatter; measured 5-9x the
      scatter rate on a v5e chip (PERF.md round 5);
    * ``"mxu"``: one-hot matmul + overlap-add (``ops.mxu_pileup``,
      compact slot transfer) — RETIRED from auto on TPU backends: its
      ``[E, TP]`` start one-hot has density 1/TP, so it pays ``6*TP``
      MACs per counted cell and measured ~3x slower than scatter
      end-to-end on the chip (round-4 verdict; PERF.md "MXU
      retirement").  Kept as an explicit strategy: it is the only
      device formulation whose FLOPs land on the systolic array, and
      the CPU-mesh test surface pins its semantics;
    * ``"auto"``: ONLINE AUTOTUNE via ``PileupAutoTuner`` (shared with the
      dp-sharded accumulator, parallel/dp.py).  Rather than hard-coding a
      winner that depends on the runtime (round 1's padded-transfer MXU
      layout won on-device microbenchmarks ~11x yet lost end-to-end
      through the tunneled link), auto measures each strategy on early
      steady-state slabs — warm a strategy on one slab, time it on the
      NEXT slab of the same shape (so jit compilation never pollutes the
      number), scatter first, then the device kernel (pallas on real
      TPUs, mxu elsewhere) — and locks in the winner by per-cell
      throughput from then on.  The kernel measurement starts before
      host planning, so it is honestly end-to-end (host plan + transfer
      + device); a trial that keeps hitting skewed slabs gives up after
      ``MAX_SKEW_RETRIES`` and locks in scatter.  Runs too small to
      finish the trial stay on scatter; every trial slab still
      accumulates exactly (all strategies are exact), so the tuning is
      free of correctness cost.
    """

    def __init__(self, total_len: int, device=None, strategy: str = "auto",
                 wire: str = "packed5"):
        from . import mxu_pileup, pallas_pileup

        self.total_len = total_len
        self.device = device
        self.strategy = strategy
        #: resolved row wire codec (sam2consensus_tpu/wire): the backend
        #: passes the run-level ``--wire`` decision; "delta8" compresses
        #: every staged/shipped slab, with per-slab packed5 fallback
        self.wire = wire
        self._tile = mxu_pileup.TILE_POSITIONS
        # position axis padded to whole tiles; the scatter path's
        # sacrificial row (index total_len) lives inside the pad.  THE
        # shared definition (padded_total_len) — the serve-mode prewarm
        # compiles against the same counts shape, so a drift here would
        # silently turn prewarm into dead weight
        self.padded_len = padded_total_len(total_len)
        # the mem_alloc fault site: the device count-tensor allocation
        # boundary (the one ops/mxu_pileup.py's HBM-OOM note names).
        # Raises InjectedOomError -> CAPACITY, so the forensic-dump +
        # split/demote path is testable without a real OOM; the host
        # rung allocates no device tensor and carries no site.
        fault_check("mem_alloc")
        counts = jnp.zeros((self.padded_len, NUM_SYMBOLS), dtype=jnp.int32)
        if device is not None:
            counts = jax.device_put(counts, device)
        self._counts = counts
        memplane.track_obj("counts", self,
                           self.padded_len * NUM_SYMBOLS * 4)
        self.strategy_used: dict = {}
        self.bytes_h2d = 0                 # wire accounting for bench
        self._mxu_rows_real = 0            # occupancy accounting: run
        self._mxu_rows_padded = 0          # aggregate, not last-slab
        # the pallas kernel compiles for the real TPU; anywhere else
        # (CPU tests, cpu-fallback bench) it runs in interpret mode
        plat = (device.platform if device is not None
                else jax.default_backend())
        self._pallas_interpret = plat != "tpu"
        self._pallas_tile = pallas_pileup.TILE_POSITIONS
        self._tuner = PileupAutoTuner(
            kernel="pallas" if plat == "tpu" else "mxu") \
            if strategy == "auto" else None

    def sync(self) -> None:
        """Block until every dispatched scatter/matmul has landed in the
        count tensor.  Profiling hook (S2C_SYNC_ACCUMULATE): dispatches
        are async, so without a barrier the accumulate phase's clock
        stops while the device queue is still draining.  A one-element
        fetch, not block_until_ready — the tunneled runtime returns
        early from the latter (same reason run_tuned_slab fetches)."""
        np.asarray(self._counts[0, 0])

    def stage(self, batch: SegmentBatch) -> None:
        """Device-stage a batch's bucket operands.

        Called from the decode prefetch thread (backends/jax_backend.py
        ``_Prefetcher``): nibble-packing and ``device_put`` here overlap
        this batch's h2d transfer with the consumer's dispatch of the
        PREVIOUS batch — the transfers otherwise serialize on the link,
        which round-3 bench profiles showed capping the device pileup at
        ~half the link rate (ecoli `pileup_dispatch_sec`).

        A device failure here (the ``device_put`` / ``wire_encode``
        injection sites) is caught by the staging pipeline, which
        invalidates the batch's staging slot and delivers it unstaged —
        the consumer's own encode + transfer then meets the same
        failure under the retry policy (resilience/)."""
        fault_check("device_put")
        for w, (starts, codes) in batch.buckets.items():
            if self.wire == "delta8":
                # canonical (sorted) row order, written back into the
                # batch so the consumer's host-side kernel planning sees
                # exactly the rows the staged decode will produce
                starts, codes = wire_codec.canonicalize_rows(starts,
                                                             codes)
                batch.buckets[w] = (starts, codes)
            batch.staged[w] = self._ship_slab(starts, codes)

    def _ship_slab(self, starts, codes) -> StagedSlab:
        """Encode + device_put one bucket's rows under the run's wire
        codec; returns the StagedSlab whose operands ``_consume_slab``
        turns back into (starts_dev, packed_dev)."""
        raw = wire_codec.packed5_slab_bytes(len(starts), codes.shape[1])
        slab = encode_wire_slab(self.wire, starts, codes)
        if slab is not None:
            ops = tuple(jax.device_put(a, self.device)
                        for a in slab.arrays())
            staged = StagedSlab("delta8", ops, slab.wire_bytes, raw,
                                meta=(slab.width, slab.sentinel))
        else:
            packed = pack_nibbles(codes)
            staged = StagedSlab(
                "packed5",
                (jax.device_put(starts, self.device),
                 jax.device_put(packed, self.device)),
                starts.nbytes + packed.nbytes, raw)
        # staging-slot residency: released when the slab is consumed
        # and dropped (observability/memplane.py)
        memplane.track_obj("wire_staging", staged, staged.nbytes)
        return staged

    def _consume_slab(self, staged: StagedSlab):
        """(starts_dev, packed_dev) from a shipped slab — the delta8
        unpack stage runs here, on device, reconstituting the exact
        legacy operands before any kernel sees them."""
        from ..wire import device as wire_device

        if not staged.billed:
            # bill once per slab, not per attempt: a retry / ladder
            # replay re-consumes the same device operands without the
            # bytes re-crossing the link
            staged.billed = True
            self.bytes_h2d += staged.nbytes
            account_h2d(staged.nbytes)
            account_wire(staged.codec, staged.nbytes, staged.raw_nbytes)
            if staged.codec == "delta8":
                # recorded in strategy_used only when the codec engaged
                # — the packed5 default is the absence of the key (and
                # the wire/* metrics carry the full story either way)
                self.strategy_used["wire_delta8"] = \
                    self.strategy_used.get("wire_delta8", 0) + 1
        if staged.codec == "delta8":
            width, sentinel = staged.meta
            return wire_device.decode_to_packed(
                *staged.operands, width=width, sentinel=sentinel)
        return staged.operands

    def add(self, batch: SegmentBatch) -> None:
        from . import mxu_pileup, pallas_pileup

        fault_check("pileup_dispatch")
        kernel_name = (self._tuner.kernel if self._tuner is not None
                       else self.strategy)
        for w, (starts, codes) in sorted(batch.buckets.items()):
            staged = batch.staged.get(w)
            if self.wire == "delta8" and staged is None:
                # unstaged delta8 slab: canonicalize here (the staging
                # path already did, and wrote the batch back)
                starts, codes = wire_codec.canonicalize_rows(starts,
                                                             codes)
            # slab pow2 padding appends a contiguous all-PAD tail at
            # start 0; those rows count nothing (scatter self-redirects
            # them) but would pile into MXU tile 0 and trip the skew
            # gate.  Find the all-PAD suffix with two vectorized scans
            # (first-cell prefilter, then full rows over the candidate
            # tail only) and plan/run the MXU path on real rows only.
            codes_np = np.asarray(codes)
            nz = np.nonzero(codes_np[:, 0] != PAD_CODE)[0]
            tail_lo = int(nz[-1]) + 1 if len(nz) else 0
            row_pad = (codes_np[tail_lo:] == PAD_CODE).all(axis=1)
            nz2 = np.nonzero(~row_pad)[0]
            n_real = tail_lo + (int(nz2[-1]) + 1 if len(nz2) else 0)
            # round the working row count back UP to a power of two: jit
            # trace keys are operand shapes, so slicing to the exact
            # n_real would compile per slab and break the autotuner's
            # warm/time shape pairing; pow2 keeps the cache O(log) while
            # still excluding the bulk of the pad tail from MXU tile 0
            n_rows = min(len(starts),
                         1 << max(3, (n_real - 1).bit_length())) \
                if n_real else 0

            def put_operands():
                """(starts_dev, packed_dev): staged by the prefetch
                thread when available, encoded + transferred here
                otherwise (same wire codec either way)."""
                if staged is not None:
                    return self._consume_slab(staged)
                fault_check("device_put")
                return self._consume_slab(self._ship_slab(starts, codes))

            def plan_mxu():
                if n_rows == 0:
                    return None
                # auto keeps the tight blowup gate (padding waste loses
                # the tuner trial anyway); an EXPLICIT --pileup mxu
                # tolerates more padding before falling back — the user
                # asked for the MXU formulation, and 4-16x lane waste is
                # an efficiency question, not a memory-safety one
                return mxu_pileup.plan_slots(
                    np.asarray(starts)[:n_rows], w, self.padded_len,
                    self._tile,
                    max_blowup=(16.0 if self.strategy == "mxu"
                                else mxu_pileup.MAX_BLOWUP),
                    coarse=(self._tuner is not None
                            and self._tuner.winner is None))

            def exec_mxu(plan):
                st, pk = put_operands()
                self.bytes_h2d += plan.slot.nbytes
                account_h2d(plan.slot.nbytes)
                # occupancy accounting for the bench: padded/real row
                # ratio aggregated over the run (a last-slab snapshot
                # would report whichever bucket ran last) — and only for
                # runs whose COMMITTED strategy is mxu: a locked-scatter
                # autotune run must not report occupancy for two trial
                # slabs that did ~0% of the work
                if self.strategy == "mxu" or (
                        self._tuner is not None
                        and self._tuner.winner == "mxu"):
                    self._mxu_rows_real += n_real
                    self._mxu_rows_padded += (plan.n_tiles
                                              * plan.rows_per_tile)
                    self.strategy_used["mxu_blowup"] = round(
                        self._mxu_rows_padded / self._mxu_rows_real, 3)
                self._counts = mxu_pileup.pileup_mxu_packed(
                    self._counts, st[:n_rows], pk[:n_rows],
                    jnp.asarray(plan.slot), tile=self._tile,
                    n_tiles=plan.n_tiles,
                    rows_per_tile=plan.rows_per_tile, width=plan.width)

            def plan_pallas():
                if n_rows == 0:
                    return None
                if w % 2:
                    # odd widths widen under the nibble wire (pack_nibbles
                    # appends a PAD column, so unpack returns W+1 columns)
                    # and would shape-mismatch the kernel at trace time;
                    # scatter handles them — same guard as the sp/dpsp
                    # routers' _routed_kernel_add.  Encoder buckets are
                    # even today; this covers a future odd halo-split
                    # bucket reaching the single-device path.
                    return None
                if pallas_pileup._cw(w) * 2 > self._pallas_tile:
                    return None        # overhang carry needs W <= TP/2
                return pallas_pileup.plan_rows(
                    np.asarray(starts)[:n_rows].astype(np.int64), w,
                    self.padded_len, self._pallas_tile)

            def exec_pallas(plan):
                st, pk = put_operands()
                self.bytes_h2d += (plan.rank.nbytes + plan.blk_lo.nbytes
                                   + plan.blk_n.nbytes)
                account_h2d(plan.rank.nbytes + plan.blk_lo.nbytes
                            + plan.blk_n.nbytes)
                self._counts = pallas_pileup.pileup_pallas_packed(
                    self._counts, st[:n_rows], pk[:n_rows],
                    jax.device_put(plan.rank, self.device),
                    tile=self._pallas_tile, n_tiles=plan.n_tiles,
                    width=w, row_block=plan.row_block,
                    max_blocks=plan.max_blocks,
                    n_rows_padded=plan.n_rows_padded,
                    blk_lo=jax.device_put(plan.blk_lo, self.device),
                    blk_n=jax.device_put(plan.blk_n, self.device),
                    interpret=self._pallas_interpret)

            def exec_scatter():
                st, pk = put_operands()
                for lo, hi in iter_row_slices(n_rows, w):
                    # counted dispatch: classifies each scatter call as
                    # a jit-cache hit or miss in the run's registry —
                    # the serve-mode amortization proof rides on it
                    self._counts = jitcache.counted_call(
                        _scatter_segments_packed, self._counts,
                        st[lo:hi], pk[lo:hi], self.total_len)

            if n_rows == 0:
                continue               # all-pad bucket: counts nothing
            # completion is forced with a one-element fetch, NOT
            # block_until_ready: the latter returns early over the axon
            # tunnel (tools/tunnel_probe.py) and would bias the trial
            # toward whichever strategy does more device-side work
            key = run_tuned_slab(
                self._tuner, self.strategy, n_rows, w,
                plan_pallas if kernel_name == "pallas" else plan_mxu,
                exec_pallas if kernel_name == "pallas" else exec_mxu,
                exec_scatter,
                lambda: np.asarray(self._counts[0, 0]))
            if self._tuner is not None and self._tuner.stats is not None:
                self.strategy_used["autotune"] = self._tuner.stats
            key = f"{key}_w{w}"
            self.strategy_used[key] = self.strategy_used.get(key, 0) + 1

    @property
    def counts(self) -> jax.Array:
        """Valid counts, ``[total_len, 6]`` (tile pad rows dropped)."""
        return self._counts[: self.total_len]

    def counts_host(self):
        """Valid counts on host, ``[total_len, 6]`` (same surface as the
        sharded accumulator, for checkpointing).  The full-tensor pull
        (checkpoint snapshots, ladder demotions, paranoid cross-checks)
        bills the d2h choke point — these were the unaccounted
        host-vote return paths."""
        from ..wire import fetch_d2h

        return fetch_d2h(self._counts)[: self.total_len]

    def set_counts(self, counts) -> None:
        """Restore from a checkpoint: counts of shape [total_len, 6]."""
        padded = np.zeros((self.padded_len, NUM_SYMBOLS), dtype=np.int32)
        padded[: self.total_len] = np.asarray(counts)
        self._counts = jnp.asarray(padded)
