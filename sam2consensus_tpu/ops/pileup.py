"""Device-side pileup accumulation: the reference's hot loop as one scatter.

The reference spends ~all wall-clock doing one Python dict increment per
aligned base (``/root/reference/sam2consensus.py:211-218``, SURVEY.md CS3).
Here reads arrive as segment rows — flat-genome start + uint8 code row
(``encoder.events.SegmentBatch``) — and the device expands positions with an
iota and scatter-adds into a flat ``[total_len + 1, 6]`` int32 tensor.  XLA's
scatter accumulates duplicate indices exactly, so read order and sharding
cannot change the result (addition commutes; SURVEY.md §5).

Design note: an earlier COO formulation (one int32 position + one int32 code
per aligned base, expanded on host) was host-transfer-bound — ~8 bytes/base
over the PCIe/tunnel link dominated end-to-end time while the TPU scatter
itself was ~free.  Segment rows move ~1 byte/base and push the expansion
into the compiled program, where it fuses into the scatter's index
computation.

Rows are padded (PAD_CODE) and bucketed to power-of-two shapes so the jit
cache holds O(log²) entries; PAD positions are redirected to the sacrificial
row ``total_len``, which is dropped at read time.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..constants import NUM_SYMBOLS
from ..encoder.events import SegmentBatch


#: cap on expanded scatter cells (rows x width) per device call, bounding the
#: int32 position/code temporaries to ~32MB each even if XLA materializes them
SCATTER_CELL_BUDGET = 1 << 23


def expand_segment_positions(starts: jax.Array, codes: jax.Array,
                             sacrificial) -> tuple:
    """Expand segment rows to flat (pos, code) scatter operands.

    Pure traceable function shared by every consumer of SegmentBatch rows
    (single-device scatter here, the fused model step, the shard_map DP path)
    so PAD/validity semantics cannot drift between them.  PAD cells are
    redirected to the ``sacrificial`` position with code 0.
    """
    w = codes.shape[1]
    pos = starts[:, None] + jax.lax.iota(jnp.int32, w)[None, :]
    valid = codes < NUM_SYMBOLS
    pos = jnp.where(valid, pos, sacrificial)
    code = jnp.where(valid, codes, 0).astype(jnp.int32)
    return pos.reshape(-1), code.reshape(-1)


@partial(jax.jit, donate_argnums=0, static_argnums=3)
def _scatter_segments(counts: jax.Array, starts: jax.Array,
                      codes: jax.Array, sacrificial: int) -> jax.Array:
    pos, code = expand_segment_positions(starts, codes, sacrificial)
    return counts.at[pos, code].add(1)


def iter_row_slices(n_rows: int, width: int, multiple_of: int = 1):
    """Yield (lo, hi) row slices capping hi-lo at SCATTER_CELL_BUDGET cells.

    The step stays a power of two (assuming the budget and width are), so
    pre-padded power-of-two batches stay power-of-two per slice and the jit
    cache stays small; ``multiple_of`` additionally aligns the step for
    even sharding over a device mesh.
    """
    step = max(multiple_of, (SCATTER_CELL_BUDGET // width)
               // multiple_of * multiple_of)
    for lo in range(0, n_rows, step):
        yield lo, min(n_rows, lo + step)


class PileupAccumulator:
    """Streaming accumulator for one device (sharded use lives in parallel/)."""

    def __init__(self, total_len: int, device=None):
        self.total_len = total_len
        self.device = device
        counts = jnp.zeros((total_len + 1, NUM_SYMBOLS), dtype=jnp.int32)
        if device is not None:
            counts = jax.device_put(counts, device)
        self._counts = counts

    def add(self, batch: SegmentBatch) -> None:
        for w, (starts, codes) in sorted(batch.buckets.items()):
            for lo, hi in iter_row_slices(len(starts), w):
                self._counts = _scatter_segments(
                    self._counts, jnp.asarray(starts[lo:hi]),
                    jnp.asarray(codes[lo:hi]), self.total_len)

    @property
    def counts(self) -> jax.Array:
        """Valid counts, ``[total_len, 6]`` (sacrificial row dropped)."""
        return self._counts[:-1]

    def counts_host(self):
        """Valid counts on host, ``[total_len, 6]`` (same surface as the
        sharded accumulator, for checkpointing)."""
        import numpy as np

        return np.asarray(self._counts)[:-1]

    def set_counts(self, counts: jax.Array) -> None:
        """Restore from a checkpoint: counts of shape [total_len, 6]."""
        self._counts = jnp.concatenate(
            [jnp.asarray(counts, dtype=jnp.int32),
             jnp.zeros((1, NUM_SYMBOLS), dtype=jnp.int32)], axis=0)
