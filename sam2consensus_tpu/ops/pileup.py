"""Device-side pileup accumulation: the reference's hot loop as one scatter.

The reference spends ~all wall-clock doing one Python dict increment per
aligned base (``/root/reference/sam2consensus.py:211-218``, SURVEY.md CS3).
Here the same update is ``counts.at[positions, codes].add(1)`` on a flat
``[total_len + 1, 6]`` int32 tensor — XLA lowers it to a vectorized scatter
whose duplicate-index accumulation is exact, so read order and sharding
cannot change the result (addition commutes; SURVEY.md §5).

Chunks arrive padded to a fixed size so the jitted update compiles once:
pad rows point at the sacrificial row ``total_len`` which is dropped at read
time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..encoder.events import PileupChunk


@jax.jit
def _scatter_add(counts: jax.Array, positions: jax.Array,
                 codes: jax.Array) -> jax.Array:
    return counts.at[positions, codes].add(1)


class PileupAccumulator:
    """Streaming accumulator for one device (sharded use lives in parallel/)."""

    def __init__(self, total_len: int, pad_to: int = 1 << 22,
                 device=None):
        self.total_len = total_len
        self.pad_to = pad_to
        self.device = device
        counts = jnp.zeros((total_len + 1, 6), dtype=jnp.int32)
        if device is not None:
            counts = jax.device_put(counts, device)
        self._counts = counts

    def add(self, chunk: PileupChunk) -> None:
        n = len(chunk.positions)
        if n == 0:
            return
        for start in range(0, n, self.pad_to):
            pos = chunk.positions[start:start + self.pad_to]
            code = chunk.codes[start:start + self.pad_to]
            if len(pos) < self.pad_to:
                # pad the tail slice up to a power-of-two bucket so jit
                # compiles O(log) distinct shapes; pad rows write into the
                # sacrificial row (counts[total_len])
                target = max(1024, 1 << (len(pos) - 1).bit_length())
                pad = target - len(pos)
                pos = np.concatenate(
                    [pos, np.full(pad, self.total_len, dtype=np.int32)])
                code = np.concatenate([code, np.zeros(pad, dtype=np.int32)])
            self._counts = _scatter_add(self._counts,
                                        jnp.asarray(pos), jnp.asarray(code))

    @property
    def counts(self) -> jax.Array:
        """Valid counts, ``[total_len, 6]`` (sacrificial row dropped)."""
        return self._counts[:-1]

    def set_counts(self, counts: jax.Array) -> None:
        """Restore from a checkpoint: counts of shape [total_len, 6]."""
        self._counts = jnp.concatenate(
            [jnp.asarray(counts, dtype=jnp.int32),
             jnp.zeros((1, 6), dtype=jnp.int32)], axis=0)
