"""Pallas TPU kernel: the pileup as a tile-CSR VMEM histogram.

This is the round-5 successor to the one-hot-matmul MXU pileup
(``ops.mxu_pileup``).  That formulation put the FLOPs on the systolic
array but paid ``6 * TILE`` MACs per counted cell *by construction* —
its ``[E, TP]`` start one-hot has density ``1/TP``, so at TP=2048 the
MXU multiplied 12k zeros per real cell and the measured end-to-end rate
lost to the plain XLA scatter ~3x (round-4 verdict; PERF.md §"MXU
retirement").  The scatter, in turn, is bounded by XLA's serialized
duplicate-index handling at ~53 M cells/s on a v5e chip.

The histogram the pileup actually is — ``counts[start_r + j,
codes_r[j]] += 1`` (the reference's hot loop,
``/root/reference/sam2consensus.py:211-218``) — wants neither a matmul
nor a serialized scatter.  It wants what this kernel does:

* the host counting-sorts segment rows by **position tile**
  (``start // TP``, the same sort ``mxu_pileup`` planned with) and
  computes, per tile, the range of fixed-size row blocks holding its
  rows (CSR, scalar-prefetched ``blk_lo``/``blk_n`` — the same scheme
  as ``pallas_insertion``); **nothing is padded per tile**, so the
  lane-occupancy question of the MXU layout does not exist here;
* rows ship exactly as the scatter path ships them (4-bit packed codes
  + int32 start, +4 B/row for the dense sort rank) and are re-ordered
  on device by one unique-index row scatter;
* the grid walks ``(tile, row block)``; each step loops its block's
  rows, builds the row's ``[8, W]`` symbol one-hot with one VPU
  compare (PAD unpacks to 15, matches no symbol lane, and so
  self-suppresses — no sacrificial slot), and accumulates it into a
  ``[8, TP + W]`` int32 VMEM accumulator at the row's tile-local
  offset.  Duplicate positions hit VMEM at VPU speed instead of
  serializing an HBM scatter;
* rows extending past the tile land in the accumulator's ``[TP,
  TP+W)`` overhang, which is **carried in scratch to the next grid
  step** (TPU grids iterate sequentially, tiles ascending) and folded
  into that tile's head — so the kernel emits dense ``[NT, 8, TP]``
  counts with no separate overlap-add pass;
* boundary row blocks shared by adjacent tiles are visited by both;
  rows outside the visiting tile mask to zero (their local offset
  falls outside ``[0, TP)``), exactly like the insertion kernel's
  key-block discipline.

Everything is integer-exact (int32 accumulation).  ``interpret=True``
runs the same kernel on CPU for CI; equivalence against the scatter
path is pinned by tests/test_pallas_pileup.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: positions per tile.  The kernel's compute scales with rows, not TP
#: (unlike the retired MXU matmul, whose MACs scaled as 6*TP per cell),
#: so TP trades only VMEM footprint (~4.2 MB accumulator at 2^17)
#: against boundary-block overlap; on-chip sweep (v5e, L=4.6M, 131k
#: rows): 2^15 483, 2^17 573-735 Mcells/s.
TILE_POSITIONS = 1 << 17

#: cells (rows x width) per row block: bounds the block's VMEM window.
#: On-chip sweep at W=128: 2^16 573, 2^17 735, 2^18 488 Mcells/s.
ROW_BLOCK_CELLS = 1 << 17

#: symbol lanes (6 real + 2 sublane pad — int32 tiles are 8x128)
SYM_LANES = 8


def _row_block(width: int) -> int:
    """Rows per grid block for a bucket width (multiple of 8, >= 8)."""
    return max(8, (ROW_BLOCK_CELLS // max(width, 1)) // 8 * 8)


def _cw(width: int) -> int:
    """Carry width: the overhang region rounded up to whole lane tiles
    (Mosaic vector stores must start 128-aligned, so the accumulator is
    addressed in 128-lane units)."""
    return -(-width // 128) * 128


def _kernel(blk_lo_ref, blk_n_ref, starts_ref, codes_ref, out_ref,
            acc_ref, carry_ref, *, tile: int, width: int, row_block: int):
    t = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)
    cw = _cw(width)
    ww = cw + 128                       # rolled one-hot window width

    @pl.when(jnp.logical_and(t == 0, j == 0))
    def _init_carry():
        carry_ref[...] = jnp.zeros_like(carry_ref)

    @pl.when(j == 0)
    def _init_acc():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j < blk_n_ref[t])
    def _accumulate():
        sym = jax.lax.broadcasted_iota(jnp.int32, (SYM_LANES, width), 0)
        base = t * tile

        def body(r, _):
            start = starts_ref[0, 0, r]
            local = start - base
            # rows of neighboring tiles sharing this boundary block mask
            # to zero; their own tile's grid steps count them
            ok = jnp.logical_and(local >= 0, local < tile)
            lc = jnp.where(ok, local, 0)
            # Mosaic needs 128-aligned dynamic lane offsets: store at
            # the aligned base below lc and lane-rotate the one-hot up
            # by the remainder (the rotate is a native VPU permute)
            a = lc // 128
            m = lc - a * 128
            row = codes_ref[0, pl.ds(r, 1), :]              # [1, W]
            oh = jnp.where(ok, (row == sym).astype(jnp.int32), 0)
            rolled = pltpu.roll(
                jnp.pad(oh, ((0, 0), (0, ww - width))), m, 1)
            acc_ref[:, pl.ds(pl.multiple_of(a * 128, 128), ww)] += rolled
            return 0

        jax.lax.fori_loop(0, row_block, body, 0)

    @pl.when(j == nb - 1)
    def _emit():
        # fold the PREVIOUS tile's overhang into this tile's head, then
        # hand this tile's overhang to the next grid step via scratch
        # (grids iterate tiles in ascending order on TPU)
        out_ref[0, :, :cw] = acc_ref[:, :cw] + carry_ref[...]
        out_ref[0, :, cw:] = acc_ref[:, cw:tile]
        carry_ref[...] = acc_ref[:, tile:tile + cw]


@functools.partial(jax.jit, static_argnames=(
    "tile", "n_tiles", "width", "row_block", "max_blocks", "interpret"))
def _pileup_call(starts2, codes3, blk_lo, blk_n, *, tile, n_tiles, width,
                 row_block, max_blocks, interpret=False):
    """[NT, 8, TP] int32 tile counts from sorted row blocks."""
    n_row_blocks = codes3.shape[0]
    cw = _cw(width)
    kernel = functools.partial(_kernel, tile=tile, width=width,
                               row_block=row_block)

    def rb_index(t, j, blk_lo, blk_n):
        # steps past the tile's real block range (j >= blk_n, compute
        # skipped) clamp to the LAST real block, not the global tail:
        # repeating the previous step's index lets pallas skip the DMA
        # entirely, so skewed/sorted slabs (few dense tiles driving a
        # large max_blocks axis) don't pay dead transfers on the rest
        # outer clamp: an empty tile at the stream's end has
        # blk_lo == n_row_blocks (cumsum boundary), which must not index
        return (jnp.minimum(
            jnp.minimum(blk_lo[t] + j,
                        blk_lo[t] + jnp.maximum(blk_n[t] - 1, 0)),
            n_row_blocks - 1), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles, max_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, row_block), rb_index,
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, row_block, width), rb_index),
        ],
        out_specs=pl.BlockSpec((1, SYM_LANES, tile),
                               lambda t, j, lo, n: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((SYM_LANES, tile + cw), jnp.int32),
            pltpu.VMEM((SYM_LANES, cw), jnp.int32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, SYM_LANES, tile),
                                       jnp.int32),
        interpret=interpret,
    )(blk_lo, blk_n, starts2, codes3)


class RowPlan(NamedTuple):
    """Host-side CSR plan: dense sort rank + per-tile row-block ranges.

    Nothing is padded per tile — ``rank`` is a permutation of
    ``[0, N)`` (plus a tail of PAD rows up to the row-block multiple),
    so the kernel's only redundancy is boundary blocks shared by two
    tiles.
    """
    rank: np.ndarray       # [N] int32: row -> position in tile-sorted order
    blk_lo: np.ndarray     # [NT] int32 first row block per tile
    blk_n: np.ndarray      # [NT] int32 row blocks per tile
    n_tiles: int
    n_rows_padded: int     # row-block multiple
    row_block: int
    max_blocks: int        # grid's row-block axis (pow2-rounded)


def plan_rows(starts: np.ndarray, width: int, padded_len: int,
              tile: int = TILE_POSITIONS) -> RowPlan:
    """Counting-sort rows by position tile; CSR block ranges per tile."""
    n = len(starts)
    n_tiles = max(1, -(-padded_len // tile))
    row_block = _row_block(width)
    tile_of = starts // tile
    order = np.argsort(tile_of, kind="stable")
    rank = np.empty(n, dtype=np.int32)
    rank[order] = np.arange(n, dtype=np.int32)
    per_tile = np.bincount(tile_of, minlength=n_tiles)
    hi = np.cumsum(per_tile)
    lo = hi - per_tile
    blk_lo = (lo // row_block).astype(np.int32)
    last = np.maximum(hi - 1, lo)
    blk_n = np.where(per_tile > 0,
                     last // row_block + 1 - blk_lo, 0).astype(np.int32)
    mb = int(blk_n.max(initial=1))
    # pow2 grid rounding: the row-block axis is a static grid dimension,
    # so per-slab max variation would otherwise recompile every slab
    max_blocks = 1 << max(0, (max(mb, 1) - 1).bit_length())
    n_rows_padded = -(-max(n, 1) // row_block) * row_block
    return RowPlan(rank, blk_lo, blk_n, n_tiles, n_rows_padded,
                   row_block, max_blocks)


def local_tile_counts(starts: jax.Array, packed: jax.Array,
                      rank: jax.Array, blk_lo: jax.Array,
                      blk_n: jax.Array, *, tile: int, n_tiles: int,
                      width: int, row_block: int, max_blocks: int,
                      n_rows_padded: int, out_len: int,
                      interpret: bool = False) -> jax.Array:
    """Traceable core: one 4-bit-packed row slab -> dense ``[out_len, 6]``.

    Shared by the single-device accumulator and the sharded (dp/sp/dpsp)
    shard_map bodies, where ``starts`` are shard-local coordinates.  The
    tile-sorted order is materialized on device by one unique-index row
    scatter (``rank`` is dense — no padding blowup, unlike the retired
    MXU slot layout).
    """
    from .pileup import unpack_nibbles

    codes = unpack_nibbles(packed).astype(jnp.int32)        # [N, W]
    sorted_codes = jnp.full((n_rows_padded, width), 15,
                            dtype=jnp.int32).at[rank].set(codes)
    sorted_starts = jnp.zeros((n_rows_padded,),
                              dtype=jnp.int32).at[rank].set(starts)
    # PAD-filled tail rows keep start 0: they visit tile 0 in-range but
    # their codes (15) match no symbol lane, adding zero
    n_row_blocks = n_rows_padded // row_block
    out = _pileup_call(
        sorted_starts.reshape(n_row_blocks, 1, row_block),
        sorted_codes.reshape(n_row_blocks, row_block, width),
        blk_lo, blk_n, tile=tile, n_tiles=n_tiles, width=width,
        row_block=row_block, max_blocks=max_blocks, interpret=interpret)
    return jnp.transpose(out, (0, 2, 1)).reshape(
        n_tiles * tile, SYM_LANES)[:out_len, :6]


@functools.partial(jax.jit, donate_argnums=0, static_argnames=(
    "tile", "n_tiles", "width", "row_block", "max_blocks", "n_rows_padded",
    "interpret"))
def pileup_pallas_packed(counts: jax.Array, starts: jax.Array,
                         packed: jax.Array, rank: jax.Array, *, tile: int,
                         n_tiles: int, width: int, row_block: int,
                         max_blocks: int, n_rows_padded: int,
                         blk_lo: jax.Array, blk_n: jax.Array,
                         interpret: bool = False) -> jax.Array:
    """Accumulate a 4-bit-packed row slab into ``counts`` [>=NT*TP, 6].

    Rows ship exactly as the scatter path ships them (+4 B/row rank).
    """
    return counts + local_tile_counts(
        starts, packed, rank, blk_lo, blk_n, tile=tile, n_tiles=n_tiles,
        width=width, row_block=row_block, max_blocks=max_blocks,
        n_rows_padded=n_rows_padded, out_len=counts.shape[0],
        interpret=interpret)


class StackedRowPlan(NamedTuple):
    """Uniform-shape per-device CSR plans for SPMD (shard_map) use.

    ``rank``/``blk_lo``/``blk_n`` carry one leading device axis; the
    static fields (row_block, max_blocks, n_rows_padded) are maxima over
    the devices so every shard traces one common shape.
    """
    rank: np.ndarray       # [D, R] int32
    blk_lo: np.ndarray     # [D, NT] int32
    blk_n: np.ndarray      # [D, NT] int32
    n_tiles: int
    n_rows_padded: int
    row_block: int
    max_blocks: int


def plan_rows_stacked(starts2d: np.ndarray, width: int, local_len: int,
                      tile: int = TILE_POSITIONS) -> StackedRowPlan:
    """Per-device CSR plans over a common local coordinate space.

    ``starts2d`` is ``[D, R]`` shard-local starts (the sp/dpsp routers'
    dense slot grids, or dp's even row chunks); rows a device does not
    own must be PAD rows parked at start 0 (they count nothing).
    """
    d, r = starts2d.shape
    plans = [plan_rows(starts2d[i].astype(np.int64), width, local_len,
                       tile) for i in range(d)]
    row_block = plans[0].row_block
    max_blocks = max(p.max_blocks for p in plans)
    n_rows_padded = max(p.n_rows_padded for p in plans)
    return StackedRowPlan(
        np.stack([p.rank for p in plans]),
        np.stack([p.blk_lo for p in plans]),
        np.stack([p.blk_n for p in plans]),
        plans[0].n_tiles, n_rows_padded, row_block, max_blocks)


def pileup_pallas_host(counts_len: int, starts: np.ndarray,
                       codes: np.ndarray, tile: int = TILE_POSITIONS,
                       interpret: bool = False) -> np.ndarray:
    """Convenience wrapper (tests/microbench): plan + run one slab
    against zero counts; returns host ``[counts_len, 6]``."""
    from .pileup import pack_nibbles

    width = codes.shape[1]
    assert width % 2 == 0, "pallas pileup rides the nibble wire (even W)"
    padded_len = -(-(counts_len + 1) // tile) * tile
    plan = plan_rows(starts.astype(np.int64), width, padded_len, tile)
    counts = jnp.zeros((counts_len, 6), dtype=jnp.int32)
    out = pileup_pallas_packed(
        counts, jnp.asarray(starts.astype(np.int32)),
        jnp.asarray(pack_nibbles(codes)), jnp.asarray(plan.rank),
        tile=tile, n_tiles=plan.n_tiles, width=width,
        row_block=plan.row_block, max_blocks=plan.max_blocks,
        n_rows_padded=plan.n_rows_padded,
        blk_lo=jnp.asarray(plan.blk_lo), blk_n=jnp.asarray(plan.blk_n),
        interpret=interpret)
    return np.asarray(out)
