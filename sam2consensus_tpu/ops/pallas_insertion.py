"""Pallas TPU kernel: insertion-table build as a segmented one-hot dot.

The insertion "mini-alignment" table (SURVEY.md §2b; reference semantics at
``/root/reference/sam2consensus.py:256-311``) is a segmented reduction of
(site key, column, base) events into a ``[K, C, 6]`` count table.  The
pure-JAX path scatters (``ops.insertions.build_insertion_table``); this
kernel instead contracts one-hot matrices on the MXU, CSR-style:

* the host sorts events by site key and computes, per 128-key block, the
  range of 512-event blocks that can contain its events (scalar-prefetched
  ``blk_lo``/``blk_n``);
* the grid walks ``(key block, event block)``; each step builds
  ``A[e, k] = [key_e == block_base + k]`` and
  ``B[e, m] = [col_e*6 + code_e == m]`` as f32 one-hots and accumulates
  ``AᵀB`` into a VMEM scratch block — all shapes static and lane-aligned,
  so Mosaic needs no dynamic-offset vector stores;
* events belonging to other key blocks one-hot to zero rows (keys are
  disjoint across blocks), so the event-range skipping is purely a
  performance device, not a correctness one — except for clamped re-visits
  of the last event block, which the ``j < blk_n`` gate suppresses;
* f32 accumulation is exact for counts below 2^24 (the table is per-run
  event counts; the int32 cast on write would overflow long before f32
  loses integers).

``interpret=True`` runs the same kernel on CPU for CI (SURVEY.md §4);
equivalence against the scatter path is pinned by
tests/test_pallas_insertion.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..constants import NUM_SYMBOLS

#: keys per grid block (lane-aligned)
KEY_BLOCK = 128
#: events per grid block
EVENT_BLOCK = 512


def _kernel(blk_lo_ref, blk_n_ref, key_ref, cc_ref, out_ref, acc_ref, *,
            c6p: int, n_event_blocks: int):
    i = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j < blk_n_ref[i])
    def _accumulate():
        key = key_ref[0]                                     # [EB, 1] int32
        cc = cc_ref[0]                                       # [EB, 1] int32
        local = key - i * KEY_BLOCK
        a = (local == jax.lax.broadcasted_iota(
            jnp.int32, (EVENT_BLOCK, KEY_BLOCK), 1)).astype(jnp.float32)
        b = (cc == jax.lax.broadcasted_iota(
            jnp.int32, (EVENT_BLOCK, c6p), 1)).astype(jnp.float32)
        acc_ref[...] += jax.lax.dot_general(
            a, b, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nb - 1)
    def _emit():
        out_ref[0] = acc_ref[...].astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("kp", "c6p", "max_blocks", "interpret"))
def _table_call(key3, cc3, blk_lo, blk_n, *, kp, c6p, max_blocks,
                interpret=False):
    n_event_blocks = key3.shape[0]
    kernel = functools.partial(_kernel, c6p=c6p,
                               n_event_blocks=n_event_blocks)

    def ev_index(i, j, blk_lo, blk_n):
        return (jnp.minimum(blk_lo[i] + j, n_event_blocks - 1), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(kp // KEY_BLOCK, max_blocks),
        in_specs=[
            pl.BlockSpec((1, EVENT_BLOCK, 1), ev_index),
            pl.BlockSpec((1, EVENT_BLOCK, 1), ev_index),
        ],
        out_specs=pl.BlockSpec((1, KEY_BLOCK, c6p),
                               lambda i, j, lo, n: (i, 0, 0)),
        scratch_shapes=[pltpu.VMEM((KEY_BLOCK, c6p), jnp.float32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((kp // KEY_BLOCK, KEY_BLOCK, c6p),
                                       jnp.int32),
        interpret=interpret,
    )(blk_lo, blk_n, key3, cc3)


class EventPlan(NamedTuple):
    """Host-side kernel plan: key-sorted event blocks + CSR block ranges."""
    key3: np.ndarray       # [NEB, EVENT_BLOCK, 1] int32, key-sorted
    cc3: np.ndarray        # [NEB, EVENT_BLOCK, 1] int32, col*6+code
    blk_lo: np.ndarray     # [kp/KEY_BLOCK] int32 first event block per key blk
    blk_n: np.ndarray      # [kp/KEY_BLOCK] int32 event blocks per key blk
    kp: int                # padded key count (KEY_BLOCK multiple)
    c6p: int               # padded flattened column-code lanes
    max_blocks: int        # grid's event-block axis (fullest key block)


def plan_events(ev_key: np.ndarray, ev_col: np.ndarray,
                ev_code: np.ndarray, n_keys: int, cp: int) -> EventPlan:
    """Sort events by key and compute per-key-block event ranges.

    ``cp`` is the (possibly already padded) column count of the table the
    caller wants back; lanes pad to ``c6p = roundup(cp*6, 128)``.
    """
    e = len(ev_key)
    order = np.argsort(ev_key, kind="stable")
    key_s = ev_key[order].astype(np.int32)
    cc_s = (ev_col[order] * NUM_SYMBOLS + ev_code[order]).astype(np.int32)

    kp = max(KEY_BLOCK, -(-n_keys // KEY_BLOCK) * KEY_BLOCK)
    c6p = max(128, -(-(cp * NUM_SYMBOLS) // 128) * 128)
    ep = max(EVENT_BLOCK, -(-e // EVENT_BLOCK) * EVENT_BLOCK)
    if ep != e:
        # pad keys with int32 max: keeps key_s ascending (searchsorted
        # below relies on it) and matches no key block's local iota
        key_s = np.concatenate(
            [key_s, np.full(ep - e, np.iinfo(np.int32).max,
                            dtype=np.int32)])
        cc_s = np.concatenate([cc_s, np.zeros(ep - e, dtype=np.int32)])
    n_event_blocks = ep // EVENT_BLOCK

    bounds = np.arange(0, kp + KEY_BLOCK, KEY_BLOCK)
    ev_bounds = np.searchsorted(key_s, bounds, side="left")
    blk_lo = (ev_bounds[:-1] // EVENT_BLOCK).astype(np.int32)
    last = np.maximum(ev_bounds[1:] - 1, ev_bounds[:-1])
    blk_hi = np.where(ev_bounds[1:] > ev_bounds[:-1],
                      last // EVENT_BLOCK + 1, blk_lo)
    blk_n = (blk_hi - blk_lo).astype(np.int32)
    return EventPlan(
        key_s.reshape(n_event_blocks, EVENT_BLOCK, 1),
        cc_s.reshape(n_event_blocks, EVENT_BLOCK, 1),
        blk_lo, blk_n, kp, c6p, max(1, int(blk_n.max(initial=1))))


def build_insertion_table_pallas(ev_key: np.ndarray, ev_col: np.ndarray,
                                 ev_code: np.ndarray, n_keys: int,
                                 max_cols: int,
                                 interpret: bool = False) -> jax.Array:
    """Segmented-reduce insertion events into an int32 ``[n_keys, C, 6]``.

    Same contract as ``ops.insertions.build_insertion_table`` applied to a
    zero table.
    """
    plan = plan_events(ev_key, ev_col, ev_code, n_keys, max_cols)
    out = _table_call(
        jnp.asarray(plan.key3), jnp.asarray(plan.cc3),
        jnp.asarray(plan.blk_lo), jnp.asarray(plan.blk_n),
        kp=plan.kp, c6p=plan.c6p, max_blocks=plan.max_blocks,
        interpret=interpret)
    table = out.reshape(plan.kp, plan.c6p)[:n_keys,
                                           : max_cols * NUM_SYMBOLS]
    return table.reshape(n_keys, max_cols, NUM_SYMBOLS)
