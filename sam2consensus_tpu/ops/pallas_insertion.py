"""Pallas TPU kernel: insertion-table build as a segmented one-hot dot.

The insertion "mini-alignment" table (SURVEY.md §2b; reference semantics at
``/root/reference/sam2consensus.py:256-311``) is a segmented reduction of
(site key, column, base) events into a ``[K, C, 6]`` count table.  The
pure-JAX path scatters (``ops.insertions.build_insertion_table``); this
kernel instead contracts one-hot matrices on the MXU, CSR-style:

* the host sorts events by site key and computes, per 128-key block, the
  range of 512-event blocks that can contain its events (scalar-prefetched
  ``blk_lo``/``blk_n``);
* the grid walks ``(key block, event block)``; each step builds the
  TRANSPOSED f32 one-hots ``Aᵀ[k, e] = [key_e == block_base + k]`` and
  ``Bᵀ[m, e] = [col_e*6 + code_e == m]`` by broadcast compare (events
  on lanes — see ``_accumulate_block`` for why) and accumulates their
  lane-contracted product into a VMEM scratch block — all shapes
  static and lane-aligned, so Mosaic needs no dynamic-offset vector
  stores and no relayouts;
* events belonging to other key blocks one-hot to zero rows (keys are
  disjoint across blocks), so the event-range skipping is purely a
  performance device, not a correctness one — except for clamped re-visits
  of the last event block, which the ``j < blk_n`` gate suppresses;
* f32 accumulation is exact for counts below 2^24 (the table is per-run
  event counts; the int32 cast on write would overflow long before f32
  loses integers).

``interpret=True`` runs the same kernel on CPU for CI (SURVEY.md §4);
equivalence against the scatter path is pinned by
tests/test_pallas_insertion.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..constants import NUM_SYMBOLS

#: keys per grid block (lane-aligned)
KEY_BLOCK = 128
#: events per grid block
EVENT_BLOCK = 512


def _accumulate_block(key_ref, cc_ref, acc_ref, i: int, c6p: int) -> None:
    """One event block into the key block's VMEM accumulator.

    Events live on the LANE axis (``[1, EB]`` blocks): the round-4
    ``[EB, 1]`` layout put one scalar per sublane row, which XLA/Mosaic
    tile-padded 128x in HBM — 256 KB of DMA per block visit for 2 KB of
    events, most of the kernel's measured cost (and a 9.5 GB HLO temp
    at 2e7 events).  The one-hots are built TRANSPOSED by broadcast
    compares (iota on sublanes vs events on lanes — no relayout), and
    ``dot_general`` contracts the shared lane axis; the MXU takes both
    operand orientations natively, so the MAC count is unchanged.
    """
    key = key_ref[0]                                     # [1, EB] int32
    cc = cc_ref[0]                                       # [1, EB] int32
    local = key - i * KEY_BLOCK
    at = (local == jax.lax.broadcasted_iota(
        jnp.int32, (KEY_BLOCK, EVENT_BLOCK), 0)).astype(jnp.float32)
    bt = (cc == jax.lax.broadcasted_iota(
        jnp.int32, (c6p, EVENT_BLOCK), 0)).astype(jnp.float32)
    acc_ref[...] += jax.lax.dot_general(
        at, bt, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def _kernel(blk_lo_ref, blk_n_ref, key_ref, cc_ref, out_ref, acc_ref, *,
            c6p: int, n_event_blocks: int):
    i = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j < blk_n_ref[i])
    def _accumulate():
        _accumulate_block(key_ref, cc_ref, acc_ref, i, c6p)

    @pl.when(j == nb - 1)
    def _emit():
        out_ref[0] = acc_ref[...].astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("kp", "c6p", "max_blocks", "interpret"))
def _table_call(key3, cc3, blk_lo, blk_n, *, kp, c6p, max_blocks,
                interpret=False):
    n_event_blocks = key3.shape[0]
    kernel = functools.partial(_kernel, c6p=c6p,
                               n_event_blocks=n_event_blocks)

    def ev_index(i, j, blk_lo, blk_n):
        return (jnp.minimum(blk_lo[i] + j, n_event_blocks - 1), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(kp // KEY_BLOCK, max_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, EVENT_BLOCK), ev_index),
            pl.BlockSpec((1, 1, EVENT_BLOCK), ev_index),
        ],
        out_specs=pl.BlockSpec((1, KEY_BLOCK, c6p),
                               lambda i, j, lo, n: (i, 0, 0)),
        scratch_shapes=[pltpu.VMEM((KEY_BLOCK, c6p), jnp.float32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((kp // KEY_BLOCK, KEY_BLOCK, c6p),
                                       jnp.int32),
        interpret=interpret,
    )(blk_lo, blk_n, key3, cc3)


class EventPlan(NamedTuple):
    """Host-side kernel plan: key-sorted event blocks + CSR block ranges."""
    key3: np.ndarray       # [NEB, 1, EVENT_BLOCK] int32, key-sorted
    cc3: np.ndarray        # [NEB, 1, EVENT_BLOCK] int32, col*6+code
    blk_lo: np.ndarray     # [kp/KEY_BLOCK] int32 first event block per key blk
    blk_n: np.ndarray      # [kp/KEY_BLOCK] int32 event blocks per key blk
    kp: int                # padded key count (KEY_BLOCK multiple)
    c6p: int               # padded flattened column-code lanes
    max_blocks: int        # grid's event-block axis (fullest key block)


def plan_events(ev_key: np.ndarray, ev_col: np.ndarray,
                ev_code: np.ndarray, n_keys: int, cp: int) -> EventPlan:
    """Sort events by key and compute per-key-block event ranges.

    ``cp`` is the (possibly already padded) column count of the table the
    caller wants back; lanes pad to ``c6p = roundup(cp*6, 128)``.
    """
    e = len(ev_key)
    order = np.argsort(ev_key, kind="stable")
    key_s = ev_key[order].astype(np.int32)
    cc_s = (ev_col[order] * NUM_SYMBOLS + ev_code[order]).astype(np.int32)

    kp = max(KEY_BLOCK, -(-n_keys // KEY_BLOCK) * KEY_BLOCK)
    c6p = max(128, -(-(cp * NUM_SYMBOLS) // 128) * 128)
    ep = max(EVENT_BLOCK, -(-e // EVENT_BLOCK) * EVENT_BLOCK)
    if ep != e:
        # pad keys with int32 max: keeps key_s ascending (searchsorted
        # below relies on it) and matches no key block's local iota
        key_s = np.concatenate(
            [key_s, np.full(ep - e, np.iinfo(np.int32).max,
                            dtype=np.int32)])
        cc_s = np.concatenate([cc_s, np.zeros(ep - e, dtype=np.int32)])
    n_event_blocks = ep // EVENT_BLOCK

    bounds = np.arange(0, kp + KEY_BLOCK, KEY_BLOCK)
    ev_bounds = np.searchsorted(key_s, bounds, side="left")
    blk_lo = (ev_bounds[:-1] // EVENT_BLOCK).astype(np.int32)
    last = np.maximum(ev_bounds[1:] - 1, ev_bounds[:-1])
    blk_hi = np.where(ev_bounds[1:] > ev_bounds[:-1],
                      last // EVENT_BLOCK + 1, blk_lo)
    blk_n = (blk_hi - blk_lo).astype(np.int32)
    return EventPlan(
        key_s.reshape(n_event_blocks, 1, EVENT_BLOCK),
        cc_s.reshape(n_event_blocks, 1, EVENT_BLOCK),
        blk_lo, blk_n, kp, c6p, max(1, int(blk_n.max(initial=1))))


def _vote_kernel(blk_lo_ref, blk_n_ref, key_ref, cc_ref, cov_ref, enc_ref,
                 out_ref, acc_ref, *, c6p: int, cpp: int,
                 n_thresholds: int):
    """Fused table + vote: the count table never leaves VMEM.

    Accumulation is identical to :func:`_kernel`; at the key block's
    last event step the vote runs in-registers — six static one-hot
    matmuls de-interleave the ``[KB, c6p]`` accumulator into symbol
    planes (an MXU relayout costing ~KB*c6p*cpp flops ONCE per key
    block, vs. paying 6x wider one-hots on every event block), the gap
    lane completes from site coverage (quirk 4 — may go negative), the
    strictly-greater sums and the exact float64 cutoffs
    (``ops.cutoff.exact_cutoff`` — pure elementwise int32, so it runs
    unchanged inside the kernel) gate the included set, and the
    IUPAC *bitmask* is emitted per threshold.  The host-side LUT lookup
    and skip logic stay outside (a 64-entry gather is XLA-cheap; the
    [K, C, 6] HBM table round trip was not).
    """
    from .cutoff import exact_cutoff

    i = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j < blk_n_ref[i])
    def _accumulate():
        _accumulate_block(key_ref, cc_ref, acc_ref, i, c6p)

    @pl.when(j == nb - 1)
    def _vote():
        acc = acc_ref[...]                                   # [KB, c6p]
        cov = cov_ref[0, :, :]                               # [KB, 1] int32
        m_iota = jax.lax.broadcasted_iota(jnp.int32, (c6p, cpp), 0)
        c_iota = jax.lax.broadcasted_iota(jnp.int32, (c6p, cpp), 1)
        planes = []
        for sym in range(NUM_SYMBOLS):
            sel = (m_iota == c_iota * NUM_SYMBOLS + sym).astype(
                jnp.float32)
            planes.append(jax.lax.dot_general(
                acc, sel, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(jnp.int32))
        colsum = planes[0]
        for p in planes[1:]:
            colsum = colsum + p
        planes[0] = cov - colsum      # gap completion; negative is real
        nonzero = [p != 0 for p in planes]
        sgs = []
        for sym in range(NUM_SYMBOLS):
            s = jnp.zeros_like(planes[0])
            for k in range(NUM_SYMBOLS):
                s = s + planes[k] * (planes[k] > planes[sym])
            sgs.append(s)
        for t in range(n_thresholds):
            enc_row = (enc_ref[t, 0], enc_ref[t, 1], enc_ref[t, 2],
                       enc_ref[t, 3], enc_ref[t, 4])
            cutoff = exact_cutoff(cov, enc_row)              # [KB, 1]
            mask = jnp.zeros_like(planes[0])
            for sym in range(NUM_SYMBOLS):
                mask = mask + jnp.where(
                    nonzero[sym] & (sgs[sym] < cutoff), 1 << sym, 0)
            out_ref[0, t] = mask


@functools.partial(jax.jit, static_argnames=(
    "kp", "c6p", "cpp", "n_thresholds", "max_blocks", "interpret"))
def _table_vote_call(key3, cc3, blk_lo, blk_n, site_cov, thr_enc, *, kp,
                     c6p, cpp, n_thresholds, max_blocks, interpret=False):
    """[NKB, T, KEY_BLOCK, cpp] int32 IUPAC bitmasks, voted in-kernel."""
    n_event_blocks = key3.shape[0]
    n_key_blocks = kp // KEY_BLOCK
    kernel = functools.partial(_vote_kernel, c6p=c6p, cpp=cpp,
                               n_thresholds=n_thresholds)

    def ev_index(i, j, blk_lo, blk_n):
        return (jnp.minimum(blk_lo[i] + j, n_event_blocks - 1), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_key_blocks, max_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, EVENT_BLOCK), ev_index),
            pl.BlockSpec((1, 1, EVENT_BLOCK), ev_index),
            pl.BlockSpec((1, KEY_BLOCK, 1),
                         lambda i, j, lo, n: (i, 0, 0)),
            pl.BlockSpec((n_thresholds, 5),
                         lambda i, j, lo, n: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, n_thresholds, KEY_BLOCK, cpp),
                               lambda i, j, lo, n: (i, 0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((KEY_BLOCK, c6p), jnp.float32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (n_key_blocks, n_thresholds, KEY_BLOCK, cpp), jnp.int32),
        interpret=interpret,
    )(blk_lo, blk_n, key3, cc3,
      site_cov.reshape(n_key_blocks, KEY_BLOCK, 1).astype(jnp.int32),
      thr_enc)


#: fused-vote kernel bound on the padded column count: the emit step's
#: de-interleave selectors are [c6p, cpp] f32 VMEM temporaries, ~6 MB at
#: cp=512; past that the two-dispatch path (table kernel + XLA vote) wins
FUSED_VOTE_MAX_CP = 512


def vote_insertions_fused(key3, cc3, blk_lo, blk_n, site_cov, n_cols,
                          thr_enc, *, kp: int, c6p: int, cp: int,
                          max_blocks: int, interpret: bool = False):
    """Traceable twin of ``ops.insertions.vote_insertions`` riding the
    fused kernel: returns uint8 ``[T, kp, cp]`` with FILL_SENTINEL in
    skipped columns.  ``site_cov``/``n_cols`` must be padded to ``kp``.
    """
    from .vote import FILL_SENTINEL, iupac_select

    n_thresholds = thr_enc.shape[0]
    cpp = max(128, -(-cp // 128) * 128)
    out = _table_vote_call(
        key3, cc3, blk_lo, blk_n, site_cov, thr_enc, kp=kp, c6p=c6p,
        cpp=cpp, n_thresholds=n_thresholds, max_blocks=max_blocks,
        interpret=interpret)
    mask = jnp.transpose(out, (1, 0, 2, 3)).reshape(
        n_thresholds, kp, cpp)[:, :, :cp]
    syms = iupac_select(mask)
    valid = (jnp.arange(cp)[None, :] < n_cols[:, None])
    skip = (syms == ord("-")) | ~valid[None]
    return jnp.where(skip, jnp.uint8(FILL_SENTINEL), syms)


def vote_insertions_pallas(eplan: "EventPlan", site_cov, n_cols, thr_enc,
                           cp: int, interpret: bool = False):
    """Host-array convenience wrapper of :func:`vote_insertions_fused`."""
    return vote_insertions_fused(
        jnp.asarray(eplan.key3), jnp.asarray(eplan.cc3),
        jnp.asarray(eplan.blk_lo), jnp.asarray(eplan.blk_n),
        jnp.asarray(site_cov), jnp.asarray(n_cols),
        jnp.asarray(thr_enc), kp=eplan.kp, c6p=eplan.c6p, cp=cp,
        max_blocks=eplan.max_blocks, interpret=interpret)


def build_insertion_table_pallas(ev_key: np.ndarray, ev_col: np.ndarray,
                                 ev_code: np.ndarray, n_keys: int,
                                 max_cols: int,
                                 interpret: bool = False) -> jax.Array:
    """Segmented-reduce insertion events into an int32 ``[n_keys, C, 6]``.

    Same contract as ``ops.insertions.build_insertion_table`` applied to a
    zero table.
    """
    plan = plan_events(ev_key, ev_col, ev_code, n_keys, max_cols)
    out = _table_call(
        jnp.asarray(plan.key3), jnp.asarray(plan.cc3),
        jnp.asarray(plan.blk_lo), jnp.asarray(plan.blk_n),
        kp=plan.kp, c6p=plan.c6p, max_blocks=plan.max_blocks,
        interpret=interpret)
    table = out.reshape(plan.kp, plan.c6p)[:n_keys,
                                           : max_cols * NUM_SYMBOLS]
    return table.reshape(n_keys, max_cols, NUM_SYMBOLS)
