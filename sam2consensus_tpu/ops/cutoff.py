"""Exact device-side threshold cutoffs: ``ceil(float64(t) * cov)`` in int32.

The reference's greedy vote compares an integer running total against the
Python float product ``t * coverage`` (``/root/reference/sam2consensus.py:
359-367`` — a float64 multiply, then an int-vs-float comparison).  Rounds
1-2 made this exact on device with a host-precomputed LUT
``lut[cov] = ceil(float64(t) * cov)``; correct, but with two measured costs
on the tunneled chip (tools/tunnel_probe.py):

* the LUT's size depends on ``max(cov)``, whose host fetch is a ~65 ms
  round trip that *serializes* the post-accumulation tail;
* the ``[L]``-wide table gather costs ~46 ms at L = 4.6 M, while every
  non-gather op in the vote measures ~free (TPU vector units hate gathers,
  love elementwise int32).

This module deletes the LUT: it evaluates ``ceil(fl64(t * cov))`` exactly
with int32 limb arithmetic on device — *including the float64 rounding of
the product* (round-to-nearest-even at 53 bits), which the LUT inherited
from numpy and which must be reproduced bit-for-bit for byte-identity with
the oracle:

1. host: ``t = M * 2^(e-53)`` exactly (``math.frexp``; M is t's 53-bit
   integer mantissa), shipped as four 14-bit limbs of M plus e — five
   int32s per threshold (``encode_thresholds``);
2. device: ``P = M * cov`` in base-2^14 limbs (every partial product and
   carry column stays < 2^30, int32-safe);
3. round P to 53 significant bits (RNE) → Q', the exact mantissa of
   ``fl64(t * cov)``;
4. ``cutoff = ceil(Q' * 2^(r+e-53))`` via two-word integer shifts, clamped
   to ``[0, 2^31-1]`` — the clamp preserves the predicate ``S < cutoff``
   for every achievable S (S ≤ cov < 2^31).

Everything is elementwise int32 — no gathers, no tables — so XLA fuses it
into the vote at ~zero cost.  ``tests/test_cutoff.py`` pins equality with
``threshold_luts`` (numpy's float64 product) exhaustively over coverage
ranges and property-based over random doubles.

Supported domain (documented contract): ``t > 0`` finite, ``0 ≤ cov < 2^31``
— the reference itself is int32-bounded here because total aligned bases
are counted in int32.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax.numpy as jnp
import numpy as np

LIMB = 14
MASK = (1 << LIMB) - 1
INT32_MAX = (1 << 31) - 1


def encode_thresholds(thresholds: Sequence[float]) -> np.ndarray:
    """Pack thresholds as int32 ``[T, 5]``: four 14-bit mantissa limbs + e.

    ``t = M * 2^(e-53)`` exactly, with ``M = int(frexp(t).frac * 2^53)``
    (t's full 53-bit mantissa, so no precision is lost for any double).
    """
    rows = []
    for t in thresholds:
        t = float(t)
        if not (t > 0.0) or not math.isfinite(t):
            raise ValueError(f"threshold must be a positive finite float, "
                             f"got {t!r}")
        frac, e = math.frexp(t)            # t = frac * 2^e, frac in [0.5, 1)
        m = int(frac * (1 << 53))          # exact: frac has <= 53 sig bits
        rows.append([m & MASK, (m >> LIMB) & MASK, (m >> (2 * LIMB)) & MASK,
                     (m >> (3 * LIMB)) & MASK, e - 53])
    return np.asarray(rows, dtype=np.int32)


def exact_cutoff(cov, enc_row):
    """``ceil(fl64(t * cov))`` for int32 ``cov >= 0``; pure traceable fn.

    Args:
      cov: int32 array (any shape), each value in ``[0, 2^31)``.
      enc_row: int32 ``[5]`` — one row of :func:`encode_thresholds`.

    Returns:
      int32 cutoffs, same shape as ``cov``, clamped to ``[0, 2^31-1]``.
    """
    cov = cov.astype(jnp.int32)
    m0, m1, m2, m3, e = (enc_row[0], enc_row[1], enc_row[2], enc_row[3],
                         enc_row[4])

    c0 = cov & MASK
    c1 = (cov >> LIMB) & MASK
    c2 = (cov >> (2 * LIMB)) & MASK                      # < 2^3

    # P = M * cov, base-2^14 columns; each column < 3*2^28 + carry < 2^30
    cols = (m0 * c0,
            m0 * c1 + m1 * c0,
            m0 * c2 + m1 * c1 + m2 * c0,
            m1 * c2 + m2 * c1 + m3 * c0,
            m2 * c2 + m3 * c1,
            m3 * c2)
    p = []
    carry = jnp.zeros_like(cov)
    for col in cols:
        cur = col + carry
        p.append(cur & MASK)
        carry = cur >> LIMB
    p.append(carry)                        # p6 == 0 (P < 2^84); pads selects

    # bit length of cov (valid for cov >= 1; cov == 0 handled at the end)
    x = cov
    blc = jnp.zeros_like(cov)
    for s in (16, 8, 4, 2, 1):
        big = x >= (1 << s)
        blc = blc + jnp.where(big, s, 0)
        x = jnp.where(big, x >> s, x)
    blc = blc + 1                                         # floor(log2)+1

    # nbits(P) is blc+52 or blc+53: test bit blc+52 of P
    k = blc + 52
    kl = k // LIMB                                        # in {3, 4, 5}
    kb = k % LIMB
    lk = jnp.where(kl == 3, p[3], jnp.where(kl == 4, p[4], p[5]))
    topbit = (lk >> kb) & 1
    r = blc + topbit - 1                                  # nbits-53, [0, 31]

    # R = P mod 2^r -> round + sticky bits (RNE)
    low31 = p[0] | (p[1] << LIMB) | ((p[2] & 0x7) << (2 * LIMB))
    rm1 = jnp.maximum(r - 1, 0)
    mask_r = jnp.where(r > 0, (jnp.left_shift(1, rm1) - 1) * 2 + 1, 0)
    rr = low31 & mask_r
    rnd = jnp.where(r > 0, (rr >> rm1) & 1, 0)
    sticky = (rr & (jnp.left_shift(1, rm1) - 1)) != 0

    # Q = P >> r as four 14-bit limbs (53 bits)
    rl = r // LIMB                                        # in {0, 1, 2}
    rb = r % LIMB

    def sel(i):
        return jnp.where(rl == 0, p[i], jnp.where(rl == 1, p[i + 1],
                                                  p[i + 2]))

    q = []
    for i in range(4):
        li, ln = sel(i), sel(i + 1)
        q.append(((li >> rb) | (ln << (LIMB - rb))) & MASK)
    q_lo = q[0] | (q[1] << LIMB)                          # bits 0..27
    q_hi = q[2] | (q[3] << LIMB)                          # bits 28..55 (<2^25)

    # round to nearest even -> Q' in (q_lo, q_hi), possibly 2^53 exactly
    odd = (q[0] & 1) == 1
    inc = jnp.where((rnd == 1) & (sticky | odd), 1, 0)
    q_lo = q_lo + inc
    q_hi = q_hi + (q_lo >> 28)
    q_lo = q_lo & ((1 << 28) - 1)

    # cutoff = ceil(Q' * 2^(r+e)), e already biased by -53; s = right shift
    s = -e - r
    s_c = jnp.clip(s, 1, 53)

    # s in [1, 27]: shift across both words; pre-clamp values >= 2^31
    s1 = jnp.clip(s_c, 1, 27)
    over1 = q_hi >= jnp.left_shift(1, s1 + 3)             # Q' >= 2^(31+s)
    hi_safe = jnp.where(over1, 0, q_hi)
    floor1 = jnp.left_shift(hi_safe, 28 - s1) | (q_lo >> s1)
    rem1 = (q_lo & (jnp.left_shift(1, s1) - 1)) != 0
    ceil1 = floor1 + rem1
    ceil1 = jnp.where(over1 | (ceil1 < 0), INT32_MAX, ceil1)

    # s in [28, 53]: high word only
    s2 = jnp.clip(s_c - 28, 0, 25)
    floor2 = q_hi >> s2
    rem2 = ((q_hi & (jnp.left_shift(1, s2) - 1)) != 0) | (q_lo != 0)
    ceil2 = floor2 + rem2

    cutoff = jnp.where(s_c < 28, ceil1, ceil2)
    cutoff = jnp.where(s <= 0, INT32_MAX,                 # value >= 2^52
                       jnp.where(s >= 54, 1, cutoff))     # 0 < value < 1
    return jnp.where(cov == 0, 0, cutoff).astype(jnp.int32)
