"""Insertion-table construction and vote on device (pure-JAX reference path).

The reference treats each insertion site as a "mini-alignment of motifs"
(``/root/reference/sam2consensus.py:256-311``): per site, columns up to the
longest motif; per column, nucleotide counts weighted by motif multiplicity;
then the gap lane is completed as ``coverage[site] - sum(column counts)``
(which may legitimately go negative — quirk 4) and the same greedy vote runs
with the *site's* ``t * coverage`` cutoff (``:369-385``).

Grouping motifs first and weighting by multiplicity is arithmetically
identical to scatter-adding one event per (motif occurrence, column) — so the
whole table build is a single scatter over rows pre-grouped by the host
encoder (``encoder.events.group_insertions``).  A Pallas segmented-reduce
variant of the same contraction lives in ``pallas_insertion.py``.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from .cutoff import exact_cutoff
from .vote import FILL_SENTINEL, iupac_select


@jax.jit
def build_insertion_table(table: jax.Array, ev_key: jax.Array,
                          ev_col: jax.Array, ev_code: jax.Array) -> jax.Array:
    """Scatter insertion events into the ``[K, max_cols, 6]`` count table."""
    return table.at[ev_key, ev_col, ev_code].add(1)


def insertion_tail_host(kp: int, cp: int, ev_key: np.ndarray,
                        ev_col: np.ndarray, ev_code: np.ndarray,
                        site_cov: np.ndarray, n_cols: np.ndarray,
                        thresholds, k_valid: int) -> np.ndarray:
    """Whole insertion tail (table build + vote) on the host for
    link-free native tails: the C++ twin when the library loads, the
    numpy twins otherwise.  Returns uint8 ``[T, k_valid, cp]``."""
    from .. import native

    lib = native.load()
    if lib is not None and k_valid > 0:
        from ..constants import IUPAC_MASK_LUT

        table = np.zeros(kp * cp * 6, dtype=np.int32)
        lib.s2c_ins_table(
            np.ascontiguousarray(ev_key, np.int32),
            np.ascontiguousarray(ev_col, np.int32),
            np.ascontiguousarray(ev_code, np.int32),
            len(ev_key), table, cp)
        out = np.empty(len(thresholds) * k_valid * cp, dtype=np.uint8)
        lib.s2c_ins_vote(
            table, k_valid, cp,
            np.ascontiguousarray(site_cov[:k_valid], np.int32),
            np.ascontiguousarray(n_cols[:k_valid], np.int32),
            np.asarray(thresholds, np.float64), len(thresholds),
            IUPAC_MASK_LUT, out)
        return out.reshape(len(thresholds), k_valid, cp)
    table = build_insertion_table_host(kp, cp, ev_key, ev_col, ev_code)
    return vote_insertions_host(table[:k_valid], site_cov[:k_valid],
                                n_cols[:k_valid], thresholds)


def build_insertion_table_host(kp: int, cp: int, ev_key: np.ndarray,
                               ev_col: np.ndarray,
                               ev_code: np.ndarray) -> np.ndarray:
    """Numpy twin of :func:`build_insertion_table` for link-free native
    tails (backends/jax_backend.py): one bincount over the flattened
    event indices replaces an XLA scatter dispatch that measures ~100 ms
    warm on the CPU backend at north-star scale."""
    idx = (ev_key.astype(np.int64) * cp + ev_col) * 6 + ev_code
    return np.bincount(idx, minlength=kp * cp * 6).astype(
        np.int32).reshape(kp, cp, 6)


def vote_insertions_host(table: np.ndarray, site_cov: np.ndarray,
                         n_cols: np.ndarray, thresholds) -> np.ndarray:
    """Numpy twin of :func:`vote_insertions` (same greedy semantics).

    The host has real float64, so ``ceil(t * cov)`` is computed directly
    the way the oracle's float comparison behaves (``S < t*cov`` for
    integer S  <=>  ``S < ceil(t*cov)``; sam2consensus.py:359-366) —
    the device needed ops/cutoff.py's limb arithmetic only because the
    chip lacks float64.
    """
    from .vote import FILL_SENTINEL as _fill
    from ..constants import IUPAC_MASK_LUT as _lut

    k, cp = table.shape[0], table.shape[1]
    completed = table.copy()
    completed[:, :, 0] = site_cov[:, None] - table.sum(axis=-1)  # quirk 4:
    # the gap lane may legitimately go negative (sam2consensus.py:294)
    # strictly-greater sums, one donor lane at a time: [K, C, 6] temps
    # instead of the [K, C, 6, 6] broadcast (which costs ~6x more here)
    sgs = np.zeros(completed.shape, dtype=np.int32)       # [K, C, 6]
    for j in range(6):
        cj = completed[:, :, j:j + 1]
        sgs += cj * (cj > completed)
    nonzero = completed != 0
    bits = (1 << np.arange(6, dtype=np.int32))
    valid = np.arange(cp, dtype=np.int32)[None, :] < n_cols[:, None]
    out = np.empty((len(thresholds), k, cp), dtype=np.uint8)
    cov64 = site_cov.astype(np.float64)
    for ti, t in enumerate(thresholds):
        cutoff = np.ceil(np.float64(t) * cov64)           # [K]
        included = nonzero & (sgs < cutoff[:, None, None])
        mask = (included * bits).sum(axis=-1)             # [K, C]
        syms = _lut[mask]
        skip = (syms == ord("-")) | ~valid
        out[ti] = np.where(skip, np.uint8(_fill), syms)
    return out


@jax.jit
def vote_insertions(table: jax.Array, site_cov: jax.Array,
                    n_cols: jax.Array, thr_enc: jax.Array) -> jax.Array:
    """Vote every insertion column for every threshold.

    Args:
      table: int32 ``[K, C, 6]`` raw base counts (gap lane all zero).
      site_cov: int32 ``[K]`` coverage at each site's reference position
        (0 for end-of-contig sites) — the cutoff uses the SITE's coverage,
        not the column sum (sam2consensus.py:376).
      n_cols: int32 ``[K]`` valid column count per site (longest motif).
      thr_enc: int32 ``[T, 5]`` encoded thresholds
        (``ops.cutoff.encode_thresholds``).

    Returns:
      uint8 ``[T, K, C]``: output byte per column; FILL_SENTINEL where the
      column is skipped (past n_cols, or the call is "-",
      sam2consensus.py:381-382).
    """
    # gap-lane completion: cov - sum(all lanes); may be negative (quirk 4)
    colsum = table.sum(axis=-1)                                # [K, C]
    completed = table.at[:, :, 0].set(site_cov[:, None] - colsum)

    greater = completed[..., None, :] > completed[..., :, None]
    strictly_greater_sum = jnp.sum(
        jnp.where(greater, completed[..., None, :], 0), axis=-1)  # [K, C, 6]
    nonzero = completed != 0
    bit = (1 << jnp.arange(6, dtype=jnp.int32))
    valid = (jnp.arange(table.shape[1])[None, :] < n_cols[:, None])  # [K, C]

    def per_threshold(enc_row):
        cutoff = exact_cutoff(site_cov, enc_row)               # [K]
        included = nonzero & (strictly_greater_sum < cutoff[:, None, None])
        mask = jnp.sum(jnp.where(included, bit, 0), axis=-1)   # [K, C]
        syms = iupac_select(mask)
        skip = (syms == ord("-")) | ~valid
        return jnp.where(skip, jnp.uint8(FILL_SENTINEL), syms)

    return jax.vmap(per_threshold)(thr_enc)
