"""Insertion-table construction and vote on device (pure-JAX reference path).

The reference treats each insertion site as a "mini-alignment of motifs"
(``/root/reference/sam2consensus.py:256-311``): per site, columns up to the
longest motif; per column, nucleotide counts weighted by motif multiplicity;
then the gap lane is completed as ``coverage[site] - sum(column counts)``
(which may legitimately go negative — quirk 4) and the same greedy vote runs
with the *site's* ``t * coverage`` cutoff (``:369-385``).

Grouping motifs first and weighting by multiplicity is arithmetically
identical to scatter-adding one event per (motif occurrence, column) — so the
whole table build is a single scatter over rows pre-grouped by the host
encoder (``encoder.events.group_insertions``).  A Pallas segmented-reduce
variant of the same contraction lives in ``pallas_insertion.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .cutoff import exact_cutoff
from .vote import FILL_SENTINEL, iupac_select


@jax.jit
def build_insertion_table(table: jax.Array, ev_key: jax.Array,
                          ev_col: jax.Array, ev_code: jax.Array) -> jax.Array:
    """Scatter insertion events into the ``[K, max_cols, 6]`` count table."""
    return table.at[ev_key, ev_col, ev_code].add(1)


@jax.jit
def vote_insertions(table: jax.Array, site_cov: jax.Array,
                    n_cols: jax.Array, thr_enc: jax.Array) -> jax.Array:
    """Vote every insertion column for every threshold.

    Args:
      table: int32 ``[K, C, 6]`` raw base counts (gap lane all zero).
      site_cov: int32 ``[K]`` coverage at each site's reference position
        (0 for end-of-contig sites) — the cutoff uses the SITE's coverage,
        not the column sum (sam2consensus.py:376).
      n_cols: int32 ``[K]`` valid column count per site (longest motif).
      thr_enc: int32 ``[T, 5]`` encoded thresholds
        (``ops.cutoff.encode_thresholds``).

    Returns:
      uint8 ``[T, K, C]``: output byte per column; FILL_SENTINEL where the
      column is skipped (past n_cols, or the call is "-",
      sam2consensus.py:381-382).
    """
    # gap-lane completion: cov - sum(all lanes); may be negative (quirk 4)
    colsum = table.sum(axis=-1)                                # [K, C]
    completed = table.at[:, :, 0].set(site_cov[:, None] - colsum)

    greater = completed[..., None, :] > completed[..., :, None]
    strictly_greater_sum = jnp.sum(
        jnp.where(greater, completed[..., None, :], 0), axis=-1)  # [K, C, 6]
    nonzero = completed != 0
    bit = (1 << jnp.arange(6, dtype=jnp.int32))
    valid = (jnp.arange(table.shape[1])[None, :] < n_cols[:, None])  # [K, C]

    def per_threshold(enc_row):
        cutoff = exact_cutoff(site_cov, enc_row)               # [K]
        included = nonzero & (strictly_greater_sum < cutoff[:, None, None])
        mask = jnp.sum(jnp.where(included, bit, 0), axis=-1)   # [K, C]
        syms = iupac_select(mask)
        skip = (syms == ord("-")) | ~valid
        return jnp.where(skip, jnp.uint8(FILL_SENTINEL), syms)

    return jax.vmap(per_threshold)(thr_enc)
