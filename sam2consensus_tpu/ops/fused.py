"""One-dispatch pipeline tail: position vote + insertion table + vote.

On a tunneled TPU every dispatch→fetch round trip costs tens of
milliseconds, which dwarfs the actual vote compute (an elementwise int32
reduction).  So the whole post-accumulation tail runs as ONE jitted call
producing ONE packed uint8 buffer:

    [ syms  T*L  |  insertion syms  T*Kp*Cp ]

and the host does exactly two device round trips after accumulation:

1. fetch coverage (needed on host anyway for the threshold LUTs, the
   min-depth gates and the FASTA headers) — started asynchronously so the
   host's insertion grouping overlaps the transfer;
2. fetch the packed vote output.

Insertion-site count ``Kp`` and column count ``Cp`` are padded to powers of
two so the jit cache stays O(log²) across runs; pad events scatter into the
sacrificial last table row, whose votes the host slices off.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .insertions import build_insertion_table, vote_insertions
from .vote import vote_block


@jax.jit
def coverage(counts: jax.Array) -> jax.Array:
    """Per-position depth ``[L]`` — gaps and Ns count (quirk 5)."""
    return counts.sum(axis=-1)


@partial(jax.jit, static_argnames=("min_depth", "cp"))
def vote_packed(counts: jax.Array, t_luts: jax.Array, ev_key: jax.Array,
                ev_col: jax.Array, ev_code: jax.Array, site_cov: jax.Array,
                n_cols: jax.Array, min_depth: int, cp: int) -> jax.Array:
    """Position vote + insertion table build + insertion vote, packed uint8.

    ``site_cov``/``n_cols`` are the padded ``[Kp]`` site arrays; ``cp`` is
    the padded insertion-table column count (static).
    """
    syms, _cov = vote_block(counts, t_luts, min_depth)          # [T, L]
    kp = site_cov.shape[0]
    table = jnp.zeros((kp, cp, 6), dtype=jnp.int32)
    table = build_insertion_table(table, ev_key, ev_col, ev_code)
    ins_syms = vote_insertions(table, site_cov, n_cols, t_luts)  # [T, Kp, Cp]
    return jnp.concatenate([syms.reshape(-1), ins_syms.reshape(-1)])


def next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


@partial(jax.jit, static_argnames=("min_depth", "cp", "kp", "c6p",
                                   "max_blocks", "interpret"))
def vote_packed_pallas(counts: jax.Array, t_luts: jax.Array,
                       key3: jax.Array, cc3: jax.Array, blk_lo: jax.Array,
                       blk_n: jax.Array, site_cov: jax.Array,
                       n_cols: jax.Array, min_depth: int, cp: int, kp: int,
                       c6p: int, max_blocks: int,
                       interpret: bool = False) -> jax.Array:
    """``vote_packed`` with the insertion table built by the Pallas
    segmented-reduce kernel (ops/pallas_insertion.py) instead of the XLA
    scatter — still one dispatch, one packed uint8 result.

    Inputs are the kernel's host-planned arrays (key-sorted event blocks +
    CSR block ranges); ``site_cov``/``n_cols`` are padded to ``kp``.
    """
    from .pallas_insertion import _table_call

    syms, _cov = vote_block(counts, t_luts, min_depth)          # [T, L]
    out = _table_call(key3, cc3, blk_lo, blk_n, kp=kp, c6p=c6p,
                      max_blocks=max_blocks, interpret=interpret)
    table = out.reshape(kp, c6p)[:, : cp * 6].reshape(kp, cp, 6)
    ins_syms = vote_insertions(table, site_cov, n_cols, t_luts)
    return jnp.concatenate([syms.reshape(-1), ins_syms.reshape(-1)])
