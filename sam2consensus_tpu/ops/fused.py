"""One-dispatch pipeline tail: vote + insertion table + host-facing stats.

On a tunneled TPU every dispatch→fetch round trip costs ~65 ms and the
link moves ~40 MB/s (tools/tunnel_probe.py), which dwarfs the actual vote
compute (an elementwise int32 reduction, measured ~free).  So the whole
post-accumulation tail runs as ONE jitted call producing ONE packed uint8
buffer:

    [ syms T*L | insertion syms T*Kp*Cp | contig cov sums C*4 | site cov Kp*4 ]

and the host does exactly ONE device round trip after accumulation.  The
stats tail replaces the round-2 flow (fetch the full [L] coverage vector —
18 MB ≈ 450 ms at L = 4.6 M — then build LUTs, then dispatch the vote):

* per-contig coverage sums (for FASTA headers and the zero-coverage prune)
  come from one cumulative sum, differenced at the contig offsets;
* per-insertion-site coverage (for min-depth gates, header sums and the
  insertion vote's cutoffs) is a K-wide gather, K ~ thousands;
* the threshold cutoffs are computed exactly on device
  (``ops.cutoff.exact_cutoff``), so nothing in the tail depends on
  ``max(cov)`` and no LUT round trip exists at all.

Insertion-site count ``Kp`` and column count ``Cp`` are padded to powers of
two so the jit cache stays O(log²) across runs; pad events scatter into the
sacrificial last table row, whose votes the host slices off.

Int32 note: the cumulative coverage sum is exact while total aligned bases
stay < 2^31 — the same bound the int32 count tensor already imposes; the
backend enforces it host-side.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..observability import jitcache
from .insertions import build_insertion_table, vote_insertions
from .vote import emit_gate, vote_block


def next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


#: bucket width for :func:`pad_cap` above 1 MiB — coarse enough to keep
#: the jit cache small, fine enough that padding waste stays < 1 MiB
_CAP_BUCKET = 1 << 20


def pad_cap(n: int) -> int:
    """Jit-bucketed padding for the sparse-output capacity.

    Power-of-two below 1 MiB (small recompiles are cheap), then the next
    multiple of 1 MiB: pow2 padding at 10 M+ covered positions would
    inflate the d2h fetch by up to 2x and flip the dense-vs-sparse
    decision against sparse exactly where sparse matters most (the
    40 Mbp bench config), while 1 MiB buckets bound both the padding
    waste and the number of distinct compiled shapes."""
    if n <= _CAP_BUCKET:
        return next_pow2(n)
    return -(-n // _CAP_BUCKET) * _CAP_BUCKET


@jax.jit
def coverage(counts: jax.Array) -> jax.Array:
    """Per-position depth ``[L]`` — gaps and Ns count (quirk 5).
    Widens first: the host-counts path stores uint8/uint16 on device."""
    return counts.astype(jnp.int32).sum(axis=-1)


def _bytes_of_i32(x: jax.Array) -> jax.Array:
    """Portable little-endian byte split of an int32 vector → uint8 [n*4]."""
    parts = [((x >> (8 * i)) & 0xFF).astype(jnp.uint8) for i in range(4)]
    return jnp.stack(parts, axis=-1).reshape(-1)


def unpack_i32(buf, n: int):
    """Host-side inverse of :func:`_bytes_of_i32` (numpy uint8 slice)."""
    import numpy as np

    b = np.asarray(buf, dtype=np.uint8).reshape(n, 4).astype(np.uint32)
    out = b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16) | (b[:, 3] << 24)
    return out.astype(np.int64)


def _tail_stats(cov: jax.Array, offsets: jax.Array, site_keys: jax.Array):
    """(contig_sums [C], site_cov [Kp]) from resident coverage."""
    prefix = jnp.concatenate(
        [jnp.zeros(1, dtype=cov.dtype), jnp.cumsum(cov)])
    contig_sums = prefix[offsets[1:]] - prefix[offsets[:-1]]
    safe = jnp.maximum(site_keys, 0)
    site_cov = jnp.where(site_keys >= 0, cov[safe], 0).astype(jnp.int32)
    return contig_sums.astype(jnp.int32), site_cov


def _pack_bits_le(mask: jax.Array) -> jax.Array:
    """Bool ``[L]`` → uint8 ``[ceil(L/8)]``, little bit order (host inverse
    is ``np.unpackbits(..., bitorder="little")``)."""
    length = mask.shape[0]
    pad = (-length) % 8
    m = mask.astype(jnp.int32)
    if pad:
        m = jnp.concatenate([m, jnp.zeros((pad,), jnp.int32)])
    m = m.reshape(-1, 8)
    return jnp.sum(m << jnp.arange(8, dtype=jnp.int32)[None, :],
                   axis=1).astype(jnp.uint8)


def _sparse_syms(syms: jax.Array, emit: jax.Array, cap: int):
    """Compact the per-threshold output to covered positions only.

    The emit gate (cov>0 ∧ cov>=min_depth) is threshold-INDEPENDENT, so
    one L/8-byte bitmask plus ``T × cap`` compacted characters replaces
    the dense ``T × L`` fetch — the d2h win for sparse-coverage genomes
    (a 40 Mbp contig with 100k reads is ~99.5% fill bytes otherwise).
    Emitted characters are never FILL_SENTINEL, so compaction is exact.
    """
    bits = _pack_bits_le(emit)
    idx = jnp.cumsum(emit.astype(jnp.int32)) - 1
    tgt = jnp.where(emit, idx, cap)               # pad writes -> row cap
    compact = jnp.zeros((syms.shape[0], cap + 1),
                        jnp.uint8).at[:, tgt].set(syms)
    return bits, compact[:, :cap]


def _pack5_planes(code5: jax.Array):
    """Split ``[T, L]`` 5-bit symbol codes into the wire planes.

    The vote emits exactly 32 distinct symbols, so the dense block
    carries 5 bits/char of information; shipping a nibble plane
    (``[T, ceil(L/2)]``) plus a high-bit plane (``[T, ceil(L/8)]``)
    costs 0.625 B/char on the link instead of 1 — with NO compaction
    scatter (unlike the sparse path, whose scatter measured
    ~12 ns/position).  The codes arrive straight from the vote's one-hot
    select (``ops.vote.IUPAC_MASK_LUT5``), so re-encoding is free; this
    is pure shifts + CONTIGUOUS reshapes (stride-2 slicing lowered
    poorly on the chip).
    """
    c = code5.astype(jnp.int32)
    t, length = c.shape
    pad = (-length) % 8
    if pad:
        c = jnp.concatenate([c, jnp.zeros((t, pad), jnp.int32)], axis=1)
    pairs = (c & 15).reshape(t, -1, 2)
    nibs = (pairs[..., 0] | (pairs[..., 1] << 4)).astype(jnp.uint8)
    nibs = nibs[:, : (length + 1) // 2]
    octs = (c >> 4).reshape(t, -1, 8)
    hbits = jnp.sum(octs << jnp.arange(8, dtype=jnp.int32)[None, None, :],
                    axis=-1).astype(jnp.uint8)
    hbits = hbits[:, : (length + 7) // 8]
    return nibs, hbits


def _sym_space(out_enc) -> str:
    """The vote's symbol space for a wire encoding: packed5 votes
    directly in 5-bit codes (``ops.vote.IUPAC_MASK_LUT5``); dense and
    sparse ship ASCII."""
    return "code5" if out_enc == "packed5" else "ascii"


def _dash_code(out_enc) -> int:
    """The ``'-'`` symbol in the vote's wire symbol space: raw ASCII
    for dense/sparse, the SYM32 index (1) for packed5."""
    return 1 if out_enc == "packed5" else ord("-")


def contig_dash_counts(syms: jax.Array, offsets: jax.Array,
                       dash_code: int) -> jax.Array:
    """Per-(threshold, contig) ``'-'`` totals of the POST-FILL symbols —
    the device-resident epilogue's stripped-length math.

    The reference renders each contig's sequence, substitutes the fill
    character, then counts ``'-'`` to decide the empty-sequence drop
    and the header's stripped length (``sam2consensus.py:400-406``) —
    two O(L) host passes per (threshold, contig).  Here the count runs
    on device while the vote output is still resident: vmapped over the
    THRESHOLD grid, with the CONTIG axis as a segmented prefix-sum
    difference at the contig offsets (the same one-cumsum trick as
    ``_tail_stats`` — a [C]-wide gather pair, not a per-contig loop).
    ``syms`` must already carry the device-substituted fill
    (``ops.vote.device_fill_code``), so a ``'-'`` fill's unemitted
    positions are counted exactly like the reference's host pass would.
    Returns int32 ``[T, C]``."""

    def per_threshold(row):
        is_dash = (row == jnp.uint8(dash_code)).astype(jnp.int32)
        prefix = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(is_dash)])
        return prefix[offsets[1:]] - prefix[offsets[:-1]]

    return jax.vmap(per_threshold)(syms)


def _epilogue_sections(syms, offsets, out_enc, epilogue: bool) -> list:
    """The packed buffer's trailing epilogue section (``[T*C]`` dash
    counts as LE bytes) — empty when the epilogue is host-routed."""
    if not epilogue:
        return []
    dash = contig_dash_counts(syms, offsets, _dash_code(out_enc))
    return [_bytes_of_i32(dash.reshape(-1))]


def _syms_head(syms, cov, min_depth: int, out_enc):
    """Position-symbol section of the packed buffer.

    ``out_enc`` selects the wire encoding: ``None`` → dense ``[T*L]``
    ASCII; an int → sparse (emit bitmask + chars compacted to that
    capacity; the gate is :func:`ops.vote.emit_gate` — the same
    definition that placed the FILL sentinels, so mask and symbols
    cannot drift apart); ``"packed5"`` → 5-bit planes
    (:func:`_pack5_planes`; ``syms`` must then hold code5 symbols —
    :func:`_sym_space`).  The backend picks by measured cost
    (backends/jax_backend.py output-encoding gate)."""
    if out_enc is None:
        return [syms.reshape(-1)]
    if out_enc == "packed5":
        nibs, hbits = _pack5_planes(syms)
        return [nibs.reshape(-1), hbits.reshape(-1)]
    bits, compact = _sparse_syms(syms, emit_gate(cov, min_depth),
                                 out_enc)
    return [bits, compact.reshape(-1)]


def _vote_packed_simple_body(counts: jax.Array, thr_enc: jax.Array,
                             offsets: jax.Array, min_depth: int,
                             out_enc=None, fill_code: int = 0,
                             epilogue: bool = False) -> jax.Array:
    """No-insertion tail: position vote + contig sums, one packed buffer.
    ``out_enc`` as in :func:`_syms_head`; ``fill_code``/``epilogue`` are
    the device-resident epilogue statics (``ops.vote.device_fill_code``
    substitution inside the vote + per-(T, C) dash counts appended)."""
    jitcache.note_trace("vote_packed_simple")
    syms, cov = vote_block(counts, thr_enc, min_depth,
                           _sym_space(out_enc), fill_code)  # [T, L]
    contig_sums, _ = _tail_stats(cov, offsets,
                                 jnp.full((1,), -1, jnp.int32))
    return jnp.concatenate(
        _syms_head(syms, cov, min_depth, out_enc)
        + [_bytes_of_i32(contig_sums)]
        + _epilogue_sections(syms, offsets, out_enc, epilogue))


_SIMPLE_STATICS = ("min_depth", "out_enc", "fill_code", "epilogue")
vote_packed_simple = partial(
    jax.jit, static_argnames=_SIMPLE_STATICS)(_vote_packed_simple_body)
#: same computation with the counts operand DONATED: XLA may reuse the
#: count buffer's allocation for the packed output, so a warm serve job
#: never holds counts + output live at once.  The backend gates use
#: (the operand must be a dead temp — see jax_backend._donate_counts).
vote_packed_simple_donated = partial(
    jax.jit, donate_argnums=0,
    static_argnames=_SIMPLE_STATICS)(_vote_packed_simple_body)


def _vote_packed_body(counts: jax.Array, thr_enc: jax.Array,
                      offsets: jax.Array, site_keys: jax.Array,
                      n_cols: jax.Array, ev_key: jax.Array,
                      ev_col: jax.Array, ev_code: jax.Array,
                      min_depth: int, cp: int, out_enc=None,
                      fill_code: int = 0,
                      epilogue: bool = False) -> jax.Array:
    """Position vote + insertion table + insertion vote + stats, packed.

    ``site_keys``/``n_cols`` are the padded ``[Kp]`` site arrays
    (flat genome position, -1 for end-of-contig and pad sites); ``cp`` is
    the padded insertion-table column count (static).  Pad events scatter
    into the sacrificial row Kp-1.  ``out_enc`` selects the
    position-symbol wire encoding (:func:`_syms_head`);
    ``fill_code``/``epilogue`` the device-resident epilogue statics.
    """
    jitcache.note_trace("vote_packed")
    syms, cov = vote_block(counts, thr_enc, min_depth,
                           _sym_space(out_enc), fill_code)  # [T, L]
    contig_sums, site_cov = _tail_stats(cov, offsets, site_keys)
    kp = site_keys.shape[0]
    table = jnp.zeros((kp, cp, 6), dtype=jnp.int32)
    table = build_insertion_table(table, ev_key, ev_col, ev_code)
    ins_syms = vote_insertions(table, site_cov, n_cols, thr_enc)  # [T,Kp,Cp]
    return jnp.concatenate(
        _syms_head(syms, cov, min_depth, out_enc)
        + [ins_syms.reshape(-1),
           _bytes_of_i32(contig_sums), _bytes_of_i32(site_cov)]
        + _epilogue_sections(syms, offsets, out_enc, epilogue))


_PACKED_STATICS = ("min_depth", "cp", "out_enc", "fill_code", "epilogue")
vote_packed = partial(
    jax.jit, static_argnames=_PACKED_STATICS)(_vote_packed_body)
vote_packed_donated = partial(
    jax.jit, donate_argnums=0,
    static_argnames=_PACKED_STATICS)(_vote_packed_body)


_PALLAS_STATICS = ("min_depth", "cp", "kp", "c6p", "max_blocks",
                   "interpret", "out_enc", "fill_code", "epilogue")


def _vote_packed_pallas_body(counts: jax.Array, thr_enc: jax.Array,
                             offsets: jax.Array, site_keys: jax.Array,
                             n_cols: jax.Array, key3: jax.Array,
                             cc3: jax.Array,
                             blk_lo: jax.Array, blk_n: jax.Array,
                             min_depth: int, cp: int, kp: int, c6p: int,
                             max_blocks: int, interpret: bool = False,
                             out_enc=None, fill_code: int = 0,
                             epilogue: bool = False) -> jax.Array:
    """``vote_packed`` with the insertion table built by the Pallas
    segmented-reduce kernel (ops/pallas_insertion.py) instead of the XLA
    scatter — still one dispatch, one packed uint8 result.

    Inputs are the kernel's host-planned arrays (key-sorted event blocks +
    CSR block ranges); ``site_keys``/``n_cols`` are padded to the KERNEL's
    key padding ``kp`` (a KEY_BLOCK multiple), not the scatter padding.
    ``out_enc`` selects the position-symbol wire encoding
    (:func:`_syms_head`).

    The insertion vote runs INSIDE the kernel (round-4 verdict #2,
    ``pallas_insertion._vote_kernel``): the ``[Kp, Cp, 6]`` count table
    never leaves VMEM — no HBM round trip, no separate vote dispatch —
    except for pathologically wide tables (``cp`` past
    ``FUSED_VOTE_MAX_CP``), where the de-interleave selectors outgrow
    VMEM and the two-step path serves.
    """
    from .pallas_insertion import (FUSED_VOTE_MAX_CP, _table_call,
                                   vote_insertions_fused)

    syms, cov = vote_block(counts, thr_enc, min_depth,
                           _sym_space(out_enc), fill_code)  # [T, L]
    contig_sums, site_cov = _tail_stats(cov, offsets, site_keys)
    if cp <= FUSED_VOTE_MAX_CP:
        ins_syms = vote_insertions_fused(
            key3, cc3, blk_lo, blk_n, site_cov, n_cols, thr_enc,
            kp=kp, c6p=c6p, cp=cp, max_blocks=max_blocks,
            interpret=interpret)
    else:
        out = _table_call(key3, cc3, blk_lo, blk_n, kp=kp, c6p=c6p,
                          max_blocks=max_blocks, interpret=interpret)
        table = out.reshape(kp, c6p)[:, : cp * 6].reshape(kp, cp, 6)
        ins_syms = vote_insertions(table, site_cov, n_cols, thr_enc)
    return jnp.concatenate(
        _syms_head(syms, cov, min_depth, out_enc)
        + [ins_syms.reshape(-1),
           _bytes_of_i32(contig_sums), _bytes_of_i32(site_cov)]
        + _epilogue_sections(syms, offsets, out_enc, epilogue))


vote_packed_pallas = partial(
    jax.jit, static_argnames=_PALLAS_STATICS)(_vote_packed_pallas_body)
vote_packed_pallas_donated = partial(
    jax.jit, donate_argnums=0,
    static_argnames=_PALLAS_STATICS)(_vote_packed_pallas_body)
