from .cigar import split_ops, walk  # noqa: F401
