"""CIGAR engine: the golden scalar walker.

Spec source: ``parsecigar`` at ``/root/reference/sam2consensus.py:46-82``.
Semantics reproduced exactly, including the deliberate quirks documented in
SURVEY.md §2:

* ``M``/``=``/``X`` copy read bases and advance both cursors (``:66-69``);
* ``D``/``N``/``P`` emit ``"-"`` and advance the *reference* cursor
  (``:70-72``) — note ``P`` (padding) consumes reference here, diverging from
  the SAM spec where ``P`` consumes neither (quirk 2);
* ``I`` records ``(ref_cursor, inserted_seq)`` — the cursor value is the index
  of the *next* reference base, which is what produces the right-by-one
  insertion placement in the output (quirk 3) — and advances the read cursor
  (``:73-75``);
* ``S`` skips read bases (``:76-77``); ``H`` is a no-op (``:78-79``);
* any other op prints the reference's (misleading) warning (``:80-81``).

Ops are parsed with the same regex, so malformed CIGAR text degrades the same
way (unmatched trailing garbage is silently ignored).
"""

from __future__ import annotations

import re
from typing import List, Tuple

_CIGAR_RE = re.compile(r"(\d+)([MIDNSHPX=]{1})")

#: ops that consume the reference cursor *as implemented by the reference*
#: (P included — quirk 2), not as the SAM spec defines.
CONSUMES_REF_AS_GAP = frozenset("DNP")
CONSUMES_BOTH = frozenset("M=X")

#: BAM binary op order (SAM spec §4.2: ``op = cigar_u32 & 0xF`` indexes
#: this string) — the one definition shared by the BAM decoder, the BAM
#: writer and the C++ record parser's mirror table (decoder.cpp kOpChr).
BAM_OPS = "MIDNSHP=X"


def render_ops(ops) -> str:
    """((length, op), ...) → CIGAR text (``"*"`` for the empty tuple) —
    the inverse of :func:`split_ops` for in-contract op lists."""
    if not ops:
        return "*"
    return "".join(f"{n}{op}" for n, op in ops)


def split_ops(cigarstring: str) -> List[Tuple[int, str]]:
    """Parse a CIGAR string into (length, op) pairs via the spec regex."""
    return [(int(n), op) for n, op in _CIGAR_RE.findall(cigarstring)]


def walk(cigarstring: str, seq: str, pos_ref: int,
         warn=print) -> Tuple[str, List[Tuple[int, str]]]:
    """Return (aligned_seq, insertions) exactly like the reference.

    ``aligned_seq`` is the read projected onto reference coordinates starting
    at ``pos_ref``: read bases for M/=/X, ``"-"`` runs for D/N/P.
    ``insertions`` is a list of ``(ref_index_of_next_base, motif)`` tuples.
    """
    start = 0
    start_ref = pos_ref
    out: List[str] = []
    insert: List[Tuple[int, str]] = []
    for length, op in split_ops(cigarstring):
        if op in CONSUMES_BOTH:
            out.append(seq[start:start + length])
            start += length
            start_ref += length
        elif op in CONSUMES_REF_AS_GAP:
            out.append("-" * length)
            start_ref += length
        elif op == "I":
            insert.append((start_ref, seq[start:start + length]))
            start += length
        elif op == "S":
            start += length
        elif op == "H":
            continue
        else:  # pragma: no cover - regex admits no other ops
            warn("SAM file probably contains unmapped reads")
    return "".join(out), insert
