"""Streaming consensus sessions: a journaled, crash-safe materialized view.

Every job the serve stack ran before this module was a file that
already existed.  Real heavy-traffic consensus (live basecalling,
read-until adaptive sampling, surveillance feeds) streams reads for
hours against a fixed reference set — so this module promotes PR 12's
per-reference count cache from "warm state between jobs" to a
long-lived per-tenant SESSION whose count tensors are a continuously
updated materialized view over everything absorbed so far.

The unit of ingest is a WAVE: one POST body of SAM read lines against
the session's reference set.  A wave's lifecycle is a strict durability
order, and every crash window between two steps is safe by
construction:

1. the raw body is SPOOLED next to the journal (tmp + fsync + rename —
   a crash leaves either no spool or a whole spool);
2. a ``wave_received`` journal segment records the durable INTENT —
   wave number, body sha256, read count — BEFORE any ingest work.  A
   crash after (1) but before (2) simply never ACKs: the client
   re-sends;
3. the wave is ABSORBED exactly-once into the session's count tensors
   via the checkpoint-shaped seed/capture handoff the count cache
   already proved (one backend run per wave, ``source_id =
   "wave:<n>:<sha12>"``): the session's ``CheckpointState`` seeds the
   run, the wave's reads scatter on top, the vote re-runs, and the
   captured state is saved back ATOMICALLY as the session checkpoint.
   The state is self-fencing: ``sources`` lists every absorbed wave, so
   replaying a wave the checkpoint already covers is a structural no-op
   (the backend's duplicate-shard skip — zero decode, zero scatter,
   same vote);
4. a ``wave_absorbed`` segment commits the wave — sha, cumulative read
   count, the consensus digest, and (fleet mode) the worker + claim
   lineage that lets the journal's lease fence void a zombie's stale
   absorb.

The COUNT-BANK RULE from the cache governs failure: a fault mid-wave
(the ``session_wave_append`` site) invalidates the wave's partition
WHOLE — in-memory state is dropped, the next absorb re-seeds from the
last atomically-saved checkpoint, and the wave replays from its spool.
Nothing is ever half-counted.

Sessions are JOURNAL ENTITIES with the fleet's claim/lease semantics
(the lease machinery in serve/journal.py + serve/fleet.py is
key-generic): a SIGKILLed worker's open session is reaped and stolen
lease-and-all by a peer, which recovers by loading the newest session
checkpoint and replaying exactly the ``wave_received`` intents not yet
covered by ``wave_absorbed`` — 0 lost reads, 0 double-counted reads.
A torn spool (sha mismatch against the journaled intent) is rejected
with reason ``torn`` and surfaces on the session's ``resend`` list —
re-requested, never absorbed.

Wave numbers are NEVER reused, absorbed or rejected: a pre-receive
rejection (declared-sha mismatch, malformed body) consumes its wave
number too.  A ``wave_rejected`` record must uniquely name the wave it
voids — if a later valid wave reused the number, recovery would read
the old rejection as covering the new wave and silently drop ACKed
reads.  Journal replay adds a structural second fence (the rejection's
``seq`` must post-date the wave's intent to gate replay; see
``journal.effective_rejections``), so even a journal written before
this rule holds cannot lose a received wave to a stale rejection.

Early stability (the read-until loop): after every absorb the consensus
digest is compared to the previous wave's; ``stability_waves``
consecutive identical digests emit a ``session_stable`` journal event,
a ``session/stability_events`` counter and a ``stable: true`` field in
every subsequent wave ACK — the signal telling the client to stop
sequencing this target.

Re-vote without re-ingest: an on-demand (or debounced) re-vote runs the
backend with the session seed and an already-absorbed ``source_id`` —
the duplicate-shard skip decodes nothing, scatters nothing, and only
the vote tail runs.

The network front door lives in serve/stream_server.py; this module is
transport-agnostic (tools and tests drive a :class:`SessionManager`
directly).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from .. import observability as obs
from ..config import resolve_decode_threads
from ..formats import open_alignment_input
from ..io.fasta import write_outputs
from ..utils import checkpoint as uckpt
from . import journal as sjournal

logger = logging.getLogger("sam2consensus_tpu.serve.session")

#: consecutive identical consensus digests before the stability verdict
DEFAULT_STABILITY_WAVES = 3
#: seconds a received wave may sit journaled-but-unabsorbed before the
#: next tick absorbs it (0 = absorb synchronously in the request)
DEFAULT_REVOTE_DEBOUNCE = 0.0
#: journaled-but-unabsorbed waves per session before 429 backpressure
DEFAULT_MAX_PENDING = 64
#: absorb attempts per wave before the wave is surfaced as a transient
#: failure to the client (the spool + intent survive for a later retry)
ABSORB_ATTEMPTS = 3


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def consensus_digest(fastas) -> str:
    """Deterministic digest of a vote result — the stability signal and
    the fuzz harness's state-invariance oracle.  Hashes the consensus
    SEQUENCES per reference, deliberately NOT the FASTA headers: the
    header embeds the running coverage, which moves with every absorbed
    wave even when the called consensus has long converged — hashing it
    would make the read-until verdict structurally unreachable."""
    blob = json.dumps(
        [(ref, [r.seq for r in recs])
         for ref, recs in sorted(fastas.items())],
        sort_keys=True)
    return "sha256:" + sha256_hex(blob.encode("utf-8"))


class SessionError(Exception):
    """Typed session-layer failure: ``status`` is the HTTP status the
    front door answers with, ``reason`` the machine-readable label.
    DATA-class rejections (malformed waves) carry ``data_error`` so the
    policy layer never retries or demotes on them."""

    def __init__(self, status: int, reason: str, detail: str = "",
                 retry_after: Optional[float] = None):
        super().__init__(detail or reason)
        self.status = int(status)
        self.reason = reason
        self.retry_after = retry_after
        self.data_error = status == 422


def _atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp + fsync + rename: the spool discipline — a crash leaves
    either no file or a whole file, never a torn one."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _parse_header(header_text: str) -> List[str]:
    """Reference names from the session's SAM header; raises the
    DATA-class SessionError on a header with no usable @SQ line."""
    refs: List[str] = []
    for line in header_text.splitlines():
        if not line.startswith("@SQ"):
            continue
        name = None
        has_len = False
        for f in line.split("\t")[1:]:
            if f.startswith("SN:"):
                name = f[3:]
            elif f.startswith("LN:"):
                try:
                    has_len = int(f[3:]) > 0
                except ValueError:
                    has_len = False
        if name and has_len:
            refs.append(name)
    if not refs:
        raise SessionError(
            422, "bad_header",
            "session header carries no usable @SQ line (SN + LN)")
    return refs


def _count_reads(body: bytes) -> int:
    """Read-line count of a wave body; raises the DATA-class
    SessionError on a line that cannot be a SAM record (fewer than the
    11 mandatory fields).  This is the cheap structural gate — deep
    validation happens in the decoder under the session's bad-record
    policy; a blown budget there is the same DATA class."""
    reads = 0
    for ln, raw in enumerate(body.split(b"\n"), 1):
        if not raw or raw.startswith(b"@"):
            continue
        if raw.count(b"\t") < 10:
            raise SessionError(
                422, "malformed_wave",
                f"wave body line {ln} has "
                f"{raw.count(chr(9).encode()) + 1} fields, not a SAM "
                f"record (11+ expected)")
        reads += 1
    if reads == 0:
        raise SessionError(422, "empty_wave",
                           "wave body carries no read lines")
    return reads


def _load_state(state_dir: str) -> Optional[uckpt.CheckpointState]:
    """The session checkpoint, if present and intact.  The genome
    length is read from the file itself (the session's reference set is
    fixed at open, and the backend re-validates the seed's shape), so
    recovery needs no layout computation before its first absorb."""
    path = uckpt.path_for(state_dir)
    if not os.path.exists(path):
        return None
    try:
        import numpy as np

        with np.load(path, allow_pickle=False) as z:
            n = int(z["counts"].shape[0])
    except Exception:
        return uckpt.load(state_dir, 0)     # counted corrupt -> None
    return uckpt.load(state_dir, n)


@dataclasses.dataclass
class StreamSession:
    """In-memory face of one journaled session (the journal + spool
    directory are the durable truth; everything here is recoverable)."""

    sid: str
    tenant: str
    root: str                       # sessions/<sid>/
    header_text: str
    header_sha: str
    refs: List[str]
    wave_next: int = 1
    #: wave numbers journaled as received but not yet absorbed/rejected
    pending: List[int] = dataclasses.field(default_factory=list)
    #: wave -> {"sha", "reads", "bytes"} for every received wave
    waves: Dict[int, dict] = dataclasses.field(default_factory=dict)
    absorbed: set = dataclasses.field(default_factory=set)
    #: torn waves awaiting a client re-send (new wave number)
    resend: List[int] = dataclasses.field(default_factory=list)
    state: Optional[uckpt.CheckpointState] = None
    fastas: Optional[dict] = None
    reads_total: int = 0
    digest: str = ""
    prev_digest: str = ""
    stable_streak: int = 0
    stable: bool = False
    stable_wave: Optional[int] = None
    closed: bool = False
    stolen_from: str = ""
    last_wave_mono: float = dataclasses.field(
        default_factory=time.monotonic)
    last_wave_unix: float = dataclasses.field(default_factory=time.time)
    #: serializes THIS session's wave lifecycle (receive/absorb/
    #: revote/close) — see SessionManager's concurrency contract
    lock: threading.RLock = dataclasses.field(
        default_factory=threading.RLock, repr=False, compare=False)

    @property
    def state_dir(self) -> str:
        return os.path.join(self.root, "state")

    @property
    def out_dir(self) -> str:
        return os.path.join(self.root, "out")

    def header_path(self) -> str:
        return os.path.join(self.root, "header.sam")

    def body_path(self, wave: int) -> str:
        return os.path.join(self.root, f"wave-{wave:04d}.body.sam")

    def job_path(self, wave: int) -> str:
        return os.path.join(self.root, f"wave-{wave:04d}.job.sam")


class SessionManager:
    """All live sessions of one serve runner, plus the absorb engine.

    Concurrency contract — three lock planes, ordered so observability
    and other tenants never wait behind one session's absorb (a backend
    run can take seconds to minutes):

    * ``_lock`` (manager): guards the ``sessions`` map only — lookups,
      open/adopt inserts, close/zombie pops, gauge sweeps.  Held for
      microseconds, never across a journal replay or a backend run.
    * per-session ``StreamSession.lock``: serializes one session's
      wave lifecycle (receive -> absorb -> commit, revote, close), so
      a slow tenant's absorb blocks only its own session's ingest.
    * ``_backend_lock``: the seed/execute/capture critical section of
      :meth:`_run_wave`.  The backend's ``serve_count_*`` handoff
      registers are process-global, so actual backend runs still
      serialize — but ONLY the runs, not the spool/journal/ACK path,
      not :meth:`status`, not :meth:`health_summary`.

    Ordering: a thread holding a session lock may take the manager or
    backend lock; a thread holding the manager lock never waits on a
    session lock (no cycles).  :meth:`status` and
    :meth:`health_summary` read per-session fields WITHOUT the session
    lock — each field read is GIL-atomic, the snapshot is advisory
    observability, and taking the wave lock would reintroduce the
    absorb-blocks-every-prober stall this contract exists to prevent.

    Session mode owns the runner: no batch queue runs concurrently
    (the CLI enforces it)."""

    def __init__(self, runner, base_cfg,
                 stability_waves: int = DEFAULT_STABILITY_WAVES,
                 revote_debounce: float = DEFAULT_REVOTE_DEBOUNCE,
                 max_pending: int = DEFAULT_MAX_PENDING):
        if runner.journal is None:
            raise ValueError("streaming sessions require --journal: the "
                             "journal IS the session's durable state")
        self.runner = runner
        self.registry = runner.registry
        self.journal = runner.journal
        self.base_cfg = base_cfg
        self.stability_waves = max(1, int(stability_waves))
        self.revote_debounce = max(0.0, float(revote_debounce))
        self.max_pending = max(0, int(max_pending))
        self.sessions: Dict[str, StreamSession] = {}
        self._lock = threading.RLock()          # sessions-map guard
        self._backend_lock = threading.Lock()   # seed/execute/capture
        #: last orphan scan (monotonic); the scan replays the journal
        #: tail from disk, so it runs on its own cadence (a fraction
        #: of the lease TTL, like the fleet reap scan) instead of at
        #: every 10 Hz drain tick
        self._orphan_scan_mono = 0.0
        self.sessions_root = os.path.join(self.journal.root, "sessions")
        os.makedirs(self.sessions_root, exist_ok=True)

    # -- small helpers -----------------------------------------------------
    def _fleet(self):
        return getattr(self.runner, "fleet", None)

    def _get(self, sid: str) -> StreamSession:
        """Resolve a session WITHOUT taking its wave lock — callers
        that mutate re-check ``closed`` under ``sess.lock``."""
        with self._lock:
            sess = self.sessions.get(sid)
        if sess is None:
            # a client retargeting this worker right after its peer
            # died must not wait for the next steal tick: try a
            # one-shot adoption from the journal before 404ing
            sess = self._try_adopt(sid)
        if sess is None:
            raise SessionError(404, "unknown_session",
                               f"no open session {sid!r} on this worker")
        if sess.closed:
            raise SessionError(409, "session_closed",
                               f"session {sid} is closed")
        return sess

    def _check_open(self, sess: StreamSession) -> None:
        """Re-check under ``sess.lock``: a close/zombie-drop may have
        raced the lockless lookup in :meth:`_get`."""
        if sess.closed:
            raise SessionError(409, "session_closed",
                               f"session {sess.sid} is closed")

    def _try_adopt(self, sid: str) -> Optional[StreamSession]:
        """Adopt one journaled session on demand: after a restart (no
        fleet: the journal alone is authority) or a steal (fleet: only
        with a won lease — a live peer's session stays theirs)."""
        try:
            st = self.journal.read_state()
        except Exception:
            return None
        view = st.sessions.get(sid)
        if view is None or view.get("status") == "closed":
            return None
        fl = self._fleet()
        stolen_from = ""
        if fl is not None:
            cur = st.claims.get(sid)
            if cur is not None and cur["worker"] != fl.worker_id \
                    and time.time() < cur["expires_unix"]:
                return None             # live lease elsewhere
            if not fl.try_claim(sid, sid, st=st):
                return None
            if cur is not None and cur["worker"] != fl.worker_id:
                stolen_from = cur["worker"]
        return self._recover(sid, view,
                             tenant=st.tenants.get(sid, ""),
                             stolen_from=stolen_from)

    def _gauges(self) -> None:
        with self._lock:
            sessions = list(self.sessions.values())
        g = self.registry.gauge
        g("session/open").set(float(
            sum(1 for s in sessions if not s.closed)))
        g("session/pending_waves").set(float(
            sum(len(s.pending) for s in sessions)))

    def _append(self, ev: str, **fields) -> None:
        """Journal append via the runner's failure-absorbing wrapper
        for audit events; the DURABLE-INTENT appends (wave_received)
        must raise instead — a wave whose intent could not be journaled
        must not be ACKed."""
        self.runner._journal_append(ev, **fields)

    def _lease_fields(self, sid: str) -> dict:
        fl = self._fleet()
        if fl is None:
            return {}
        return {"worker": fl.worker_id,
                "claim_seq": fl.claim_seqs.get(sid)}

    def _confirm_lease(self, sess: StreamSession) -> None:
        """Fresh-replay confirmation that this worker still holds the
        session's lease — the same pre-commit discipline the fleet job
        path uses.  Losing it means a peer already stole the session
        mid-wave: this worker is the zombie and must drop its state
        (the thief's replay owns the wave now)."""
        fl = self._fleet()
        if fl is None:
            return
        if not fl.holds(sess.sid):
            with self._lock:
                self.sessions.pop(sess.sid, None)
            self._gauges()
            raise SessionError(
                409, "lease_lost",
                f"session {sess.sid} was stolen by a peer (this worker "
                f"stalled past its lease TTL); re-target the thief")

    # -- lifecycle ---------------------------------------------------------
    def open_session(self, header_text: str, tenant: str = "") -> dict:
        """Open a session against a reference set (a SAM header)."""
        refs = _parse_header(header_text)
        header_sha = sha256_hex(header_text.encode("utf-8"))
        with self._lock:
            n_live = len(self.sessions)
        sid = "s-" + sha256_hex(
            f"{header_sha}:{tenant}:{os.getpid()}:"
            f"{time.time():.6f}:{n_live}"
            .encode("utf-8"))[:12]
        root = os.path.join(self.sessions_root, sid)
        os.makedirs(root, exist_ok=True)
        os.makedirs(os.path.join(root, "state"), exist_ok=True)
        os.makedirs(os.path.join(root, "out"), exist_ok=True)
        _atomic_write_bytes(os.path.join(root, "header.sam"),
                            header_text.encode("utf-8"))
        sess = StreamSession(sid=sid, tenant=tenant, root=root,
                             header_text=header_text,
                             header_sha=header_sha, refs=refs)
        fl = self._fleet()
        if fl is not None and not fl.try_claim(sid, sid):
            raise SessionError(  # fresh sid: only a journal outage
                503, "lease_unavailable",
                f"could not open a lease for session {sid}")
        self.journal.append("session_open", key=sid, tenant=tenant,
                            header_sha=header_sha, refs=len(refs))
        with self._lock:
            self.sessions[sid] = sess
        self.registry.add("session/opened", 1)
        self._gauges()
        logger.info("session %s opened (%d reference(s), tenant=%r)",
                    sid, len(refs), tenant or "")
        return {"sid": sid, "refs": len(refs),
                "stability_waves": self.stability_waves}

    def receive_wave(self, sid: str, body: bytes,
                     declared_sha: Optional[str] = None) -> dict:
        """Spool + journal one wave; absorb synchronously unless the
        debounce window defers it to the next tick."""
        sess = self._get(sid)
        with sess.lock:
            self._check_open(sess)
            dec = self.runner.admission.price_wave(
                tenant=sess.tenant, body_bytes=len(body),
                pending_waves=len(sess.pending),
                max_pending=self.max_pending)
            if not dec.admitted:
                self.registry.add("session/waves_shed", 1)
                self.registry.add(
                    f"serve/admission_rejected/{dec.reason}", 1)
                raise SessionError(
                    429, dec.reason,
                    f"wave rejected ({dec.reason}): "
                    f"{len(sess.pending)} wave(s) pending",
                    retry_after=max(1.0, self.revote_debounce or 1.0))
            sha = sha256_hex(body)
            if declared_sha and declared_sha.removeprefix("sha256:") \
                    != sha:
                # the rejection CONSUMES its wave number (wave_next
                # advances): the journaled wave_rejected must never
                # name a number a later valid wave will reuse, or
                # recovery would drop that wave as rejected
                n = sess.wave_next
                sess.wave_next = n + 1
                self._reject_wave(sess, n, "sha_mismatch")
                raise SessionError(
                    422, "sha_mismatch",
                    f"declared body sha256 {declared_sha!r} does not "
                    f"match received bytes ({sha[:12]}…) — torn upload")
            try:
                reads = _count_reads(body)
            except SessionError as exc:
                n = sess.wave_next
                sess.wave_next = n + 1      # consumed, like sha_mismatch
                self._reject_wave(sess, n, exc.reason)
                raise
            n = sess.wave_next
            _atomic_write_bytes(sess.body_path(n), body)
            # the durable intent: this append RAISES on failure (no
            # ACK without a journaled wave) — unlike the audit appends
            self.journal.append("wave_received", key=sid, wave=n,
                                sha=sha, reads=reads, bytes=len(body))
            sess.wave_next = n + 1
            sess.waves[n] = {"sha": sha, "reads": reads,
                             "bytes": len(body)}
            sess.pending.append(n)
            sess.last_wave_mono = time.monotonic()
            sess.last_wave_unix = time.time()
            self.registry.add("session/waves", 1)
            self._gauges()
            if self.revote_debounce > 0:
                return {"sid": sid, "wave": n, "status": "pending",
                        "pending": len(sess.pending),
                        "reads_total": sess.reads_total,
                        "digest": sess.digest, "stable": sess.stable}
            self._absorb_pending(sess)
            return {"sid": sid, "wave": n, "status": "absorbed",
                    "reads_total": sess.reads_total,
                    "digest": sess.digest, "stable": sess.stable,
                    "stable_wave": sess.stable_wave}

    def revote(self, sid: str) -> dict:
        """On-demand re-vote over the absorbed state — zero decode,
        zero scatter (the duplicate-shard skip), only the vote tail."""
        sess = self._get(sid)
        with sess.lock:
            self._check_open(sess)
            self.runner._fault_check("session_revote")
            if sess.pending:
                self._absorb_pending(sess)
            if not sess.absorbed:
                raise SessionError(409, "no_absorbed_waves",
                                   f"session {sid} has absorbed no "
                                   f"waves yet — nothing to vote on")
            n = max(sess.absorbed)
            out = self._run_wave(sess, n, revote=True)
            sess.fastas = out.fastas
            sess.digest = consensus_digest(out.fastas)
            self.registry.add("session/revotes", 1)
            return {"sid": sid, "digest": sess.digest,
                    "reads_total": sess.reads_total,
                    "stable": sess.stable}

    def status(self, sid: str) -> dict:
        """Advisory snapshot, read WITHOUT the session's wave lock (a
        mid-absorb probe answers immediately; see the class
        docstring's concurrency contract)."""
        with self._lock:
            sess = self.sessions.get(sid)
        if sess is None:
            raise SessionError(404, "unknown_session",
                               f"no session {sid!r} on this worker")
        return {
            "sid": sid, "tenant": sess.tenant,
            "closed": sess.closed, "refs": len(sess.refs),
            "waves": len(sess.waves),
            "absorbed": len(sess.absorbed),
            "pending": sorted(list(sess.pending)),
            "resend": sorted(list(sess.resend)),
            "reads_total": sess.reads_total,
            "digest": sess.digest, "stable": sess.stable,
            "stable_wave": sess.stable_wave,
            "stolen_from": sess.stolen_from,
            "last_wave_age_sec": round(
                time.monotonic() - sess.last_wave_mono, 3)}

    def close_session(self, sid: str) -> dict:
        """Absorb the backlog, write the final FASTA outputs, journal
        the terminal event (closing the lease) and forget the session."""
        sess = self._get(sid)
        with sess.lock:
            self._check_open(sess)
            if sess.pending:
                self._absorb_pending(sess)
            outputs: Dict[str, Optional[dict]] = {}
            if sess.fastas is None and sess.absorbed:
                out = self._run_wave(sess, max(sess.absorbed),
                                     revote=True)
                sess.fastas = out.fastas
                sess.digest = consensus_digest(out.fastas)
            if sess.fastas is not None:
                cfg = self.base_cfg
                paths = write_outputs(
                    sess.fastas, sess.out_dir + os.sep,
                    cfg.prefix or sess.sid, cfg.nchar, cfg.thresholds,
                    echo=lambda *a, **k: None)
                outputs = {p: sjournal.file_fingerprint(p)
                           for p in paths}
            self._confirm_lease(sess)
            self._append("session_closed", key=sid, digest=sess.digest,
                         outputs=outputs, reads_total=sess.reads_total,
                         **self._lease_fields(sid))
            fl = self._fleet()
            if fl is not None:
                fl.held.pop(sid, None)      # terminal event closed it
                fl.claim_seqs.pop(sid, None)
            sess.closed = True
            with self._lock:
                self.sessions.pop(sid, None)
            self.registry.add("session/closed", 1)
            self._gauges()
            logger.info("session %s closed: %d wave(s), %d read(s), "
                        "digest %s", sid, len(sess.absorbed),
                        sess.reads_total, sess.digest[:19])
            return {"sid": sid, "digest": sess.digest,
                    "outputs": sorted(outputs),
                    "reads_total": sess.reads_total,
                    "waves": len(sess.absorbed),
                    "stable": sess.stable}

    # -- absorb engine -----------------------------------------------------
    def _reject_wave(self, sess: StreamSession, wave: int,
                     reason: str) -> None:
        """DATA-class wave rejection: journaled for the audit, counted,
        charged to the tenant's poison tally — never retried, never a
        rung demotion (the policy layer's DATA contract)."""
        self._append("wave_rejected", key=sess.sid, wave=wave,
                     reason=reason)
        self.registry.add("session/waves_rejected", 1)
        self.runner.admission.note_poison(sess.tenant)
        if wave in sess.pending:
            sess.pending.remove(wave)

    def _absorb_pending(self, sess: StreamSession) -> None:
        """Drain the session's pending waves IN ORDER, one backend run
        per wave (grouping is forbidden: a crash between group members
        must not change how reads partition into absorbs on replay).
        Caller holds ``sess.lock``."""
        while sess.pending:
            n = sess.pending[0]
            self._absorb_wave(sess, n)

    def _absorb_wave(self, sess: StreamSession, n: int) -> None:
        meta = sess.waves.get(n) or {}
        # spool integrity against the journaled intent: a torn/partial
        # spool is re-requested, never absorbed
        try:
            with open(sess.body_path(n), "rb") as fh:
                body = fh.read()
        except OSError:
            body = b""
        if sha256_hex(body) != meta.get("sha"):
            sess.pending.remove(n)
            sess.resend.append(n)
            self.registry.add("session/torn_waves", 1)
            self._append("wave_rejected", key=sess.sid, wave=n,
                         reason="torn")
            logger.warning("session %s wave %d spool is torn (sha "
                           "mismatch): re-requested, not absorbed",
                           sess.sid, n)
            return
        last_exc: Optional[BaseException] = None
        for attempt in range(ABSORB_ATTEMPTS):
            try:
                self.runner._fault_check("session_wave_append")
                out = self._run_wave(sess, n)
            except SessionError:
                raise
            except Exception as exc:
                from ..resilience.policy import classify

                last_exc = exc
                # count-bank rule: ANY fault mid-wave drops the
                # in-memory state whole; the next attempt re-seeds
                # from the last atomically-saved checkpoint and the
                # wave replays from its spool
                sess.state = None
                if classify(exc) == "data":
                    sess.pending.remove(n)
                    self._reject_wave(sess, n, f"data:{exc}")
                    raise SessionError(
                        422, "poison_wave",
                        f"wave {n} failed DATA-class: {exc}") from exc
                logger.warning(
                    "session %s wave %d absorb attempt %d/%d failed "
                    "(%s: %s)", sess.sid, n, attempt + 1,
                    ABSORB_ATTEMPTS, type(exc).__name__, exc)
                continue
            # -- success: commit the wave -----------------------------
            was_new = n not in sess.absorbed
            if was_new:
                sess.reads_total += int(meta.get("reads", 0))
                self.registry.add("session/reads_absorbed",
                                  int(meta.get("reads", 0)))
            sess.fastas = out.fastas
            digest = consensus_digest(out.fastas)
            self._confirm_lease(sess)
            self.journal.append(
                "wave_absorbed", key=sess.sid, wave=n,
                sha=meta.get("sha", ""), reads_total=sess.reads_total,
                digest=digest, **self._lease_fields(sess.sid))
            sess.absorbed.add(n)
            if n in sess.pending:
                sess.pending.remove(n)
            self.registry.add("session/waves_absorbed", 1)
            self._gauges()
            self._note_stability(sess, n, digest)
            return
        raise SessionError(
            503, "absorb_failed",
            f"wave {n} failed {ABSORB_ATTEMPTS} absorb attempts "
            f"({type(last_exc).__name__}: {last_exc}); the wave stays "
            f"journaled and will be retried", retry_after=1.0)

    def _note_stability(self, sess: StreamSession, n: int,
                        digest: str) -> None:
        if digest == sess.prev_digest:
            sess.stable_streak += 1
        else:
            sess.stable_streak = 1
        sess.prev_digest = digest
        sess.digest = digest
        if sess.stable_streak >= self.stability_waves \
                and not sess.stable:
            sess.stable = True
            sess.stable_wave = n
            self._append("session_stable", key=sess.sid, wave=n,
                         digest=digest,
                         waves_stable=sess.stable_streak)
            self.registry.add("session/stability_events", 1)
            logger.info("session %s consensus stable: digest unchanged "
                        "for %d wave(s) (read-until: stop sequencing)",
                        sess.sid, sess.stable_streak)

    def _run_wave(self, sess: StreamSession, n: int,
                  revote: bool = False):
        """One backend run: seed with the session state, absorb wave
        ``n`` (or skip-decode it on a re-vote of an absorbed wave),
        capture the new state back, save it atomically."""
        meta = sess.waves.get(n) or {}
        sha12 = str(meta.get("sha", ""))[:12]
        source_id = f"wave:{n}:{sha12}"
        job_path = sess.job_path(n)
        if not os.path.exists(job_path):
            with open(sess.body_path(n), "rb") as fh:
                body = fh.read()
            _atomic_write_bytes(
                job_path,
                sess.header_text.rstrip("\n").encode("utf-8") + b"\n"
                + body)
        cfg = dataclasses.replace(
            self.base_cfg, incremental=True, source_id=source_id,
            checkpoint_dir=None, trace_out=None, metrics_out=None,
            json_metrics=None, profile_dir=None,
            outfolder=sess.out_dir + os.sep)
        robs = obs.prepare_run(config=cfg)
        ai = open_alignment_input(job_path, "sam", binary=True,
                                  threads=resolve_decode_threads(cfg))
        runner = self.runner
        job_id = f"{sess.sid}:w{n}" + (":revote" if revote else "")
        if sess.state is None:
            sess.state = _load_state(sess.state_dir)
        # the backend's serve_count_* handoff registers are process-
        # global: the seed/execute/capture sequence is the one section
        # two sessions' absorbs must not interleave
        with self._backend_lock:
            runner._plant_seed(sess.state)
            dlog: List = []
            try:
                out = runner._execute(ai.contigs, ai.stream, cfg, robs,
                                      dlog, job_id)
            except Exception:
                runner.backend.serve_count_result = None
                runner.backend.serve_count_seed = None
                runner.backend.serve_capture_counts = False
                raise
            finally:
                ai.close()
                try:
                    obs.finish_run(robs)
                except Exception:       # instruments are derived state
                    pass
                try:
                    runner.registry.fold(robs.registry, job_id=job_id,
                                         tenant=sess.tenant)
                except Exception:
                    runner.registry.add("telemetry/fold_failed", 1)
            result = getattr(runner.backend, "serve_count_result", None)
            runner.backend.serve_count_result = None
            runner.backend.serve_count_seed = None
            runner.backend.serve_capture_counts = False
        if result is not None and not revote:
            # the atomic save IS the count bank: a crash between here
            # and the wave_absorbed append replays the wave, and the
            # self-describing ``sources`` makes that replay a no-op.
            # A re-vote deliberately skips this — its captured state
            # would list the re-voted source twice.
            sess.state = result
            uckpt.save(sess.state_dir, result)
        return out

    # -- drain / recovery --------------------------------------------------
    def tick(self) -> int:
        """One heartbeat: absorb debounce-expired waves and (on its
        own throttled cadence) adopt orphaned sessions (fleet mode).
        Returns absorbed-wave count — the drain loop's idleness
        signal."""
        absorbed = 0
        with self._lock:
            sessions = list(self.sessions.values())
        now = time.monotonic()
        for sess in sessions:
            if sess.closed or not sess.pending:
                continue
            if self.revote_debounce > 0 and \
                    now - sess.last_wave_mono < self.revote_debounce:
                continue
            with sess.lock:
                if sess.closed:
                    continue
                before = len(sess.absorbed)
                try:
                    self._absorb_pending(sess)
                except SessionError as exc:
                    logger.warning("session %s backlog drain: %s",
                                   sess.sid, exc)
                absorbed += len(sess.absorbed) - before
        fl = self._fleet()
        if fl is not None:
            # the orphan scan replays the journal tail from disk —
            # at the 10 Hz drain cadence that is 10 tail replays/sec
            # per worker for nothing, so it runs at the fleet reap
            # scan's throttle (a fraction of the lease TTL) instead;
            # recovery latency stays bounded by ~TTL + one scan period
            mono = time.monotonic()
            if mono - self._orphan_scan_mono >= max(0.25, fl.ttl / 4):
                self._orphan_scan_mono = mono
                absorbed += self._adopt_orphans()
        return absorbed

    def _adopt_orphans(self) -> int:
        """Steal abandoned sessions: any journal-open session this
        worker doesn't hold in memory whose lease is absent, expired,
        or our own (a restart under the same ``--worker-id``: the
        orphan must not wait for a client to happen to hit its sid) is
        claimed lease-and-all, recovered from its checkpoint + spool
        directory, and its uncovered waves replayed — the fleet's
        work-stealing protocol applied to session keys."""
        fl = self._fleet()
        st = self.journal.read_state()
        absorbed = 0
        now = time.time()
        with self._lock:
            have = set(self.sessions)
        for sid, view in sorted(st.sessions.items()):
            if view.get("status") == "closed" or sid in have:
                continue
            cur = st.claims.get(sid)
            # skip only a LIVE lease held by a PEER (mirrors
            # _try_adopt); our own lease — live or expired — over a
            # session we don't hold in memory is a restart's orphan,
            # and try_claim adopts it by renewal
            if cur is not None and cur["worker"] != fl.worker_id \
                    and now < cur["expires_unix"]:
                continue
            if not fl.try_claim(sid, sid, st=st):
                continue                # lost the steal race
            stolen_from = ""
            if cur is not None and cur["worker"] != fl.worker_id:
                stolen_from = cur["worker"]
            sess = self._recover(sid, view,
                                 tenant=st.tenants.get(sid, ""),
                                 stolen_from=stolen_from)
            if sess is None:
                continue
            with sess.lock:
                before = len(sess.absorbed)
                try:
                    self._absorb_pending(sess)
                except SessionError as exc:
                    logger.warning("stolen session %s replay: %s",
                                   sid, exc)
                absorbed += len(sess.absorbed) - before
        return absorbed

    def _recover(self, sid: str, view: dict, tenant: str = "",
                 stolen_from: str = "") -> Optional[StreamSession]:
        """Rebuild a session's in-memory face from the journal view +
        its on-disk directory; pending = received − absorbed −
        effectively-rejected (the exactly-once replay set).  Only an
        EFFECTIVE rejection gates replay — one journaled after the
        wave's intent, or for a wave never received at all; a stale
        rejection naming a number a later wave legitimately carries
        must not suppress that wave (journal.effective_rejections)."""
        root = os.path.join(self.sessions_root, sid)
        try:
            with open(os.path.join(root, "header.sam"),
                      encoding="utf-8") as fh:
                header_text = fh.read()
            refs = _parse_header(header_text)
        except (OSError, SessionError) as exc:
            logger.warning("session %s unrecoverable (header: %s) — "
                           "leaving it journaled", sid, exc)
            return None
        waves = {int(w): dict(m)
                 for w, m in (view.get("waves") or {}).items()}
        absorbed = {int(w) for w in (view.get("absorbed") or {})}
        rejected = {int(w)
                    for w in sjournal.effective_rejections(view)}
        pending = sorted(set(waves) - absorbed - rejected)
        sess = StreamSession(
            sid=sid, tenant=tenant,
            root=root, header_text=header_text,
            header_sha=sha256_hex(header_text.encode("utf-8")),
            refs=refs, waves=waves, absorbed=absorbed,
            pending=pending,
            reads_total=int(view.get("reads_total") or 0),
            digest=str(view.get("digest", "")),
            prev_digest=str(view.get("digest", "")),
            stable=bool(view.get("stable")),
            stable_wave=view.get("stable_wave"),
            stolen_from=stolen_from)
        # wave_next clears EVERY journaled number, rejected ones
        # included: reusing a rejected number would let its old
        # wave_rejected record void the next wave on a later recovery
        sess.wave_next = max(
            max(waves, default=0),
            max((int(w) for w in (view.get("rejected") or {})),
                default=0)) + 1
        with self._lock:
            existing = self.sessions.get(sid)
            if existing is not None:
                return existing     # a concurrent adopter won the race
            self.sessions[sid] = sess
        self.registry.add("session/recovered", 1)
        if stolen_from:
            self.registry.add("session/steals", 1)
        self._gauges()
        logger.info(
            "session %s adopted (%s): %d wave(s) received, %d absorbed,"
            " %d to replay", sid,
            f"stolen from {stolen_from}" if stolen_from else "recovered",
            len(waves), len(absorbed), len(pending))
        return sess

    # -- health ------------------------------------------------------------
    def health_summary(self) -> dict:
        """The ``sessions`` health-snapshot section (serve/health.py)
        and the s2c_top sessions line's data source.  Built WITHOUT
        any session's wave lock (the map lock is held only for the
        snapshot of the map itself): a mid-absorb health probe answers
        immediately, which is what lets health.py promise that nothing
        in this section blocks."""
        with self._lock:
            sessions = dict(self.sessions)
        now = time.monotonic()
        live = {sid: s for sid, s in sessions.items()
                if not s.closed}
        newest = max((s.last_wave_mono for s in live.values()),
                     default=None)
        return {
            "open": len(live),
            "waves_received": int(
                self.registry.value("session/waves")),
            "waves_absorbed": int(
                self.registry.value("session/waves_absorbed")),
            "waves_rejected": int(
                self.registry.value("session/waves_rejected")),
            "pending": sum(len(s.pending) for s in live.values()),
            "stable": sum(1 for s in live.values() if s.stable),
            "steals": int(self.registry.value("session/steals")),
            "last_wave_age_sec": round(now - newest, 3)
            if newest is not None else None,
            "sessions": {
                sid: {"tenant": s.tenant, "waves": len(s.waves),
                      "absorbed": len(s.absorbed),
                      "pending": len(s.pending),
                      "reads_total": s.reads_total,
                      "stable": s.stable,
                      "digest": s.digest[:19],
                      "last_wave_age_sec": round(
                          now - s.last_wave_mono, 3)}
                for sid, s in sorted(live.items())}}
