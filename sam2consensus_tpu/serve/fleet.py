"""Fleet coordination: N serve workers stealing work from one journal.

ROADMAP item 2(b), scale-out half: every serve capability so far runs
inside exactly one worker process — one crash, one wedge, or one long
job stalls the whole queue.  This module turns the journal's existing
exactly-once machinery (atomic single-event segments, job-key
fingerprints, commit-time output discipline) into a fleet coordinator:
N ``s2c serve --journal DIR --worker-id W`` processes share ONE
journal as a work-stealing queue.

The protocol, built entirely from journal events (serve/journal.py):

* **claim** — before running a job, a worker appends a ``claimed``
  event.  Segment publication is O_EXCL-atomic, so concurrent claims
  for the same key land as distinct, totally-ordered segments; the
  FIRST one (while no lease is open) wins, and the loser observes the
  winner on the post-append replay and moves on.  A claim carries a
  wall-clock lease ``expires_unix = now + lease_ttl``;
* **renew** — the holding worker pushes its leases' expiry on the
  watchdog tick (``lease_renewed``, at half-TTL margin).  Renewal is
  process-liveness, deliberately not job-progress: a wedged DISPATCH
  inside a live worker is the in-process watchdog's job
  (``--stall-timeout`` fails it locally); the lease layer exists for
  workers that stop executing at all — SIGKILL, SIGSTOP, hardware;
* **reap + steal** — every worker's tick also scans peers' leases; one
  past its ``expires_unix`` gets a ``lease_expired`` event (effective
  only if no renewal published first — journal order arbitrates) and
  the reaper re-claims the job, resuming from the dead worker's
  per-job checkpoint when one survived.  The job fingerprint +
  commit-at-output-time discipline already make the re-run idempotent;
  the lease just bounds WHO may run it WHEN;
* **commit confirmation** — immediately before committing outputs, a
  worker re-replays and confirms it still holds the lease.  A worker
  whose lease was reaped (it was frozen, then woke) abandons its
  commit (``fleet/lease_lost``) — the thief owns the job's lifecycle.

Clocks: leases compare wall-clock across processes, so the fleet
assumes workers share a clock (same host, or NTP-bounded skew well
under the TTL).  Two processes with the SAME ``--worker-id`` are
operator error — the id IS the lease identity.

Fleet-global tenant state: ``started``/``committed`` events carry the
tenant, so admission evidence (per-tenant in-flight counts, SLO e2e
burn over committed ``elapsed_sec``) is computed from journal-visible
fleet state rather than one worker's private counters —
:meth:`FleetCoordinator.fleet_burn` / :meth:`seed_window_counts`.

The claim/lease machinery is deliberately KEY-GENERIC: a key is any
journal string, not only a job fingerprint.  Streaming sessions
(serve/session.py) lease their session ids through the same
``try_claim``/renew/reap protocol — a dead worker's open session is
stolen lease-and-all and its unabsorbed waves replayed — with one
asymmetry: a session's ``wave_absorbed`` commits are lease-fenced
like job commits but NOT terminal (the lease stays open until
``session_closed``).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Optional

logger = logging.getLogger("sam2consensus_tpu.serve.fleet")

#: default lease TTL seconds (``--lease-ttl`` / S2C_LEASE_TTL).  Long
#: enough that a healthy worker's renewal cadence (half-TTL, riding
#: the 0.1 s watchdog poll) has two orders of magnitude of margin;
#: short enough that a dead worker's job is re-claimed quickly —
#: recovery latency is bounded by ~TTL + one reap-scan period.
DEFAULT_LEASE_TTL = 30.0


def resolve_lease_ttl(lease_ttl: Optional[float]) -> float:
    if lease_ttl is None:
        raw = os.environ.get("S2C_LEASE_TTL", "")
        if raw:
            try:
                lease_ttl = float(raw)
            except ValueError:
                logger.warning("S2C_LEASE_TTL=%r is not a number: using "
                               "the %gs default", raw, DEFAULT_LEASE_TTL)
    ttl = DEFAULT_LEASE_TTL if lease_ttl is None else float(lease_ttl)
    if not ttl > 0:
        raise ValueError(f"--lease-ttl must be > 0, got {ttl!r}")
    return ttl


class FleetCoordinator:
    """One worker's view of the shared-journal fleet protocol.

    Owned by a :class:`~.runner.ServeRunner` with ``worker_id`` set;
    all journal arbitration happens on FRESH disk reads
    (``journal.read_state()`` — O(tail) thanks to journal checkpoints,
    and mirror-free so the hot path skips replay()'s deepcopy), never
    on the runner's incremental mirror, which cannot see peers'
    appends."""

    def __init__(self, journal, worker_id: str, lease_ttl: float,
                 registry, verify_mode: str = "fast"):
        self.journal = journal
        self.worker_id = worker_id
        self.ttl = float(lease_ttl)
        self.registry = registry
        self.verify_mode = verify_mode
        #: key -> expires_unix for leases THIS worker holds
        self.held: Dict[str, float] = {}
        #: key -> the winning claim's segment seq — the lease LINEAGE
        #: stamped into the commit event, which journal replay fences
        #: against the open claim (a zombie's stale commit is void)
        self.claim_seqs: Dict[str, int] = {}
        #: key -> monotonic time of the last successful renewal
        self.last_renew: Dict[str, float] = {}
        #: key -> wall time this worker WON the key's lease — the
        #: flight recorder's claim-latency epoch (claim_unix -
        #: journal submit time); consumed by the runner at finalize
        self.claim_unix: Dict[str, float] = {}
        #: key -> measured steal gap for leases this worker STOLE:
        #: victim's last lease sign of life (claims entry ``t``) ->
        #: our winning re-claim.  The per-job number fleet_soak's
        #: 2xTTL bound is asserted against, surfaced as
        #: ``sched/<tenant>/steal_latency`` at finalize.
        self.steal_gaps: Dict[str, float] = {}
        self.reaped = 0
        self._last_reap_scan = 0.0
        #: drain liveness backstop (see drain()): seconds of ZERO
        #: journal advance with jobs pending before the drain fails
        #: loudly — a healthy fleet renews within ttl/2, so 6 TTLs of
        #: silence means every append path is dead
        self.drain_stall_budget = max(60.0, 6 * self.ttl)

    # -- journal plumbing --------------------------------------------------
    def _append(self, ev: str, **fields) -> Optional[int]:
        """Append, absorbing write failures (the runner's discipline:
        a journal that cannot be written degrades coordination, never
        correctness — an unjournaled claim simply is not held)."""
        try:
            return self.journal.append(ev, **fields)
        except Exception as exc:
            self.registry.add("fleet/journal_write_failed", 1)
            logger.warning("fleet journal append %s failed (%s: %s)",
                           ev, type(exc).__name__, exc)
            return None

    # -- claims ------------------------------------------------------------
    def _claim_blocked(self, st, key: str,
                       reclaim_stale_failed: bool) -> bool:
        """True when ``key`` is terminal in ``st`` and must NOT be
        (re-)claimed: a HEALTHY commit (outputs verify — claiming
        would re-run and double-commit), or a failure that is not a
        stale pre-restart one the caller chose to retry.  A committed
        record whose outputs no longer verify is claimable: the
        re-run restores them (the serial restart path's contract)."""
        rec = st.committed.get(key)
        if rec is not None:
            return self.journal.verify_outputs(rec,
                                               mode=self.verify_mode)
        if key in st.failed:
            return not reclaim_stale_failed
        return False

    def try_claim(self, key: str, job_id: str, st=None,
                  reclaim_stale_failed: bool = False) -> bool:
        """Contend for ``key``; True iff this worker now holds its
        lease.  Sequence: early-outs on ``st`` (the caller's already-
        fresh view, e.g. the drain round's — saves an O(tail) replay
        per peer-held pending job per poll) -> fresh replay -> (reap
        if expired) -> append ``claimed`` -> re-replay to learn who
        won.  A key terminal in the fresh view is never claimable
        (see :meth:`_claim_blocked`): a peer's healthy commit landing
        between the caller's scan and this call must not let us
        re-run the job — a second commit is exactly the duplication
        the audit forbids."""
        if st is not None:
            now = time.time()
            cur = st.claims.get(key)
            if self._claim_blocked(st, key, reclaim_stale_failed):
                return False
            if cur is not None and cur["worker"] != self.worker_id \
                    and now < cur["expires_unix"]:
                return False            # live lease elsewhere
        try:
            st = self.journal.read_state()
        except Exception as exc:
            logger.warning("fleet claim replay failed (%s: %s)",
                           type(exc).__name__, exc)
            return False
        if self._claim_blocked(st, key, reclaim_stale_failed):
            return False                # went terminal since the scan
        now = time.time()
        cur = st.claims.get(key)
        stole = False
        if cur is not None:
            if cur["worker"] == self.worker_id \
                    and now < cur["expires_unix"]:
                # our own LIVE lease (a restart under the same
                # --worker-id): adopt by renewal — then CONFIRM, like
                # any claim: a peer may have legitimately reaped and
                # stolen it between our replay and the renewal append
                exp = now + self.ttl
                if self._append("lease_renewed", key=key,
                                worker=self.worker_id,
                                expires_unix=round(exp, 3)) is None:
                    return False
                try:
                    st = self.journal.read_state()
                except Exception:
                    return False
                cur = st.claims.get(key)
                if cur is not None \
                        and cur["worker"] == self.worker_id:
                    self.held[key] = exp
                    self.claim_seqs[key] = int(
                        cur.get("claim_seq", 0))
                    self.last_renew[key] = time.monotonic()
                    self.claim_unix[key] = now
                    self.registry.add("fleet/claims", 1)
                    return True
                self.registry.add("fleet/claim_lost", 1)
                self.registry.add("sched/lease_churn", 1)
                return False
            if cur["worker"] != self.worker_id \
                    and now < cur["expires_unix"]:
                return False            # live lease elsewhere
            # expired (a peer's, or a stale incarnation of our own
            # id): reap (journal order voids this if a renewal
            # published first), then contend for the re-claim
            self._append("lease_expired", key=key, worker=cur["worker"],
                         reaper=self.worker_id)
            self.reaped += 1
            self.registry.add("fleet/lease_reaped", 1)
            self.registry.add("sched/lease_churn", 1)
            stole = cur["worker"] != self.worker_id
            # the victim's last lease sign of life (claims entry
            # ``t``): the epoch the steal gap is measured from
            victim_last_t = float(cur.get(
                "t", cur["expires_unix"] - self.ttl))
        exp = now + self.ttl
        seq = self._append("claimed", key=key, job=job_id,
                           worker=self.worker_id,
                           expires_unix=round(exp, 3))
        if seq is None:
            return False                # never run a job we can't claim
        try:
            st = self.journal.read_state()
        except Exception:
            return False
        cur = st.claims.get(key)
        won = cur is not None and cur.get("claim_seq") == seq
        if won:
            self.held[key] = exp
            self.claim_seqs[key] = seq
            self.last_renew[key] = time.monotonic()
            self.claim_unix[key] = now
            self.registry.add("fleet/claims", 1)
            if stole:
                self.registry.add("fleet/steals", 1)
                self.steal_gaps[key] = max(0.0, now - victim_last_t)
        else:
            self.registry.add("fleet/claim_lost", 1)
            self.registry.add("sched/lease_churn", 1)
        return won

    def holds(self, key: str) -> bool:
        """Fresh-replay confirmation that this worker still owns the
        lease — called immediately before committing outputs.  False
        means the lease was reaped (we were presumed dead): the thief
        owns the job now, and our result must be abandoned."""
        try:
            st = self.journal.read_state()
        except Exception:
            return False
        cur = st.claims.get(key)
        ok = (cur is not None and cur["worker"] == self.worker_id
              and time.time() < cur["expires_unix"])
        if not ok:
            self.held.pop(key, None)
            self.claim_seqs.pop(key, None)
            self.last_renew.pop(key, None)
        return ok

    def renew_now(self, key: str) -> None:
        """Unconditionally push a held lease's expiry to now + TTL —
        called right before a potentially slow commit (output write +
        fingerprinting run with no watchdog ticks), so the commit
        window starts with a full TTL of margin."""
        if key not in self.held:
            return
        exp = time.time() + self.ttl
        if self._append("lease_renewed", key=key,
                        worker=self.worker_id,
                        expires_unix=round(exp, 3)) is not None:
            self.held[key] = exp
            self.last_renew[key] = time.monotonic()
            self.registry.add("fleet/lease_renewals", 1)

    def release(self, key: str) -> None:
        """Local bookkeeping after a terminal event (the journal-side
        lease is closed by the ``committed``/``failed`` event)."""
        self.held.pop(key, None)
        self.claim_seqs.pop(key, None)
        self.last_renew.pop(key, None)
        self.claim_unix.pop(key, None)
        self.steal_gaps.pop(key, None)

    # -- the watchdog-tick duties ------------------------------------------
    def tick(self) -> None:
        """Rides the runner's watchdog poll / telemetry tick: renew
        held leases at half-TTL margin; reap peers' expired leases on
        a throttled cadence (a replay per tick would be wasteful at
        the 0.1 s poll rate)."""
        now = time.time()
        for key, exp in list(self.held.items()):
            if exp - now < self.ttl / 2:
                nexp = now + self.ttl
                if self._append("lease_renewed", key=key,
                                worker=self.worker_id,
                                expires_unix=round(nexp, 3)) is not None:
                    self.held[key] = nexp
                    self.last_renew[key] = time.monotonic()
                    self.registry.add("fleet/lease_renewals", 1)
        mono = time.monotonic()
        if mono - self._last_reap_scan >= max(0.25, self.ttl / 4):
            self._last_reap_scan = mono
            try:
                st = self.journal.read_state()
            except Exception:
                return
            self.reap_expired(st)

    def reap_expired(self, st) -> int:
        """Append ``lease_expired`` for every PEER lease past its
        expiry in ``st``; returns the number reaped.  Reaping only
        frees the key — stealing is the subsequent claim."""
        now = time.time()
        n = 0
        for key, cur in list(st.claims.items()):
            if cur["worker"] != self.worker_id \
                    and now >= cur["expires_unix"]:
                self._append("lease_expired", key=key,
                             worker=cur["worker"],
                             reaper=self.worker_id)
                self.reaped += 1
                self.registry.add("fleet/lease_reaped", 1)
                self.registry.add("sched/lease_churn", 1)
                n += 1
                logger.warning(
                    "reaped expired lease: key %s held by worker %r "
                    "(%.1fs past TTL) — its job is re-claimable", key,
                    cur["worker"], now - cur["expires_unix"])
        return n

    # -- fleet-visible state -----------------------------------------------
    def lease_summary(self) -> dict:
        """The health snapshot's ``lease`` section."""
        now = time.time()
        mono = time.monotonic()
        reg = self.registry
        return {
            "ttl_sec": self.ttl,
            "held": {
                key: {
                    "expires_in_sec": round(exp - now, 3),
                    "last_renew_age_sec": round(
                        mono - self.last_renew.get(key, mono), 3),
                } for key, exp in sorted(self.held.items())},
            "reaped": self.reaped,
            "claims": int(reg.value("fleet/claims")),
            "claim_lost": int(reg.value("fleet/claim_lost")),
            "steals": int(reg.value("fleet/steals")),
            "lease_lost": int(reg.value("fleet/lease_lost")),
            "renewals": int(reg.value("fleet/lease_renewals")),
        }

    def fleet_burn(self, st, slo: Optional[dict]) -> Dict[str, int]:
        """Journal-visible SLO e2e burn per tenant: committed events
        whose recorded ``elapsed_sec`` beat the e2e objective — the
        fleet-global counterpart of each worker's private burn
        counters (a tenant cannot reset its burn by spreading slow
        jobs across workers)."""
        obj = (slo or {}).get("e2e")
        out: Dict[str, int] = {}
        if not obj:
            return out
        for key, rec in st.committed.items():
            if float(rec.get("elapsed_sec", 0.0)) > obj:
                t = rec.get("tenant") or st.tenants.get(key) or ""
                out[t] = out.get(t, 0) + 1
        return out

    def seed_window_counts(self, st, own_keys) -> Dict[str, int]:
        """Per-tenant counts of OTHER workers' journal-visible live
        jobs (submitted/started, not terminal, not ours) — seeded into
        the admission window so ``--tenant-quota`` holds against the
        fleet's queue, not just this worker's submission."""
        out: Dict[str, int] = {}
        own = set(own_keys)
        terminal = set(st.committed) | set(st.failed)
        for key in st.submitted:
            if key in own or key in terminal:
                continue
            t = st.tenants.get(key)
            if t:
                out[t] = out.get(t, 0) + 1
        return out

    # -- the work-stealing drain -------------------------------------------
    def drain(self, runner, plan, window_t0, replay, recovery_info):
        """Drain a planned queue cooperatively: claim-run entries this
        worker wins, observe peers' commits/failures for the rest, and
        steal expired leases until every entry is terminal.  Returns
        one JobResult per plan entry, in order."""
        results: Dict[int, object] = {}
        # fleet-global admission evidence (see the module docstring)
        burn = self.fleet_burn(replay, runner.slo)
        for t, n in burn.items():
            if n > runner.admission.slo_burn_by_tenant.get(t, 0):
                runner.admission.slo_burn_by_tenant[t] = n
        # windowed counterpart: feed peer-committed breaches into the
        # burn monitor WITH their commit stamps, so fleet-observed
        # burn decays out of the alert windows like local burn does
        # (getattr: bare stub runners in tests have no monitor)
        note = getattr(runner, "note_fleet_burn", None)
        if callable(note):
            note(replay)
        for i, entry in enumerate(plan):
            if entry["action"] in ("skip", "reject"):
                results[i] = runner._resolve_nonrun(entry, i)
        pending = {i for i, e in enumerate(plan)
                   if e["action"] == "run"}
        #: failures visible at PLAN time are a previous process's —
        #: re-runnable, exactly like the serial restart path (a
        #: failure during THIS drain is terminal for the run).  Each
        #: worker retries a stale failure at most once (``attempted``).
        stale_failed = set(replay.failed) if replay is not None \
            else set()
        attempted: set = set()
        poll = min(0.25, self.ttl / 5)
        #: liveness backstop: a healthy fleet ALWAYS advances the
        #: journal within half a TTL (renewals if nothing else), and a
        #: waiting worker's own reaps advance it too — so a static
        #: last_seq with jobs still pending means every append path is
        #: dead (disk full, permissions): fail LOUDLY instead of
        #: spinning forever
        stall_budget = self.drain_stall_budget
        last_seq_seen = -1
        last_advance = time.monotonic()
        while pending:
            try:
                st = self.journal.read_state()
            except Exception as exc:
                logger.warning("fleet drain replay failed (%s: %s)",
                               type(exc).__name__, exc)
                time.sleep(poll)
                continue
            if st.last_seq != last_seq_seen:
                last_seq_seen = st.last_seq
                last_advance = time.monotonic()
            elif time.monotonic() - last_advance > stall_budget:
                raise RuntimeError(
                    f"fleet drain stalled: {len(pending)} job(s) "
                    f"pending but the journal at {self.journal.root} "
                    f"has not advanced past seq {st.last_seq} for "
                    f"{stall_budget:.0f}s — every append path "
                    f"(claims, renewals, reaps; "
                    f"{int(self.registry.value('fleet/journal_write_failed'))}"
                    f" failed write(s) so far) appears dead.  Check "
                    f"disk space/permissions on the journal volume")
            self.reap_expired(st)
            progressed = False
            for i in sorted(pending):
                entry = plan[i]
                key = entry["key"]
                rec = st.committed.get(key)
                if rec is not None:
                    # terminal ONLY if the recorded outputs verify —
                    # a stale commit whose files drifted or vanished
                    # is exactly what the plan-time verify re-runs in
                    # serial mode, and fleet mode must too (otherwise
                    # corruption is reported as success forever)
                    if runner.journal.verify_outputs(
                            rec, mode=runner.verify_mode):
                        results[i] = \
                            runner._resolve_completed_elsewhere(
                                entry, i, rec)
                        pending.discard(i)
                        progressed = True
                        continue
                    logger.warning(
                        "job %s: journal commit exists but its "
                        "outputs no longer verify — re-claiming to "
                        "re-run", entry["job_id"])
                if key in st.failed and (key not in stale_failed
                                         or key in attempted):
                    results[i] = runner._resolve_failed_elsewhere(
                        entry, i, st.failed[key])
                    pending.discard(i)
                    progressed = True
                    continue
                if not self.try_claim(
                        key, entry["job_id"], st=st,
                        reclaim_stale_failed=(key in stale_failed
                                              and key not in attempted)):
                    continue
                attempted.add(key)
                res = runner._run_claimed_entry(entry, i, window_t0,
                                                recovery_info)
                self.release(key)
                results[i] = res
                pending.discard(i)
                progressed = True
                break           # a whole job ran: the round's view is
                # stale — re-replay before touching the rest
            if pending and not progressed:
                # nothing claimable this round: peers hold every
                # remaining lease.  Tick (renewals are vacuous here,
                # but the reap scan inside is how their deaths are
                # noticed) and wait.
                runner.telemetry_tick()
                time.sleep(poll)
        return [results[i] for i in range(len(plan))]
