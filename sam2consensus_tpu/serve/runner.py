"""The persistent multi-job runner behind ``s2c serve`` / ``submit_jobs``.

One :class:`~..backends.jax_backend.JaxBackend` lives for the server's
lifetime; jobs flow through it sequentially on the device while each
NEXT job's host decode runs ahead on a side thread.  See the package
docstring for the design; the load-bearing pieces here are:

* :class:`_DecodeAhead` — decodes job N+1 (header + segment batches,
  the same ``_make_encoder`` path a cold run uses) on a daemon thread
  with job N+1's OWN instruments thread-bound
  (``observability.bind_run_to_thread``), logging per-batch decode
  intervals;
* the cross-job overlap join — after job N completes, its device
  dispatch intervals (planted via the backend's ``serve_dispatch_log``
  attribute) are intersected with job N+1's decode intervals
  (``wire.pipeline.intersect_sec``) and the result lands in job N+1's
  registry as ``serve/overlap_sec`` before that job runs;
* prewarm — ``ops.pileup.prewarm_scatter`` over the layout's canonical
  slab shapes, bound to the SERVER registry so per-job registries show
  prewarmed shapes as pure ``compile/jit_cache_hit``s.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from .. import observability as obs
from ..config import RunConfig
from ..observability import jitcache
from ..observability.metrics import MetricsRegistry

logger = logging.getLogger("sam2consensus_tpu.serve")

#: decode-ahead batch cap: bounds the memory a pre-decoded job can pin
#: (each batch is ~chunk_reads rows).  Past the cap the remainder
#: decodes lazily inside the job's own run, exactly like a cold run.
DEFAULT_AHEAD_BATCHES = 64


def _ahead_batch_cap() -> int:
    try:
        return max(1, int(os.environ.get("S2C_SERVE_AHEAD_BATCHES",
                                         DEFAULT_AHEAD_BATCHES)))
    except ValueError:
        return DEFAULT_AHEAD_BATCHES


@dataclass
class JobSpec:
    """One consensus job: an input path plus its full RunConfig.

    ``config.backend`` is ignored (the server IS the jax backend);
    checkpoint/incremental modes are rejected — their contract is
    serial decode with stream-consistent snapshots, which serve-mode
    decode-ahead would break."""

    filename: str
    config: RunConfig = field(default_factory=lambda: RunConfig(
        backend="jax"))
    job_id: str = ""


@dataclass
class JobResult:
    """One job's outcome; the server returns one per submitted spec,
    in order, failed jobs included (``error`` set, ``fastas`` None)."""

    job_id: str
    filename: str
    fastas: Optional[dict] = None        # {reference: [FastaRecord]}
    stats: Optional[object] = None       # BackendStats
    error: Optional[str] = None
    elapsed_sec: float = 0.0
    #: 0-based submit order; job 0 pays whatever compile the prewarm
    #: did not hide, jobs 1+ are the warm path
    index: int = 0
    #: per-job counter subset: serve/*, compile/*, resilience/*,
    #: fault/* and phase/*_sec — the amortization/isolation story
    metrics: dict = field(default_factory=dict)
    #: degradation rungs this job ended on ({} = never demoted)
    rungs: dict = field(default_factory=dict)
    manifest: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return self.error is None


class _PredecodedJob:
    """Records-carrier the backend consumes in place of a ReadStream
    (``JaxBackend._make_encoder`` dispatches on ``is_predecoded``)."""

    is_predecoded = True

    def __init__(self, ahead: "_DecodeAhead"):
        self._ahead = ahead

    @property
    def encoder(self):
        return self._ahead.encoder

    @property
    def n_lines(self) -> int:
        stream = self._ahead.stream
        return stream.n_lines if stream is not None else 0

    def batches(self):
        """Already-decoded batches first, then any live remainder; a
        decode error captured on the ahead thread re-raises HERE, at
        the point the cold streaming path would have hit it (same
        exception object, so type/message parity holds)."""
        a = self._ahead
        for batch in a.done_batches:
            yield batch
        if a.error is not None:
            raise a.error
        if a.rest is not None:
            yield from a.rest


class _DecodeAhead:
    """Decode one job's input on a daemon thread, instruments bound."""

    def __init__(self, backend, spec: JobSpec,
                 robs: "obs.RunObservability", cap: int):
        self.spec = spec
        self.robs = robs
        self.contigs = None
        self.stream = None
        self.encoder = None
        self.done_batches: list = []
        self.rest = None
        self.error: Optional[BaseException] = None
        self._backend = backend
        self._cap = cap
        self._lock = threading.Lock()
        self._intervals: List[Tuple[float, float]] = []
        self._handle = None
        self.thread = threading.Thread(target=self._work, daemon=True,
                                       name="serve-decode-ahead")
        self.thread.start()

    def intervals(self) -> List[Tuple[float, float]]:
        with self._lock:
            return list(self._intervals)

    def decode_sec(self) -> float:
        with self._lock:
            return sum(t1 - t0 for t0, t1 in self._intervals)

    def _work(self) -> None:
        from ..encoder.events import GenomeLayout
        from ..io.sam import ReadStream, opener, read_header

        with obs.bind_run_to_thread(self.robs):
            reg = obs.metrics()
            tr = obs.tracer()
            tr.name_thread("serve-decode-ahead")
            try:
                handle = opener(self.spec.filename, binary=True)
                self._handle = handle
                contigs, _n, first = read_header(handle)
                stream = ReadStream(handle, first)
                layout = GenomeLayout(contigs)
                # acc=None: never the fused host-counting encoder — the
                # job's accumulator does not exist yet.  Same native/py
                # decode selection as a cold run otherwise.
                encoder, gen = self._backend._make_encoder(
                    layout, stream, self.spec.config, None)
                self.encoder = encoder
                self.stream = stream
                self.contigs = contigs
                while len(self.done_batches) < self._cap:
                    with tr.span("decode"):
                        t0 = time.perf_counter()
                        try:
                            batch = next(gen)
                        except StopIteration:
                            gen = None
                            break
                        t1 = time.perf_counter()
                        reg.add("phase/decode_sec", t1 - t0)
                    with self._lock:
                        self._intervals.append((t0, t1))
                    self.done_batches.append(batch)
                self.rest = gen
            except BaseException as exc:
                # surfaced to the job when it consumes past the decoded
                # prefix (_PredecodedJob.batches) — or immediately, when
                # even the header never parsed (contigs is None)
                self.error = exc

    def close(self) -> None:
        if self._handle is not None:
            try:
                self._handle.close()
            except OSError:
                pass


class ServeRunner:
    """A warm server: one backend, many jobs (see the package docs).

    ``prewarm``: ``"auto"`` compiles the first job's canonical slab
    shapes on a background thread while that job decodes (device-pileup
    jobs only — a host-routed job dispatches no scatter), ``"off"``
    disables, and :meth:`prewarm` takes explicit shapes at any time.
    ``decode_ahead=False`` serializes jobs exactly like cold runs
    (keeping only the compile-cache wins).  ``persistent_cache``
    controls the on-disk jax compilation cache
    (``observability/jitcache.py``; S2C_JIT_CACHE overrides).
    """

    def __init__(self, prewarm: str = "auto", decode_ahead: bool = True,
                 persistent_cache: bool = True,
                 echo: Optional[Callable] = None):
        from ..backends.jax_backend import JaxBackend

        if prewarm not in ("auto", "off"):
            raise ValueError(f"prewarm={prewarm!r}: use 'auto' or 'off'")
        self.prewarm_mode = prewarm
        self.decode_ahead = decode_ahead
        self.echo = echo or (lambda *a, **k: None)
        self.backend = JaxBackend()
        #: server-lifetime instruments: prewarm traces land here (so
        #: per-job registries show prewarmed shapes as pure hits) plus
        #: the aggregate serve/* counters across the whole queue
        self.registry = MetricsRegistry()
        self.jobs_run = 0
        self._prewarmed: set = set()
        self._prewarm_threads: list = []
        self._prewarm_stop = threading.Event()
        self.cache_dir = jitcache.setup_persistent_cache() \
            if persistent_cache else None
        # a daemon thread killed MID-XLA-COMPILE at interpreter exit
        # aborts the whole process from C++ ("terminate called without
        # an active exception"); close() stops the prewarm loop at the
        # next shape boundary and joins, so exit costs at most one
        # in-flight compile
        import atexit

        atexit.register(self.close)

    def close(self) -> None:
        """Stop background prewarm at the next shape boundary and wait
        for it; idempotent (also registered atexit — and unregistered
        here, so a closed runner is GC-able instead of pinned in the
        atexit table for the process lifetime)."""
        self._prewarm_stop.set()
        for t in self._prewarm_threads:
            if t.is_alive():
                t.join()
        self._prewarm_threads.clear()
        import atexit

        try:
            atexit.unregister(self.close)
        except Exception:
            pass

    # -- prewarm ---------------------------------------------------------
    def prewarm(self, total_len: int, shapes) -> int:
        """Compile the packed scatter for ``shapes`` (``(rows, width)``
        pairs) against a genome of ``total_len`` positions, into the
        server's registry.  Idempotent per (total_len, shape)."""
        from ..ops.pileup import prewarm_scatter

        todo = [s for s in shapes
                if (total_len, tuple(s)) not in self._prewarmed]
        if not todo:
            return 0
        server_obs = obs.RunObservability(
            tracer=obs.tracer(), registry=self.registry,
            ledger=obs.DecisionLedger())
        with obs.bind_run_to_thread(server_obs):
            n = prewarm_scatter(total_len, todo)
        for s in todo:
            self._prewarmed.add((total_len, tuple(s)))
        self.registry.add("compile/prewarm_shapes", n)
        logger.info("prewarmed %d scatter shape(s) for L=%d", n,
                    total_len)
        return n

    def _auto_prewarm(self, spec: JobSpec, total_len: int) -> None:
        """First-job prewarm, hidden behind its decode: compile the
        canonical shapes on a thread.  Device-pileup jobs only — a
        host-routed pileup dispatches no scatter to warm."""
        from ..ops.pileup import canonical_slab_shapes

        if self.prewarm_mode != "auto":
            return
        if spec.config.pileup not in ("scatter", "pallas", "mxu"):
            # --pileup auto resolves per job inside the backend (host
            # vs device by the placement gate) — a host-routed job
            # dispatches no scatter to warm, so auto-prewarm only
            # engages for explicitly device-pinned pileups.  Say so:
            # a silent no-op here reads as "prewarm is broken".
            logger.info(
                "prewarm skipped: --pileup %s (auto-prewarm engages "
                "for explicit device pileups scatter/pallas/mxu; use "
                "ServeRunner.prewarm() for manual shape control)",
                spec.config.pileup)
            return
        shapes = canonical_slab_shapes(
            total_len, chunk_reads=spec.config.chunk_reads)

        def _worker():
            # one shape per prewarm() call so close() can stop the loop
            # at a compile boundary instead of abandoning a C++ compile
            for shape in shapes:
                if self._prewarm_stop.is_set():
                    return
                self.prewarm(total_len, [shape])

        t = threading.Thread(target=_worker, name="serve-prewarm",
                             daemon=True)
        t.start()
        self._prewarm_threads.append(t)

    # -- per-job export destinations -------------------------------------
    def _job_out(self, cfg_value: Optional[str], env_name: str,
                 index: int) -> Optional[str]:
        """A job's metrics/trace destination.  An explicit per-job
        config value wins untouched; an ENV-derived base (S2C_*_OUT)
        is suffixed per job — without this, every serve job would
        resolve to the same env path inside prepare_run and overwrite
        the previous job's artifacts (mode 'w' exports).  ``index`` is
        the offset from ``jobs_run`` AT CALL TIME (0 = the job about
        to run, 1 = the decode-ahead next job)."""
        if cfg_value:
            return cfg_value
        env = os.environ.get(env_name)
        if env:
            return f"{env}.job{self.jobs_run + index}"
        return None

    # -- job validation --------------------------------------------------
    @staticmethod
    def _validate(spec: JobSpec) -> None:
        if spec.config.pileup == "host" and spec.config.shards > 1:
            raise ValueError(
                "--pileup host accumulates on the single host; it does "
                "not compose with --shards (same contract as the "
                "one-shot CLI)")
        if spec.config.checkpoint_dir:
            raise ValueError(
                "serve mode does not compose with --checkpoint-dir: "
                "checkpoints need serial decode with stream-consistent "
                "snapshots, which decode-ahead breaks; run checkpointed "
                "jobs through the one-shot CLI")
        if spec.config.incremental:
            raise ValueError("serve mode does not compose with "
                             "--incremental (see --checkpoint-dir)")

    # -- the queue -------------------------------------------------------
    def submit_jobs(self, specs: List[JobSpec]) -> List[JobResult]:
        """Run the queue; returns one :class:`JobResult` per spec, in
        order.  The server survives failed jobs (their error rides the
        result) and stays warm afterwards for the next submit."""
        from ..io.sam import ReadStream, opener, read_header
        from ..resilience import ladder as rladder
        from ..wire.pipeline import intersect_sec

        for spec in specs:
            self._validate(spec)
        results: List[JobResult] = []
        ahead: Optional[_DecodeAhead] = None
        cap = _ahead_batch_cap()
        for i, spec in enumerate(specs):
            job_id = spec.job_id or \
                f"job{self.jobs_run}:{os.path.basename(spec.filename)}"
            cfg = spec.config
            # -- job context: from the decode-ahead thread, or inline --
            close_handle = None
            if ahead is not None:
                ahead.thread.join()
                robs = ahead.robs
                contigs = ahead.contigs
                records = _PredecodedJob(ahead)
                header_err = ahead.error if contigs is None else None
                close_handle = ahead.close
            else:
                robs = obs.prepare_run(
                    trace_out=self._job_out(cfg.trace_out,
                                            "S2C_TRACE_OUT", 0),
                    metrics_out=self._job_out(cfg.metrics_out,
                                              "S2C_METRICS_OUT", 0),
                    config=cfg)
                contigs = records = None
                header_err = None
                try:
                    handle = opener(spec.filename, binary=True)
                    close_handle = handle.close
                    contigs, _n, first = read_header(handle)
                    records = ReadStream(handle, first)
                except Exception as exc:
                    header_err = exc
            ahead = None
            if i == 0 and contigs is not None:
                from ..encoder.events import GenomeLayout

                self._auto_prewarm(spec, GenomeLayout(contigs).total_len)
            # -- launch the NEXT job's decode-ahead before running ----
            if self.decode_ahead and i + 1 < len(specs):
                nxt = specs[i + 1]
                ahead = _DecodeAhead(
                    self.backend, nxt,
                    obs.prepare_run(
                        trace_out=self._job_out(nxt.config.trace_out,
                                                "S2C_TRACE_OUT", 1),
                        metrics_out=self._job_out(
                            nxt.config.metrics_out, "S2C_METRICS_OUT",
                            1),
                        config=nxt.config), cap)
            # -- run this job -----------------------------------------
            res = JobResult(job_id=job_id, filename=spec.filename,
                            index=i)
            dlog: List[Tuple[float, float]] = []
            t0 = time.perf_counter()
            if header_err is not None:
                res.error = f"{type(header_err).__name__}: {header_err}"
                if close_handle is not None:
                    close_handle()
            else:
                self.backend.serve_prepared_obs = robs
                self.backend.serve_dispatch_log = dlog
                try:
                    out = self.backend.run(contigs, records, cfg)
                    res.fastas, res.stats = out.fastas, out.stats
                except Exception as exc:
                    res.error = f"{type(exc).__name__}: {exc}"
                    logger.warning("job %s failed: %s", job_id,
                                   res.error)
                finally:
                    self.backend.serve_prepared_obs = None
                    self.backend.serve_dispatch_log = None
                    if close_handle is not None:
                        close_handle()
            res.elapsed_sec = time.perf_counter() - t0
            snap = robs.registry.snapshot()
            res.metrics = {
                k: v for k, v in snap["counters"].items()
                if k.startswith(("serve/", "compile/", "resilience/",
                                 "fault/", "phase/"))}
            res.rungs = rladder.job_rungs(snap)
            res.manifest = obs.last_manifest() if res.ok else None
            results.append(res)
            self.jobs_run += 1
            self.registry.add("serve/jobs", 1)
            if not res.ok:
                self.registry.add("serve/jobs_failed", 1)
            # -- cross-job overlap: bill it to the job whose decode
            #    was hidden (N+1), before that job runs ---------------
            if ahead is not None:
                ov = intersect_sec(ahead.intervals(), dlog)
                ahead.robs.registry.add("serve/overlap_sec", ov)
                ahead.robs.registry.add("serve/decode_ahead_sec",
                                        ahead.decode_sec())
                ahead.robs.registry.gauge("serve/overlap").set_info({
                    "overlap_sec": round(ov, 4),
                    "decode_ahead_sec": round(ahead.decode_sec(), 4),
                    "overlapped_job": job_id})
                self.registry.add("serve/overlap_sec", ov)
            self.echo(f"[serve] {job_id}: "
                      + (f"ok in {res.elapsed_sec:.2f}s"
                         if res.ok else f"FAILED ({res.error})"))
        return results


def submit_jobs(specs: List[JobSpec], **runner_kwargs) -> List[JobResult]:
    """One-call API: build a :class:`ServeRunner`, run the queue, return
    the results (the runner — and its warm caches — is discarded; hold a
    ServeRunner yourself to amortize across submits)."""
    runner = ServeRunner(**runner_kwargs)
    try:
        return runner.submit_jobs(specs)
    finally:
        runner.close()                  # join prewarm + drop atexit ref
