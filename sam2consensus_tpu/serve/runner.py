"""The persistent multi-job runner behind ``s2c serve`` / ``submit_jobs``.

One :class:`~..backends.jax_backend.JaxBackend` lives for the server's
lifetime; jobs flow through it sequentially on the device while each
NEXT job's host decode runs ahead on a side thread.  See the package
docstring for the design; the load-bearing pieces here are:

* :class:`_DecodeAhead` — decodes job N+1 (header + segment batches,
  the same ``_make_encoder`` path a cold run uses) on a daemon thread
  with job N+1's OWN instruments thread-bound
  (``observability.bind_run_to_thread``), logging per-batch decode
  intervals;
* the cross-job overlap join — after job N completes, its device
  dispatch intervals (planted via the backend's ``serve_dispatch_log``
  attribute) are intersected with job N+1's decode intervals
  (``wire.pipeline.intersect_sec``) and the result lands in job N+1's
  registry as ``serve/overlap_sec`` before that job runs;
* prewarm — ``ops.pileup.prewarm_scatter`` over the layout's canonical
  slab shapes, bound to the SERVER registry so per-job registries show
  prewarmed shapes as pure ``compile/jit_cache_hit``s.

Survivability layer (this PR), all opt-in and orthogonal to the warm
path:

* **journal** (``journal_dir=``/``--journal``) — every job's lifecycle
  is durably recorded (serve/journal.py) and each journaled job gets a
  per-job PR-2 checkpoint home, so a ``kill -9`` mid-queue costs at
  most the uncheckpointed part of ONE job: a restarted server skips
  committed jobs by output fingerprint and resumes the in-flight one
  from its checkpoint.  Journal mode writes each job's outputs itself
  (commit = outputs durably on disk) and disables decode-ahead —
  checkpoint consistency requires serial decode (the same reason the
  one-shot CLI forces it);
* **watchdog** (``job_timeout=``/``--job-timeout``/``S2C_JOB_TIMEOUT``,
  plus ``stall_timeout``/``S2C_STALL_TIMEOUT``) — jobs run on a worker
  thread monitored against a wall-clock deadline AND a dispatch
  heartbeat (the dispatch-interval log the runner already keeps for
  the overlap join).  A wedged dispatch fails ONLY its job
  (classified via resilience/policy.py; under ``--on-device-error
  fallback`` the job retries once on the ladder's host rung), and the
  server keeps draining the queue;
* **admission control** (``max_queue=``/``tenant_quota=``,
  serve/admission.py) — bounded submission with reject-with-reason,
  per-tenant quotas, and degraded-tenant pinning riding
  ``ladder.job_rungs``;
* **health** (serve/health.py) — a readiness snapshot cut at every job
  boundary, written atomically to ``--health-out`` and embedded in
  each job's manifest ``serve`` section.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from .. import observability as obs
from ..config import RunConfig
from ..observability import jitcache
from ..observability import ratecard as rcard
from ..observability import telemetry as stele
from ..observability.burn import BurnMonitor
from ..observability.metrics import MetricsRegistry
from . import health as shealth
from . import journal as sjournal
from .admission import AdmissionController

logger = logging.getLogger("sam2consensus_tpu.serve")

#: decode-ahead batch cap: bounds the memory a pre-decoded job can pin
#: (each batch is ~chunk_reads rows).  Past the cap the remainder
#: decodes lazily inside the job's own run, exactly like a cold run.
DEFAULT_AHEAD_BATCHES = 64

#: watchdog poll period — cheap (a thread join with timeout), frequent
#: enough that a 1 s --job-timeout overshoots by at most ~10%
WATCHDOG_POLL_S = 0.1


def _ahead_batch_cap() -> int:
    try:
        return max(1, int(os.environ.get("S2C_SERVE_AHEAD_BATCHES",
                                         DEFAULT_AHEAD_BATCHES)))
    except ValueError:
        return DEFAULT_AHEAD_BATCHES


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        logger.warning("%s=%r is not a number: ignored", name, raw)
        return None


@dataclass
class JobSpec:
    """One consensus job: an input path plus its full RunConfig.

    ``config.backend`` is ignored (the server IS the jax backend);
    checkpoint/incremental modes are rejected — their contract is
    serial decode with stream-consistent snapshots, which serve-mode
    decode-ahead would break (journal mode manages per-job checkpoints
    itself, with decode-ahead off).  ``tenant`` scopes admission
    quotas and degraded-tenant pinning ("" = untenanted)."""

    filename: str
    config: RunConfig = field(default_factory=lambda: RunConfig(
        backend="jax"))
    job_id: str = ""
    tenant: str = ""


@dataclass
class JobResult:
    """One job's outcome; the server returns one per submitted spec,
    in order, failed jobs included (``error`` set, ``fastas`` None)."""

    job_id: str
    filename: str
    fastas: Optional[dict] = None        # {reference: [FastaRecord]}
    stats: Optional[object] = None       # BackendStats
    error: Optional[str] = None
    elapsed_sec: float = 0.0
    #: 0-based submit order; job 0 pays whatever compile the prewarm
    #: did not hide, jobs 1+ are the warm path
    index: int = 0
    #: per-job counter subset: serve/*, compile/*, resilience/*,
    #: fault/* and phase/*_sec — the amortization/isolation story
    metrics: dict = field(default_factory=dict)
    #: degradation rungs this job ended on ({} = never demoted)
    rungs: dict = field(default_factory=dict)
    manifest: Optional[dict] = None
    #: journal resume: True = skipped because a previous process
    #: committed this job and its outputs still fingerprint-match
    resumed: bool = False
    #: output files this job's commit wrote (journal mode only — the
    #: runner writes outputs there so commit == durably on disk)
    output_paths: List[str] = field(default_factory=list)
    #: admission verdict: None = admitted clean, "pinned:<rung>" =
    #: admitted on the tenant's demoted rung, else the reject reason
    admission: Optional[str] = None
    #: tolerant decode (--on-bad-record): malformed records this job
    #: skipped/quarantined (0 under the strict default)
    bad_records: int = 0
    #: entries captured to the job's quarantine sidecar
    quarantined: int = 0
    #: True = the job failed because its --max-bad-records budget blew
    #: (DATA class: failed fast, no retry, no rung demotion, tenant
    #: stays on the device path)
    budget_exhausted: bool = False
    #: fleet mode: the worker that committed this job — THIS worker's
    #: id when it ran the job itself, a peer's id when the result is a
    #: journal-observed commit (``resumed`` True, ``fastas`` None)
    worker: str = ""

    @property
    def ok(self) -> bool:
        return self.error is None


class _PredecodedJob:
    """Records-carrier the backend consumes in place of a ReadStream
    (``JaxBackend._make_encoder`` dispatches on ``is_predecoded``)."""

    is_predecoded = True

    def __init__(self, ahead: "_DecodeAhead"):
        self._ahead = ahead

    @property
    def encoder(self):
        return self._ahead.encoder

    @property
    def n_lines(self) -> int:
        stream = self._ahead.stream
        return stream.n_lines if stream is not None else 0

    def batches(self):
        """Already-decoded batches first, then any live remainder; a
        decode error captured on the ahead thread re-raises HERE, at
        the point the cold streaming path would have hit it (same
        exception object, so type/message parity holds)."""
        a = self._ahead
        for batch in a.done_batches:
            yield batch
        if a.error is not None:
            raise a.error
        if a.rest is not None:
            yield from a.rest


class _DecodeAhead:
    """Decode one job's input on a daemon thread, instruments bound.

    ``fault_cb`` is the runner's queue-lifetime injector hook — the
    ``serve_decode_ahead`` site fires per decoded batch (and before
    the header parse, so call 0 models a poisoned open)."""

    def __init__(self, backend, spec: JobSpec,
                 robs: "obs.RunObservability", cap: int,
                 fault_cb: Optional[Callable[[str], None]] = None):
        self.spec = spec
        self.robs = robs
        self.contigs = None
        self.stream = None
        self.encoder = None
        self.done_batches: list = []
        self.rest = None
        self.error: Optional[BaseException] = None
        self._backend = backend
        self._cap = cap
        self._fault_cb = fault_cb
        self._lock = threading.Lock()
        self._intervals: List[Tuple[float, float]] = []
        self._handle = None
        self.thread = threading.Thread(target=self._work, daemon=True,
                                       name="serve-decode-ahead")
        self.thread.start()

    def intervals(self) -> List[Tuple[float, float]]:
        with self._lock:
            return list(self._intervals)

    def decode_sec(self) -> float:
        with self._lock:
            return sum(t1 - t0 for t0, t1 in self._intervals)

    def _work(self) -> None:
        from ..config import resolve_decode_threads
        from ..encoder.events import GenomeLayout
        from ..formats import open_alignment_input

        with obs.bind_run_to_thread(self.robs):
            stele.set_log_context(job_id=self.spec.job_id,
                                  tenant=self.spec.tenant,
                                  thread="decode-ahead")
            reg = obs.metrics()
            tr = obs.tracer()
            tr.name_thread("serve-decode-ahead")
            try:
                if self._fault_cb is not None:
                    self._fault_cb("serve_decode_ahead")
                ai = open_alignment_input(
                    self.spec.filename,
                    getattr(self.spec.config, "input_format", "auto"),
                    binary=True,
                    threads=resolve_decode_threads(self.spec.config))
                self._handle = ai
                contigs, stream = ai.contigs, ai.stream
                layout = GenomeLayout(contigs)
                # acc=None: never the fused host-counting encoder — the
                # job's accumulator does not exist yet.  Same native/py
                # decode selection as a cold run otherwise.
                encoder, gen = self._backend._make_encoder(
                    layout, stream, self.spec.config, None)
                self.encoder = encoder
                self.stream = stream
                self.contigs = contigs
                while len(self.done_batches) < self._cap:
                    if self._fault_cb is not None:
                        self._fault_cb("serve_decode_ahead")
                    with tr.span("decode"):
                        t0 = time.perf_counter()
                        try:
                            batch = next(gen)
                        except StopIteration:
                            gen = None
                            break
                        t1 = time.perf_counter()
                        reg.add("phase/decode_sec", t1 - t0)
                    with self._lock:
                        self._intervals.append((t0, t1))
                    self.done_batches.append(batch)
                    # residency: predecoded batches pin memory until
                    # job N+1 consumes them (memplane decode_ahead
                    # family; released when the batch is collected)
                    from ..observability import memplane

                    memplane.track_obj("decode_ahead", batch,
                                       memplane.batch_nbytes(batch))
                self.rest = gen
            except BaseException as exc:
                # surfaced to the job when it consumes past the decoded
                # prefix (_PredecodedJob.batches) — or immediately, when
                # even the header never parsed (contigs is None)
                self.error = exc

    def close(self) -> None:
        if self._handle is not None:
            try:
                self._handle.close()
            except OSError:
                pass


class ServeRunner:
    """A warm server: one backend, many jobs (see the package docs).

    ``prewarm``: ``"auto"`` compiles the first job's canonical slab
    shapes on a background thread while that job decodes (device-pileup
    jobs only — a host-routed job dispatches no scatter), ``"off"``
    disables, and :meth:`prewarm` takes explicit shapes at any time.
    ``decode_ahead=False`` serializes jobs exactly like cold runs
    (keeping only the compile-cache wins).  ``persistent_cache``
    controls the on-disk jax compilation cache
    (``observability/jitcache.py``; S2C_JIT_CACHE overrides).

    Survivability knobs (all default-off; see the module docstring):
    ``journal_dir``, ``job_timeout``/``stall_timeout`` (env
    S2C_JOB_TIMEOUT / S2C_STALL_TIMEOUT when None), ``max_queue``,
    ``tenant_quota``, ``health_out``, and ``fault_inject`` — the
    runner-scope injector spec for the serve-level sites
    (serve_decode_ahead / journal_write; env S2C_FAULT_INJECT when
    empty).

    Continuous batching (``batch``/``batch_window``, default off —
    serve/scheduler.py): eligible small jobs are packed into shared
    slabs riding one dispatch sequence, with per-job count partitions
    extracted for byte-identical per-job consensus; SLO-burning
    tenants flush the filling batch immediately.

    Fleet mode (``worker_id``/``lease_ttl`` — serve/fleet.py;
    requires ``journal_dir``, excludes ``batch``): this runner joins
    the journal as one of N work-stealing workers — submit_jobs
    arbitrates every entry through atomic claim/lease events instead
    of the serial loop.  ``verify_outputs`` ("fast"/"full") controls
    resume-time output verification (stat fast path vs full re-hash).
    """

    def __init__(self, prewarm: str = "auto", decode_ahead: bool = True,
                 persistent_cache: bool = True,
                 echo: Optional[Callable] = None,
                 journal_dir: Optional[str] = None,
                 job_timeout: Optional[float] = None,
                 stall_timeout: Optional[float] = None,
                 max_queue: int = 0, tenant_quota: int = 0,
                 health_out: Optional[str] = None,
                 fault_inject: str = "",
                 telemetry_out: Optional[str] = None,
                 telemetry_port: Optional[int] = None,
                 telemetry_interval: Optional[float] = None,
                 slo=None,
                 profile_capture_dir: Optional[str] = None,
                 batch="off", batch_window: Optional[float] = None,
                 count_cache=None, mem_budget=None,
                 worker_id: str = "",
                 lease_ttl: Optional[float] = None,
                 verify_outputs: str = "fast"):
        from ..backends.jax_backend import JaxBackend

        if prewarm not in ("auto", "off"):
            raise ValueError(f"prewarm={prewarm!r}: use 'auto' or 'off'")
        self.prewarm_mode = prewarm
        self.decode_ahead = decode_ahead
        self.echo = echo or (lambda *a, **k: None)
        self.backend = JaxBackend()
        #: server-lifetime instruments (observability/telemetry.py
        #: AggregateRegistry): prewarm traces land here (so per-job
        #: registries show prewarmed shapes as pure hits), the
        #: aggregate serve/* counters across the whole queue, and —
        #: folded in at every job end — each job's phase counters,
        #: gauges and histograms, plus the per-tenant SLO histograms
        self.registry = stele.AggregateRegistry()
        self.jobs_run = 0
        #: flight recorder (observability/flight.py) state: journal
        #: submit wall time per key (replay.submit_times for restarted
        #: queues, append time for fresh submissions) — the epoch the
        #: journal-measured queue wait and claim latency count from
        self._submit_unix: dict = {}
        #: accumulated run-attempt seconds — the live numerator of the
        #: sched/occupancy_ratio gauge (busy / uptime)
        self._busy_sec = 0.0
        self._prewarmed: set = set()
        self._prewarm_threads: list = []
        self._prewarm_stop = threading.Event()
        self.cache_dir = jitcache.setup_persistent_cache() \
            if persistent_cache else None
        # -- survivability state --------------------------------------
        self.job_timeout = job_timeout if job_timeout is not None \
            else _env_float("S2C_JOB_TIMEOUT")
        self.stall_timeout = stall_timeout if stall_timeout is not None \
            else _env_float("S2C_STALL_TIMEOUT")
        # capacity-priced admission (observability/memplane.py): a job
        # whose predicted peak exceeds the budget is shed with reason
        # "capacity" instead of being allowed to OOM the warm server.
        # Same size grammar as --count-cache; a typo fails the start.
        from . import countcache as _ccache

        try:
            _mem_budget = _ccache.parse_budget(
                mem_budget if mem_budget is not None
                else os.environ.get("S2C_MEM_BUDGET"))
        except ValueError as exc:
            raise ValueError(str(exc).replace(
                "--count-cache", "--mem-budget")) from None
        # mesh scale-out capacity (hosts the fleet can dedicate to one
        # sharded job): lets the capacity gate PLAN instead of shed —
        # an over-budget job admitted with a "needs K hosts"
        # mesh_shards verdict (observability/memplane.plan_mesh_shards)
        try:
            _mesh_hosts = int(os.environ.get("S2C_MESH_HOSTS", "0"))
        except ValueError:
            raise ValueError(
                "S2C_MESH_HOSTS must be an integer host count") \
                from None
        self.admission = AdmissionController(
            max_queue=max_queue, tenant_quota=tenant_quota,
            mem_budget=_mem_budget, mesh_hosts=_mesh_hosts)
        # -- continuous batching (serve/scheduler.py) -----------------
        # a typo'd --batch must fail the server start, same discipline
        # as --slo / --fault-inject
        from .scheduler import BatchScheduler

        self.scheduler = BatchScheduler(self, batch=batch,
                                        window_ms=batch_window)
        # -- incremental consensus (serve/countcache.py) ---------------
        # a typo'd budget fails the server start, same discipline as
        # --batch / --slo
        from . import countcache as ccache

        self.count_cache = ccache.from_config(
            count_cache if count_cache is not None
            else os.environ.get("S2C_COUNT_CACHE"))
        self.health = shealth.HealthState()
        #: last finished job's tolerant-decode verdict, surfaced in the
        #: health snapshot (per-job history lives in each JobResult)
        self.last_job_badrec: Optional[dict] = None
        self.health_out = health_out
        self._fault = self._build_fault_injector(fault_inject)
        if verify_outputs not in ("fast", "full"):
            raise ValueError(
                f"verify_outputs={verify_outputs!r}: use 'fast' "
                f"(skip-by-stat, re-hash on drift) or 'full' "
                f"(re-hash everything)")
        self.verify_mode = verify_outputs
        self.journal: Optional[sjournal.JobJournal] = None
        if journal_dir:
            self.journal = sjournal.JobJournal(journal_dir,
                                               fault_cb=self._fault_check)
            if self.decode_ahead:
                # checkpoint consistency requires serial decode (the
                # stream offset snapshotted must match the batches
                # already committed to counts) — same contract that
                # makes the one-shot CLI serialize under
                # --checkpoint-dir.  Survivability buys it here.
                logger.info("journal mode: decode-ahead disabled "
                            "(per-job checkpoints need serial decode)")
                self.decode_ahead = False
        # -- fleet mode (serve/fleet.py): N workers, one journal -------
        from .fleet import FleetCoordinator, resolve_lease_ttl

        self.worker_id = str(worker_id or "")
        self.fleet: Optional[FleetCoordinator] = None
        if self.worker_id:
            if self.journal is None:
                raise ValueError(
                    "--worker-id requires --journal: the shared "
                    "journal IS the fleet's work-stealing queue")
            if self.scheduler.enabled:
                raise ValueError(
                    "--worker-id does not compose with --batch: "
                    "packed batches would need batch-level leases; "
                    "run fleet workers serial (the fleet IS the "
                    "parallelism)")
            if self.count_cache is not None:
                raise ValueError(
                    "--worker-id does not compose with --count-cache: "
                    "incremental jobs are already rejected on a "
                    "journaled server, so the cache could never be "
                    "consulted — configuring it would be a silent "
                    "no-op")
            ttl = resolve_lease_ttl(lease_ttl)
            self.fleet = FleetCoordinator(self.journal, self.worker_id,
                                          ttl, self.registry,
                                          verify_mode=self.verify_mode)
            self.registry.gauge("fleet/worker").set_info(
                {"worker": self.worker_id, "lease_ttl_sec": ttl})
            self._fleet_first_run_seen = False
            logger.info("fleet worker %r on journal %s (lease TTL "
                        "%gs)", self.worker_id, self.journal.root, ttl)
        # -- telemetry plane (observability/telemetry.py) --------------
        # strictly best-effort: every write path below degrades to the
        # per-job manifests (telemetry/write_failed counter + warning)
        # and never fails a job
        self.slo = dict(slo) if isinstance(slo, dict) \
            else stele.parse_slo(slo)
        self.telemetry_out = telemetry_out
        try:
            self.telemetry_interval = float(
                telemetry_interval if telemetry_interval is not None
                else os.environ.get("S2C_TELEMETRY_INTERVAL",
                                    stele.DEFAULT_INTERVAL_S))
        except ValueError:
            self.telemetry_interval = stele.DEFAULT_INTERVAL_S
        self._telemetry_last = 0.0
        #: profiler captures land next to the journal (the durable
        #: place an operator already looks), else next to the
        #: exposition file, else the cwd
        cap_dir = profile_capture_dir or \
            (self.journal.root if self.journal is not None else None) \
            or (os.path.dirname(telemetry_out) or "."
                if telemetry_out else ".")
        self.profiler = stele.ProfilerCapture(cap_dir)
        self.profiler.install_signal()
        self.http: Optional[stele.TelemetryServer] = None
        if telemetry_port is not None:
            self.http = stele.TelemetryServer(
                self.render_telemetry, self.health_snapshot,
                port=telemetry_port)
            logger.info("telemetry endpoint on 127.0.0.1:%d "
                        "(/metrics, /healthz)", self.http.port)
        # -- evidence plane: rate card + burn monitor ------------------
        # the card learns per-worker throughput constants from finished
        # jobs; journaled servers persist it next to the journal so a
        # restart resumes with aged-but-confident estimates instead of
        # cold defaults.  A corrupt or stale card reads as absent (with
        # a counter) — it never fails a job.
        card_name = self.worker_id or "serve"
        if self.journal is not None:
            self.ratecard = rcard.RateCard.load(
                rcard.card_path(self.journal.root, card_name),
                worker=card_name, registry=self.registry)
        else:
            self.ratecard = rcard.RateCard(worker=card_name)
        rcard.install(self.ratecard)
        self.ratecard.publish(self.registry)
        self.registry.gauge("process/start_time_seconds").set(
            round(time.time(), 3))
        self.burn = BurnMonitor(self.registry)
        self.admission.burn_monitor = self.burn
        #: latest evidence-only scale hint (journaled servers); the
        #: drain episode tracker joins projected vs measured drain
        self.last_scale_hint: Optional[dict] = None
        self._drain_t0: Optional[float] = None
        self._drain_hint: Optional[dict] = None
        self._scale_hint_episodes = 0
        #: journal keys already fed to the burn monitor (local
        #: finalizes + fleet replay) — prevents double-counting when
        #: drain() replays this life's own commits
        self._burn_fed_keys: set = set()
        # a daemon thread killed MID-XLA-COMPILE at interpreter exit
        # aborts the whole process from C++ ("terminate called without
        # an active exception"); close() stops the prewarm loop at the
        # next shape boundary and joins, so exit costs at most one
        # in-flight compile
        import atexit

        atexit.register(self.close)

    @staticmethod
    def _build_fault_injector(spec: str):
        from ..resilience.faultinject import FaultInjector, parse_spec

        spec = spec or os.environ.get("S2C_FAULT_INJECT", "")
        if not spec:
            return None
        try:
            rules = parse_spec(spec)
        except ValueError:
            # a malformed env spec is the backend's problem to report
            # (it validates per job); the runner-scope sites just stay
            # silent rather than double-raising
            return None
        seed = int(os.environ.get("S2C_FAULT_SEED", "0"))
        return FaultInjector(rules, seed=seed)

    def _fault_check(self, site: str) -> None:
        """Queue-lifetime injection for the serve-scope sites — call
        counts survive across jobs (the per-run injector resets per
        job, which would make ``journal_write:rpc:2`` meaningless)."""
        if self._fault is not None:
            self._fault.check(site)

    def close(self) -> None:
        """Stop background prewarm at the next shape boundary and wait
        for it; idempotent (also registered atexit — and unregistered
        here, so a closed runner is GC-able instead of pinned in the
        atexit table for the process lifetime)."""
        self._prewarm_stop.set()
        for t in self._prewarm_threads:
            if t.is_alive():
                t.join()
        self._prewarm_threads.clear()
        if self.http is not None:
            self.http.close()
            self.http = None
        if getattr(self, "ratecard", None) is not None:
            if rcard.installed() is self.ratecard:
                rcard.install(None)
            try:
                self.ratecard.save()
            except Exception:
                pass
        import atexit

        try:
            atexit.unregister(self.close)
        except Exception:
            pass

    # -- prewarm ---------------------------------------------------------
    def prewarm(self, total_len: int, shapes) -> int:
        """Compile the packed scatter for ``shapes`` (``(rows, width)``
        pairs) against a genome of ``total_len`` positions, into the
        server's registry.  Idempotent per (total_len, shape)."""
        from ..ops.pileup import prewarm_scatter

        todo = [s for s in shapes
                if (total_len, tuple(s)) not in self._prewarmed]
        if not todo:
            return 0
        server_obs = obs.RunObservability(
            tracer=obs.tracer(), registry=self.registry,
            ledger=obs.DecisionLedger())
        with obs.bind_run_to_thread(server_obs):
            n = prewarm_scatter(total_len, todo)
        for s in todo:
            self._prewarmed.add((total_len, tuple(s)))
        self.registry.add("compile/prewarm_shapes", n)
        logger.info("prewarmed %d scatter shape(s) for L=%d", n,
                    total_len)
        return n

    def _auto_prewarm(self, spec: JobSpec, total_len: int) -> None:
        """First-job prewarm, hidden behind its decode: compile the
        canonical shapes on a thread.  Device-pileup jobs only — a
        host-routed pileup dispatches no scatter to warm."""
        from ..ops.pileup import canonical_slab_shapes

        if self.prewarm_mode != "auto":
            return
        if spec.config.pileup not in ("scatter", "pallas", "mxu"):
            # --pileup auto resolves per job inside the backend (host
            # vs device by the placement gate) — a host-routed job
            # dispatches no scatter to warm, so auto-prewarm only
            # engages for explicitly device-pinned pileups.  Say so:
            # a silent no-op here reads as "prewarm is broken".
            logger.info(
                "prewarm skipped: --pileup %s (auto-prewarm engages "
                "for explicit device pileups scatter/pallas/mxu; use "
                "ServeRunner.prewarm() for manual shape control)",
                spec.config.pileup)
            return
        from ..encoder.events import resolve_segment_width

        shapes = canonical_slab_shapes(
            total_len, chunk_reads=spec.config.chunk_reads,
            segment_width=resolve_segment_width(
                getattr(spec.config, "segment_width", 0)))

        def _worker():
            # one shape per prewarm() call so close() can stop the loop
            # at a compile boundary instead of abandoning a C++ compile
            for shape in shapes:
                if self._prewarm_stop.is_set():
                    return
                self.prewarm(total_len, [shape])

        t = threading.Thread(target=_worker, name="serve-prewarm",
                             daemon=True)
        t.start()
        self._prewarm_threads.append(t)

    # -- per-job export destinations -------------------------------------
    def _job_out(self, cfg_value: Optional[str], env_name: str,
                 jobnum: int) -> Optional[str]:
        """A job's metrics/trace destination.  An explicit per-job
        config value wins untouched; an ENV-derived base (S2C_*_OUT)
        is suffixed per job — without this, every serve job would
        resolve to the same env path inside prepare_run and overwrite
        the previous job's artifacts (mode 'w' exports).  ``jobnum``
        is the job's absolute number across the server's lifetime."""
        if cfg_value:
            return cfg_value
        env = os.environ.get(env_name)
        if env:
            return f"{env}.job{jobnum}"
        return None

    # -- job validation --------------------------------------------------
    def _validate(self, spec: JobSpec) -> None:
        if spec.config.pileup == "host" and spec.config.shards > 1:
            raise ValueError(
                "--pileup host accumulates on the single host; it does "
                "not compose with --shards (same contract as the "
                "one-shot CLI)")
        if spec.config.shards > 1 and spec.config.backend != "cpu":
            # typed up-front capacity check (parallel.mesh
            # MeshCapacityError): a --shards over the runtime's device
            # count must reject at admission, not as a late XLA/mesh
            # failure after the queue is journaled and inputs staged
            from ..parallel.mesh import validate_shards

            validate_shards(spec.config.shards,
                            pileup=spec.config.pileup)
        if self.journal is not None:
            # journal mode injects a per-job checkpoint_dir, and BAM
            # inputs do not support checkpoint resume yet — failing the
            # QUEUE up front beats journaling every such job failed
            # twice (first attempt + host-rung retry)
            fmt = getattr(spec.config, "input_format", "auto")
            if fmt == "auto" and os.path.exists(spec.filename):
                from ..formats import detect_format

                try:
                    fmt = detect_format(spec.filename)
                except OSError:
                    pass
            if fmt == "bam":
                raise ValueError(
                    f"--journal checkpoints every job, and BAM input "
                    f"{spec.filename!r} does not support checkpoint "
                    f"resume yet — convert it to SAM/SAM.gz or run the "
                    f"queue without --journal")
        if spec.config.checkpoint_dir:
            raise ValueError(
                "serve mode does not compose with --checkpoint-dir: "
                "checkpoints need serial decode with stream-consistent "
                "snapshots, which decode-ahead breaks; use --journal "
                "for crash-safe serving (the runner manages per-job "
                "checkpoints itself) or run checkpointed jobs through "
                "the one-shot CLI")
        if spec.config.incremental:
            # incremental IS a serve feature now — but only through the
            # count cache (the checkpoint-file flavor needs serial
            # decode + a --checkpoint-dir, which serve rejects above)
            if self.count_cache is None:
                raise ValueError(
                    "incremental serve jobs need the per-reference "
                    "count cache: start the server with --count-cache "
                    "SIZE (e.g. 512M) or S2C_COUNT_CACHE")
            if self.journal is not None:
                raise ValueError(
                    "--journal injects a per-job checkpoint home, "
                    "which conflicts with count-cache seeding (two "
                    "sources of resumable state); run incremental "
                    "jobs on an unjournaled server")

    # -- health -----------------------------------------------------------
    def health_snapshot(self) -> dict:
        return shealth.snapshot(self)

    def _publish_health(self) -> None:
        if self.health_out:
            try:
                shealth.write_health(self.health_out,
                                     self.health_snapshot())
            except Exception as exc:
                self.registry.add("telemetry/write_failed", 1)
                logger.warning("health snapshot write failed: %s", exc)

    # -- telemetry plane ---------------------------------------------------
    def _update_live_gauges(self) -> None:
        """Refresh the heartbeat-aged liveness gauges from runner state
        — the mid-job signal that makes a hung job visible WHILE it
        hangs (the per-job registries only fold in at job end)."""
        h = self.health
        now = time.monotonic()
        reg = self.registry
        reg.gauge("serve/up").set(1.0)
        reg.gauge("serve/uptime_sec").set(
            round(now - h._started_mono, 3))
        reg.gauge("serve/queue_depth").set(float(h.queue_depth))
        reg.gauge("serve/heartbeat_age_sec").set(
            round(now - h.last_beat, 3))
        # single read before the None test: HTTP scrape threads call
        # this concurrently with job_finished() clearing the field
        since = h.in_flight_since
        reg.gauge("serve/inflight_age_sec").set(
            round(now - since, 3) if since is not None else 0.0)
        if self.fleet is not None:
            reg.gauge("fleet/leases_held").set(
                float(len(self.fleet.held)))
        # flight-recorder occupancy: fraction of serve uptime spent in
        # run attempts — the live counterpart of the per-worker
        # occupancy lane fleet_trace derives from the journal offline
        uptime = now - h._started_mono
        reg.gauge("sched/occupancy_ratio").set(
            round(self._busy_sec / uptime, 4) if uptime > 0 else 0.0)

    def render_telemetry(self) -> str:
        """The OpenMetrics exposition over the server-lifetime
        aggregate, gauges refreshed first — an HTTP scrape between
        watchdog ticks still sees current heartbeat ages."""
        self._update_live_gauges()
        return stele.render_openmetrics(
            self.registry.snapshot(),
            worker=self.worker_id or None,
            restart_epoch=self.ratecard.restarts
            if self.worker_id else None)

    def telemetry_tick(self, force: bool = False) -> None:
        """One heartbeat of the telemetry plane, driven from the
        watchdog poll loop and (``force=True``) every job boundary:
        refresh liveness gauges, honor a pending profiler-capture
        request, and — on the configured cadence — atomically rewrite
        the exposition file AND the health snapshot (one shared
        writer, so ``--health-out`` is no longer frozen while a job
        hangs under ``--job-timeout``).  Every failure degrades to the
        per-job manifests: counted, warned, never raised."""
        self._update_live_gauges()
        if self.fleet is not None:
            # lease duty cycle rides the same heartbeat: renew what we
            # hold, reap what peers abandoned (serve/fleet.py)
            self.fleet.tick()
        if self.profiler.pending():
            path = self.profiler.capture(
                tracer=obs.tracer(), registry=self.registry,
                context={"in_flight": self.health.in_flight,
                         "queue_depth": self.health.queue_depth})
            if path is not None:
                self.registry.add("telemetry/profile_captures", 1)
                self.registry.gauge("telemetry/last_profile").set_info(
                    {"path": path, "in_flight": self.health.in_flight})
        try:
            self.burn.tick()
        except Exception as exc:     # alerting is derived state
            logger.warning("burn tick failed: %s", exc)
        now = time.monotonic()
        if not force and now - self._telemetry_last \
                < self.telemetry_interval:
            return
        self._telemetry_last = now
        # rate-card cadence work: refresh the exported gauges, persist
        # the card (journaled servers), and recompute the evidence-only
        # scale hint.  All best-effort — the card never fails a job.
        try:
            self.ratecard.publish(self.registry)
            if self.ratecard.path:
                self.ratecard.save()
        except Exception as exc:
            self.registry.add("rate/card_write_failed", 1)
            logger.warning("rate card persist failed: %s", exc)
        if self.journal is not None:
            try:
                self._scale_hint_tick()
            except Exception as exc:
                logger.warning("scale hint tick failed: %s", exc)
        # low-rate watermark sampler (observability/memplane.py): rides
        # the telemetry cadence, so a mid-hang scrape of the exposition
        # or health file shows memory too — and the bounded history
        # ring this feeds is the OOM forensic dump's watermark tail
        from ..observability import memplane

        memplane.sample(self.registry)
        if self.telemetry_out:
            try:
                stele.atomic_write_text(self.telemetry_out,
                                        self.render_telemetry())
            except Exception as exc:
                self.registry.add("telemetry/write_failed", 1)
                logger.warning(
                    "telemetry exposition write failed (%s: %s) — "
                    "degrading to per-job manifests",
                    type(exc).__name__, exc)
        self._publish_health()

    # -- scale-hint evidence plane (observability/ratecard.py) -------------
    def _scale_hint_tick(self) -> None:
        """Recompute the evidence-only fleet scale hint from every
        persisted rate card in the journal root (own card live, peers
        read-only from disk), the burn plane's alert states, and the
        live queue depth.  Publishes ``fleet/scale_hint`` and tracks
        drain episodes: when the queue empties, the hint that opened
        the episode is joined against the measured drain time as a
        band=0 ``scale_hint`` ledger decision.  No actuation."""
        import glob as _glob

        cards = [self.ratecard.snapshot()]
        own = os.path.basename(self.ratecard.path or "")
        for p in sorted(_glob.glob(os.path.join(
                self.journal.root, "ratecard-*.json"))):
            if os.path.basename(p) == own:
                continue
            peer = rcard.RateCard.load(p)
            if peer.restarts or peer.snapshot()["rates"]:
                cards.append(peer.snapshot())
        workers = max(1, len(cards)) if self.worker_id else 1
        hint = rcard.compute_scale_hint(
            cards, queue_depth=self.health.queue_depth,
            workers=workers, burn_states=self.burn.states())
        self.last_scale_hint = hint
        g = self.registry.gauge("fleet/scale_hint")
        g.set(float(hint["delta"]))
        g.set_info(hint)
        # drain-episode join: projected (at queue-open) vs measured
        now = time.monotonic()
        if self.health.queue_depth > 0 and self._drain_t0 is None \
                and hint.get("projected_drain_sec") is not None:
            self._drain_t0 = now
            self._drain_hint = hint
        elif self.health.queue_depth == 0 \
                and self._drain_t0 is not None:
            measured = now - self._drain_t0
            opened = self._drain_hint
            self._drain_t0 = None
            self._drain_hint = None
            if opened is not None:
                self._join_scale_hint(opened, measured)

    def _join_scale_hint(self, hint: dict, measured_sec: float) -> None:
        """Hindsight-join one drain episode: the hint's projected
        drain vs the wall-clock measured drain, as a band=0
        ``scale_hint`` decision in an episode-scoped ledger (the
        per-run ledgers finalize at backend end — an episode spans
        runs).  The residual gauges mirror into the server registry so
        the exposition and s2c_top carry them."""
        led = obs.DecisionLedger()
        led.record(
            "scale_hint", hint["verdict"], inputs=hint,
            predicted={"drain_sec": hint["projected_drain_sec"]},
            measured={"drain_sec": {
                "counters": ["fleet/drain_measured_sec"]}},
            band=0)
        ep = MetricsRegistry()
        ep.add("fleet/drain_measured_sec", round(measured_sec, 3))
        # NOTE: observability.ledger the ATTRIBUTE is the
        # current-ledger accessor function; the module import must be
        # explicit
        from ..observability.ledger import finalize as _finalize

        _finalize(led, ep)
        for name in ("residual/scale_hint", "residual/scale_hint/"
                     "drain_sec"):
            src = ep.gauge(name)
            dst = self.registry.gauge(name)
            dst.set(src.value)
            if getattr(src, "info", None):
                dst.set_info(dict(src.info))
        self._scale_hint_episodes += 1
        self.registry.add("fleet/drain_episodes", 1)
        self.registry.gauge("fleet/drain_measured_sec").set(
            round(measured_sec, 3))

    def note_fleet_burn(self, replay) -> None:
        """Feed peer-committed SLO breaches from a journal replay into
        the windowed burn monitor WITH their commit stamps — an old
        breach ages out of the fast/slow windows naturally, unlike the
        lifetime ``slo_burn_by_tenant`` dict it complements.  Keys this
        life already observed locally are skipped (no double count)."""
        obj = self.slo.get("e2e")
        if obj is None or replay is None:
            return
        for key, rec in getattr(replay, "committed", {}).items():
            if key in self._burn_fed_keys:
                continue
            self._burn_fed_keys.add(key)
            elapsed = rec.get("elapsed_sec")
            if elapsed is None:
                continue
            stamp = float(rec.get("t", 0.0)) or None
            try:
                self.burn.observe_job(
                    rec.get("tenant") or "default", evaluated=1,
                    violated=1 if float(elapsed) > obj else 0,
                    now=stamp)
            except Exception:
                continue

    def _telemetry_job_end(self, robs, res: JobResult, snap: dict,
                           tenant: str, queue_wait: float) -> None:
        """Job-boundary telemetry: fold the job's registry into the
        server-lifetime aggregate, observe its per-phase latency into
        the tenant's SLO histograms, burn violation counters, and feed
        the verdict into the job's manifest ``serve.slo`` section (the
        manifest file is rewritten in place when the job exported
        one)."""
        try:
            self.registry.fold(robs.registry, job_id=res.job_id,
                               tenant=tenant)
        except Exception as exc:     # aggregation is derived state
            self.registry.add("telemetry/fold_failed", 1)
            logger.warning("telemetry fold failed for %s: %s",
                           res.job_id, exc)
        phases = stele.slo_phase_seconds(snap["counters"],
                                         res.elapsed_sec, queue_wait)
        tlabel = tenant or "default"
        violated = []
        for ph, sec in phases.items():
            self.registry.observe(f"slo/{tlabel}/{ph}", sec)
            obj = self.slo.get(ph)
            if obj is not None and sec > obj:
                violated.append(ph)
                self.registry.add("slo/violations", 1)
                self.registry.add(f"slo/violations/{tlabel}/{ph}", 1)
        evaluated = [ph for ph in phases
                     if self.slo.get(ph) is not None]
        if evaluated:
            # windowed burn view: one observation per job under the
            # same label, stamped now — the fast/slow ratios the alert
            # state machine reads (observability/burn.py)
            try:
                self.burn.observe_job(tlabel, evaluated=len(evaluated),
                                      violated=len(violated))
            except Exception:
                pass
        if violated:
            # burn under the SAME label the exposition/manifest use
            # ("default" for untenanted jobs) so an operator can
            # cross-reference the two surfaces key-for-key
            self.admission.note_slo(tlabel, len(violated))
            logger.warning(
                "job %s breached SLO objective(s) %s "
                "(phases %s vs objectives %s)", res.job_id,
                ",".join(violated),
                {k: round(v, 3) for k, v in phases.items()}, self.slo)
        verdict = {
            "job": res.job_id, "tenant": tlabel,
            "phases_sec": {k: round(v, 4) for k, v in phases.items()},
            "objectives_sec": dict(self.slo),
            "violated": violated,
            "burn": {ph: int(self.registry.value(
                f"slo/violations/{tlabel}/{ph}"))
                for ph in stele.SLO_PHASES
                if self.registry.value(
                    f"slo/violations/{tlabel}/{ph}")},
        }
        self.registry.gauge("slo/last_job").set_info(verdict)
        if res.manifest is not None:
            res.manifest.setdefault("serve", {})["slo"] = verdict
            if robs.metrics_out:
                from ..observability import manifest as _manifest

                try:
                    _manifest.write_manifest(
                        _manifest.manifest_path_for(robs.metrics_out),
                        res.manifest)
                except Exception as exc:
                    self.registry.add("telemetry/write_failed", 1)
                    logger.warning("manifest slo rewrite failed: %s",
                                   exc)

    # -- flight recorder (observability/flight.py) -------------------------
    def _stamp_trace(self, robs, entry: dict) -> None:
        """Propagate the job's trace-context onto every artifact this
        run will export: ``trace_id`` (= the journal key) into the
        tracer's meta (export.write_chrome_trace emits it as the
        ``s2c`` block), and the same identity as the ``sched/trace``
        info gauge so the metrics JSONL and the manifest ``lifecycle``
        section carry it too — a per-worker artifact then joins its
        journal per-job track without filename guessing.  Safe (and a
        near-no-op) for journal-less runs: the job id stands in for
        the key."""
        from ..observability import flight

        key = entry.get("key")
        info = {"trace_id": flight.trace_id(key) if key
                else entry["job_id"],
                "key": key or "", "job": entry["job_id"]}
        if self.worker_id:
            info["worker"] = self.worker_id
        tr = getattr(robs, "tracer", None)
        if tr is not None and hasattr(tr, "meta"):
            tr.meta.update(info)
        robs.registry.gauge("sched/trace").set_info(info)

    def _sched_lifecycle(self, entry: dict, window_queue_wait: float):
        """The job's journal-measured lifecycle numbers, as stamped
        into its manifest ``lifecycle`` section.  Returns
        ``(lifecycle_dict, journal_queue_wait_or_None)`` — the journal
        number (started append wall time minus the key's FIRST
        submitted wall time) is the queue-wait truth source when a
        journal is present; the window-epoch measure rides along as
        ``window_queue_wait_sec`` so the two stay cross-checkable
        (they agree on a clean queue; they diverge exactly when a
        restart or steal hid wall time from the window epoch)."""
        from ..observability import flight

        key = entry.get("key")
        lc: dict = {
            "trace_id": flight.trace_id(key) if key
            else entry["job_id"],
            "key": key or "",
            "worker": self.worker_id or "",
            "window_queue_wait_sec": round(
                max(0.0, window_queue_wait), 4)}
        sub = self._submit_unix.get(key) if key else None
        started = entry.get("started_unix")
        journal_qw = None
        if sub is not None:
            lc["submit_unix"] = sub
        if started is not None:
            lc["started_unix"] = started
        if sub is not None and started is not None:
            journal_qw = max(0.0, started - sub)
            lc["queue_wait_sec"] = round(journal_qw, 4)
        if self.fleet is not None and key:
            cu = self.fleet.claim_unix.get(key)
            if cu is not None and sub is not None:
                lc["claim_latency_sec"] = round(
                    max(0.0, cu - sub), 4)
            sg = self.fleet.steal_gaps.get(key)
            if sg is not None:
                lc["steal_latency_sec"] = round(sg, 4)
                lc["stolen"] = True
        return lc, journal_qw

    # -- journal helpers ---------------------------------------------------
    def _journal_append(self, ev: str, **fields) -> None:
        """Append, absorbing write failures: a journal that cannot be
        written must not kill the job whose work it records.  The safe
        direction is re-RUNNING work on restart (a missing commit means
        the job re-runs and re-fingerprints, byte-identical), never
        skipping it — so append failures degrade durability, not
        correctness, and they are loudly counted."""
        if self.journal is None:
            return
        try:
            self.journal.append(ev, **fields)
        except Exception as exc:
            self.registry.add("serve/journal_write_failed", 1)
            logger.warning("journal append %s failed (%s: %s): the job "
                           "will re-run on restart instead of resuming",
                           ev, type(exc).__name__, exc)

    # -- guarded execution (watchdog) --------------------------------------
    def _execute(self, contigs, records, cfg, robs,
                 dlog: List[Tuple[float, float]], job_id: str):
        """Run one job through the backend — directly when no watchdog
        is configured (zero extra threads, the PR-5 path), else on a
        monitored worker thread.

        The monitor enforces two independent bounds: total wall clock
        (``job_timeout`` -> JobDeadlineExceeded) and dispatch-heartbeat
        age (``stall_timeout`` -> HungDispatchError), the heartbeat
        being the newest dispatch-interval end in ``dlog`` — the log
        the runner already keeps for the overlap join.  On timeout the
        worker is ABANDONED (daemon): a wedged XLA dispatch cannot be
        interrupted from Python, only disowned.  The abandoned thread
        keeps ITS job's instruments thread-bound
        (``bind_run_to_thread``), so if it ever wakes it records into
        its own registry, not the next job's."""
        from ..resilience.policy import (HungDispatchError,
                                         JobDeadlineExceeded)

        self.backend.serve_prepared_obs = robs
        self.backend.serve_dispatch_log = dlog
        try:
            if self.job_timeout is None and self.stall_timeout is None \
                    and self.fleet is None:
                # fleet mode always takes the monitored path: the poll
                # loop's telemetry_tick is what renews this worker's
                # leases mid-job (no deadline is enforced unless set)
                return self.backend.run(contigs, records, cfg)

            box: list = []

            log_ctx = stele.get_log_context()

            def work():
                stele.set_log_context(**log_ctx)
                with obs.bind_run_to_thread(robs):
                    try:
                        box.append(("ok", self.backend.run(
                            contigs, records, cfg)))
                    except BaseException as exc:
                        box.append(("exc", exc))

            t = threading.Thread(target=work, daemon=True,
                                 name=f"serve-job-{job_id}")
            start = time.perf_counter()
            beats_seen = 0
            t.start()
            while t.is_alive() and not box:
                t.join(WATCHDOG_POLL_S)
                if box:
                    break               # finished during the poll: a
                    # result beats a deadline that expired in the race
                # mid-job telemetry heartbeat: liveness gauges, the
                # exposition/health cadence writer, and profiler-
                # capture triggers all ride the watchdog poll — a hung
                # dispatch is visible (and profileable) WHILE it hangs
                self.telemetry_tick()
                now = time.perf_counter()
                last = dlog[-1][1] if dlog else start
                if len(dlog) > beats_seen:
                    # beat only on NEW dispatch completions — a wedged
                    # job's published heartbeat age must GROW (the
                    # signature health.py documents for probers)
                    beats_seen = len(dlog)
                    self.health.beat()
                if (self.job_timeout is not None
                        and now - start > self.job_timeout):
                    raise JobDeadlineExceeded(
                        f"job {job_id} exceeded its "
                        f"{self.job_timeout:.3g}s deadline "
                        f"({len(dlog)} dispatches completed)")
                if (self.stall_timeout is not None
                        and now - max(last, start) > self.stall_timeout):
                    raise HungDispatchError(
                        f"job {job_id}: no dispatch heartbeat for "
                        f"{now - max(last, start):.1f}s "
                        f"(stall budget {self.stall_timeout:.3g}s, "
                        f"{len(dlog)} dispatches completed)")
            if not box:
                t.join()
            tag, val = box[0]
            if tag == "exc":
                raise val
            return val
        finally:
            self.backend.serve_prepared_obs = None
            self.backend.serve_dispatch_log = None

    def _join_ahead(self, ahead: "_DecodeAhead",
                    stall_t: Optional[float]) -> None:
        """Wait for a decode-ahead thread, declaring it wedged only
        when it stops MAKING PROGRESS (no new decoded batch) for
        ``stall_t`` — a large input decoding steadily is not a hang,
        however long it takes.  ``stall_t`` None = wait forever (no
        watchdog configured; the PR-5 behavior)."""
        if stall_t is None:
            ahead.thread.join()
            return
        last_n = -1
        last_progress = time.perf_counter()
        while ahead.thread.is_alive():
            ahead.thread.join(min(0.5, stall_t / 4))
            self.telemetry_tick()       # a wedged decode is mid-job too
            n = len(ahead.intervals())
            now = time.perf_counter()
            if n != last_n:
                last_n = n
                last_progress = now
            elif now - last_progress > stall_t:
                return                   # caller sees is_alive() == True

    def _note_timeout(self, robs, exc, server: bool = True) -> None:
        robs.registry.add("serve/watchdog_timeouts", 1)
        robs.registry.gauge("serve/watchdog").set_info(
            {"error": f"{type(exc).__name__}: {exc}",
             "job_timeout_s": self.job_timeout,
             "stall_timeout_s": self.stall_timeout})
        if server:               # once per timeout, not once per registry
            self.registry.add("serve/watchdog_timeouts", 1)

    # -- the queue -------------------------------------------------------
    def submit_jobs(self, specs: List[JobSpec]) -> List[JobResult]:
        """Run the queue; returns one :class:`JobResult` per spec, in
        order.  The server survives failed jobs (their error rides the
        result) and stays warm afterwards for the next submit."""
        from ..config import resolve_decode_threads
        from ..formats import open_alignment_input
        from ..io.fasta import write_outputs
        from ..resilience import ladder as rladder
        from ..wire.pipeline import intersect_sec

        for spec in specs:
            self._validate(spec)

        # -- plan: admission + journal replay, before anything runs ---
        replay = self.journal.replay() if self.journal is not None \
            else None
        if self.fleet is None and replay is not None \
                and replay.claimed_ever:
            # commits on ever-claimed keys are lease-fenced: a
            # worker-less server's commits on them would be VOID on
            # replay (it can hold no lease) — refuse loudly instead
            # of running jobs whose commits silently never land
            raise ValueError(
                "this journal has fleet claim/lease history "
                f"({len(replay.claimed_ever)} claimed key(s)): "
                "restart with --worker-id so commits carry the lease "
                "lineage the journal now enforces")
        self.admission.open_window()
        if self.fleet is not None and replay is not None:
            # fleet-global quotas: peers' journal-visible live jobs
            # count against this window's per-tenant quota too
            self.admission.seed_window(self.fleet.seed_window_counts(
                replay, {sjournal.job_key(s.filename, s.config)
                         for s in specs}))
        jobs_base = self.jobs_run
        plan: List[dict] = []           # one entry per spec, in order
        n_skipped = 0
        inflight_resumed: List[str] = []
        for j, spec in enumerate(specs):
            jobnum = jobs_base + j
            job_id = spec.job_id or \
                f"job{jobnum}:{os.path.basename(spec.filename)}"
            key = sjournal.job_key(spec.filename, spec.config) \
                if self.journal is not None else None
            entry = {"spec": spec, "job_id": job_id, "key": key,
                     "jobnum": jobnum, "action": "run", "cfg": spec.config,
                     "admission": None, "resume_ckpt": False}
            if replay is not None and key in replay.committed \
                    and self.journal.verify_outputs(
                        replay.committed[key], mode=self.verify_mode):
                entry["action"] = "skip"
                entry["outputs"] = \
                    list(replay.committed[key].get("outputs", {}))
                n_skipped += 1
                plan.append(entry)
                continue
            # capacity signal (observability/memplane.py): only priced
            # when a --mem-budget is set — the header probe reuses the
            # batch scheduler's cached-handle discipline, so a later
            # pack/decode never re-sniffs the container
            predicted = None
            shard_plan = None
            if self.admission.mem_budget:
                total_len = self.scheduler._probe_total_len(entry)
                if total_len:
                    from ..observability import memplane

                    predicted = memplane.predict_job_peak_bytes(
                        total_len, spec.config)
                    entry["mem_predicted"] = predicted
                    if (predicted > self.admission.mem_budget
                            and self.admission.mesh_hosts > 1):
                        # the memory plane as planner: price the job
                        # per-host across K hosts and admit it with a
                        # "needs K hosts" verdict when it fits the
                        # fleet, instead of shedding it (the
                        # mesh_shards ledger decision records the
                        # choice + its alternatives)
                        shard_plan = memplane.plan_mesh_shards(
                            total_len, spec.config,
                            budget_bytes=self.admission.mem_budget,
                            max_hosts=self.admission.mesh_hosts)
                if not self.scheduler.enabled:
                    # without batching nothing downstream reuses the
                    # probe handle (decode-ahead re-opens per job) —
                    # close NOW, or a wide submission window holds one
                    # open fd per probed spec until the queue drains
                    ai = entry.pop("batch_handle", None)
                    if ai is not None:
                        ai.close()
            dec = self.admission.admit(spec.tenant,
                                       predicted_bytes=predicted,
                                       shard_plan=shard_plan)
            if dec.admitted and dec.mesh_shards:
                entry["mesh_shards"] = dec.mesh_shards
                self.registry.add("serve/admission_mesh", 1)
                self.registry.gauge("mesh/planned_hosts").set(
                    dec.mesh_shards)
            if not dec.admitted:
                entry["action"] = "reject"
                entry["admission"] = dec.reason
                if dec.reason == "capacity":
                    self.registry.add("serve/admission_capacity", 1)
                    ai = entry.pop("batch_handle", None)
                    if ai is not None:
                        ai.close()
                plan.append(entry)
                continue
            cfg = spec.config
            if getattr(cfg, "on_bad_record", "fail") == "quarantine" \
                    and not getattr(cfg, "quarantine_out", None):
                # default sidecar naming keyed on the job's UNIQUE
                # server-lifetime number, not on outfolder+prefix: two
                # jobs over the same upload — serial OR packed into one
                # batch (concurrent commit) — must never clobber each
                # other's evidence files.  An explicit --quarantine-out
                # wins untouched (the CLI already stamps its own .jobN).
                cfg = dataclasses.replace(cfg, quarantine_out=os.path.join(
                    cfg.outfolder or "./",
                    f"{cfg.prefix or 'quarantine'}_quarantine"
                    f".job{jobnum}.jsonl"))
            if self.journal is not None:
                cfg = dataclasses.replace(
                    cfg, checkpoint_dir=self.journal.ckpt_dir(key))
                if replay is not None and key in replay.inflight:
                    entry["resume_ckpt"] = True
                    inflight_resumed.append(job_id)
            entry["cfg"] = cfg
            plan.append(entry)

        # durable queue: every to-run job is journaled as submitted
        # BEFORE anything executes, so a crash during job 0 still
        # remembers the whole queue
        if self.journal is not None:
            already = replay.submitted if replay is not None else set()
            if replay is not None:
                # restarted queue: prior submissions keep their
                # ORIGINAL journal submit time — a job's queue wait
                # spans the crash, which is exactly the point of
                # measuring it from the journal instead of the window
                self._submit_unix.update(replay.submit_times)
            for entry in plan:
                if entry["action"] == "run" \
                        and entry["key"] not in already:
                    self._journal_append(
                        "submitted", job=entry["job_id"],
                        key=entry["key"],
                        filename=os.path.abspath(
                            entry["spec"].filename),
                        outfolder=entry["spec"].config.outfolder,
                        tenant=entry["spec"].tenant or "",
                        **({"mesh_shards": entry["mesh_shards"]}
                           if entry.get("mesh_shards") else {}))
                    # mirror of the append's own stamp (same clock,
                    # same 1 ms rounding) — saves a replay per job
                    self._submit_unix.setdefault(
                        entry["key"], round(time.time(), 3))
            for entry in plan:
                if entry["action"] == "skip":
                    self._journal_append("resumed", job=entry["job_id"],
                                         key=entry["key"],
                                         mode="skipped")
                elif entry["resume_ckpt"]:
                    self._journal_append("resumed", job=entry["job_id"],
                                         key=entry["key"],
                                         mode="inflight")
                elif entry["action"] == "reject":
                    self._journal_append("rejected", job=entry["job_id"],
                                         key=entry["key"],
                                         reason=entry["admission"])
        recovery_info = None
        if replay is not None and replay.events:
            recovery_info = {
                "resumed": True,
                "journal_last_seq": replay.last_seq,
                "committed_skipped": n_skipped,
                "inflight_resumed": inflight_resumed,
            }
            self.registry.gauge("serve/recovery").set_info(recovery_info)
            self.registry.add("serve/resume_skipped", n_skipped)
            self.registry.add("serve/resume_inflight",
                              len(inflight_resumed))

        self.health.queue_depth = sum(1 for e in plan
                                      if e["action"] == "run")
        #: queue-wait epoch: every job's SLO queue_wait is measured
        #: from here — the wall time a submission spent behind earlier
        #: jobs of its own window (a hung job inflates every
        #: successor's queue_wait, which is exactly the signal)
        window_t0 = time.perf_counter()
        self.telemetry_tick(force=True)

        # -- fleet mode (serve/fleet.py): claim/lease arbitration over
        #    the shared journal replaces the serial loop — this worker
        #    runs the entries whose leases it wins, observes peers'
        #    commits for the rest, and steals expired leases
        if self.fleet is not None:
            try:
                return self.fleet.drain(self, plan, window_t0, replay,
                                        recovery_info)
            finally:
                self.scheduler.release_handles(plan)
                self.telemetry_tick(force=True)

        # -- continuous batching (serve/scheduler.py): compose packed
        #    batches over the eligible small jobs up front; the loop
        #    below executes each batch when it reaches the batch's
        #    first member and routes demoted members back through the
        #    untouched serial path
        batch_results: dict = {}
        batch_by_first: dict = {}
        batched: set = set()
        if self.scheduler.enabled:
            for b in self.scheduler.compose(plan):
                batch_by_first[b.indices[0]] = b
                batched.update(b.indices)
            # entries probed but not packed must not leak their probe
            # handles (the packed ones are consumed by the decode phase)
            for j, e in enumerate(plan):
                if j not in batched:
                    ai = e.pop("batch_handle", None)
                    if ai is not None:
                        ai.close()
            if batched:
                logger.info("continuous batching: %d job(s) in %d "
                            "batch(es)", len(batched),
                            len(batch_by_first))

        results: List[JobResult] = []
        ahead: Optional[_DecodeAhead] = None
        ahead_for: Optional[int] = None
        cap = _ahead_batch_cap()
        first_run_seen = False
        for i, entry in enumerate(plan):
            if i in batch_results:
                results.append(batch_results.pop(i))
                continue
            b = batch_by_first.pop(i, None)
            if b is not None:
                done, leftovers = self.scheduler.run_batch(
                    b, plan, window_t0)
                batch_results.update(done)
                for k in leftovers:
                    batched.discard(k)  # serial re-run when reached
                if i in batch_results:
                    results.append(batch_results.pop(i))
                    continue
                # i itself demoted: fall through to the serial path
            spec = entry["spec"]
            job_id = entry["job_id"]
            cfg = entry["cfg"]
            jobnum = entry["jobnum"]
            # -- non-running entries -----------------------------------
            if entry["action"] in ("skip", "reject"):
                results.append(self._resolve_nonrun(entry, i))
                continue
            self.registry.add("serve/admission_admitted", 1)
            # degraded-tenant isolation, decided at JOB-START time (a
            # tenant degraded by the previous job of this very batch
            # must already be pinned): the job runs, but on the rung
            # its tenant already proved it needs — never on the
            # fleet's device path
            rung = self.admission.pin_rung(spec.tenant)
            if rung is not None and cfg.pileup != "host":
                cfg = rladder.job_host_rung_config(cfg)
                entry["cfg"] = cfg
                entry["admission"] = f"pinned:{rung}"
            if entry["admission"]:       # pinned:<rung>
                self.registry.add("serve/admission_pinned", 1)
            # -- job context: from the decode-ahead thread, or inline --
            close_handle = None
            contigs = records = None
            header_err = None
            robs = None
            if ahead is not None and ahead_for == i:
                join_t = self.stall_timeout \
                    if self.stall_timeout is not None else self.job_timeout
                self._join_ahead(ahead, join_t)
                if ahead.thread.is_alive():
                    # the decode-ahead thread itself is wedged: this is
                    # exactly the "stuck decode-ahead thread wedges the
                    # whole server forever" bug — disown it and fail
                    # only its job
                    from ..resilience.policy import HungDispatchError

                    header_err = HungDispatchError(
                        f"job {job_id}: decode-ahead thread made no "
                        f"progress within {join_t:.3g}s")
                    robs = ahead.robs
                    self._note_timeout(ahead.robs, header_err)
                    close_handle = ahead.close
                else:
                    robs = ahead.robs
                    contigs = ahead.contigs
                    records = _PredecodedJob(ahead)
                    header_err = ahead.error if contigs is None else None
                    close_handle = ahead.close
            else:
                if ahead is not None:
                    ahead.close()        # stale (intervening skip/reject)
                robs = obs.prepare_run(
                    trace_out=self._job_out(cfg.trace_out,
                                            "S2C_TRACE_OUT", jobnum),
                    metrics_out=self._job_out(cfg.metrics_out,
                                              "S2C_METRICS_OUT", jobnum),
                    config=cfg)
                try:
                    # a batch demotion may have left this entry's probe
                    # handle open (header already parsed): resume from it
                    ai = entry.pop("batch_handle", None)
                    if ai is None:
                        ai = open_alignment_input(
                            spec.filename,
                            getattr(cfg, "input_format", "auto"),
                            binary=True,
                            threads=resolve_decode_threads(cfg))
                    close_handle = ai.close
                    contigs, records = ai.contigs, ai.stream
                except Exception as exc:
                    header_err = exc
            ahead = None
            ahead_for = None
            # trace-context onto this run's artifacts (works for the
            # decode-ahead robs too: its trace file is written at
            # finish_run, after this stamp)
            self._stamp_trace(robs, entry)
            if not first_run_seen and contigs is not None:
                from ..encoder.events import GenomeLayout

                self._auto_prewarm(spec, GenomeLayout(contigs).total_len)
            first_run_seen = True
            # -- launch the NEXT runnable job's decode-ahead -----------
            if self.decode_ahead:
                for k in range(i + 1, len(plan)):
                    if plan[k]["action"] == "run" and k not in batched:
                        nxt = plan[k]
                        ahead = _DecodeAhead(
                            self.backend, JobSpec(
                                filename=nxt["spec"].filename,
                                config=nxt["cfg"],
                                job_id=nxt["job_id"],
                                tenant=nxt["spec"].tenant),
                            obs.prepare_run(
                                trace_out=self._job_out(
                                    nxt["cfg"].trace_out,
                                    "S2C_TRACE_OUT", nxt["jobnum"]),
                                metrics_out=self._job_out(
                                    nxt["cfg"].metrics_out,
                                    "S2C_METRICS_OUT", nxt["jobnum"]),
                                config=nxt["cfg"]), cap,
                            fault_cb=self._fault_check
                            if self._fault is not None else None)
                        ahead_for = k
                        break
            # -- run this job -----------------------------------------
            if recovery_info is not None:
                robs.registry.gauge("serve/recovery").set_info(
                    recovery_info)
            robs.registry.gauge("serve/health").set_info({
                "queue_depth": self.health.queue_depth,
                "in_flight": job_id,
                "tenant_rungs": dict(self.admission.tenant_rungs),
                **({"journal_last_seq": replay.last_seq}
                   if replay is not None else {})})
            res = JobResult(job_id=job_id, filename=spec.filename,
                            index=i, admission=entry["admission"])
            # incremental consensus: seed the job from the warm
            # per-reference count state (serve/countcache.py) and ask
            # the backend to hand back the final state for re-insertion
            cache_key = cache_seed = None
            if header_err is None:
                cache_key, cache_seed, cfg = self._cache_begin(
                    spec, cfg, contigs, robs)
                entry["cfg"] = cfg
            dlog: List[Tuple[float, float]] = []
            # log-correlation IDs for every record this job emits —
            # the watchdog worker and (already-bound) decode-ahead
            # threads inherit/set the same fields (--log-format json)
            stele.set_log_context(
                job_id=job_id, tenant=spec.tenant,
                rung=(entry["admission"] or cfg.pileup))
            self.health.job_started(job_id)
            self._journal_append("started", job=job_id,
                                 key=entry["key"],
                                 ckpt=cfg.checkpoint_dir or "")
            # mirror of the started append's wall stamp: the journal-
            # measured queue wait's right edge (flight recorder)
            entry["started_unix"] = round(time.time(), 3)
            t0 = time.perf_counter()
            if header_err is not None:
                res.error = f"{type(header_err).__name__}: {header_err}"
                if close_handle is not None:
                    close_handle()
            else:
                out = None
                try:
                    out = self._execute(contigs, records, cfg, robs,
                                        dlog, job_id)
                except Exception as exc:
                    self._note_timeout_if_deadline(robs, exc)
                    self._note_poison(spec, exc, res)
                    self._note_capacity(spec, exc, robs)
                    retry_cfg = self._retry_config(cfg, exc)
                    if retry_cfg is not None:
                        if cache_key is not None:
                            # the first attempt consumed (or dropped)
                            # the seed; the host-rung retry must run
                            # against the SAME warm base or its output
                            # would cover only the delta reads
                            self._plant_seed(cache_seed)
                        out, robs, res.error = self._retry_on_host_rung(
                            spec, retry_cfg, exc, jobnum, job_id)
                    else:
                        res.error = f"{type(exc).__name__}: {exc}"
                    if res.error is not None:
                        logger.warning("job %s failed: %s", job_id,
                                       res.error)
                finally:
                    if close_handle is not None:
                        close_handle()
                if out is not None:
                    res.fastas, res.stats = out.fastas, out.stats
                    res.error = None
                if cache_key is not None:
                    self._cache_end(cache_key, out is not None)
            res.elapsed_sec = time.perf_counter() - t0
            self._finalize_job(entry, res, robs, spec,
                               queue_wait=t0 - window_t0)
            results.append(res)
            # -- cross-job overlap: bill it to the job whose decode
            #    was hidden (N+1), before that job runs ---------------
            if ahead is not None:
                ov = intersect_sec(ahead.intervals(), dlog)
                ahead.robs.registry.add("serve/overlap_sec", ov)
                ahead.robs.registry.add("serve/decode_ahead_sec",
                                        ahead.decode_sec())
                ahead.robs.registry.gauge("serve/overlap").set_info({
                    "overlap_sec": round(ov, 4),
                    "decode_ahead_sec": round(ahead.decode_sec(), 4),
                    "overlapped_job": job_id})
                self.registry.add("serve/overlap_sec", ov)
        self.scheduler.release_handles(plan)     # no probe-handle leaks
        self.telemetry_tick(force=True)
        return results

    # -- plan-entry resolution (shared: serial loop + fleet drain) ---------
    def _resolve_nonrun(self, entry: dict, i: int) -> JobResult:
        """A plan entry that never executes: journal-resumed skip or
        admission reject — one result, counters, echo, bookkeeping."""
        spec = entry["spec"]
        job_id = entry["job_id"]
        res = JobResult(job_id=job_id, filename=spec.filename, index=i)
        if entry["action"] == "skip":
            res.resumed = True
            res.output_paths = entry.get("outputs", [])
            res.metrics = {"serve/resume_skipped": 1}
            self.echo(f"[serve] {job_id}: resumed (committed in "
                      f"journal, outputs verified)")
        else:
            reason = entry["admission"]
            res.admission = reason
            detail = ""
            if reason == "capacity":
                detail = (
                    f": predicted peak "
                    f"{entry.get('mem_predicted', 0) / 1e6:.1f}"
                    f" MB > --mem-budget "
                    f"{self.admission.mem_budget / 1e6:.1f} MB"
                    f" — re-offer to a host that fits")
            res.error = f"admission rejected: {reason}{detail}"
            self.registry.add("serve/admission_rejected", 1)
            self.registry.add(
                f"serve/admission_rejected/{reason}", 1)
            self.echo(f"[serve] {job_id}: REJECTED "
                      f"({reason}{detail})")
        self.jobs_run += 1
        return res

    def _resolve_completed_elsewhere(self, entry: dict, i: int,
                                     rec: dict) -> JobResult:
        """Fleet: a peer's journal commit resolves this entry — the
        drain verified the recorded outputs before calling this (a
        drifted commit is re-claimed and re-run instead), so this
        worker never decodes a byte."""
        job_id = entry["job_id"]
        res = JobResult(job_id=job_id, filename=entry["spec"].filename,
                        index=i, resumed=True)
        res.worker = rec.get("worker", "")
        res.output_paths = list(rec.get("outputs") or {})
        res.metrics = {"fleet/completed_elsewhere": 1}
        # NOT serve/jobs: that family counts jobs THIS worker ran —
        # the peer already counted the run on its side (the fleet view
        # sums workers' counters, and a double count would misreport)
        self.registry.add("fleet/completed_elsewhere", 1)
        self.jobs_run += 1
        self.health.queue_depth = max(0, self.health.queue_depth - 1)
        self.echo(f"[serve] {job_id}: committed by worker "
                  f"{res.worker or '?'} in "
                  f"{rec.get('elapsed_sec', 0.0):.2f}s")
        return res

    def _resolve_failed_elsewhere(self, entry: dict, i: int,
                                  error: str) -> JobResult:
        """Fleet: a peer journaled this job failed — terminal for the
        queue run, exactly as a local failure would be."""
        job_id = entry["job_id"]
        res = JobResult(job_id=job_id, filename=entry["spec"].filename,
                        index=i)
        res.error = f"failed on another worker: {error}"
        # like completed-elsewhere: the peer owns the serve/jobs_*
        # accounting for the run itself
        self.registry.add("fleet/failed_elsewhere", 1)
        self.jobs_run += 1
        self.health.queue_depth = max(0, self.health.queue_depth - 1)
        self.echo(f"[serve] {job_id}: FAILED on another worker "
                  f"({error})")
        return res

    def _run_claimed_entry(self, entry: dict, i: int, window_t0: float,
                           recovery_info) -> JobResult:
        """Run one claim-won plan entry — the fleet drain's execution
        body: the serial loop's run path minus decode-ahead (journal
        mode already forces serial decode), batching and count-cache
        seeding (both rejected with ``--worker-id``), plus the
        lease-confirmation gate before the commit.

        KEEP IN SYNC with the serial loop's run block in
        :meth:`submit_jobs` (open-input/prewarm/health-gauge prologue,
        the _execute/_note_*/_retry_on_host_rung failure sequence) —
        the two are deliberate near-twins until a shared _run_one
        extraction unifies them; both are pinned by byte-identity
        suites (tests/test_serve.py vs tests/test_fleet.py), so drift
        fails tests, but fix bugs in BOTH places."""
        from ..config import resolve_decode_threads
        from ..formats import open_alignment_input
        from ..resilience import ladder as rladder

        spec = entry["spec"]
        job_id = entry["job_id"]
        cfg = entry["cfg"]
        jobnum = entry["jobnum"]
        self.registry.add("serve/admission_admitted", 1)
        rung = self.admission.pin_rung(spec.tenant)
        if rung is not None and cfg.pileup != "host":
            cfg = rladder.job_host_rung_config(cfg)
            entry["cfg"] = cfg
            entry["admission"] = f"pinned:{rung}"
        if entry["admission"]:
            self.registry.add("serve/admission_pinned", 1)
        robs = obs.prepare_run(
            trace_out=self._job_out(cfg.trace_out,
                                    "S2C_TRACE_OUT", jobnum),
            metrics_out=self._job_out(cfg.metrics_out,
                                      "S2C_METRICS_OUT", jobnum),
            config=cfg)
        self._stamp_trace(robs, entry)
        close_handle = None
        contigs = records = None
        header_err = None
        try:
            ai = open_alignment_input(
                spec.filename, getattr(cfg, "input_format", "auto"),
                binary=True, threads=resolve_decode_threads(cfg))
            close_handle = ai.close
            contigs, records = ai.contigs, ai.stream
        except Exception as exc:
            header_err = exc
        if contigs is not None and not self._fleet_first_run_seen:
            from ..encoder.events import GenomeLayout

            self._auto_prewarm(spec, GenomeLayout(contigs).total_len)
            self._fleet_first_run_seen = True
        if recovery_info is not None:
            robs.registry.gauge("serve/recovery").set_info(
                recovery_info)
        robs.registry.gauge("serve/health").set_info({
            "queue_depth": self.health.queue_depth,
            "in_flight": job_id, "worker": self.worker_id,
            "tenant_rungs": dict(self.admission.tenant_rungs)})
        res = JobResult(job_id=job_id, filename=spec.filename,
                        index=i, admission=entry["admission"])
        res.worker = self.worker_id
        dlog: List[Tuple[float, float]] = []
        stele.set_log_context(
            job_id=job_id, tenant=spec.tenant,
            rung=(entry["admission"] or cfg.pileup),
            worker=self.worker_id)
        self.health.job_started(job_id)
        self._journal_append("started", job=job_id, key=entry["key"],
                             ckpt=cfg.checkpoint_dir or "",
                             worker=self.worker_id,
                             tenant=spec.tenant or "")
        entry["started_unix"] = round(time.time(), 3)
        t0 = time.perf_counter()
        if header_err is not None:
            res.error = f"{type(header_err).__name__}: {header_err}"
            if close_handle is not None:
                close_handle()
        else:
            out = None
            try:
                out = self._execute(contigs, records, cfg, robs,
                                    dlog, job_id)
            except Exception as exc:
                self._note_timeout_if_deadline(robs, exc)
                self._note_poison(spec, exc, res)
                self._note_capacity(spec, exc, robs)
                retry_cfg = self._retry_config(cfg, exc)
                if retry_cfg is not None:
                    out, robs, res.error = self._retry_on_host_rung(
                        spec, retry_cfg, exc, jobnum, job_id)
                else:
                    res.error = f"{type(exc).__name__}: {exc}"
                if res.error is not None:
                    logger.warning("job %s failed: %s", job_id,
                                   res.error)
            finally:
                if close_handle is not None:
                    close_handle()
            if out is not None:
                res.fastas, res.stats = out.fastas, out.stats
                res.error = None
        res.elapsed_sec = time.perf_counter() - t0
        # -- lease confirmation: only the live holder may journal -----
        # (ok AND failed outcomes: a woken zombie's "failed" append
        # would pop the thief's live claim and wreck ITS commit — the
        # thief owns the whole lifecycle once it re-claims)
        journal_lifecycle = True
        if not self.fleet.holds(entry["key"]):
            self.registry.add("fleet/lease_lost", 1)
            journal_lifecycle = False
            if res.ok:
                # abandon the result: no outputs, no journal events —
                # a second commit is exactly the duplication the
                # audit forbids
                res.fastas = None
                res.error = (
                    f"lease lost: worker {self.worker_id!r} held job "
                    f"{job_id} past its TTL and the lease was "
                    f"re-claimed by a peer; result abandoned (the "
                    f"re-claiming worker commits it)")
            else:
                res.error = (
                    f"{res.error} [lease lost mid-run: failure not "
                    f"journaled — the re-claiming worker owns the "
                    f"job's lifecycle]")
        self._finalize_job(entry, res, robs, spec,
                           queue_wait=t0 - window_t0,
                           journal_lifecycle=journal_lifecycle)
        return res

    def _finalize_job(self, entry: dict, res: JobResult, robs,
                      spec: JobSpec, queue_wait: float,
                      echo_suffix: str = "",
                      journal_lifecycle: bool = True) -> None:
        """Everything after a job's run attempt, shared by the serial
        loop and the batch scheduler (serve/scheduler.py) so the two
        execution paths cannot drift: metrics subset + rung/manifest
        capture, journal commit/failed events (outputs durably on disk
        BEFORE the commit event), telemetry fold + per-tenant SLO
        verdict, admission feedback, health bookkeeping, operator
        echo."""
        from ..io.fasta import write_outputs
        from ..resilience import ladder as rladder

        cfg = entry["cfg"]
        job_id = entry["job_id"]
        snap = robs.registry.snapshot()
        res.metrics = {
            k: v for k, v in snap["counters"].items()
            if k.startswith(("serve/", "compile/", "resilience/",
                             "fault/", "phase/", "ingest/",
                             "quarantine/", "cache/", "epilogue/"))}
        res.bad_records = int(
            snap["counters"].get("ingest/bad_records", 0))
        res.quarantined = int(
            snap["counters"].get("quarantine/records", 0))
        if res.bad_records:
            # fleet-level aggregation for the health snapshot (the
            # per-job numbers live in each job's own registry)
            self.registry.add("serve/bad_records", res.bad_records)
        res.rungs = rladder.job_rungs(snap)
        res.manifest = obs.last_manifest() if res.ok else None
        if self.worker_id and res.manifest is not None:
            # which worker committed the job — stamped BEFORE the slo
            # rewrite below persists the manifest file
            res.manifest.setdefault("serve", {})["worker"] = \
                self.worker_id
        # -- flight recorder: journal-measured lifecycle -----------
        # (computed BEFORE the commit below releases fleet claim
        # bookkeeping, stamped BEFORE the slo rewrite persists the
        # manifest).  When a journal is present its wall-clock queue
        # wait is the SLO truth source; the window-epoch measure rides
        # in the lifecycle section as the cross-check.
        lifecycle, journal_qw = self._sched_lifecycle(entry, queue_wait)
        tlabel = spec.tenant or "default"
        if journal_qw is not None:
            self.registry.observe(f"sched/{tlabel}/queue_wait",
                                  journal_qw)
        if "claim_latency_sec" in lifecycle:
            self.registry.observe(f"sched/{tlabel}/claim_latency",
                                  lifecycle["claim_latency_sec"])
        if "steal_latency_sec" in lifecycle:
            self.registry.observe(f"sched/{tlabel}/steal_latency",
                                  lifecycle["steal_latency_sec"])
        self._busy_sec += max(0.0, res.elapsed_sec)
        if res.manifest is not None:
            res.manifest["lifecycle"] = lifecycle
        # -- commit: outputs durably on disk, then the journal -----
        if res.ok and res.fastas is not None \
                and self.journal is not None and journal_lifecycle:
            if self.fleet is not None:
                # the output write + fingerprint pass below runs with
                # no watchdog ticks (no renewals): start the commit
                # window with a full TTL of margin
                self.fleet.renew_now(entry["key"])
            try:
                res.output_paths = write_outputs(
                    res.fastas, cfg.outfolder, cfg.prefix,
                    cfg.nchar, cfg.thresholds, echo=self.echo)
                fps = {p: sjournal.file_fingerprint(p)
                       for p in res.output_paths}
            except Exception as exc:
                # a commit-time write failure (disk full, bad
                # outfolder) fails THIS job, never the queue — the
                # server's survive-failed-jobs contract holds at
                # the commit boundary too
                res.error = (f"output commit failed: "
                             f"{type(exc).__name__}: {exc}")
                res.fastas = None
                res.output_paths = []
                logger.warning("job %s: %s", job_id, res.error)
            else:
                if self.fleet is not None \
                        and not self.fleet.holds(entry["key"]):
                    # the write outlived even the renewed lease and a
                    # peer re-claimed: appending "committed" NOW would
                    # be the duplicate commit the audit forbids — the
                    # thief owns the lifecycle.  (The bytes on disk
                    # are identical to what the thief writes, so the
                    # files themselves are not a hazard.)
                    self.registry.add("fleet/lease_lost", 1)
                    journal_lifecycle = False
                    res.output_paths = []
                    res.fastas = None
                    res.error = (
                        f"lease lost during commit: job {job_id}'s "
                        f"output write outlived the lease TTL and a "
                        f"peer re-claimed the job; commit abandoned "
                        f"(the re-claiming worker commits it)")
                    logger.warning("job %s: %s", job_id, res.error)
                else:
                    fence = {}
                    if self.fleet is not None:
                        # lease lineage: replay voids a commit whose
                        # (worker, claim_seq) does not match the open
                        # lease — the structural duplicate guard
                        cs = self.fleet.claim_seqs.get(entry["key"])
                        if cs is not None:
                            fence["claim_seq"] = cs
                    self._journal_append(
                        "committed", job=job_id, key=entry["key"],
                        outputs=fps,
                        elapsed_sec=round(res.elapsed_sec, 3),
                        worker=self.worker_id,
                        tenant=spec.tenant or "", **fence)
                    self.journal.drop_ckpt(entry["key"])
        if not res.ok and journal_lifecycle:
            self._journal_append("failed", job=job_id,
                                 key=entry["key"], error=res.error)
        # fold the job's registry into the server-lifetime
        # aggregate + per-tenant SLO verdict (never fails a job).
        # Journal-measured queue wait is the truth source when
        # available (PERF.md R15): it spans restarts and steals,
        # which the process-local window epoch cannot.
        self._telemetry_job_end(robs, res, snap, spec.tenant,
                                queue_wait=journal_qw
                                if journal_qw is not None
                                else queue_wait)
        # fold the job's measured throughput into this worker's rate
        # card (observability/ratecard.py) — successful jobs only, so
        # a crash-looping input cannot poison the learned constants
        if res.ok:
            try:
                try:
                    in_bytes = os.path.getsize(spec.filename)
                except OSError:
                    in_bytes = 0
                self.ratecard.observe_job(
                    snap, res.elapsed_sec, input_bytes=in_bytes,
                    decode_cores=max(
                        1, int(getattr(cfg, "decode_threads", 1) or 1)),
                    packed=snap["counters"].get("serve/batched", 0) > 0,
                    lifecycle=lifecycle)
            except Exception as exc:
                logger.warning("rate card fold failed for %s: %s",
                               job_id, exc)
        if entry.get("key"):
            # this life observed the job's SLO verdict directly — a
            # later fleet replay must not feed it to the burn
            # monitor again
            self._burn_fed_keys.add(entry["key"])
        self.jobs_run += 1
        self.registry.add("serve/jobs", 1)
        if not res.ok:
            self.registry.add("serve/jobs_failed", 1)
        self.admission.note_result(
            spec.tenant, res.rungs, res.ok,
            was_pinned=bool(entry["admission"]
                            and str(entry["admission"]).startswith(
                                "pinned")))
        self.last_job_badrec = {
            "job": job_id,
            "bad_records": res.bad_records,
            "quarantined": res.quarantined,
            "budget_exhausted": res.budget_exhausted,
        }
        stele.set_log_context()     # job done: clear correlation
        self.health.job_finished()
        self.health.queue_depth = max(
            0, self.health.queue_depth - 1)
        self.telemetry_tick(force=True)
        self.echo(f"[serve] {job_id}: "
                  + (f"ok in {res.elapsed_sec:.2f}s"
                     if res.ok else f"FAILED ({res.error})")
                  + echo_suffix)

    # -- incremental consensus (serve/countcache.py) -----------------------
    def _plant_seed(self, seed) -> None:
        """Arm the backend for one count-cache job: consume ``seed``
        (None = cold absorb) and capture the final state back."""
        self.backend.serve_count_seed = seed
        self.backend.serve_capture_counts = True

    def _cache_begin(self, spec: JobSpec, cfg: RunConfig, contigs, robs):
        """Seed an incremental job from the warm per-reference state.

        Returns ``(key, seed, cfg)`` — key None for non-incremental
        jobs (cache off / flag off / header unread); cfg gains a
        default ``source_id`` (the input's absolute path, the one-shot
        CLI's convention) so duplicate-shard detection works without
        per-job plumbing.  The warm/cold verdict is a priced ledger
        decision in the JOB's manifest: predicted decode seconds for
        THIS input's bytes, joined against the measured decode phase
        (band=0 — the decode-threads decision already owns enforcing
        the rate model; this one documents what the cache saved)."""
        if self.count_cache is None \
                or not getattr(cfg, "incremental", False) \
                or contigs is None:
            return None, None, cfg
        from . import countcache as ccache

        if not cfg.source_id:
            cfg = dataclasses.replace(
                cfg, source_id=os.path.abspath(spec.filename))
        key = ccache.reference_key(contigs, cfg, spec.tenant)
        seed = self.count_cache.get(key, self.registry)
        self._plant_seed(seed)
        chosen = "warm" if seed is not None else "cold"
        # same (plural) counter names as the cache's server-lifetime
        # family, so a per-job manifest joins the s2c_cache_*
        # exposition key-for-key
        robs.registry.add(
            f"cache/{'hits' if seed is not None else 'misses'}", 1)
        try:
            size = os.path.getsize(spec.filename)
        except OSError:
            size = 0
        # decode rate by precedence: env override, learned rate card
        # (this worker's measured per-core rate), baked default — the
        # same ladder the decode_threads decision prices from, stamped
        # with the consultation's provenance
        from ..observability import ratecard as _rc

        if "S2C_DECODE_MBPS_PER_CORE" in os.environ:
            try:
                rate_mbps = float(
                    os.environ["S2C_DECODE_MBPS_PER_CORE"])
            except ValueError:
                rate_mbps = 330.0
            rc_prov = {"source": "env", "key": "decode_mbps_per_core"}
        else:
            rate_mbps, rc_prov = _rc.consult("decode_mbps_per_core",
                                             330.0)
        rate = rate_mbps * 1e6
        cstats = self.count_cache.stats()
        with obs.bind_run_to_thread(robs):
            obs.record_decision(
                "count_cache", chosen,
                inputs={"entries": cstats["entries"],
                        "resident_mb": cstats["resident_mb"],
                        "input_bytes": int(size),
                        "base_sources": len(seed.sources or [])
                        if seed is not None else 0,
                        "tenant": spec.tenant or ""},
                predicted={"sec": size / rate} if size else {},
                measured={"sec": {"counters": ["phase/decode_sec"]}},
                band=0, provenance=rc_prov)
        return key, seed, cfg

    def _cache_end(self, key: str, ok: bool) -> None:
        """Commit or invalidate the job's entry — the count-bank rule:
        only a job that finished whole re-inserts its state; ANY
        failure after seeding drops the entry entirely (a half-applied
        base must never seed the next job)."""
        result = getattr(self.backend, "serve_count_result", None)
        self.backend.serve_count_result = None
        self.backend.serve_count_seed = None
        self.backend.serve_capture_counts = False
        if ok and result is not None:
            self.count_cache.put(key, result, self.registry)
        else:
            self.count_cache.invalidate(key, self.registry)

    def _note_capacity(self, spec: JobSpec, exc: BaseException,
                       robs) -> None:
        """OOM forensics (observability/memplane.py): a CAPACITY-class
        job failure writes ``mem_dump.json`` next to the journal (the
        durable place an operator already looks — the profiler-capture
        home otherwise): per-family live/peak, the watermark tail, the
        capacity prediction, the error.  The job still classifies and
        (under fallback) demotes exactly as before — forensics never
        changes the recovery path, it explains it."""
        from ..observability import memplane

        if robs.registry.value("mem/oom_dumps"):
            # the backend already dumped next to the job's own metrics
            # artifact (JaxBackend.run's except path — jobs with a
            # per-job metrics_out); count it fleet-side, don't write a
            # second dump over it
            path = os.path.join(
                os.path.dirname(os.path.abspath(robs.metrics_out)),
                memplane.MEM_DUMP_NAME) if robs.metrics_out else None
        else:
            out_dir = self.journal.root if self.journal is not None \
                else self.profiler.out_dir
            path = memplane.dump_on_capacity(
                exc, out_dir, registry=robs.registry,
                context={"job_id": self.health.in_flight,
                         "tenant": spec.tenant})
        if path is not None:
            self.registry.add("serve/oom_dumps", 1)
            self.registry.gauge("serve/last_oom_dump").set_info(
                {"path": path, "job": self.health.in_flight,
                 "error": f"{type(exc).__name__}: {exc}"})

    def _note_poison(self, spec: JobSpec, exc: BaseException,
                     res: JobResult) -> None:
        """Poison-job accounting (DATA class — the input is rotten, not
        the fleet): count the submission per tenant
        (``serve/admission_poison``) WITHOUT touching the tenant's
        ladder rung — a tenant uploading garbage must not be demoted
        off the device path, only told precisely what was wrong.  The
        counter is admission's evidence base for future poison-rate
        throttling."""
        from ..ingest.badrecords import is_data_error

        if not is_data_error(exc):
            return
        res.budget_exhausted = bool(
            getattr(exc, "budget_exhausted", False))
        self.registry.add("serve/admission_poison", 1)
        self.admission.note_poison(spec.tenant)

    # -- job-level ladder --------------------------------------------------
    def _retry_config(self, cfg: RunConfig,
                      exc: BaseException) -> Optional[RunConfig]:
        """The job-level demotion decision: a timed-out/hung/faulted
        job may re-run ONCE, pinned to the ladder's host rung — only
        under fallback mode (the same opt-in the in-run ladder uses),
        only for device-shaped failures, and only when the job was not
        already on the host rung."""
        from ..resilience import ladder as rladder
        from ..resilience.policy import DATA, PASSTHROUGH, classify

        kind = classify(exc)
        on_error = os.environ.get("S2C_ON_DEVICE_ERROR",
                                  getattr(cfg, "on_device_error",
                                          "retry"))
        if on_error != "fallback" or kind in (PASSTHROUGH, DATA):
            # DATA (poison input): the host rung would re-decode the
            # same bytes and fail identically — fail fast with the
            # quarantine summary, keep the tenant on the fast path
            return None
        if cfg.pileup == "host":
            return None                 # already on the bottom rung
        return rladder.job_host_rung_config(cfg)

    def _retry_on_host_rung(self, spec: JobSpec, cfg: RunConfig,
                            exc: BaseException, jobnum: int,
                            job_id: str):
        """Re-run a failed job pinned to the host rung, with fresh
        instruments (the abandoned attempt may still hold its own).
        Returns ``(result_or_None, robs, error_or_None)``."""
        from ..config import resolve_decode_threads
        from ..formats import open_alignment_input
        from ..resilience import ladder as rladder

        self.registry.add("serve/job_retries", 1)
        self.echo(f"[serve] {job_id}: retrying on the host rung "
                  f"after {type(exc).__name__}")

        def _suffix(p):
            # the abandoned first attempt may still write its exports
            # when/if it wakes — the retry must not race it on the
            # same paths
            return f"{p}.retry" if p else p

        robs = obs.prepare_run(
            trace_out=_suffix(self._job_out(cfg.trace_out,
                                            "S2C_TRACE_OUT", jobnum)),
            metrics_out=_suffix(self._job_out(cfg.metrics_out,
                                              "S2C_METRICS_OUT",
                                              jobnum)),
            config=cfg)
        robs.registry.add("serve/job_retries", 1)
        rladder.record_job_demotion(
            robs.registry, f"{type(exc).__name__}: {exc}")
        self._note_timeout_if_deadline(robs, exc, server=False)
        self._journal_append("started", job=job_id,
                             key=sjournal.job_key(spec.filename,
                                                  spec.config),
                             ckpt=cfg.checkpoint_dir or "",
                             retry=True)
        dlog: List[Tuple[float, float]] = []
        handle = None
        try:
            handle = open_alignment_input(
                spec.filename, getattr(cfg, "input_format", "auto"),
                binary=True, threads=resolve_decode_threads(cfg))
            contigs, records = handle.contigs, handle.stream
            out = self._execute(contigs, records, cfg, robs, dlog,
                                f"{job_id}#retry")
            return out, robs, None
        except Exception as exc2:
            return None, robs, (f"{type(exc).__name__}: {exc}; retry on "
                                f"host rung also failed: "
                                f"{type(exc2).__name__}: {exc2}")
        finally:
            if handle is not None:
                try:
                    handle.close()
                except OSError:
                    pass

    def _note_timeout_if_deadline(self, robs, exc,
                                  server: bool = True) -> None:
        from ..resilience.policy import (HungDispatchError,
                                         JobDeadlineExceeded)

        if isinstance(exc, (JobDeadlineExceeded, HungDispatchError)):
            self._note_timeout(robs, exc, server=server)


def submit_jobs(specs: List[JobSpec], **runner_kwargs) -> List[JobResult]:
    """One-call API: build a :class:`ServeRunner`, run the queue, return
    the results (the runner — and its warm caches — is discarded; hold a
    ServeRunner yourself to amortize across submits)."""
    runner = ServeRunner(**runner_kwargs)
    try:
        return runner.submit_jobs(specs)
    finally:
        runner.close()                  # join prewarm + drop atexit ref
