"""Per-reference count cache: incremental consensus as a serving feature.

The checkpoint subsystem already proves the count tensor + insertion
log are the ENTIRE resumable job state (utils/checkpoint.py, SURVEY.md
§5) — this module promotes that fact from crash recovery to the warm
serving path.  A server holding the cache keeps each reference set's
accumulated ``CheckpointState`` resident across jobs, keyed by a
fingerprint of the reference layout + the count-relevant encode knobs
+ the tenant; a tenant streaming new reads against a warm reference
(``--incremental`` serve jobs) pays only decode-of-the-delta + scatter
+ re-vote instead of re-ingesting everything absorbed so far, and the
combined consensus is byte-identical to a cold run over the
concatenated inputs (the same sum-decomposition the checkpointed
``--incremental`` CLI mode pins in tests/test_checkpoint.py).

Residency: entries live in process memory — on link-free rigs that IS
device memory, and on real accelerators the entry re-uploads once on a
hit (dtype-narrowed, the HostPileupAccumulator wire discipline) while
still skipping the re-ingest that dominates the cold cost.  The upload
is priced by the same tail-placement link constants as everything
else.

Eviction: strict LRU under a byte budget (``--count-cache SIZE`` /
``S2C_COUNT_CACHE``).  The count-bank rule governs failure: an
incremental job that fails after seeding invalidates its entry WHOLE —
partially-applied state must never seed the next job — and a job only
(re-)inserts its entry after it commits.  An entry evicted while a job
holds its seed is harmless: the job owns the state by reference, and
re-inserts it (updated) at commit.

Streaming sessions (serve/session.py, ``--ingest-port``) are this
cache's journaled successor: the same seed/capture handoff and the
same count-bank rule, but the warm state is a per-session checkpoint
file under the journal instead of an LRU entry — durable across
SIGKILL and stealable by fleet peers, which is why the two modes are
mutually exclusive at the CLI (one authority per count bank).
"""

from __future__ import annotations

import hashlib
import logging
import re
import threading
from collections import OrderedDict
from typing import Optional

from ..observability import memplane

logger = logging.getLogger("sam2consensus_tpu.serve.countcache")


def parse_budget(value) -> int:
    """``--count-cache`` grammar -> byte budget (0 = disabled).

    Accepts ``off``/``0``/empty (disabled) or a size with an optional
    K/M/G suffix (``512M``, ``2G``, ``1048576``).  Raises ValueError on
    anything else — a typo'd cache budget must fail the server start,
    not silently disable incremental serving."""
    if value is None:
        return 0
    v = str(value).strip().lower()
    if v in ("", "off", "0", "none"):
        return 0
    m = re.fullmatch(r"(\d+(?:\.\d+)?)([kmg]?)b?", v)
    if not m:
        raise ValueError(
            f"--count-cache {value!r}: use 'off' or a byte budget like "
            f"'512M', '2G', '1048576'")
    mult = {"": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30}[m.group(2)]
    n = int(float(m.group(1)) * mult)
    if n <= 0:
        return 0
    return n


#: RunConfig fields that change what the COUNT TENSOR holds for a given
#: input stream — two configs differing here must never share an entry.
#: Vote/render knobs (thresholds, min_depth, fill, prefix, nchar) are
#: deliberately absent: counts are pre-vote state, so a tenant can
#: re-vote a warm reference under new thresholds for free.
COUNT_KEY_FIELDS = ("maxdel", "strict", "py2_compat")


def reference_key(contigs, cfg, tenant: str = "") -> str:
    """Cache key: sha256 over the reference layout (names + lengths in
    declaration order), the count-relevant config, and the tenant —
    tenants never share count state (an entry holds one tenant's
    accumulated reads; leaking it across tenants would merge their
    consensus inputs)."""
    h = hashlib.sha256()
    h.update(tenant.encode("utf-8", "surrogateescape"))
    h.update(b"\x00")
    for c in contigs:
        h.update(str(c.name).encode("utf-8", "surrogateescape"))
        h.update(b"\x01")
        h.update(str(int(c.length)).encode("ascii"))
        h.update(b"\x02")
    for f in COUNT_KEY_FIELDS:
        h.update(f"{f}={getattr(cfg, f, None)!r};".encode("utf-8"))
    return h.hexdigest()


def entry_nbytes(state) -> int:
    """Resident bytes of one cached CheckpointState (counts + the
    insertion chunk arrays — the two unbounded payloads)."""
    n = int(state.counts.nbytes)
    for c, l, ml, ch in state.insertions.array_chunks:
        n += int(c.nbytes + l.nbytes + ml.nbytes + ch.nbytes)
    return n


class CountCache:
    """LRU byte-budgeted map ``reference_key -> CheckpointState``.

    Thread-safe (the serve runner's telemetry HTTP threads read stats
    concurrently with the job loop).  All mutations publish the
    ``cache/*`` counter/gauge family into the registry handed in —
    the serve runner passes its server-lifetime AggregateRegistry, so
    the exposition carries ``s2c_cache_*`` and tools/s2c_top.py can
    render the cache line without extra plumbing."""

    def __init__(self, budget_bytes: int):
        self.budget = int(budget_bytes)
        self._entries: "OrderedDict[str, object]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.evicted_bytes = 0
        self.inserts = 0
        self.invalidated = 0

    # -- accounting --------------------------------------------------------
    def _publish(self, registry, prev_bytes: Optional[int] = None) -> None:
        if prev_bytes is not None:
            # residency accounting (observability/memplane.py): the
            # cache bills its byte delta into the count_cache family,
            # so warm entries show up in every memory surface
            memplane.adjust("count_cache", self._bytes - prev_bytes)
        if registry is None:
            return
        registry.gauge("cache/entries").set(float(len(self._entries)))
        registry.gauge("cache/resident_bytes").set(float(self._bytes))

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "resident_mb": round(self._bytes / 1e6, 3),
                "budget_mb": round(self.budget / 1e6, 3),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "evicted_mb": round(self.evicted_bytes / 1e6, 3),
                "inserts": self.inserts,
                "invalidated": self.invalidated,
            }

    # -- the map -----------------------------------------------------------
    def get(self, key: str, registry=None):
        """The warm state for ``key`` (LRU-touched), or None.  Counted
        as a hit/miss in both the cache and ``registry``."""
        with self._lock:
            state = self._entries.get(key)
            if state is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                if registry is not None:
                    registry.add("cache/hits", 1)
            else:
                self.misses += 1
                if registry is not None:
                    registry.add("cache/misses", 1)
            self._publish(registry)
            return state

    def put(self, key: str, state, registry=None) -> None:
        """(Re-)insert ``key`` as most-recently-used and evict LRU
        entries until the budget holds.  A state larger than the whole
        budget is not cached (it would evict everything for nothing)."""
        nbytes = entry_nbytes(state)
        with self._lock:
            prev = self._bytes
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= entry_nbytes(old)
            if nbytes > self.budget:
                if registry is not None:
                    registry.add("cache/oversize_skipped", 1)
                self._publish(registry, prev_bytes=prev)
                return
            self._entries[key] = state
            self._bytes += nbytes
            self.inserts += 1
            if registry is not None:
                registry.add("cache/inserts", 1)
            evicted = 0
            while self._bytes > self.budget and len(self._entries) > 1:
                _k, victim = self._entries.popitem(last=False)
                vbytes = entry_nbytes(victim)
                self._bytes -= vbytes
                self.evictions += 1
                self.evicted_bytes += vbytes
                evicted += vbytes
                if registry is not None:
                    registry.add("cache/evictions", 1)
                    # the silent-budget fix: eviction under pressure
                    # used to log nothing fleet-wide — the evicted
                    # BYTES now ride the exposition (s2c_cache_
                    # evicted_bytes_total), the health snapshot and
                    # the s2c_top memory line
                    registry.add("cache/evicted_bytes", vbytes)
            if evicted:
                logger.info(
                    "count cache evicted %.1f MB under the %.0f MB "
                    "budget (%d entr%s resident, %.1f MB)",
                    evicted / 1e6, self.budget / 1e6,
                    len(self._entries),
                    "y" if len(self._entries) == 1 else "ies",
                    self._bytes / 1e6)
            self._publish(registry, prev_bytes=prev)

    def invalidate(self, key: str, registry=None) -> bool:
        """Drop ``key`` whole — the count-bank rule's failure edge: a
        seeded job that failed may have observed (or half-applied)
        state the next job must not inherit."""
        with self._lock:
            prev = self._bytes
            state = self._entries.pop(key, None)
            if state is not None:
                self._bytes -= entry_nbytes(state)
                self.invalidated += 1
                if registry is not None:
                    registry.add("cache/invalidated", 1)
            self._publish(registry, prev_bytes=prev)
            return state is not None


def from_config(value) -> Optional[CountCache]:
    """``--count-cache``/S2C_COUNT_CACHE -> a CountCache or None."""
    budget = parse_budget(value)
    return CountCache(budget) if budget else None
