"""Cold-vs-warm serving benchmark: the serve tentpole's measured claim.

COLD is the shape every round before this one shipped: one process per
SAM file (the one-shot CLI in a subprocess — interpreter + jax import +
jit compile + link probe per job).  WARM is the same jobs through one
:class:`~.runner.ServeRunner`.  Both sides produce FASTA bytes that are
compared against each other per job — a serving speedup that changed
the output would be meaningless — and the summary carries the warm
side's ``compile/jit_cache_{hit,miss}`` and ``serve/overlap_sec``
counters so the "why" of the speedup is in the artifact, not asserted.

Consumed by ``tools/serve_bench.py`` (standalone, JSONL artifact for
the campaign) and ``bench.py`` (the ``serve_warm`` row riding the
regression gate).
"""

from __future__ import annotations

import os
import statistics
import subprocess
import sys
import tempfile
import time
from typing import Callable, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _simulate_jobs(tmp: str, n_jobs: int, n_reads: int, contig_len: int,
                   read_len: int, gzip_last: bool) -> list:
    """N single-contig inputs over the SAME reference layout (the
    serving scenario: one reference, many samples — and the layout
    match is what makes jit shapes reusable across jobs)."""
    from ..utils.simulate import SimSpec, simulate

    paths = []
    for k in range(n_jobs):
        spec = SimSpec(n_contigs=1, contig_len=contig_len,
                       n_reads=n_reads, read_len=read_len,
                       contig_len_jitter=0.0, seed=1000 + k,
                       contig_prefix="serveref")
        name = f"serve_job{k}.sam"
        if gzip_last and k == n_jobs - 1:
            name += ".gz"
        path = os.path.join(tmp, name)
        text = simulate(spec)
        if name.endswith(".gz"):
            import gzip as _gzip

            with _gzip.open(path, "wb") as fh:
                fh.write(text.encode("ascii"))
        else:
            with open(path, "w") as fh:
                fh.write(text)
        paths.append(path)
    return paths


def _cold_cmd(path: str, outdir: str, pileup: str) -> list:
    return [sys.executable, "-m", "sam2consensus_tpu.cli",
            "-i", path, "-o", outdir, "--backend", "jax",
            "--pileup", pileup, "--quiet"]


def run_serve_batch_bench(n_jobs: int = 16, n_reads: int = 256,
                          contig_len: int = 5386, read_len: int = 150,
                          pileup: str = "scatter", passes: int = 5,
                          cold: bool = False, cold_timeout: int = 600,
                          log: Optional[Callable] = None) -> dict:
    """Continuous-batching benchmark: warm-SERIAL vs warm-PACKED jobs/sec
    over the same small-job queue (optionally plus the cold-process
    floor), byte-compared per job.

    The job class is the batching sweet spot the tentpole targets: many
    SMALL jobs (amplicon-scale reference, shallow coverage) where the
    per-job device-path machinery — per-job accumulator + dispatch
    sequence + tail + prefetch threads — dominates the actual counting
    work, so packing N jobs into shared slabs with one shared
    dispatch+tail amortizes it.  Both warm sides run one warmup pass
    then ``passes`` measured passes, scoring MIN wall per side
    (alternating, the tolerant_overhead discipline — noisy-neighbor
    spikes poison means, not mins).  Outputs are compared packed vs
    serial (and vs cold when enabled) before anything is timed.
    """
    import statistics as _st

    from ..config import RunConfig, default_prefix
    from ..io.fasta import render_file
    from .runner import JobSpec, ServeRunner

    log = log or (lambda *a: None)
    rows = []
    with tempfile.TemporaryDirectory() as tmp:
        paths = _simulate_jobs(tmp, n_jobs, n_reads, contig_len,
                               read_len, gzip_last=False)

        def specs():
            return [JobSpec(filename=p,
                            config=RunConfig(backend="jax",
                                             pileup=pileup,
                                             prefix=default_prefix(p)),
                            job_id=f"sb{k}")
                    for k, p in enumerate(paths)]

        def rendered(res):
            return {n: render_file(r, 0) for n, r in res.fastas.items()}

        cold_secs = []
        cold_out = {}
        if cold:
            env = dict(os.environ)
            env["PYTHONPATH"] = REPO + os.pathsep \
                + env.get("PYTHONPATH", "")
            env["S2C_JIT_CACHE"] = ""
            for k, path in enumerate(paths):
                outdir = os.path.join(tmp, f"cold{k}")
                os.makedirs(outdir)
                t0 = time.perf_counter()
                r = subprocess.run(_cold_cmd(path, outdir, pileup),
                                   capture_output=True, text=True,
                                   timeout=cold_timeout, env=env,
                                   cwd=REPO)
                dt = time.perf_counter() - t0
                rows.append({"mode": "cold", "job": k,
                             "sec": round(dt, 3), "rc": r.returncode})
                if r.returncode == 0:
                    cold_secs.append(dt)
                    outs = {}
                    for f in sorted(os.listdir(outdir)):
                        with open(os.path.join(outdir, f)) as fh:
                            outs[f] = fh.read()
                    cold_out[k] = outs
        # both warm sides: persistent cache off (round-comparable, the
        # serve_bench discipline), prewarm off (nothing to hide behind
        # on repeated passes)
        r_serial = ServeRunner(prewarm="off", persistent_cache=False,
                               batch="off")
        r_packed = ServeRunner(prewarm="off", persistent_cache=False,
                               batch=str(n_jobs))
        try:
            res_s = r_serial.submit_jobs(specs())     # warmup + bytes
            res_p = r_packed.submit_jobs(specs())
            identical = []
            for k, (a, b) in enumerate(zip(res_p, res_s)):
                same = a.ok and b.ok and rendered(a) == rendered(b)
                if same and cold and k in cold_out:
                    warm_files = {
                        ref + "__" + default_prefix(paths[k])
                        + ".fasta": render_file(recs, 0)
                        for ref, recs in a.fastas.items()}
                    same = warm_files == cold_out[k]
                identical.append(same)
            t_serial, t_packed = [], []
            for _ in range(max(1, passes)):          # alternating
                t0 = time.perf_counter()
                r_packed.submit_jobs(specs())
                t_packed.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                r_serial.submit_jobs(specs())
                t_serial.append(time.perf_counter() - t0)
            # the measured-pass batch decision (prediction residual):
            # from the LAST packed pass's first member manifest
            last = r_packed.submit_jobs(specs())
            decision = None
            for res in last:
                man = res.manifest or {}
                for d in man.get("decisions", []):
                    if d.get("decision") == "serve_batch":
                        decision = d
                        break
                if decision:
                    break
            snap = r_packed.registry.snapshot()
            binfo = snap["gauges"].get("serve/batch", {}).get("info", {})
        finally:
            r_serial.close()
            r_packed.close()
        for i, (tp, ts) in enumerate(zip(t_packed, t_serial)):
            rows.append({"mode": "warm_pass", "i": i,
                         "packed_sec": round(tp, 4),
                         "serial_sec": round(ts, 4)})
        serial_min = min(t_serial)
        packed_min = min(t_packed)
        summary = {
            "summary": True,
            "n_jobs": n_jobs, "n_reads": n_reads,
            "contig_len": contig_len, "read_len": read_len,
            "pileup": pileup, "passes": passes,
            "warm_serial_min_sec": round(serial_min, 4),
            "warm_packed_min_sec": round(packed_min, 4),
            "warm_serial_jobs_per_sec": round(n_jobs / serial_min, 2),
            "warm_packed_jobs_per_sec": round(n_jobs / packed_min, 2),
            "packed_vs_serial": round(serial_min / packed_min, 2),
            "warm_serial_median_sec": round(_st.median(t_serial), 4),
            "warm_packed_median_sec": round(_st.median(t_packed), 4),
            "identical": bool(identical) and all(identical),
            "cold_per_job_sec": round(_st.mean(cold_secs), 3)
            if cold_secs else None,
            "batch": binfo,
            "decision": decision,
        }
        log(f"[serve_batch] warm-serial {summary['warm_serial_jobs_per_sec']}"
            f" jobs/s vs warm-packed "
            f"{summary['warm_packed_jobs_per_sec']} jobs/s = "
            f"{summary['packed_vs_serial']}x, identical="
            f"{summary['identical']}")
    return {"rows": rows, "summary": summary}


def run_incremental_bench(n_reads: int = 1_000_000, extra_pct: int = 10,
                          contig_len: int = 50_000, read_len: int = 100,
                          passes: int = 3, cache_budget: str = "256M",
                          log: Optional[Callable] = None) -> dict:
    """Incremental-consensus benchmark: +``extra_pct``% reads against a
    warm reference vs the cold job over the combined input.

    COLD is what every tenant paid before the count cache: re-submit
    the whole (grown) input as one job.  WARM is the incremental path:
    the reference's count state is already resident (absorbed by an
    earlier job), so the delta shard pays only its own decode + scatter
    + re-vote.  Both run through the SAME warm ServeRunner so the
    ratio isolates the cache, not process cold-start; each warm pass
    first restores the cache entry to its post-base state (otherwise
    pass 2 would hit the duplicate-input no-op and flatter the
    number).  Byte identity — warm output == cold output over the
    concatenated input — is asserted before anything is timed.
    Scoring is MIN wall per side over ``passes`` alternating passes
    (the tolerant_overhead discipline).  The acceptance target is
    ``ratio <= 0.15`` (ROADMAP item 3 / ISSUE 13).
    """
    from ..config import RunConfig, default_prefix
    from ..io.fasta import render_file
    from .countcache import reference_key
    from .runner import JobSpec, ServeRunner

    log = log or (lambda *a: None)
    rows = []
    n_extra = max(1, n_reads * extra_pct // 100)
    with tempfile.TemporaryDirectory() as tmp:
        from ..utils.simulate import SimSpec, simulate

        # indel-free read set: the incremental story is decode + scatter
        # + re-vote, and the insertion tail is a FIXED cost both sides
        # pay identically (it would only blur the ratio; the
        # insertion-heavy identity matrix lives in tests/test_epilogue
        # and tests/test_countcache)
        kw = dict(n_contigs=1, contig_len=contig_len, read_len=read_len,
                  contig_len_jitter=0.0, ins_read_rate=0.0,
                  del_read_rate=0.0, contig_prefix="incrref")
        log(f"[incremental] simulating base ({n_reads} reads) + delta "
            f"({n_extra} reads)...")
        base_text = simulate(SimSpec(n_reads=n_reads, seed=71, **kw))
        extra_text = simulate(SimSpec(n_reads=n_extra, seed=72, **kw))
        base_p = os.path.join(tmp, "base.sam")
        extra_p = os.path.join(tmp, "extra.sam")
        comb_p = os.path.join(tmp, "combined.sam")
        with open(base_p, "w") as fh:
            fh.write(base_text)
        with open(extra_p, "w") as fh:
            fh.write(extra_text)
        lb = base_text.splitlines(True)
        le = extra_text.splitlines(True)
        with open(comb_p, "w") as fh:
            fh.write("".join(
                [ln for ln in lb if ln.startswith("@")]
                + [ln for ln in lb if not ln.startswith("@")]
                + [ln for ln in le if not ln.startswith("@")]))

        def spec(path, inc, jid):
            # one shared prefix: FASTA headers embed it, and the warm
            # and cold sides' bytes are compared verbatim
            return JobSpec(filename=path,
                           config=RunConfig(backend="jax",
                                            prefix="incr",
                                            incremental=inc,
                                            source_id=path if inc
                                            else ""),
                           job_id=jid)

        runner = ServeRunner(prewarm="off", persistent_cache=False,
                             count_cache=cache_budget)
        try:
            # absorb the base (warms the cache AND the jit/native
            # caches), then snapshot the post-base entry so every
            # timed warm pass replays the same delta-against-base job
            res0 = runner.submit_jobs([spec(base_p, True, "base")])
            if not res0[0].ok:
                raise RuntimeError(f"base absorb failed: {res0[0].error}")
            key = next(iter(runner.count_cache._entries))
            entry_base = runner.count_cache._entries[key]
            # identity first: warm delta == cold combined, byte for byte
            res_w = runner.submit_jobs([spec(extra_p, True, "warm0")])
            res_c = runner.submit_jobs([spec(comb_p, False, "cold0")])
            if not (res_w[0].ok and res_c[0].ok):
                raise RuntimeError(
                    f"warm/cold failed: {res_w[0].error} "
                    f"/ {res_c[0].error}")

            def rendered(res):
                return {n: render_file(v, 0)
                        for n, v in res.fastas.items()}

            identical = rendered(res_w[0]) == rendered(res_c[0])
            warm_secs, cold_secs = [], []
            decision = None
            for i in range(max(1, passes)):
                runner.count_cache.put(key, entry_base,
                                       runner.registry)
                rw = runner.submit_jobs([spec(extra_p, True,
                                              f"warm{i + 1}")])[0]
                rc = runner.submit_jobs([spec(comb_p, False,
                                              f"cold{i + 1}")])[0]
                if not (rw.ok and rc.ok):
                    raise RuntimeError(
                        f"pass {i}: {rw.error} / {rc.error}")
                warm_secs.append(rw.elapsed_sec)
                cold_secs.append(rc.elapsed_sec)
                rows.append({"mode": "pass", "i": i,
                             "warm_sec": round(rw.elapsed_sec, 4),
                             "cold_sec": round(rc.elapsed_sec, 4)})
                for d in (rw.manifest or {}).get("decisions", []):
                    if d.get("decision") == "count_cache":
                        decision = d
            cstats = runner.count_cache.stats()
        finally:
            runner.close()
        warm_min, cold_min = min(warm_secs), min(cold_secs)
        summary = {
            "summary": True,
            "n_reads": n_reads, "extra_pct": extra_pct,
            "n_extra": n_extra, "contig_len": contig_len,
            "read_len": read_len, "passes": passes,
            "warm_incr_min_sec": round(warm_min, 4),
            "cold_min_sec": round(cold_min, 4),
            "incr_cost_ratio": round(warm_min / cold_min, 4),
            "target_ratio": 0.15,
            "identical": bool(identical),
            "cache": cstats,
            "decision": decision,
        }
        log(f"[incremental] +{extra_pct}% reads: warm {warm_min:.3f}s "
            f"vs cold {cold_min:.3f}s = "
            f"{summary['incr_cost_ratio']:.2%} of cold "
            f"(target <=15%), identical={identical}")
    return {"rows": rows, "summary": summary}


def run_serve_bench(n_jobs: int = 8, n_reads: int = 5000,
                    contig_len: int = 5386, read_len: int = 100,
                    pileup: str = "scatter", gzip_last: bool = True,
                    cold_timeout: int = 600,
                    log: Optional[Callable] = None) -> dict:
    """Run the cold-process baseline then the warm server over the same
    ``n_jobs`` inputs; returns ``{"rows": [...], "summary": {...}}``.

    ``pileup`` defaults to the explicit device scatter so the jit-reuse
    story is exercised even where auto would route host-side (the warm
    path must win on the DEVICE path to matter at serving scale).
    """
    from ..config import RunConfig, default_prefix
    from ..io.fasta import render_file
    from .runner import JobSpec, ServeRunner

    log = log or (lambda *a: None)
    rows = []
    with tempfile.TemporaryDirectory() as tmp:
        paths = _simulate_jobs(tmp, n_jobs, n_reads, contig_len,
                               read_len, gzip_last)
        # -- cold: one process per job (the pre-serve reality) --------
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        # BOTH sides run with the persistent on-disk compile cache
        # disabled (cold via env below, warm via persistent_cache=
        # False): the cold baseline must model the pre-serve reality —
        # the one-shot CLI now wires the cache too — and the warm
        # numbers must not depend on what an earlier round left on
        # disk, or the gated serve series compares non-equivalent
        # conditions round to round.  The persistent cache's own win
        # is pinned separately (tests/test_serve.py cross-process).
        env["S2C_JIT_CACHE"] = ""
        cold_out = {}
        cold_secs = []
        for k, path in enumerate(paths):
            outdir = os.path.join(tmp, f"cold{k}")
            os.makedirs(outdir)
            t0 = time.perf_counter()
            r = subprocess.run(_cold_cmd(path, outdir, pileup),
                               capture_output=True, text=True,
                               timeout=cold_timeout, env=env, cwd=REPO)
            dt = time.perf_counter() - t0
            ok = r.returncode == 0
            rows.append({"mode": "cold", "job": k, "sec": round(dt, 3),
                         "rc": r.returncode})
            if ok:
                cold_secs.append(dt)
                outs = {}
                for f in sorted(os.listdir(outdir)):
                    with open(os.path.join(outdir, f)) as fh:
                        outs[f] = fh.read()
                cold_out[k] = outs
            else:
                rows[-1]["stderr_tail"] = \
                    (r.stderr.strip().splitlines() or [""])[-1]
            log(f"[serve_bench] cold job{k}: {dt:.2f}s rc={r.returncode}")
        # -- warm: one server, same jobs ------------------------------
        specs = [JobSpec(filename=p,
                         config=RunConfig(backend="jax", pileup=pileup,
                                          prefix=default_prefix(p)),
                         job_id=f"warm{k}")
                 for k, p in enumerate(paths)]
        # the warm side runs with the telemetry plane ON (exposition
        # into a scratch file): its format-lint verdict rides the
        # summary, so every committed serve_bench artifact doubles as
        # proof the exposition stays well-formed under a real queue
        tele_path = os.path.join(tmp, "serve_bench.prom")
        runner = ServeRunner(persistent_cache=False,
                             telemetry_out=tele_path,
                             telemetry_interval=0.5,
                             echo=lambda m: log(f"[serve_bench] {m}"))
        try:
            t0 = time.perf_counter()
            results = runner.submit_jobs(specs)
            warm_total = time.perf_counter() - t0
        finally:
            runner.close()              # join prewarm, drop atexit ref
        warm_secs = []
        identical = []
        for k, res in enumerate(results):
            row = {"mode": "warm", "job": k,
                   "sec": round(res.elapsed_sec, 3),
                   "ok": res.ok,
                   "jit_hit": int(res.metrics.get(
                       "compile/jit_cache_hit", 0)),
                   "jit_miss": int(res.metrics.get(
                       "compile/jit_cache_miss", 0)),
                   "overlap_sec": round(res.metrics.get(
                       "serve/overlap_sec", 0.0), 4)}
            if res.ok:
                warm_secs.append(res.elapsed_sec)
                if k in cold_out:
                    warm_files = {
                        ref + "__" + specs[k].config.prefix + ".fasta":
                        render_file(recs, 0)
                        for ref, recs in res.fastas.items()}
                    same = warm_files == cold_out[k]
                    row["identical"] = same
                    identical.append(same)
            else:
                row["error"] = res.error
            rows.append(row)
        cold_per_job = statistics.mean(cold_secs) if cold_secs else 0.0
        warm_per_job = statistics.mean(warm_secs) if warm_secs else 0.0
        warm_tail = statistics.mean(warm_secs[1:]) \
            if len(warm_secs) > 1 else warm_per_job
        summary = {
            "summary": True,
            "n_jobs": n_jobs,
            "n_reads": n_reads,
            "contig_len": contig_len,
            "pileup": pileup,
            "cold_per_job_sec": round(cold_per_job, 3),
            "warm_per_job_sec": round(warm_per_job, 3),
            "warm_tail_per_job_sec": round(warm_tail, 3),
            "warm_total_sec": round(warm_total, 3),
            "speedup_vs_cold": round(cold_per_job / warm_per_job, 2)
            if warm_per_job > 0 else 0.0,
            "identical": bool(identical) and all(identical),
            "overlap_sec_total": round(
                runner.registry.value("serve/overlap_sec"), 4),
            "jit_cache_dir": runner.cache_dir,
        }
        # the rate card the warm run learned rides the summary: a
        # committed serve_bench artifact then doubles as evidence of
        # what the capacity plane would have believed about this host
        try:
            card = runner.ratecard.snapshot()
            summary["ratecard"] = {
                k: {"mean": v["mean"], "n": v["n"],
                    "confident": v["confident"]}
                for k, v in card.get("rates", {}).items()}
        except Exception:
            summary["ratecard"] = {}
        try:
            from ..observability.telemetry import lint_openmetrics

            with open(tele_path, encoding="utf-8") as fh:
                lint = lint_openmetrics(fh.read())
            summary["telemetry"] = {
                "lint_errors": len(lint),
                "lint_first": lint[:2],
                "jobs_folded": int(runner.registry.value(
                    "telemetry/jobs_folded")),
                "write_failed": int(runner.registry.value(
                    "telemetry/write_failed")),
            }
        except OSError as exc:
            summary["telemetry"] = {"error": str(exc)}
        log(f"[serve_bench] cold {cold_per_job:.2f}s/job vs warm "
            f"{warm_per_job:.2f}s/job "
            f"({summary['speedup_vs_cold']}x), identical="
            f"{summary['identical']}")
    return {"rows": rows, "summary": summary}


def _sha_dir(d: str) -> dict:
    import hashlib

    out = {}
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        h = hashlib.sha256()
        with open(os.path.join(d, name), "rb") as fh:
            h.update(fh.read())
        out[name] = h.hexdigest()
    return out


def _fleet_cmd(paths, outdir, jdir, worker, lease_ttl, pileup):
    cmd = [sys.executable, "-m", "sam2consensus_tpu.cli", "serve"]
    for p in paths:
        cmd += ["-i", p]
    cmd += ["-o", outdir, "--journal", jdir, "--worker-id", worker,
            "--lease-ttl", str(lease_ttl), "--pileup", pileup,
            "--quiet"]
    return cmd


def run_fleet_bench(n_jobs: int = 6, n_reads: int = 4000,
                    contig_len: int = 3000, read_len: int = 100,
                    n_workers: int = 2, lease_ttl: float = 10.0,
                    pileup: str = "scatter",
                    per_process_timeout: float = 900.0,
                    log: Optional[Callable] = None) -> dict:
    """Fleet queue-drain benchmark: the SAME journaled queue drained by
    one worker vs ``n_workers`` work-stealing workers (serve/fleet.py),
    byte-compared.

    Both drains run subprocess workers against a shared persistent
    compile cache warmed by an untimed pass first, so the measurement
    is queue drain, not XLA compilation — and the comparison is fair
    (neither side pays the cold compile).  ``drain_speedup`` is the
    ROADMAP 2(b) metric: >=1.8x on a multi-core rig; on a 1-core
    harness host the workers serialize on the GIL-free decode + XLA
    dispatch anyway, so the honest expectation there is ~1.0x minus
    coordination overhead (the summary carries ``host_cores`` so the
    artifact says which world it measured).
    """
    log = log or (lambda *a, **k: None)
    rows = []
    with tempfile.TemporaryDirectory() as tmp:
        paths = []
        from ..utils.simulate import SimSpec, simulate

        for k in range(n_jobs):
            spec = SimSpec(n_contigs=1, contig_len=contig_len,
                           n_reads=n_reads, read_len=read_len,
                           contig_len_jitter=0.0, seed=7100 + k,
                           contig_prefix=f"fb{k:02d}_")
            p = os.path.join(tmp, f"fleet_job{k}.sam")
            with open(p, "w") as fh:
                fh.write(simulate(spec))
            paths.append(p)
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH",
                                                        "")
        env["S2C_JIT_CACHE"] = os.path.join(tmp, "_jit_cache")

        def drain(tag, workers):
            outdir = os.path.join(tmp, f"out_{tag}")
            jdir = os.path.join(tmp, f"j_{tag}")
            t0 = time.monotonic()
            procs = [subprocess.Popen(
                _fleet_cmd(paths, outdir, jdir, w, lease_ttl, pileup),
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE) for w in workers]
            rcs = []
            for pr in procs:
                try:
                    _, err = pr.communicate(timeout=per_process_timeout)
                except subprocess.TimeoutExpired:
                    pr.kill()
                    _, err = pr.communicate()
                rcs.append(pr.returncode)
                if pr.returncode != 0:
                    log(f"[fleet_bench] {tag} worker rc="
                        f"{pr.returncode}: "
                        f"{(err or b'').decode()[-800:]}")
            wall = time.monotonic() - t0
            from .journal import JobJournal

            return outdir, wall, rcs, JobJournal(jdir).audit()

        # untimed warmup fills the shared persistent compile cache
        drain("warmup", ["warm0"])
        out1, serial_sec, rc1, audit1 = drain("serial", ["solo"])
        workers = [f"fw{i}" for i in range(max(1, n_workers))]
        out2, fleet_sec, rc2, audit2 = drain("fleet", workers)
        want, got = _sha_dir(out1), _sha_dir(out2)
        identical = bool(want) and want == got
        speedup = round(serial_sec / fleet_sec, 3) if fleet_sec else 0.0
        # first NON-zero code per drain (a timeout-SIGKILLed worker's
        # -9 must not be masked by a peer's clean 0 — max() would)
        bad1 = next((rc for rc in rc1 if rc != 0), 0)
        bad2 = next((rc for rc in rc2 if rc != 0), 0)
        rows.append({"mode": "serial_drain", "workers": 1,
                     "drain_sec": round(serial_sec, 3),
                     "rc": bad1, "lost": len(audit1["lost"]),
                     "duplicated": len(audit1["duplicated"])})
        rows.append({"mode": "fleet_drain", "workers": len(workers),
                     "drain_sec": round(fleet_sec, 3),
                     "rc": bad2, "lost": len(audit2["lost"]),
                     "duplicated": len(audit2["duplicated"])})
        summary = {
            "summary": True,
            "n_jobs": n_jobs, "n_reads": n_reads,
            "contig_len": contig_len, "n_workers": len(workers),
            "lease_ttl_sec": lease_ttl,
            "serial_drain_sec": round(serial_sec, 3),
            "fleet_drain_sec": round(fleet_sec, 3),
            "fleet_per_job_sec": round(fleet_sec / n_jobs, 4),
            "drain_speedup": speedup,
            "identical": identical,
            "lost": len(audit2["lost"]),
            "duplicated": len(audit2["duplicated"]),
            "host_cores": os.cpu_count(),
            "ok": (identical and bad1 == 0 and bad2 == 0
                   and not audit2["lost"]
                   and not audit2["duplicated"]),
        }
        log(f"[fleet_bench] 1 worker {serial_sec:.1f}s vs "
            f"{len(workers)} workers {fleet_sec:.1f}s = {speedup}x "
            f"({os.cpu_count()} host core(s)), identical={identical}")
    return {"rows": rows, "summary": summary}


def run_streaming_bench(n_waves: int = 10, n_reads: int = 40000,
                        contig_len: int = 8000, read_len: int = 100,
                        stability_waves: int = 3,
                        per_process_timeout: float = 600.0,
                        log: Optional[Callable] = None) -> dict:
    """Streaming-session benchmark (ISSUE 17): the SAME reads absorbed
    live in ``n_waves`` waves through a journaled session
    (serve/session.py) vs the one-shot COLD batch job.

    COLD here is what cold means everywhere in this module: the
    one-shot CLI in a fresh subprocess — the basecaller's actual
    alternative to streaming is "wait for the run to end, then launch
    the batch job" (interpreter + jax import + compile + the whole
    ingest).  ``stream_cost_ratio`` = session wall (open + waves +
    close) / cold wall; target <=1.3x.  The summary also records
    ``stream_vs_warm`` against a warm IN-PROCESS one-shot of the same
    reads — the durability bill with no startup to hide behind: each
    wave pays a journal fsync, an atomic checkpoint save and a full
    vote tail, so at harness scale this ratio is well above 1 (the
    artifact says so rather than burying it).

    The READ-UNTIL dividend rides the same run: the session watches
    its consensus digest and goes STABLE once it is unchanged
    ``stability_waves`` consecutive waves — the bench stops feeding at
    that verdict (``early_stop_wave``), which is the point of
    streaming: the basecaller stops sequencing early.  The
    early-stopped consensus must still match the full cold run at
    SEQUENCE level (``consensus_digest`` — coverage annotations in
    the headers legitimately differ when fewer reads were absorbed).
    """
    log = log or (lambda *a, **k: None)
    from ..config import RunConfig
    from .runner import JobSpec, ServeRunner
    from .session import SessionManager, consensus_digest

    rows = []
    with tempfile.TemporaryDirectory() as tmp:
        from ..utils.simulate import SimSpec, simulate

        # low-noise corpus: stability must mean CONVERGED (a noisy
        # corpus keeps near-threshold columns flapping wave to wave,
        # and an early stop would then diverge from the full run)
        spec = SimSpec(n_contigs=1, contig_len=contig_len,
                       n_reads=n_reads, read_len=read_len,
                       contig_len_jitter=0.0, seed=8300,
                       contig_prefix="st_", sub_rate=0.002,
                       n_rate=0.0005)
        text = simulate(spec)
        lines = text.splitlines(keepends=True)
        header = "".join(l for l in lines if l.startswith("@"))
        reads = [l for l in lines if not l.startswith("@")]
        per = max(1, (len(reads) + n_waves - 1) // n_waves)
        waves = ["".join(reads[i:i + per]).encode("utf-8")
                 for i in range(0, len(reads), per)]
        concat = os.path.join(tmp, "stream.sam")
        with open(concat, "w") as fh:
            fh.write(text)

        # cold leg: the one-shot CLI in a fresh subprocess
        cold_out = os.path.join(tmp, "out_cold")
        t0 = time.monotonic()
        proc = subprocess.run(
            _cold_cmd(concat, cold_out, "auto"),
            env=dict(os.environ,
                     PYTHONPATH=REPO + os.pathsep
                     + os.environ.get("PYTHONPATH", "")),
            capture_output=True, timeout=per_process_timeout)
        cold_sec = time.monotonic() - t0
        if proc.returncode != 0:
            raise RuntimeError(
                f"cold one-shot failed rc={proc.returncode}: "
                f"{proc.stderr.decode()[-800:]}")

        noop = lambda *a, **k: None  # noqa: E731
        cfg = RunConfig(prefix="", outfolder=tmp + os.sep)
        # warm comparator runs on a journal-FREE runner (a journaled
        # runner would dedup the timed job against the warmup commit);
        # the session runs on a journaled one.  Same process, so the
        # warmup's XLA compile warmth covers both.
        batch_runner = ServeRunner(prewarm="off", decode_ahead=False,
                                   echo=noop)
        runner = ServeRunner(prewarm="off", decode_ahead=False,
                             echo=noop,
                             journal_dir=os.path.join(tmp, "journal"))
        try:
            def warm_shot(job_id):
                t0 = time.monotonic()
                res = batch_runner.submit_jobs(
                    [JobSpec(filename=concat, config=cfg,
                             job_id=job_id)])[0]
                if res.error or res.fastas is None:
                    raise RuntimeError(f"warm one-shot failed: "
                                       f"{res.error}")
                return time.monotonic() - t0, res.fastas

            warm_shot("warmup")         # untimed: fills the jit cache
            warm_sec, warm_fastas = warm_shot("warm")
            full_digest = consensus_digest(warm_fastas)

            manager = SessionManager(runner, cfg,
                                     stability_waves=stability_waves,
                                     revote_debounce=0.0)
            t0 = time.monotonic()
            sid = manager.open_session(header, tenant="bench")["sid"]
            waves_fed = 0
            early_stop_wave = None
            for body in waves:
                ack = manager.receive_wave(sid, body)
                waves_fed += 1
                if ack.get("stable"):
                    early_stop_wave = ack.get("stable_wave")
                    break
            final = manager.close_session(sid)
            stream_sec = time.monotonic() - t0
        finally:
            runner.close()
            batch_runner.close()

        ratio = round(stream_sec / cold_sec, 3) if cold_sec else 0.0
        vs_warm = round(stream_sec / warm_sec, 3) if warm_sec else 0.0
        digest_matches = final.get("digest") == full_digest
        rows.append({"mode": "one_shot_cold", "waves": 1,
                     "wall_sec": round(cold_sec, 3)})
        rows.append({"mode": "one_shot_warm", "waves": 1,
                     "wall_sec": round(warm_sec, 3)})
        rows.append({"mode": "streaming", "waves": waves_fed,
                     "wall_sec": round(stream_sec, 3),
                     "early_stop_wave": early_stop_wave})
        summary = {
            "summary": True,
            "n_waves": len(waves), "waves_fed": waves_fed,
            "n_reads": n_reads, "contig_len": contig_len,
            "stability_waves": stability_waves,
            "cold_sec": round(cold_sec, 3),
            "warm_one_shot_sec": round(warm_sec, 3),
            "stream_sec": round(stream_sec, 3),
            "stream_cost_ratio": ratio,
            "stream_vs_warm": vs_warm,
            "early_stop_wave": early_stop_wave,
            "stable": early_stop_wave is not None,
            "digest_matches_cold": digest_matches,
            "host_cores": os.cpu_count(),
            "ok": (digest_matches and early_stop_wave is not None
                   and ratio <= 1.3),
        }
        log(f"[streaming_bench] {waves_fed}/{len(waves)} wave(s) "
            f"{stream_sec:.2f}s vs cold one-shot {cold_sec:.2f}s = "
            f"{ratio}x (target <=1.3x; vs warm in-process "
            f"{warm_sec:.2f}s = {vs_warm}x), "
            f"early_stop_wave={early_stop_wave}, "
            f"digest_matches_cold={digest_matches}")
    return {"rows": rows, "summary": summary}


def _simulate_cohort(tmp: str, n_samples: int, n_reads: int,
                     contig_len: int, read_len: int) -> list:
    """N shared-reference samples (same contig name + length, different
    reads): the cohort scenario — one panel, many members, so every
    member's layout fingerprint matches and ONE PanelGeometry covers
    the whole manifest."""
    from ..utils.simulate import SimSpec, simulate

    paths = []
    width = len(str(max(0, n_samples - 1)))
    for k in range(n_samples):
        spec = SimSpec(n_contigs=1, contig_len=contig_len,
                       n_reads=n_reads, read_len=read_len,
                       contig_len_jitter=0.0, seed=20_000 + k,
                       contig_prefix="cohref")
        path = os.path.join(tmp, f"cohort_{k:0{width}d}.sam")
        with open(path, "w") as fh:
            fh.write(simulate(spec))
        paths.append(path)
    return paths


def run_cohort_bench(n_samples: int = 200, n_reads: int = 64,
                     contig_len: int = 1500, read_len: int = 100,
                     wave: int = 0, stranger_n: int = 0,
                     stranger_batch: int = 8, spot_checks: int = 20,
                     pin_members: int = 24, mem_budget: int = 0,
                     log: Optional[Callable] = None) -> dict:
    """Cohort-scale benchmark (ISSUE 20): one manifest submission
    streamed through :class:`~.cohort.CohortRunner` in packed waves,
    measured against the PR-11 packed-STRANGER path (the batch
    scheduler with no cohort planning: fixed max_jobs, no wave-ahead
    prefetch, no canonical-slab prewarm) on a subset of the same
    members.

    The artifact carries the acceptance evidence, not assertions:

    * ``replans_after_wave1`` / ``new_compiles_after_wave1`` — counter
      deltas between the end of wave 1 and the end of the run (the
      wave-hook seam), both required 0: one PanelGeometry and one
      compile footprint cover every wave;
    * ``identical`` — ``spot_checks`` members drawn deterministically,
      re-run through a fresh SERIAL runner and byte-compared against
      the cohort's rendered outputs;
    * ``concordance_pinned`` — a ``pin_members``-member mini-cohort's
      concordance digest vs the same members accumulated through the
      CPU oracle (:func:`~.cohort.oracle_member_counts`): table-exact
      equality, per-position;
    * ``residual_in_band`` — no ``cohort_wave`` decision drifted once
      its rate was learned (band-0 warmup decisions cannot drift by
      construction);
    * ``cohort_ge_stranger`` — cohort jobs/s >= packed-stranger
      jobs/s over the same job class.
    """
    import random

    from ..config import RunConfig, default_prefix
    from ..io.fasta import render_file
    from .cohort import (ConcordanceAccumulator, CohortRunner,
                         load_manifest, oracle_member_counts)
    from .runner import JobSpec, ServeRunner

    log = log or (lambda *a: None)
    noop = lambda *a, **k: None  # noqa: E731
    rows = []
    with tempfile.TemporaryDirectory() as tmp:
        t_sim = time.perf_counter()
        paths = _simulate_cohort(tmp, n_samples, n_reads, contig_len,
                                 read_len)
        log(f"[cohort_bench] simulated {n_samples} sample(s) in "
            f"{time.perf_counter() - t_sim:.1f}s")
        manifest = os.path.join(tmp, "manifest.txt")
        with open(manifest, "w") as fh:
            fh.write("# cohort bench manifest — one relative path per "
                     "line\n")
            fh.write("".join(os.path.basename(p) + "\n" for p in paths))
        paths = load_manifest(manifest)    # the ONE submission

        def rendered(res):
            return {n: render_file(r, 0) for n, r in res.fastas.items()}

        # warmup pass (the serve_bench discipline): pay the process-
        # level one-time costs — imports, native accumulator load,
        # first-dispatch spin-up — before EITHER timed leg, so leg
        # order stops deciding who absorbs them
        r_warm = ServeRunner(prewarm="off", persistent_cache=False,
                             echo=noop, batch="off")
        try:
            r_warm.submit_jobs(
                [JobSpec(filename=p,
                         config=RunConfig(
                             backend="jax",
                             prefix=default_prefix(p),
                             outfolder=os.path.join(tmp, "out_warm")),
                         job_id=f"warm{k}")
                 for k, p in enumerate(paths[:2])])
        finally:
            r_warm.close()

        # -- stranger leg: PR-11 packed path, no cohort planning -------
        # measured FIRST of the two timed legs (the later leg always
        # runs in a warmer process, so leg order must never favor the
        # side whose claim is under test), over MEDIAN of 3 passes: a
        # sub-second single pass on a shared box is noise, and the
        # cohort side gets no retries
        sn = stranger_n or min(n_samples, 16 * stranger_batch)
        s_paths = paths[:sn]
        s_walls, stranger_ok = [], 0
        for p_i in range(3):
            r_packed = ServeRunner(prewarm="off",
                                   persistent_cache=False, echo=noop,
                                   batch=str(stranger_batch))
            try:
                t0 = time.perf_counter()
                res_strangers = r_packed.submit_jobs(
                    [JobSpec(filename=p,
                             config=RunConfig(
                                 backend="jax",
                                 prefix=default_prefix(p),
                                 outfolder=os.path.join(
                                     tmp, "out_str")),
                             job_id=f"str{p_i}_{k}")
                     for k, p in enumerate(s_paths)])
                s_walls.append(time.perf_counter() - t0)
            finally:
                r_packed.close()
            stranger_ok = sum(1 for r in res_strangers if r.ok)
        stranger_sec = statistics.median(s_walls)
        stranger_jps = stranger_ok / max(1e-9, stranger_sec)
        rows.append({"mode": "stranger", "n": sn,
                     "ok": stranger_ok,
                     "wall_secs": [round(s, 3) for s in s_walls],
                     "wall_sec": round(stranger_sec, 3),
                     "jobs_per_sec": round(stranger_jps, 2)})

        # -- cohort leg: ONE manifest submission, streamed waves -------
        out_cohort = os.path.join(tmp, "out_cohort")
        cfg = RunConfig(backend="jax", prefix="", outfolder=out_cohort)
        runner = ServeRunner(prewarm="auto", persistent_cache=False,
                             echo=noop, batch="auto",
                             mem_budget=mem_budget or None)
        per_wave = []
        try:
            cohort = CohortRunner(runner, paths, cfg, wave=wave,
                                  echo=noop)

            def _snap(k):
                reg = runner.registry
                lw = cohort.last_wave
                per_wave.append({
                    "wave": k,
                    "panel_plans": int(reg.value("batch/panel_plans")),
                    "jit_misses": int(
                        reg.value("compile/jit_cache_miss")),
                    "jobs_per_sec": round(float(
                        lw.get("jobs_per_sec", 0.0)), 2),
                    "occupancy_pct": round(float(
                        lw.get("occupancy_pct", 0.0)), 1),
                })

            cohort.wave_hook = _snap
            t0 = time.perf_counter()
            summary_c = cohort.run()
            cohort_sec = time.perf_counter() - t0
            by_file = {r.filename: r for r in cohort.results}
        finally:
            runner.close()
        rows.extend({"mode": "cohort_wave", **pw} for pw in per_wave)
        replans_after_w1 = (per_wave[-1]["panel_plans"]
                            - per_wave[0]["panel_plans"]) \
            if len(per_wave) > 1 else 0
        compiles_after_w1 = (per_wave[-1]["jit_misses"]
                             - per_wave[0]["jit_misses"]) \
            if len(per_wave) > 1 else 0

        # -- byte-identity spot checks vs a fresh serial runner --------
        rng = random.Random(0xC0047)
        picks = rng.sample(range(n_samples),
                           min(spot_checks, n_samples))
        r_serial = ServeRunner(prewarm="off", persistent_cache=False,
                               echo=noop, batch="off")
        try:
            res_serial = r_serial.submit_jobs(
                [JobSpec(filename=paths[i],
                         config=RunConfig(
                             backend="jax",
                             prefix=default_prefix(paths[i]),
                             outfolder=os.path.join(tmp, "out_ser")),
                         job_id=f"ser{i}")
                 for i in picks])
        finally:
            r_serial.close()
        identical = []
        for i, rs in zip(picks, res_serial):
            rc = by_file.get(paths[i])
            identical.append(rc is not None and rc.ok and rs.ok
                             and rendered(rc) == rendered(rs))
        rows.append({"mode": "spot_check", "n": len(picks),
                     "identical": sum(map(bool, identical))})

        # -- concordance pin: mini-cohort digest vs the CPU oracle -----
        pin_n = min(pin_members, n_samples)
        pin_paths = paths[:pin_n]
        pin_cfg = RunConfig(backend="jax", prefix="",
                            outfolder=os.path.join(tmp, "out_pin"))
        r_pin = ServeRunner(prewarm="off", persistent_cache=False,
                            echo=noop, batch="auto")
        try:
            mini = CohortRunner(r_pin, pin_paths, pin_cfg, echo=noop)
            summary_pin = mini.run()
            oracle = ConcordanceAccumulator(mini.panel_len)
            for p in pin_paths:
                oracle.add_member(oracle_member_counts(
                    p, pin_cfg, backend=r_pin.backend))
        finally:
            r_pin.close()
        pin_device = (summary_pin.get("concordance") or {})
        pin_oracle = oracle.summary()
        concordance_pinned = pin_device.get("digest") \
            == pin_oracle.get("digest")
        rows.append({"mode": "concordance_pin", "n": pin_n,
                     "device_digest": pin_device.get("digest"),
                     "oracle_digest": pin_oracle.get("digest")})

        decisions = summary_c.get("decisions") or []
        residual_in_band = not any(d.get("drift") for d in decisions)
        cohort_jps = summary_c.get("jobs_per_sec", 0.0)
        summary = {
            "summary": True, "mode": "summary",
            "n_samples": n_samples, "n_reads": n_reads,
            "contig_len": contig_len, "read_len": read_len,
            "wave": wave, "waves": summary_c.get("waves"),
            "samples_ok": summary_c.get("samples_ok"),
            "failed": summary_c.get("failed"),
            "cohort_sec": round(cohort_sec, 3),
            "jobs_per_sec": cohort_jps,
            "occupancy_pct": round(float(
                cohort.last_wave.get("occupancy_pct", 0.0)), 1),
            "stranger_n": sn,
            "stranger_jobs_per_sec": round(stranger_jps, 2),
            "cohort_ge_stranger": cohort_jps >= stranger_jps,
            "panel_plans": summary_c.get("panel_plans"),
            "panel_reuses": summary_c.get("panel_reuses"),
            "replans_after_wave1": replans_after_w1,
            "new_compiles_after_wave1": compiles_after_w1,
            "spot_checks": len(picks),
            "identical": bool(identical) and all(identical),
            "concordance_pinned": concordance_pinned,
            "mean_concordance": (summary_c.get("concordance")
                                 or {}).get("mean_concordance"),
            "residual_in_band": residual_in_band,
            "cohort_wave_decisions": len(decisions),
            "batch_demotions": summary_c.get("batch_demotions"),
            "admission_trips": summary_c.get("admission_trips"),
            "mem_budget": mem_budget or None,
            "host_cores": os.cpu_count(),
            "ok": (summary_c.get("failed") == 0
                   and bool(identical) and all(identical)
                   and concordance_pinned
                   and replans_after_w1 == 0
                   and compiles_after_w1 == 0
                   and residual_in_band
                   and cohort_jps >= stranger_jps),
        }
        log(f"[cohort_bench] {summary['samples_ok']}/{n_samples} ok in "
            f"{summary['cohort_sec']}s ({cohort_jps} jobs/s vs "
            f"stranger {summary['stranger_jobs_per_sec']}), "
            f"identical={summary['identical']}, "
            f"concordance_pinned={concordance_pinned}, "
            f"replans_after_wave1={replans_after_w1}, "
            f"new_compiles_after_wave1={compiles_after_w1}, "
            f"ok={summary['ok']}")
    return {"rows": rows, "summary": summary}
